#!/usr/bin/env python
"""Evaluate entry point — the reference's evaluate.py surface (SURVEY.md
§3.2): restore checkpoint(s), run the test split, print a JSON report
with AUC and sensitivity at the fixed-specificity operating points
(BASELINE.json:8). Multiple --ensemble_dir flags (or an ensemble10
workdir laid out by train.py) average per-model probabilities
(BASELINE.json:10).

Examples:
  python evaluate.py --config=eyepacs_binary --data_dir=/data/eyepacs \
      --checkpoint_dir=/ckpt/run1
  python evaluate.py --config=messidor2_eval --data_dir=/data/messidor2 \
      --checkpoint_dir=/ckpt/run1 --split=test
  python evaluate.py --config=ensemble10 --data_dir=... \
      --checkpoint_dir=/ckpt/ens   # auto-discovers member_NN subdirs
"""

from __future__ import annotations

import json

from absl import app, flags

_CONFIG = flags.DEFINE_string("config", "eyepacs_binary", "preset name")
_SET = flags.DEFINE_multi_string("set", [], "config overrides")
_DATA_DIR = flags.DEFINE_string("data_dir", "", "TFRecord directory")
_CKPT = flags.DEFINE_string("checkpoint_dir", "", "checkpoint dir (or ensemble root)")
_ENSEMBLE = flags.DEFINE_multi_string(
    "ensemble_dir", [], "explicit member checkpoint dirs (repeatable; the "
    "reference's -e flag)"
)
_SPLIT = flags.DEFINE_string("split", "test", "which split to evaluate")
_THRESHOLD_SPLIT = flags.DEFINE_string(
    "threshold_split", "",
    "paper protocol: choose operating thresholds at the fixed "
    "specificities on THIS split (e.g. val) and apply them unchanged to "
    "--split, reported as operating_points_transferred",
)
_THRESHOLD_DATA_DIR = flags.DEFINE_string(
    "threshold_data_dir", "",
    "TFRecord dir for --threshold_split when it lives in ANOTHER dataset "
    "— the paper's cross-dataset protocol (EyePACS val thresholds "
    "applied to Messidor-2) needs this; default: --data_dir",
)
_BOOTSTRAP = flags.DEFINE_integer(
    "bootstrap", 0,
    "number of bootstrap resamples for 95% CIs on AUC/sensitivity "
    "(0 = off; the replication paper used 2000)",
)
_JIT_CACHE = flags.DEFINE_string(
    "jit_cache_dir", "",
    "persistent XLA compilation cache directory (share it with train.py "
    "to skip the eval-step compile). Empty = off.",
)
_CALIBRATE = flags.DEFINE_boolean(
    "calibrate", False,
    "fit a temperature on --threshold_split (required) and report "
    "calibrated Brier/ECE on --split; AUC/thresholds are unaffected "
    "(temperature is rank-preserving)",
)
_SAVE_PROBS = flags.DEFINE_string(
    "save_probs", "",
    "write per-image ensemble-averaged probabilities (name, grade, "
    "prob[, per-class]) to this CSV for error analysis / recalibration",
)
_PROFILE_OUT = flags.DEFINE_string(
    "profile_out", "",
    "write the quality-observability reference profile (obs/quality.py: "
    "score histogram, input-statistic histograms, base rate, operating "
    "thresholds) for this checkpoint set on --split to this JSON — the "
    "artifact serving's online drift monitor (obs.quality.profile_path) "
    "compares live traffic against. Emit it on the split the thresholds "
    "were chosen on (normally --split=val)",
)
_DEVICE = flags.DEFINE_enum(
    "device", "tpu", ["tpu", "cpu", "tf"],
    "backend gate (BASELINE.json:5): tpu/cpu run the Flax model under jit "
    "on that platform; tf runs the legacy-graph stand-in (keras "
    "InceptionV3 on host CPU, weights from the same orbax checkpoints) "
    "through the same untouched metrics layer",
)
_FAKE_DEVICES = flags.DEFINE_integer("fake_devices", 0, "cpu fake devices")




def main(argv):
    del argv
    if _DEVICE.value in ("cpu", "tf"):
        # tf mode still restores orbax checkpoints through jax — pin jax
        # to CPU so no TPU is required for the legacy-backend path.
        import jax

        jax.config.update("jax_platforms", "cpu")
        if _FAKE_DEVICES.value:
            from jama16_retina_tpu.parallel import mesh as _mesh_compat

            _mesh_compat.configure_fake_cpu_devices(_FAKE_DEVICES.value)

    # Multi-host bring-up BEFORE anything touches a jax backend (no-op
    # unless a coordinator is configured; SURVEY.md §3.5).
    from jama16_retina_tpu.parallel import mesh as mesh_lib

    mesh_lib.initialize_distributed()

    if _JIT_CACHE.value:
        mesh_lib.enable_persistent_compilation_cache(_JIT_CACHE.value)

    from jama16_retina_tpu import configs, trainer

    cfg = configs.get_config(_CONFIG.value)
    if _SET.value:
        cfg = configs.override(cfg, _SET.value)
    data_dir = _DATA_DIR.value or cfg.data.test_dir
    if not data_dir:
        raise app.UsageError("--data_dir is required")
    from jama16_retina_tpu.utils import checkpoint as ckpt_lib

    dirs = list(_ENSEMBLE.value) or list(cfg.eval.ensemble_dirs)
    if not dirs:
        if not _CKPT.value:
            raise app.UsageError("--checkpoint_dir or --ensemble_dir required")
        dirs = ckpt_lib.discover_member_dirs(_CKPT.value)

    report = trainer.evaluate_checkpoints(
        cfg, data_dir, dirs, split=_SPLIT.value,
        backend="tf" if _DEVICE.value == "tf" else "flax",
        threshold_split=_THRESHOLD_SPLIT.value or None,
        threshold_data_dir=_THRESHOLD_DATA_DIR.value or None,
        bootstrap=_BOOTSTRAP.value,
        save_probs=_SAVE_PROBS.value or None,
        calibrate=_CALIBRATE.value,
        profile_out=_PROFILE_OUT.value or None,
    )
    print(json.dumps(report, indent=2))


if __name__ == "__main__":
    app.run(main)
