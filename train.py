#!/usr/bin/env python
"""Train entry point — same UX surface as the reference's train.py
(SURVEY.md N1, BASELINE.json:5): pick a config, point at a data dir of
TFRecord shards, get checkpoints + metrics in --workdir. The --device
flag is the backend gate from the north star: ``tpu`` (default) uses the
ambient JAX platform (the axon TPU here), ``cpu`` forces the CPU backend
(with optional fake multi-device for sharding tests).

Examples:
  python train.py --config=eyepacs_binary --data_dir=/data/eyepacs \
      --workdir=/ckpt/run1
  python train.py --config=smoke --synthetic=64 --data_dir=/tmp/synth \
      --workdir=/tmp/ck --device=cpu
  python train.py --config=ensemble10 ...   # trains 10 seeded members
"""

from __future__ import annotations

import json
import os

from absl import app, flags

_CONFIG = flags.DEFINE_string("config", "eyepacs_binary", "preset name")
_SET = flags.DEFINE_multi_string(
    "set", [], "config overrides, section.field=value"
)
_DATA_DIR = flags.DEFINE_string("data_dir", "", "TFRecord directory")
_WORKDIR = flags.DEFINE_string(
    "workdir", "", "checkpoint/metrics directory (default: train.checkpoint_dir)"
)
_DEVICE = flags.DEFINE_enum(
    "device", "tpu", ["tpu", "cpu", "tf"],
    "backend gate (BASELINE.json:5): tpu (default) trains the Flax model "
    "on the ambient JAX platform, cpu forces the CPU backend, tf runs "
    "the legacy keras backend on host TF (trainer.fit_tf) writing the "
    "same orbax checkpoint format via weight transplant",
)
_FAKE_DEVICES = flags.DEFINE_integer(
    "fake_devices", 0,
    "with --device=cpu: number of fake XLA host devices (sharding tests)",
)
_SYNTHETIC = flags.DEFINE_integer(
    "synthetic", 0,
    "if >0 and data_dir has no train split, write N synthetic fundus "
    "examples per split first (test/bench fixture; no real data ships "
    "with this environment)",
)
_RESUME = flags.DEFINE_boolean("resume", False, "resume from latest ckpt")
_JIT_CACHE = flags.DEFINE_string(
    "jit_cache_dir", "",
    "persistent XLA compilation cache directory. Cuts the ~80s TPU "
    "compile from every later run — and from members 2..k of an "
    "ensemble run, which trace the identical graph. Empty = off.",
)


def main(argv):
    del argv
    if _DEVICE.value in ("cpu", "tf"):
        # tf mode trains in keras but writes orbax checkpoints through
        # jax — pin jax to CPU so no TPU is required for the legacy path.
        import jax

        jax.config.update("jax_platforms", "cpu")
        if _FAKE_DEVICES.value:
            from jama16_retina_tpu.parallel import mesh as _mesh_compat

            _mesh_compat.configure_fake_cpu_devices(_FAKE_DEVICES.value)

    # Multi-host bring-up BEFORE anything touches a jax backend (no-op
    # unless a coordinator is configured in the environment; SURVEY.md
    # §3.5). After this, jax.devices() spans every host and the input
    # pipeline shards files by jax.process_index().
    from jama16_retina_tpu.parallel import mesh as mesh_lib

    mesh_lib.initialize_distributed()

    if _JIT_CACHE.value:
        mesh_lib.enable_persistent_compilation_cache(_JIT_CACHE.value)

    from jama16_retina_tpu import configs, trainer
    from jama16_retina_tpu.data import tfrecord

    cfg = configs.get_config(_CONFIG.value)
    if _SET.value:
        cfg = configs.override(cfg, _SET.value)
    if _RESUME.value:
        cfg = configs.override(cfg, ["train.resume=true"])
    data_dir = _DATA_DIR.value or cfg.data.train_dir
    if not data_dir:
        raise app.UsageError("--data_dir is required")
    workdir = _WORKDIR.value or cfg.train.checkpoint_dir

    if _SYNTHETIC.value:
        try:
            tfrecord.list_split(data_dir, "train")
        except FileNotFoundError:
            n = _SYNTHETIC.value
            for split, ns, seed in (
                ("train", n, 1), ("val", max(n // 2, 8), 2), ("test", max(n // 2, 8), 3),
            ):
                tfrecord.write_synthetic_split(
                    data_dir, split, ns, cfg.model.image_size, num_shards=4,
                    seed=seed,
                )

    backend = "tf" if _DEVICE.value == "tf" else "flax"
    if cfg.train.ensemble_size > 1:
        results = trainer.fit_ensemble(cfg, data_dir, workdir, backend=backend)
    elif backend == "tf":
        results = trainer.fit_tf(cfg, data_dir, workdir)
    else:
        results = trainer.fit(cfg, data_dir, workdir)
    print(json.dumps({"config": cfg.name, "results": results}, default=str))


if __name__ == "__main__":
    app.run(main)
