#!/usr/bin/env python
"""Preprocess Messidor / Messidor-2 -> fundus-normalized TFRecord eval set
(reference entry point of the same name, SURVEY.md §3.3; the held-out
evaluation protocol of BASELINE.json:8).

Messidor-2 ships adjudicated ICDR grades (0-4) in a CSV; original
Messidor uses 0-3 retinopathy grades — both bin to referable DR at
grade >= 2, so grades are stored raw exactly like EyePACS shards. The
whole set is written as a single ``test`` split (it is an evaluation
corpus; the reference never trained on it).

Example:
  python preprocess_messidor.py --data_dir=/data/messidor2/images \
      --labels_csv=/data/messidor2/grades.csv --output_dir=/data/m2_tfr
"""

from __future__ import annotations

import json

from absl import app, flags

_DATA_DIR = flags.DEFINE_string("data_dir", "", "directory of raw images")
_LABELS = flags.DEFINE_string("labels_csv", "", "grading CSV path")
_OUT = flags.DEFINE_string("output_dir", "", "TFRecord output directory")
_SIZE = flags.DEFINE_integer("image_size", 299, "output diameter")
_SHARDS = flags.DEFINE_integer("num_shards", 8, "shards for the test split")
_BEN_GRAHAM = flags.DEFINE_boolean("ben_graham", False, "contrast enhancement")
_ENCODING = flags.DEFINE_enum(
    "encoding", "jpeg", ["jpeg", "raw"],
    "record encoding: jpeg (compact) or raw pre-decoded uint8 (see "
    "docs/PERF.md)",
)
_MIN_QUALITY = flags.DEFINE_float(
    "min_quality", 0.0,
    "drop images whose gradability score is below this [0,1] threshold "
    "(see preprocess_eyepacs.py --min_quality); scores land in "
    "quality_test.csv regardless",
)
_WORKERS = flags.DEFINE_integer(
    "workers", 0,
    "CPU worker processes for the per-image stage (0 = serial); output "
    "is byte-identical at any worker count",
)


def main(argv):
    del argv
    from jama16_retina_tpu.preprocess import datasets

    if not (_DATA_DIR.value and _LABELS.value and _OUT.value):
        raise app.UsageError("--data_dir, --labels_csv, --output_dir required")

    labels = datasets.parse_labels_csv(_LABELS.value)
    items = sorted(labels.items())
    stats = datasets.process_split(
        items, _DATA_DIR.value, _OUT.value, "test",
        image_size=_SIZE.value, num_shards=_SHARDS.value,
        ben_graham=_BEN_GRAHAM.value, encoding=_ENCODING.value,
        min_quality=_MIN_QUALITY.value, workers=_WORKERS.value,
    )
    print(json.dumps({"test": {"n_labeled": len(items), **stats.as_dict()}},
                     indent=2))


if __name__ == "__main__":
    app.run(main)
