"""Typed configuration system (SURVEY.md N2).

The reference uses per-script argparse flags (SURVEY.md §5.6); here the
same surface is expressed as frozen dataclasses plus named presets
matching the five BASELINE.json configs (BASELINE.json:7-11):

  * ``eyepacs_binary``   — Inception-v3 binary referable-DR, 299x299, batch 32
  * ``messidor2_eval``   — Messidor-2 held-out eval at sens@spec {0.87, 0.98}
  * ``icdr5``            — 5-class ICDR severity grading (multi:softmax)
  * ``ensemble10``       — 10-model ensemble with averaged logits
  * ``resnet50`` / ``efficientnet_b4`` — backbone swap under same train loop

CLI flags (absl) override individual fields; see train.py / evaluate.py.
"""

from __future__ import annotations

import dataclasses
from typing import Sequence


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Model architecture + head selection (reference: TF-Slim inception_v3)."""

    arch: str = "inception_v3"  # inception_v3 | resnet50 | efficientnet_b4 | tiny_cnn
    # "binary" -> 1-logit sigmoid referable-DR head (ICDR grade >= 2);
    # "multi"  -> 5-logit softmax ICDR severity head (BASELINE.json:9).
    head: str = "binary"
    image_size: int = 299
    dropout_rate: float = 0.2
    # bfloat16 matmuls/convs with float32 BN statistics and loss: the
    # TPU-native numerics policy (MXU-friendly; SURVEY.md §7.7).
    compute_dtype: str = "bfloat16"
    # Auxiliary logits head, mirroring TF-Slim inception_v3's aux head.
    aux_head: bool = True
    aux_weight: float = 0.4
    # Stem experiment levers for the batch-32 HBM bound (VERDICT r3 #2;
    # measured in docs/PERF.md §Stem-experiments — flags stay off unless
    # the measurement says otherwise). inception_v3 only.
    # stem_s2d: numerically exact space-to-depth rewrite of the stride-2
    # stem conv (299x299x3 -> 150x150x12 blocks; the MLPerf ResNet
    # trick) — same parameter tree, so checkpoints/transplant unchanged.
    stem_s2d: bool = False
    # remat_stem: jax.checkpoint over the stem (recompute its
    # activations in backward instead of keeping them live).
    remat_stem: bool = False

    @property
    def num_classes(self) -> int:
        """Derived from head, never stored — cannot desync via overrides."""
        return 5 if self.head == "multi" else 1


@dataclasses.dataclass(frozen=True)
class DataConfig:
    """Input pipeline config (reference: lib/dataset tf.data over TFRecords)."""

    # Split roots: one dataset directory holds train/val/test TFRecord
    # splits by name (data/tfrecord.py layout); train.py defaults its
    # --data_dir to train_dir, evaluate.py to test_dir. (A val_dir knob
    # existed through PR 8 but was consumed by nothing — the loaders
    # resolve the val split inside data_dir — and graftlint's dead-knob
    # rule retired it.)
    train_dir: str = ""
    test_dir: str = ""
    batch_size: int = 32  # global batch across all devices (BASELINE.json:7)
    # Train-stream loader (SURVEY.md N4): "tfdata" = tf.data stream with
    # deterministic replay resume (data/pipeline.py); "grain" = index-
    # sampled loader with global shuffle and O(1) derived-state resume
    # (data/grain_pipeline.py); "hbm" = whole split resident in device
    # memory, per-step on-device gather, zero steady-state H2D — for
    # splits that fit the HBM budget (data/hbm_pipeline.py, docs/PERF.md
    # §H2D); "tiered" = partial HBM residency — pin as many rows as the
    # budget allows, stream the rest through the parallel host decoder
    # with overlapped per-shard H2D staging, so throughput degrades
    # gracefully from the HBM-resident rate toward the streamed floor
    # instead of cliffing when the split outgrows HBM
    # (data/tiered_pipeline.py); "rawshard" = the tiered machinery over
    # ahead-of-time transcoded raw array shards
    # (scripts/transcode_shards.py + data/rawshard.py): decode/resize
    # paid ONCE offline, steady-state reads are mmap row memcpys —
    # bit-identical (post-decode) to the streamed path at the same
    # seed; "served" = attach to a disaggregated ingest SERVER process
    # (scripts/ingest_server.py + jama16_retina_tpu/ingest/) over a
    # shared-memory ring — the server owns the tiered/rawshard decode
    # plane once for every local consumer, and the stream stays
    # bit-identical (post-decode) to the in-process tiered path at the
    # same seed (ingest.* knobs below configure the rendezvous). Same
    # {'image','grade'} batch contract throughout.
    loader: str = "tfdata"
    # Closed-loop ingest autotuner (data/autotune.py; ISSUE 7): the
    # flax train loops observe their own stall attribution over
    # tumbling log windows and adjust decode_workers / stage_depth /
    # prefetch depth ONLINE (hill-climb with hysteresis, HBM-budget
    # clamped). Every tunable knob is content-invariant, so a tuned
    # run's batches — and final eval metrics — are bit-identical to
    # the same seed with hand-set knobs. Off by default (the hand-set
    # values below then apply verbatim).
    autotune: bool = False
    # Per-device memory-limit override (bytes, BEFORE the budget
    # fraction) for every HBM-budget derivation (hbm/tiered residency
    # gates, eval caches, the autotuner's staging headroom). 0 = detect
    # from the runtime, falling back to the conservative 8 GB smallest-
    # deployed-core assumption (hbm_pipeline.hbm_budget_bytes logs the
    # fallback and names this knob). On multi-process pod slices the
    # budget is PER HOST in effect (ISSUE 14): each host sizes, decodes
    # and stages only its own devices' addressable shard of the tiered
    # resident set (tiered_pipeline.host_spill_plan), so the knob
    # bounds what one host's devices pin — never a global sum some
    # other host would have to stage.
    hbm_budget_bytes: int = 0
    # Directory of ahead-of-time transcoded raw shards for
    # data.loader=rawshard. Empty = <data_dir>/rawshard<image_size>,
    # the default scripts/transcode_shards.py writes to.
    rawshard_dir: str = ""
    # Host decode worker THREADS for the tiered loader's streamed tier
    # and the hbm/tiered one-time resident load
    # (grain_pipeline.ParallelDecoder). 0 = auto: one per host core up
    # to 8, leaving a core for device dispatch
    # (grain_pipeline.resolve_decode_workers). Batch contents are
    # worker-count-invariant by construction (deterministic ordering),
    # so this is a pure throughput knob.
    decode_workers: int = 0
    # Tiered loader only: how many batches the loader keeps decoded +
    # dispatched AHEAD of consumption (its internal staging queue, on
    # top of prefetch_batches in the trainer's device_prefetch). 0 =
    # auto: max(2, prefetch_batches).
    stage_depth: int = 0
    # Tiered loader only: TOTAL bytes of HBM (across the mesh's data
    # axis) the resident tier may pin. -1 = auto-derive from the device
    # budget (hbm_pipeline.hbm_budget_bytes x data-axis size); 0 = pin
    # nothing (pure streamed mode — bit-identical batch sequence to
    # tiered_pipeline.streamed_batches); >0 = explicit cap (what bench
    # and tests use for reproducible partial residency).
    tiered_resident_bytes: int = -1
    # Route the tf.data loader's device placement through per-shard H2D
    # staging (pipeline.device_prefetch per_shard): each device's row
    # block is device_put separately so individual shard copies overlap
    # the train step instead of one whole-batch put. Single-process
    # meshes only (multi-process assembly already places per-device).
    stage_per_shard: bool = False
    # grain loader only: number of worker PROCESSES decoding in parallel
    # (0 = in-process). Multi-core TPU hosts want >0; resume then runs
    # off per-checkpoint persisted iterator state instead of the
    # (seed, step) derivation, which has no closed form across workers
    # (data/grain_pipeline.state_at_step).
    grain_workers: int = 0
    # NOTE: image size lives ONLY in ModelConfig.image_size; the pipeline
    # reads it from there so the two can never desync via overrides.
    shuffle_buffer: int = 4096
    prefetch_batches: int = 2
    # Augmentation mirrors the reference's online pipeline: random
    # horizontal/vertical flips plus brightness/contrast/saturation/hue
    # jitter (SURVEY.md R5). Executed in JAX on-device so it fuses into
    # the step's XLA program instead of burning host CPU.
    augment: bool = True
    flip: bool = True
    brightness_delta: float = 0.25
    contrast_range: tuple[float, float] = (0.75, 1.25)
    saturation_range: tuple[float, float] = (0.8, 1.2)
    hue_delta: float = 0.05
    rotate: bool = True  # fundus images have rotational symmetry
    # Per-record poison quarantine (ISSUE 6): a record whose payload
    # fails to decode (corrupt JPEG, truncated proto) is COUNTED
    # (data.quarantined{reason}) and deterministically substituted with
    # the next decodable record instead of killing the decode epoch on
    # the caller thread. Applies to every path through
    # grain_pipeline.ParallelDecoder — the hbm and tiered loaders; the
    # tfdata/grain loaders keep their engines' own error semantics.
    # False restores raise-through (debugging a specific bad shard).
    quarantine_bad_records: bool = True
    # Route the color half of augmentation through the fused pallas
    # kernel (ops/pallas_augment.py, SURVEY.md N13) instead of the jnp
    # composition. Same math; one HBM pass. TPU-only (tests use the
    # kernel's interpret mode explicitly).
    use_pallas: bool = False


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Train-loop config (reference: train.py session loop, SURVEY.md §3.1)."""

    steps: int = 30000
    eval_every: int = 500
    log_every: int = 50
    # --- Raw-speed training (ISSUE 11) --------------------------------
    # Train-step numerics: "fp32" keeps every existing bit-identity pin
    # (params, grads, and optimizer all float32 — the default);
    # "bf16" runs forward/backward on a bfloat16 CAST of the params
    # while the float32 MASTER weights keep taking the optimizer update
    # (mixed precision with fp32 master weights). Loss-scale-free by
    # design: bf16 shares float32's exponent range, so gradients
    # neither overflow nor underflow the way fp16 ones do. Gated by the
    # golden-curve parity check below — the train-side mirror of the
    # serve.dtype canary gate (PR 10). Flax loops only (fit_tf refuses).
    dtype: str = "fp32"
    # Pinned fp32 golden curve for the dtype gate: a metrics.jsonl (or
    # the JSON list of its eval records) from an fp32 run of the SAME
    # config/seed. When set and train.dtype != fp32, every eval's val
    # AUC is compared against the pinned curve at the same step; drift
    # beyond dtype_curve_tol raises train_lib.DtypeCurveRejected — the
    # run is REFUSED, not silently shipped. Empty = ungated (logged).
    dtype_curve_ref: str = ""
    # Max |val_auc - pinned fp32 val_auc| at matching steps before a
    # non-fp32 run is refused.
    dtype_curve_tol: float = 0.02
    # Fused Pallas step path (ops/pallas_augment.fused_normalize_color_
    # jitter + ops/pallas_opt.py): (a) normalize+color-augment in ONE
    # kernel pass with the per-image contrast means computed in-kernel
    # (the separate XLA reduce pass disappears); (b) the adamw update
    # as one fused pass over params/grads/moments per leaf instead of
    # the optax tree-map chain. adamw without gradient clipping only
    # (validated loudly); routed off (with a log) on >1-device GSPMD
    # meshes exactly like data.use_pallas (Mosaic kernels cannot be
    # auto-partitioned). Off by default; fp32-reference pins in
    # tests/test_mixedprec.py.
    use_pallas_fused: bool = False
    # Gradient accumulation: split each data.batch_size batch into this
    # many sequential micro-batches INSIDE the one jit step (grads
    # averaged in the step's compute dtype, one optimizer update per
    # recipe batch). Decouples the device's per-forward batch
    # (batch_size/accum_steps — what bounds activation HBM) from the
    # recipe batch (what the optimizer sees), feeding large-batch
    # recipes. batch_size must divide evenly; BatchNorm sees micro-
    # batch moments (ghost batch norm — the large-batch literature's
    # default). 1 = off (the step program is byte-identical to before
    # the knob existed).
    accum_steps: int = 1
    # Async checkpointing (utils/checkpoint.AsyncSaver): eval-time saves
    # snapshot the state on-device (one HBM copy) and hand the
    # device->host fetch + orbax write to a background worker, so the
    # step loop never blocks on checkpoint I/O (the k=4 stacked fetch is
    # ~48 s on this environment's tunnel). The SIGTERM preemption save
    # drains the worker first; kill -9 mid-save leaves only an
    # uncommitted orbax tmp step (invisible to resume). Single-process
    # flax loops only (multi-host gathers cannot run off-thread).
    async_save: bool = False
    # Eval overlap: dispatch the whole eval block (val predict -> AUC ->
    # best-tracking -> save) on a background worker over an on-device
    # snapshot of the state, so training continues through what used to
    # be the eval pause. Eval RESULTS are identical (same snapshot, same
    # math — pinned); only their arrival is late: early stopping fires
    # when the overlapped eval completes, a few steps after its
    # boundary. Implies async saves (orbax pins a manager's saves to
    # one thread, so the AsyncSaver worker is the save thread whenever
    # overlap is on). Single-process flax loops only.
    eval_overlap: bool = False
    learning_rate: float = 1e-3
    lr_schedule: str = "cosine"  # constant | cosine | warmup_cosine
    warmup_steps: int = 500
    weight_decay: float = 4e-5
    # adamw | sgdm | rmsprop | lamb. "lamb" is the large-batch recipe's
    # optimizer (ISSUE 14; "Training EfficientNets at Supercomputer
    # Scale", PAPERS.md): Adam moments + per-layer trust-ratio
    # adaptation, which keeps the update scale sane when the global
    # batch — and with lr_scale_ref_batch the LR — grows by an order of
    # magnitude. optax-native (optax.lamb), so optimizer state in
    # checkpoints stays optax-structure-compatible exactly like the
    # fused adamw path (ops/pallas_opt.py) — resume cannot tell which
    # optimizer family wrote the moments' tree layout.
    optimizer: str = "adamw"
    momentum: float = 0.9
    # --- Large-batch recipe (ISSUE 14) --------------------------------
    # Linear LR scaling tied to the global batch (Goyal et al.; the
    # EfficientNets-at-scale recipe): with a reference batch R > 0 the
    # effective peak LR becomes learning_rate × (global_batch / R),
    # where global_batch = data.batch_size (factored as accum_steps ×
    # per-forward device batch × data-axis ways — train.accum_steps
    # decouples the two, which is exactly what it was built for).
    # Resolved ONCE at fit entry (train_lib.resolve_large_batch, logged
    # with the factorization); pair with lr_schedule=warmup_cosine —
    # a scaled LR without warmup diverges at these scales and the
    # resolver warns when warmup is absent. 0 = off (LR verbatim).
    lr_scale_ref_batch: int = 0
    # Golden-curve parity gate for the large-batch recipe — the recipe
    # twin of dtype_curve_ref (same _DtypeCurveGate machinery): a
    # metrics.jsonl from the ACCEPTED baseline recipe (e.g. adamw at
    # the reference batch) that every eval's val AUC is compared
    # against at matching steps. Drift beyond recipe_curve_tol raises
    # train_lib.RecipeCurveRejected — a faster recipe must prove
    # quality parity on time-to-AUC terms, never silently ship. Empty
    # = ungated (logged when a lamb/scaled-LR run has no pin).
    recipe_curve_ref: str = ""
    recipe_curve_tol: float = 0.02
    # Early stopping on validation AUC (reference: stop after `patience`
    # evals without a new best; keep best checkpoint).
    early_stop_patience: int = 10
    min_delta: float = 1e-4
    seed: int = 0
    checkpoint_dir: str = "/tmp/retina_ckpt"
    max_to_keep: int = 3
    resume: bool = False
    # Warm-start entry (ISSUE 8): a checkpoint directory whose best
    # params/batch_stats (and EMA shadow, when both sides carry one)
    # seed the run's initial state at step 0 — fresh optimizer, fresh
    # schedule, full step budget. The lifecycle controller's RETRAIN
    # phase fine-tunes the LIVE model on fresh data this way instead of
    # training from random init. Ignored when resume finds an existing
    # checkpoint in the workdir (a resumed run continues itself).
    init_from: str = ""
    # Ensemble distillation (ISSUE 10 cascade): an ensemble root (or
    # single checkpoint dir) whose members are restored ONCE into a
    # device-resident stacked teacher; the run's loss then trains the
    # student against the teacher's AVERAGED SOFT SCORES on each batch
    # (sigmoid-BCE with soft targets on the binary head, soft-target CE
    # on the multi head) instead of the dataset's hard grades. The
    # student is what serve.cascade_student_dir points a CascadeEngine
    # at; combine with init_from to warm-start it from a teacher
    # member. Teacher members must share model.* with this run (same
    # checkpoint schema). Empty disables (hard labels, the default).
    distill_from: str = ""
    # Checkpoint every Nth eval (plus ALWAYS the final/early-stop eval,
    # so the run ends durable). 1 = the reference's save-every-eval
    # semantics. Raising it trades resume granularity and best-
    # checkpoint resolution (best is picked among SAVED evals) for eval
    # cadence: each save fetches the full train state device->host,
    # which is the dominant per-eval cost when the state is large or
    # the link is slow (measured: a k=4 stacked Inception state is
    # 1.56 GB ~= 48 s/eval on this environment's tunnel, >10x the eval
    # forward itself — docs/PERF.md §Eval).
    save_every_evals: int = 1
    # Always checkpoint the FIRST eval too (ADVICE r4): without it a
    # sparse-save run has no checkpoint until ordinal save_every_evals
    # and a crash in that window resumes from step 0. Default on — the
    # right call on real hardware where a save is cheap. Opt out when
    # the save is the experiment's dominant cost and the early crash
    # window is an accepted trade (scripts/time_to_auc.py: a k=4
    # stacked-state fetch is ~48 s on this environment's tunnel and
    # would land BEFORE the crossing being measured).
    save_first_eval: bool = True
    # loss-scale epsilon for label smoothing on the multi head
    label_smoothing: float = 0.0
    gradient_clip_norm: float = 0.0  # 0 disables
    # Polyak/EMA weight averaging (0 disables): eval and checkpoints use
    # the shadow params when enabled — a standard AUC lever for inception
    # training toward the >=0.97 target (SURVEY.md §6 note). Typical
    # values 0.999-0.9999. Flax path only (fit_tf rejects it).
    ema_decay: float = 0.0
    # Number of independently seeded ensemble members the train driver
    # produces (reference trains k=10, BASELINE.json:10). 1 = single model.
    ensemble_size: int = 1
    # Member-parallel ensemble training (trainer.fit_ensemble_parallel):
    # instead of the reference's k sequential runs, stack the k members
    # on a 'member' mesh axis and train them in ONE XLA program — members
    # are independent replicas (zero cross-member collectives; this is
    # ensemble data-parallelism over seeds, NOT tensor parallelism, see
    # SURVEY.md N10) so the member axis shards embarrassingly across
    # chips. Measured single-chip it is ~parity with the sequential
    # driver (bench `ensemble4_parallel_speedup` ≈ 0.89: weight/optimizer
    # HBM traffic scales with members, unlike batch scaling); the win is
    # on multi-chip slices — each member-shard group trains with FEWER
    # data-parallel ways (higher per-chip batch, the amortization
    # documented in docs/PERF.md), no gradient allreduce crosses member
    # groups, and the k-run protocol becomes one program (k× fewer
    # dispatches/compiles). Members share the batch stream (seed =
    # train.seed); diversity comes from per-member init/augmentation/
    # dropout keys (seed + m, matching the sequential driver's seeds).
    # Checkpoint layout is identical to the sequential driver's member_NN
    # dirs. Flax path; multi-host runs place each host's batch shard
    # with make_array_from_process_local_data and reshard member-sharded
    # state to replicated before host gathers (docs/MULTIHOST.md;
    # pinned 2-process vs single-process in tests/test_multiprocess.py).
    ensemble_parallel: bool = False
    # Measured-speedup gate on the stacked path: single-chip the stacked
    # step runs BELOW the sequential member rate (bench
    # ensemble4_parallel_speedup 0.85-0.89 across rounds — weight/
    # optimizer HBM traffic scales with members while batch does not),
    # so fit_ensemble auto-falls back to the sequential driver on
    # 1-device meshes, with a logged reason, rather than ship a known
    # slowdown. Set true to force the stacked path anyway (e.g. to
    # measure it, or when dispatch overhead dominates on a new chip).
    ensemble_parallel_force: bool = False
    # Run the member-parallel step with the DATA axis manual too (full
    # jax.shard_map; train_lib.make_ensemble_train_step manual_data):
    # every collective is explicit — the loss pmean whose backward IS
    # the gradient all-reduce, and axis_name='data' BatchNorm moment
    # pmeans — instead of GSPMD-derived. Same math (pinned vs the
    # auto-data form in tests/test_ensemble_parallel.py); augmentation/
    # dropout draws fold the data-shard index (pmap-style stream, same
    # distribution). Use on big meshes where GSPMD's generic activation
    # collectives dominate; ignored on 1-device meshes.
    ensemble_manual_data: bool = False
    # Profiling (SURVEY.md §5.1): if > 0, capture a jax.profiler trace of
    # this many steps (starting at step 10) into <workdir>/profile —
    # TensorBoard/Perfetto-viewable XLA op + ICI collective timeline.
    profile_steps: int = 0
    # Mirror train/eval scalars into <workdir>/tb TensorBoard events
    # (JSONL remains the system of record; SURVEY.md §5.5).
    tensorboard: bool = False
    # Debug mode (SURVEY.md §5.2): enable jax_debug_nans so the first
    # non-finite value aborts with the failing primitive's stack.
    debug: bool = False


@dataclasses.dataclass(frozen=True)
class ParallelConfig:
    """Device-mesh config (SURVEY.md N7-N9; ISSUE 14 pod-scale mesh).

    The mesh is a CONFIG AXIS, not an assumption: training meshes are
    ``(member × data)`` when the member axis is sized, pure
    data-parallel otherwise, and the serving engine assembles over its
    own mesh (``serve_devices``) through the EngineSpec seam
    (serve/assemble.py). ``model_axis_size`` is the documented
    extension seam for a future model axis — kept at 1 (SURVEY.md N10:
    Inception-v3 at ~24M params fits trivially per chip).
    """

    # Name of the data-parallel mesh axis — batches shard over it, the
    # gradient/BN all-reduces ride it. The explicit-collective ensemble
    # forms (train.ensemble_manual_data) and axis_name BatchNorm pin
    # the literal name "data" and refuse other spellings loudly; the
    # GSPMD jit paths honor any name.
    data_axis: str = "data"
    num_devices: int = 0  # 0 = all local devices
    model_axis_size: int = 1
    # Member-axis size of the (member × data) training mesh for the
    # member-parallel ensemble driver. 0 = auto (gcd(k, n_devices) —
    # the largest count dividing both, mesh.make_ensemble_mesh's
    # historical rule); >1 pins the member axis explicitly (refused
    # loudly when it does not divide the member count and the device
    # count). On a serving mesh (serve_devices > 1) a value > 1 shards
    # the STACKED serving tree across the member axis too — each
    # device group holds k/member_axis_size members, the pod-scale
    # form that finally amortizes ensemble serving.
    member_axis_size: int = 0
    # Devices the ASSEMBLED serving engine's mesh spans (ISSUE 14;
    # serve/assemble.py). 0/1 = the mesh-less single-device
    # construction — the bit-identity default every predict.py parity
    # pin rides; >1 = a GSPMD serving mesh over that many devices:
    # batch rows shard over data_axis, and with member_axis_size > 1
    # the stacked tree shards over the member axis as well.
    serve_devices: int = 0


@dataclasses.dataclass(frozen=True)
class EvalConfig:
    """Evaluation config (reference: evaluate.py, SURVEY.md §3.2)."""

    batch_size: int = 64
    # Operating points: thresholds chosen on the ROC curve at fixed
    # specificity (BASELINE.json:8).
    operating_specificities: tuple[float, float] = (0.87, 0.98)
    # Ensemble: list of checkpoint dirs whose probabilities are averaged
    # (BASELINE.json:10 "averaged logits").
    ensemble_dirs: tuple[str, ...] = ()
    # Test-time augmentation: average probabilities over the 4 flip views
    # (identity/h/v/hv) inside the one jit eval program. A quality lever
    # beyond the reference (fundus photos have no canonical orientation);
    # 4x eval FLOPs, eval only. Off by default for paper parity.
    tta: bool = False
    # Multi-host eval decode sharding (data/pipeline.eval_batches_sharded):
    # each process decodes only 1/P of the records (stride-sharded before
    # decode) instead of every host decoding the full eval set. Worth it
    # under the k-model × frequent-eval protocol on pods; off by default
    # — the unsharded path keeps the record order un-permuted. Applies to
    # the 1-D DP eval path (fit/evaluate/predict); the member-parallel
    # driver's eval ignores it (its ('member','data') layout has no
    # per-process contiguous row block to decode into) and says so.
    sharded: bool = False


@dataclasses.dataclass(frozen=True)
class ServeConfig:
    """Serving-engine config (serve/engine.py, serve/batcher.py).

    The inference twin of DataConfig: knobs for the persistent
    micro-batched serving path — how requests coalesce, which padded
    batch shapes jit compiles for, and how the ensemble members forward.
    """

    # Largest coalesced batch one engine forward serves. The
    # micro-batcher closes its window at this many rows (or at
    # max_wait_ms, whichever first); the engine chunks larger inputs.
    max_batch: int = 64
    # Longest a request waits for co-riders before the window flushes.
    # 0 serves every request the moment the engine is free (lowest
    # latency, least coalescing).
    max_wait_ms: float = 5.0
    # Padded batch shapes the engine compiles for — every forward runs
    # at one of these row counts, so jit compiles once per bucket and
    # NEVER per request size. Empty = auto: powers of two from 8 up to
    # max_batch. The largest bucket must cover max_batch. A single
    # bucket (e.g. just max_batch) additionally makes per-row results
    # bit-invariant to request interleaving: every row always runs at
    # the same compiled shape (bf16 convs can drift at ulp level across
    # shapes; see docs/PERF.md §Serve).
    bucket_sizes: tuple[int, ...] = ()
    # False (default): members forward under lax.map — one dispatch per
    # batch, bit-identical per member to the sequential restore+forward
    # path at the same batch shape (train_lib.make_serving_step).
    # True: vmapped stacked forward (make_ensemble_eval_step's body) —
    # float-equivalent, for member-shardable pod serving.
    member_parallel: bool = False
    # Fundus-normalization worker THREADS for the serving host stage
    # (serve/host.py; same resolution rule as data.decode_workers —
    # 0 = auto, one per host core up to 8).
    host_workers: int = 0
    # --- Admission control / load shedding (ISSUE 6) -------------------
    # Overload must degrade into FAST TYPED REJECTION, not unbounded
    # queue growth and p99 collapse. Both thresholds default 0 = off
    # (the bench overhead pin measures the disabled path at <= 2%);
    # when set, MicroBatcher.submit raises serve.Overloaded instead of
    # enqueueing, counted under serve.shed.queue_depth — and the same
    # thresholds are installed as alert rules over the same gauges
    # (obs/alerts.reliability_rules), so shedding and alerting can
    # never disagree about what "overloaded" means.
    # Max requests waiting in the batcher queue before submits shed.
    shed_queue_depth: int = 0
    # Max requests ADMITTED but not yet resolved (queued + in the
    # window being inferred) before submits shed.
    shed_in_flight: int = 0
    # Default per-request deadline applied at submit when the caller
    # passes none (ms; 0 = no deadline). A request whose deadline has
    # passed when its window closes is failed with
    # serve.DeadlineExceeded BEFORE any device work is spent on it,
    # counted under serve.shed.deadline.
    default_deadline_ms: float = 0.0
    # --- Cheap-path serving (ISSUE 10) ---------------------------------
    # Inference dtype of the stacked serving tree: "fp32" (restored
    # params verbatim — the bit-identity default every parity pin rides),
    # "bf16" (float params cast to bfloat16 at stacking: half the weight
    # HBM traffic, float-level score drift), or "int8" (rank>=2 kernels
    # quantized to symmetric per-output-channel int8 via AQT, dequantized
    # inside the one serving program so HBM holds int8 + scales). Non-
    # fp32 engines are REFUSED at construction (typed DtypeRejected)
    # when their golden-canary deviation exceeds dtype_canary_max_dev —
    # a quantized engine must prove operating-point parity before it can
    # take a request (serve/quantize.py; docs/PERF.md §Cheap-path).
    dtype: str = "fp32"
    # Max |score - pinned canary| a non-fp32 engine may show at its
    # construction gate (only binds when a pinned golden canary is
    # configured; fp32 keeps the byte-stability contract instead).
    dtype_canary_max_dev: float = 0.05
    # Distilled-cascade escalation half-width: requests first score
    # through the student engine, and only rows whose referable score
    # lands within this band of ANY cascade_thresholds entry re-score
    # through the full stacked ensemble (serve/cascade.py). 0 escalates
    # only exact threshold hits; the operating band is a measured
    # quality/cost dial — AUC at the operating points is gated before a
    # cascade goes live (CascadeEngine.go_live).
    cascade_band: float = 0.05
    # Operating thresholds the cascade escalates around (normally the
    # evaluate.py operating points the deployment screens at). Empty =
    # (0.5,), the neutral decision boundary.
    cascade_thresholds: tuple[float, ...] = ()
    # Student checkpoint dir (the train.distill_from product) that makes
    # predict.py serve through a CascadeEngine: student always scores,
    # the full --checkpoint_dir ensemble only sees escalated rows.
    # Empty keeps the plain ensemble engine.
    cascade_student_dir: str = ""
    # Persistent AOT compilation cache (serve/compilecache.py): per
    # (bucket, mesh shape, dtype, member count) serialized executables
    # under a model-fingerprinted directory, written atomically with the
    # rawshard-manifest discipline. A warm engine restart deserializes
    # instead of recompiling — seconds instead of the ~79 s BENCH_r01
    # cold start. A corrupt/missing entry degrades to a COUNTED
    # recompile (serve.compile_cache.misses), never a failed request; a
    # directory built for a different model fingerprint is refused with
    # a typed error naming the rebuild command. Empty disables.
    compile_cache_dir: str = ""
    # --- Lifecycle / rollback (ISSUE 8) --------------------------------
    # Seconds the engine RETAINS the previous generation's device-
    # resident stacked tree after a hot swap: within this window
    # ``engine.rollback()`` is one atomic handle re-swap (no restore
    # from disk, no warm-up — the state is still resident and warm).
    # 0 disables retention (rollback then needs the checkpoint dirs).
    # The retained tree costs one extra model residency in HBM, exactly
    # the transient ~2x a reload already needs; size the window to how
    # long a post-swap regression takes to show (the lifecycle WATCH
    # phase), not to "forever".
    rollback_keep_s: float = 900.0
    # --- Front-door router (ISSUE 12; serve/router.py) -----------------
    # Engine replicas the Router builds from its replica factory when
    # none are handed in explicitly (in-process replica handles; the
    # ReplicaHandle seam is where cross-host replicas plug in later).
    router_replicas: int = 1
    # Bin->replica dispatch policy: "least_in_flight" (default; fewest
    # rows queued+scoring wins) or "bucket_affinity" (prefer a replica
    # that already served this bucket shape — maximizes per-replica
    # compile-cache reuse, falls back to least-in-flight among the
    # warm set).
    router_policy: str = "least_in_flight"
    # Dispatch-tick cadence: how often queued rows are re-binned across
    # bucket boundaries (continuous batching). A full bucket of rows
    # dispatches at the next tick regardless of which requests
    # contributed them; only a partial remainder waits out max_wait_ms.
    router_tick_ms: float = 2.0
    # Class-aware admission control: total rows the router may hold
    # queued + in flight (the admitted-unresolved backlog) before
    # submits shed with typed Overloaded (0 = off). Interactive
    # requests shed at the full threshold; batch requests shed FIRST,
    # at router_batch_shed_frac of it.
    router_shed_rows: int = 0
    # Fraction of router_shed_rows at which the batch class sheds —
    # batch scoring yields queue headroom to interactive traffic
    # before interactive feels anything.
    router_batch_shed_frac: float = 0.5
    # Cascade-aware routing: size of the shared full-ensemble
    # EscalationPool behind student-only replicas (predict.py builds
    # this wiring when cascade_student_dir is set and --replicas > 1;
    # most replicas then pay ~1/k FLOPs).
    router_escalation_replicas: int = 1
    # Versioned serving-policy artifact (serve/policy.py) derived from
    # a measured serve_frontier sweep by scripts/derive_serve_policy.py:
    # when set, bucket sizes / max_batch / max_wait_ms / shed
    # thresholds still at their dataclass defaults are filled from the
    # artifact (hand-set knobs always win); a stale model/mesh
    # fingerprint is refused with typed PolicyStale. Empty = off.
    policy_from: str = ""
    # --- Replica autoscaling (serve/scaler.py) -------------------------
    # Bounds the scaler's desired-replica signal moves within; the
    # router acts on the signal in-process only when it owns a replica
    # factory (otherwise the gauge is the product — external
    # autoscalers read serve.scaler.desired_replicas).
    scaler_min_replicas: int = 1
    scaler_max_replicas: int = 8
    # Tumbling-window seconds one scaling decision observes.
    scaler_window_s: float = 10.0
    # p99 request-latency SLO (ms) the scaler treats as a hot signal;
    # 0 disables the latency input.
    scaler_slo_p99_ms: float = 0.0
    # --- Interactive latency frontier (ISSUE 16) -----------------------
    # All three default OFF: the machinery costs one branch each on the
    # untouched paths (pinned <= 2% by bench.py's interactive overhead
    # guard) and a policy-v2 artifact is the intended way to opt in
    # (serve.policy_from; hand-setting them also works).
    # Pallas-fused serve-side preprocess (ops/pallas_serve.py, wired
    # through serve/host.py prepare_images): normalize + per-image
    # channel statistics + channels-first layout in ONE pass over the
    # uint8 batch, so the quality monitor's input statistics stop
    # paying a separate full host-numpy pass per batch. The jnp path
    # (fused off) is the bit-reference the kernel is pinned against.
    fused_preprocess: bool = False
    # Speculative escalation (serve/cascade.py): dispatch the student
    # AND the full ensemble concurrently instead of serially, so a
    # band-adjacent row pays max(student, ensemble) latency instead of
    # student + ensemble. Results are bit-equal to the serial cascade
    # (the ensemble scores the same rows at the same bucket shape);
    # discarded speculative work is a counted ledger
    # (serve.cascade.speculated / serve.cascade.speculated.wasted).
    cascade_speculative: bool = False
    # Cross-request/cross-engine batch fusion in the Router dispatch
    # tick (serve/fusion.py): rows destined for DIFFERENT models with
    # agreeing shapes may share one dispatch bin — one stacked forward
    # over the concatenated member trees when the engines' compiled
    # shapes agree (grouped per-model calls otherwise), results demuxed
    # by offset with per-(model, replica, generation) attribution. Off:
    # bins never mix models.
    router_fusion: bool = False


@dataclasses.dataclass(frozen=True)
class QualityConfig:
    """Model/data-quality observability (obs/quality.py, obs/alerts.py;
    ISSUE 5) — the layer that watches the quantities the paper actually
    reports (score distribution, input statistics, operating-point
    behavior) instead of infra health.

    Off by default: unlike the registry/tracer (whose cost is a branch),
    the monitor needs a reference profile artifact to compare against
    (``profile_path``, written by ``evaluate.py --profile_out`` or the
    trainer's ``profile_out``). When disabled the serve hot path pays
    exactly one branch per request (pinned by bench.py's
    quality_overhead_pct guard when enabled: <= 2% of device_only).
    """

    enabled: bool = False
    # Reference-profile artifact to drift-check against (JSON written by
    # evaluate.py --profile_out / trainer profile_out). Empty + enabled
    # = positive-rate/canary monitoring only, no PSI.
    profile_path: str = ""
    # Trainer end-of-fit: write the run's own reference profile (val
    # split score/input histograms + operating thresholds) here. The
    # canonical profile for a SERVED checkpoint is evaluate.py
    # --profile_out on that checkpoint; this knob captures the final
    # train state without a separate eval invocation.
    profile_out: str = ""
    # Scores per drift window: PSI is computed and the quality.* gauges
    # republished every time this many live scores accumulate (tumbling
    # windows — O(1) bin increments per request, window math at the
    # boundary only).
    window_scores: int = 256
    # Histogram resolution over [0, 1] for scores AND input statistics.
    # Must match the loaded profile's bins (load is checked).
    score_bins: int = 20
    # Default alert thresholds for the built-in drift rules
    # (obs/alerts.py quality_rules): PSI > 0.2 is the standard
    # "significant population shift" convention; input statistics get a
    # slightly looser default (brightness/contrast jitter across clinics
    # is expected at small PSI).
    psi_alert: float = 0.2
    input_psi_alert: float = 0.25
    # Seconds a rule's condition must hold CONTINUOUSLY before it fires
    # (the `for:` of the rule grammar); 0 fires on first breach.
    alert_for_s: float = 0.0
    # Extra declarative rules (obs/alerts.py syntax), e.g.
    #   "serve.request_latency_s.p99 > 0.5 for 60 -> slo_breach"
    #   "rate(serve.input_rejected) > 2 for 120"
    alert_rules: tuple[str, ...] = ()
    # Golden-set canary: an .npz (images [n,S,S,3] uint8, optional
    # pinned scores) scored through the live engine on a cadence,
    # asserting byte-stable output per (checkpoint, bucket) — catches
    # silent numerical/preprocessing regressions distribution tests
    # can't see. Empty disables.
    canary_path: str = ""
    # Seconds between canary runs on the live engine (<= 0: only
    # explicit run_canary() calls).
    canary_every_s: float = 300.0
    # 0.0 = byte-stable comparison (the default contract); > 0 allows
    # that absolute deviation (e.g. across a serving-stack migration
    # where float-ulp drift is accepted).
    canary_atol: float = 0.0


@dataclasses.dataclass(frozen=True)
class LifecycleConfig:
    """Self-healing model lifecycle (jama16_retina_tpu/lifecycle/;
    ISSUE 8) — the drift-to-retrain flywheel that turns PR-5 alerts
    into actions: DRIFT_DETECTED -> RETRAIN (warm-start fine-tune) ->
    GATE (named candidate gates) -> STAGED_ROLLOUT (shadow + promote)
    -> WATCH (post-swap regression window) -> COMMIT or ROLLBACK.

    Off by default: the controller only runs where an operator wires it
    (``scripts/lifecycle_run.py`` or an ``AlertManager(on_fire=)``
    trigger); these knobs shape what it does when it runs. Every
    transition is journaled crash-safely under
    ``<workdir>/lifecycle/`` (lifecycle/journal.py).
    """

    enabled: bool = False
    # Alert-rule reasons that trigger a lifecycle cycle through the
    # AlertManager(on_fire=) seam; reasons outside this set only log.
    trigger_reasons: tuple[str, ...] = ("quality_drift",)
    # Fine-tune budget for a RETRAIN candidate (0 = the full
    # train.steps — usually far too much for a warm start).
    retrain_steps: int = 0
    # GATE thresholds. gate_canary_max_dev: max |candidate - live|
    # score deviation on the golden canary images — a retrained model
    # legitimately moves scores, so this is a LOOSE sanity bound
    # against degenerate candidates (random-init divergence, a
    # collapsed head), not the byte-stability atol the reload gate
    # applies to same-model rollouts.
    gate_canary_max_dev: float = 0.2
    # Reference-profile parity: max debiased PSI of the candidate's
    # val-split score histogram vs the loaded reference profile
    # (-1 = reuse obs.quality.psi_alert).
    gate_parity_psi_max: float = -1.0
    # Operating-point AUC floor: candidate val AUC must be >= the live
    # model's val AUC minus this delta.
    gate_auc_floor_delta: float = 0.01
    # Rows of the val split the parity/AUC gates score (0 = all; tests
    # and smoke deployments cap it).
    gate_eval_rows: int = 0
    # STAGED_ROLLOUT: fraction of live requests shadow-scored through
    # the candidate (deterministic every-Nth sampling), how many
    # shadowed requests to collect before promoting, and the wall-clock
    # budget to wait for them (shadow evidence is advisory — recorded
    # in the journal, never a silent veto; an idle server promotes on
    # timeout with whatever evidence exists, loudly).
    shadow_fraction: float = 0.25
    shadow_requests: int = 8
    shadow_wait_s: float = 60.0
    # WATCH: post-swap regression window. Each probe evaluates these
    # declarative rules (obs/alerts.py grammar; plain metric/threshold
    # forms only — rate() needs snapshot history the stateless probe
    # does not keep, and is rejected at controller construction)
    # against the live registry; ANY rule true = regression ->
    # ROLLBACK. The default watches the golden canary, which the
    # promote step re-pins to the candidate — so a post-swap canary
    # failure is a genuine serving regression, not the model change.
    watch_rules: tuple[str, ...] = ("quality.canary_ok < 1",)
    watch_probes: int = 3
    watch_interval_s: float = 30.0


@dataclasses.dataclass(frozen=True)
class AuditConfig:
    """Prediction provenance & audit plane (ISSUE 20; obs/audit.py).

    A sealed per-request ledger: every served row's trace id, input
    digest, scores, per-threshold decisions, and full model lineage,
    spooled through a bounded queue to a writer thread (serving never
    blocks; overflow is counted ``audit.dropped``) and sealed into
    ``seg-NNNNNN.json`` segments via the integrity/artifact seam.
    ``scripts/audit_query.py`` answers lineage queries and replays a
    recorded request bit-for-bit. Nested subsystem — override with
    ``obs.audit.<field>=value``."""

    # Master switch. Off (default) = no ledger is built; the serve hot
    # path pays one attribute read + branch per request (pinned by
    # bench.py's audit_overhead_pct guard when on).
    enabled: bool = False
    # Segment directory. Empty = "<obs workdir>/audit" at the wiring
    # sites (predict.py --obs_workdir, engine.start_telemetry); with no
    # workdir either, the ledger is skipped with a loud log line.
    dir: str = ""
    # Fraction of served requests recorded (deterministic every-Nth,
    # like the staged-rollout shadow sampler): 1.0 audits everything,
    # 0.1 every 10th request. <= 0 records nothing.
    sample: float = 1.0
    # Records per sealed segment: the writer seals (atomic sealed-JSON
    # publish, fault site ``audit.seal``) every N records and at
    # close(). Kill -9 loses at most the unsealed tail.
    seal_every: int = 64
    # Also spool the post-preprocess input tensors (consented capture;
    # the rawshard-writer discipline: sealed .npy + sha256) so
    # ``audit_query replay`` can re-score the exact served bytes — and
    # ROADMAP item 4's continual-learning capture has its substrate.
    # Off records digests only; replay then needs the original inputs.
    capture: bool = False
    # Newest SEALED segments retention GC keeps per audit dir
    # (integrity/retention.py; the newest segment always survives).
    # <= 0 = keep everything.
    retention: int = 256
    # Bounded spool depth (requests queued to the writer thread). A
    # full queue DROPS the record — counted, never blocking serving.
    queue_max: int = 1024


@dataclasses.dataclass(frozen=True)
class ObsConfig:
    """Runtime-telemetry config (jama16_retina_tpu/obs/; ISSUE 3).

    The telemetry registry's hot-path cost is pinned by bench.py's
    overhead guard (telemetry-on within 2% of off on device_only), so
    ``enabled`` defaults on; off turns every metric op into one branch
    (obs/registry.py) and skips the periodic exporter entirely.
    """

    enabled: bool = True
    # Seconds between telemetry snapshots (the JSONL `telemetry` record,
    # the atomic <workdir>/telemetry.prom rewrite, and the per-process
    # `heartbeat` record). Checked from the train loop's logging cadence
    # — a flush never lands mid-step.
    flush_every_s: float = 60.0
    # Event tracing (obs/trace.py; ISSUE 4): bounded per-thread ring
    # buffers of begin/end/instant events — the flight recorder's
    # black-box source and the Perfetto-loadable timeline behind
    # `obs_report --trace-out`. On by default (a black box is only
    # useful if it was recording): memory is bounded at
    # trace_buffer_events per recording thread and the hot-path cost is
    # pinned by bench.py's tracing_overhead_pct guard (same ≤2% budget
    # as the telemetry pin). obs.enabled=false disables tracing too.
    trace_enabled: bool = True
    # Ring capacity per recording thread (events are overwritten oldest-
    # first, never accumulated).
    trace_buffer_events: int = 4096
    # Slow-step anomaly trigger (obs/flightrec.py): a loop iteration
    # above this factor × the rolling median of recent steps dumps a
    # blackbox and requests the once-per-run profiler capture.
    # <= 0 disables the trigger.
    slow_step_factor: float = 4.0
    # How many of the newest trace events a blackbox dump carries.
    blackbox_events: int = 1024
    # Cross-run blackbox dump cap (ISSUE 13 satellite): after every
    # dump, the flight recorder deletes the OLDEST dump directories
    # under <workdir>/blackbox beyond this many (by mtime — per-run
    # sequence numbers restart, mtime orders across runs), counted as
    # obs.blackbox_pruned. One-per-reason-per-run limits a single run;
    # this bounds the workdir across a long-lived supervisor's many
    # runs. <= 0 disables the cap. integrity/retention.py applies the
    # same cap offline.
    blackbox_keep: int = 20
    # --- Fleet observability plane (ISSUE 15; obs/fleet.py) -----------
    # Shared directory the process publishes sealed telemetry segments
    # into (one <role>-p<pid>/ stream per process: snapshot + heartbeat
    # + trace rings). Point every process of a deployment — trainers,
    # predict servers, the lifecycle --watch supervisor — at ONE fleet
    # dir; `obs_report --fleet` then answers fleet-level questions
    # (merged counters/histograms, per-process gauges, who wedged) no
    # single process can. Empty (default) = off: the Snapshotter pays
    # exactly one branch per flush (bench fleet_overhead_pct pin).
    fleet_dir: str = ""
    # Role tag of this process's segment stream (trainer / server /
    # router / lifecycle ...). Empty = the wiring site's default
    # (train loops publish "trainer", serving sessions "server",
    # predict --replicas "router", lifecycle --watch "lifecycle").
    fleet_role: str = ""
    # Newest segments each process keeps in its stream (pruned at
    # publish time; integrity/retention.py additionally enforces
    # integrity.telemetry_max_bytes per stream offline). The stream's
    # depth bounds how much history fleet burn-rate windows can see.
    fleet_keep_segments: int = 64
    # Fleet-scope alert rules the AGGREGATOR evaluates over MERGED
    # snapshots (obs/alerts.parse_fleet_rule grammar): the plain rule
    # grammar over fleet sums/merges, plus the multi-window burn-rate
    # form `burn(bad_counter/total_counter, LONG, SHORT) OP threshold
    # [-> reason]` — rules a single process can never fire. Evaluated
    # by `obs_report --fleet/--check-fleet`, never by the in-process
    # AlertManager.
    fleet_rules: tuple[str, ...] = ()
    # Opt-in stdlib HTTP telemetry endpoint (obs/httpd.py): /metrics
    # serves live Prometheus text, /healthz heartbeat freshness (same
    # 0/1/2 semantics as --check-heartbeats; HTTP 200/503). 0 =
    # disabled (default) — tests bind ephemeral ports through
    # Snapshotter.serve_http(0) directly.
    http_port: int = 0
    # Model/data-quality monitoring (ISSUE 5): online drift detection
    # against a reference profile, golden-set canary, and SLO/alert
    # rules. Nested because it is a subsystem, not a knob — override
    # with obs.quality.<field>=value.
    quality: QualityConfig = dataclasses.field(default_factory=QualityConfig)
    # Prediction provenance & audit plane (ISSUE 20; obs/audit.py):
    # sealed per-request ledger + lineage queries + deterministic
    # replay. Nested subsystem — override with obs.audit.<field>=value.
    audit: AuditConfig = dataclasses.field(default_factory=AuditConfig)
    # --- Reliability (ISSUE 6) -----------------------------------------
    # Deterministic fault-injection plan (obs/faultinject.py): a JSON
    # spec string or a path to one, armed at run/engine start. The
    # JAMA16_FAULTS env var overrides. Empty (the production value) =
    # nothing armed; every fault seam then costs one branch (pinned by
    # the bench robustness guard).
    fault_plan: str = ""
    # Sustained data-plane quarantine rate (records/s over a telemetry
    # flush interval) above which the data_quarantine alert rule fires
    # — one poison record is routine; a STREAM of them is systemic rot
    # (a bad shard, a broken preprocessing deploy). <= 0 disables the
    # rule.
    quarantine_alert_per_s: float = 0.5
    # --- Causal diagnosis (ISSUE 18; obs/criticalpath.py) --------------
    # Run the critical-path analyzer inside every FlightRecorder dump:
    # the blackbox then carries diagnosis.json (typed verdict + evidence
    # fractions + exemplar waterfalls over the dumped trace events) and
    # the obs.diagnosis.{verdict,confidence} gauges update so alert
    # rules can read the verdict. Off = dumps carry raw events only;
    # the analyzer is pure and runs ONLY at dump time, so the hot path
    # never pays for it either way (bench diagnosis_overhead_pct pin).
    diagnosis_enabled: bool = True
    # Slowest exemplar waterfalls a diagnosis carries (per-request and
    # per-step each) — enough to see the pattern, small enough to read.
    diagnosis_top_k: int = 3
    # --- Device-utilization plane (ISSUE 19; obs/device.py) ------------
    # Sample device.memory_stats() + program-ledger MFU/roofline gauges
    # + the compile ledger on every telemetry flush (the DeviceMonitor
    # attached to the Snapshotter). Off = the Snapshotter pays exactly
    # one branch per flush (bench devicemon_overhead_pct pin); compile
    # sites still record into the process compile ledger either way.
    device_enabled: bool = True
    # HBM headroom fraction below which the hbm_pressure reliability
    # rule fires after 60 sustained seconds (obs/alerts.py reads
    # device.hbm.headroom_frac). <= 0 disables the rule.
    device_hbm_headroom_alert: float = 0.1


@dataclasses.dataclass(frozen=True)
class IntegrityConfig:
    """Durable-state integrity (jama16_retina_tpu/integrity/; ISSUE 13):
    retention-GC policy knobs for ``integrity/retention.py`` (driven by
    ``scripts/graftfsck.py --gc``, dry-run first) plus the fsck/repair
    machinery's defaults. Sealing itself has no knobs — every durable
    writer seals unconditionally; these bound what the workdir is
    allowed to ACCUMULATE."""

    # Total bytes of compile-cache ENTRY files (exec_*.jex + seal
    # sidecars; the manifest is never collected) one cache directory
    # may hold before the GC evicts least-recently-used entries. An
    # evicted entry recompiles + re-saves on the next warm-up — cost,
    # not correctness. <= 0 disables the cap.
    cache_max_bytes: int = 4 << 30
    # Size (bytes) above which a run's metrics JSONL (and its .p{N}
    # mirrors) is rotated to <name>.1 by the GC, with older rotations
    # deleted. OFFLINE-only (never while a run appends — graftfsck is
    # an operator tool); a rotated JSONL trims resume's best-tracking
    # replay to the new file, so rotate between runs. <= 0 disables.
    telemetry_max_bytes: int = 64 << 20
    # Retired lifecycle candidate checkpoint sets (and canary-pre
    # backups) kept beyond the ones still reachable: the newest N
    # CLOSED cycles' candidate roots survive, older ones are
    # collectible. Anything named by live.json or an OPEN cycle is
    # pinned unconditionally (never collected — tested).
    keep_candidate_cycles: int = 2


@dataclasses.dataclass(frozen=True)
class IngestConfig:
    """Disaggregated ingest service (ISSUE 17; jama16_retina_tpu/
    ingest/). One server process owns the decode plane — the existing
    rawshard/tiered/autotune stack — and streams ready batches to N
    local consumer processes over shared-memory rings, so decode is
    paid ONCE per deployment instead of once per trainer/eval/bench
    process. Consumers opt in with ``data.loader=served``."""

    # Unix control socket the ingest server listens on and every
    # data.loader=served consumer attaches through. Empty = the served
    # loader refuses loudly (there is no sane default rendezvous).
    socket_path: str = ""
    # Shared-memory ring slots per consumer: how many ready batches the
    # server may hold decoded + published ahead of the consumer's
    # credits. Pure run-ahead (content-invariant), like stage_depth.
    ring_slots: int = 4
    # Directory of per-consumer sealed lease journals (resume-without-
    # re-decode; integrity/artifact seam). Empty = "<socket dir>/leases".
    lease_dir: str = ""
    # Flush a consumer's lease journal every N credited batches (plus
    # always at detach). The durable position after kill -9 of the
    # SERVER lags at most this many batches; a killed CONSUMER loses
    # nothing while the server lives (its in-memory lease is exact).
    lease_flush_every: int = 8
    # Seconds a consumer waits for the server's ATTACHED reply (and for
    # each subsequent batch) before failing loudly.
    attach_timeout_s: float = 30.0
    # Stable consumer identity for lease resume. Empty = derived as
    # "pid<os.getpid()>" — unique but NOT resumable across restarts;
    # set it (e.g. per workdir) to make kill -9 reattach resume from
    # the lease journal instead of step 0.
    consumer_id: str = ""
    # Batch provenance stamping (ISSUE 18): the server writes a compact
    # record (seq, decode wall vs cache hit, credit wait, wire trace
    # context) into each slot's fixed provenance region before
    # announcing it, and served consumers tile their measured input
    # wait into ingest.batch.* trace segments from it. The slot region
    # exists either way (protocol v2 layout); off clears the stamp and
    # consumers fall back to unattributed waits. Cost is one small
    # memcpy per batch, pinned ≤2% by the bench diagnosis guard.
    provenance: bool = True


@dataclasses.dataclass(frozen=True)
class ExperimentConfig:
    name: str = "eyepacs_binary"
    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    train: TrainConfig = dataclasses.field(default_factory=TrainConfig)
    parallel: ParallelConfig = dataclasses.field(default_factory=ParallelConfig)
    eval: EvalConfig = dataclasses.field(default_factory=EvalConfig)
    serve: ServeConfig = dataclasses.field(default_factory=ServeConfig)
    obs: ObsConfig = dataclasses.field(default_factory=ObsConfig)
    lifecycle: LifecycleConfig = dataclasses.field(
        default_factory=LifecycleConfig
    )
    integrity: IntegrityConfig = dataclasses.field(
        default_factory=IntegrityConfig
    )
    ingest: IngestConfig = dataclasses.field(default_factory=IngestConfig)

    def replace(self, **sections) -> "ExperimentConfig":
        return dataclasses.replace(self, **sections)


def _preset_eyepacs_binary() -> ExperimentConfig:
    # use_pallas: under bench.py's fenced harness (round 3) the fused
    # color-jitter kernel runs ~1.4x the jnp composition standalone
    # (augment_pallas/augment_jnp) and is worth ~+2% on the full train
    # step, since XLA already fuses most of the jnp stage into the step
    # (docs/PERF.md "Changes that did land"). It is the production path
    # on TPU and transparently interprets on CPU (data/augment.py).
    return ExperimentConfig(
        name="eyepacs_binary", data=DataConfig(use_pallas=True)
    )


def _preset_eyepacs_binary_quality() -> ExperimentConfig:
    """eyepacs_binary plus every quality lever this framework adds over
    the reference, aimed at the >=0.97 AUC target the replication missed
    (SURVEY.md §6 note): EMA weight shadow, warmup-cosine schedule,
    label smoothing, flip-TTA at eval. Combine with the ensemble driver
    (train.ensemble_size) and preprocess --ben_graham for the full
    recipe; operating thresholds should then be transferred from val
    (evaluate.py --threshold_split=val)."""
    base = _preset_eyepacs_binary()
    return base.replace(
        name="eyepacs_binary_quality",
        train=dataclasses.replace(
            base.train,
            lr_schedule="warmup_cosine",
            ema_decay=0.999,
            label_smoothing=0.1,
        ),
        eval=dataclasses.replace(base.eval, tta=True),
    )


def _preset_messidor2_eval() -> ExperimentConfig:
    return ExperimentConfig(
        name="messidor2_eval",
        eval=EvalConfig(operating_specificities=(0.87, 0.98)),
    )


def _preset_icdr5() -> ExperimentConfig:
    return ExperimentConfig(
        name="icdr5",
        model=ModelConfig(head="multi"),
        train=TrainConfig(label_smoothing=0.1),
    )


def _preset_ensemble10() -> ExperimentConfig:
    return ExperimentConfig(name="ensemble10", train=TrainConfig(ensemble_size=10))


def _preset_resnet50() -> ExperimentConfig:
    return ExperimentConfig(name="resnet50", model=ModelConfig(arch="resnet50"))


def _preset_efficientnet_b4() -> ExperimentConfig:
    return ExperimentConfig(
        name="efficientnet_b4",
        # B4 compound scaling specifies dropout 0.4 (vs the generic 0.2).
        model=ModelConfig(arch="efficientnet_b4", dropout_rate=0.4),
    )


def _preset_smoke() -> ExperimentConfig:
    """Tiny config for tests/CI: small model, few steps."""
    return ExperimentConfig(
        name="smoke",
        model=ModelConfig(arch="tiny_cnn", image_size=64, aux_head=False),
        data=DataConfig(batch_size=8, shuffle_buffer=64),
        train=TrainConfig(
            steps=50, eval_every=25, log_every=10, learning_rate=3e-3,
            warmup_steps=5, early_stop_patience=100,
        ),
        eval=EvalConfig(batch_size=8),
    )


PRESETS = {
    "eyepacs_binary": _preset_eyepacs_binary,
    "eyepacs_binary_quality": _preset_eyepacs_binary_quality,
    "messidor2_eval": _preset_messidor2_eval,
    "icdr5": _preset_icdr5,
    "ensemble10": _preset_ensemble10,
    "resnet50": _preset_resnet50,
    "efficientnet_b4": _preset_efficientnet_b4,
    "smoke": _preset_smoke,
}


def get_config(name: str) -> ExperimentConfig:
    if name not in PRESETS:
        raise ValueError(
            f"unknown config preset {name!r}; available: {sorted(PRESETS)}"
        )
    return PRESETS[name]()


def _unknown_field(parent, attr: str, item: str) -> ValueError:
    """The loud unknown-key error with a did-you-mean hint: a typo'd
    override silently not applying (or half-applying) is exactly the
    failure mode nested configs like obs.quality.* invite."""
    import difflib

    if not dataclasses.is_dataclass(parent):
        # An over-deep path (train.steps.x=1) walked past a leaf value;
        # there are no fields to suggest from, but the error must still
        # be the clean ValueError the CLI reports, not a TypeError.
        return ValueError(
            f"override {item!r} descends into {attr!r}, but the path "
            f"already reached a {type(parent).__name__} value — remove "
            "the extra segment"
        )
    names = [f.name for f in dataclasses.fields(parent)]
    close = difflib.get_close_matches(attr, names, n=1)
    hint = f"; did you mean {close[0]!r}?" if close else ""
    return ValueError(
        f"unknown config field {attr!r} in override {item!r}{hint} "
        f"(valid {type(parent).__name__} fields: {', '.join(sorted(names))})"
    )


def override(cfg: ExperimentConfig, dotted: Sequence[str]) -> ExperimentConfig:
    """Apply ``section.field=value`` overrides (CLI --set flags).

    Paths may nest through sub-configs (``obs.quality.enabled=true``);
    every hop is validated against the dataclass it lands on, and an
    unknown key raises with a did-you-mean listing the valid fields of
    the config it missed on (the silent-typo failure mode of nested new
    configs).
    """
    for item in dotted:
        key, eq, raw = item.partition("=")
        parts = key.split(".")
        if not eq or len(parts) < 2 or not all(parts):
            raise ValueError(
                f"malformed override {item!r}; expected section.field=value "
                "(e.g. train.steps=100 or obs.quality.enabled=true)"
            )
        # Walk to the leaf's parent, validating each hop. Validation is
        # against the dataclass FIELDS, not hasattr: a property (e.g.
        # ModelConfig.num_classes) is readable but not replaceable, and
        # must get the clean did-you-mean error, not a TypeError out of
        # dataclasses.replace.
        def _is_field(obj, name: str) -> bool:
            return dataclasses.is_dataclass(obj) and any(
                f.name == name for f in dataclasses.fields(obj)
            )

        chain = [cfg]
        for p in parts[:-1]:
            parent = chain[-1]
            if not _is_field(parent, p):
                raise _unknown_field(parent, p, item)
            nxt = getattr(parent, p)
            chain.append(nxt)
        section = chain[-1]
        field = parts[-1]
        if not _is_field(section, field):
            raise _unknown_field(section, field, item)
        current = getattr(section, field)
        if dataclasses.is_dataclass(current):
            raise ValueError(
                f"override {item!r} targets the config section "
                f"{type(current).__name__}; set its fields individually "
                f"(e.g. {key}.{dataclasses.fields(current)[0].name}=...)"
            )
        try:
            if isinstance(current, bool):
                value: object = raw.lower() in ("1", "true", "yes")
            elif isinstance(current, int):
                value = int(raw)
            elif isinstance(current, float):
                value = float(raw)
            elif isinstance(current, tuple):
                elems_raw = [p for p in raw.split(",") if p]
                if current:
                    elem = type(current[0])
                else:
                    # Empty-default tuples carry no runtime element
                    # type; read it off the dataclass annotation so
                    # `serve.bucket_sizes=8,16` parses ints while
                    # `eval.ensemble_dirs=20260801` (a date-named run
                    # dir) STAYS a string path.
                    ann = str(next(
                        f.type for f in dataclasses.fields(section)
                        if f.name == field
                    ))
                    elem = (
                        int if "int" in ann
                        else float if "float" in ann else str
                    )
                value = tuple(elem(p) for p in elems_raw)
            else:
                value = raw
        except ValueError:
            raise ValueError(
                f"bad value in override {item!r}: cannot parse {raw!r} as "
                f"{type(current).__name__}"
            )
        # Rebuild the frozen chain from the leaf outward.
        obj: object = dataclasses.replace(section, **{field: value})
        for parent, name in zip(reversed(chain[:-1]), reversed(parts[:-1])):
            obj = dataclasses.replace(parent, **{name: obj})
        cfg = obj
    return cfg
