"""Crash-safe lifecycle journal: the controller's only durable state.

The state machine in lifecycle/controller.py performs one idempotent
step per transition and appends the arrival record HERE; the whole
file is rewritten atomically (tmp + fsync + os.replace — the same
discipline as rawshard manifests) on every append, so a reader (or a
controller resuming after kill -9) sees either the journal before the
transition or after it, never a torn file. A ``.tmp`` leftover from a
mid-write kill is ignored and overwritten by the next append.

Entries are append-only dicts:

    {"seq": N, "cycle": C, "state": "<STATE>", "t": <unix>, ...payload}

``state`` names the state the controller has ARRIVED at, with that
state's work complete — e.g. a ``RETRAIN`` entry means the candidate
checkpoints it lists are durable on disk. One journal spans many
cycles (one cycle per drift trigger); ``cycle_entries()`` returns the
entries of the newest cycle, which is all a resuming controller needs.

Alongside the journal lives the LIVE POINTER (``live.json``, same
atomic write): the checkpoint set the serving engine should currently
be built from. The promote and rollback steps update it BEFORE
journaling their transition, so re-applying a half-done swap after a
crash is an idempotent pointer read + reload, not a guess.
"""

from __future__ import annotations

import json
import os
import time

from jama16_retina_tpu.integrity import artifact as artifact_lib


FORMAT = "jama16.lifecycle"
VERSION = 1


class Journal:
    """The append-only, atomically rewritten transition journal.

    Construct over a directory (created on first append); an existing
    journal file loads immediately — version-checked, and a torn or
    unparseable file refuses loudly (a lifecycle controller must never
    silently restart a half-done rollout from scratch).
    """

    def __init__(self, journal_dir: str, terminal_states=("COMMIT",
                                                          "ROLLBACK"),
                 now_fn=time.time):
        # The wall clock is INJECTED (defaulting to time.time): entry
        # timestamps are the journal's only nondeterministic input, so
        # threading the clock through keeps the module's declared
        # determinism checkable (graftlint purity rule) and lets tests
        # pin byte-identical journals.
        self._now = now_fn
        self.dir = journal_dir
        self.path = os.path.join(journal_dir, "journal.json")
        self.live_path = os.path.join(journal_dir, "live.json")
        self._terminal = tuple(terminal_states)
        self.entries: list[dict] = []
        if os.path.exists(self.path):
            try:
                with open(self.path) as f:
                    doc = json.load(f)
            except (OSError, json.JSONDecodeError) as e:
                raise ValueError(
                    f"lifecycle journal {self.path} is unreadable "
                    f"({type(e).__name__}: {e}); refusing to guess at "
                    "rollout state — inspect or move it aside"
                ) from e
            if doc.get("format") != FORMAT or doc.get("version") != VERSION:
                raise ValueError(
                    f"lifecycle journal {self.path} has format "
                    f"{doc.get('format')!r} v{doc.get('version')!r}; this "
                    f"code reads {FORMAT} v{VERSION}"
                )
            # Seal check AFTER the format/version refusals above (a
            # hand-bumped version must keep its own error): a journal
            # whose sealed digest disagrees with its content raises
            # typed ArtifactCorrupt — a controller must never resume a
            # rollout from silently-damaged state (ISSUE 13).
            artifact_lib.verify_payload(doc, self.path,
                                        artifact="journal")
            self.entries = list(doc.get("entries", ()))

    # -- reads -------------------------------------------------------------

    def refresh(self) -> None:
        """Re-read entries from disk — the supervising ``--watch``
        process picks up a ``--trigger`` appended by another invocation
        this way. Writers never interleave by protocol (trigger appends
        only to a CLOSED cycle, the supervisor only to an open one)."""
        if os.path.exists(self.path):
            with open(self.path) as f:
                self.entries = list(json.load(f).get("entries", ()))

    @property
    def state(self) -> "str | None":
        """State of the newest entry (None = journal empty/idle)."""
        return self.entries[-1]["state"] if self.entries else None

    @property
    def cycle(self) -> int:
        """Newest cycle id (-1 before the first trigger)."""
        return self.entries[-1]["cycle"] if self.entries else -1

    def cycle_entries(self, cycle: "int | None" = None) -> list[dict]:
        """Entries of ``cycle`` (default: the newest one) — everything
        a resuming controller needs to pick up where the dead one
        stopped."""
        c = self.cycle if cycle is None else cycle
        return [e for e in self.entries if e["cycle"] == c]

    def cycle_open(self) -> bool:
        """True while the newest cycle has not reached a terminal
        state — exactly when trigger() must refuse to start another."""
        return bool(self.entries) and self.state not in self._terminal

    def find(self, state: str, cycle: "int | None" = None) -> "dict | None":
        """The newest entry for ``state`` within one cycle (the
        idempotency lookup: 'did this step already complete?')."""
        for e in reversed(self.cycle_entries(cycle)):
            if e["state"] == state:
                return e
        return None

    # -- writes ------------------------------------------------------------

    def append(self, state: str, cycle: "int | None" = None,
               **payload) -> dict:
        """One completed transition, durably. Returns the entry."""
        entry = {
            "seq": len(self.entries),
            "cycle": self.cycle + 1 if cycle is None else cycle,
            "state": state,
            "t": round(self._now(), 3),
            **payload,
        }
        self.entries.append(entry)
        os.makedirs(self.dir, exist_ok=True)
        artifact_lib.write_sealed_json(self.path, {
            "format": FORMAT, "version": VERSION, "entries": self.entries,
        }, schema="lifecycle.journal", version=VERSION)
        return entry

    # -- the live pointer --------------------------------------------------

    def read_live(self) -> "list[str] | None":
        """The blessed serving checkpoint set (None = never written:
        serve whatever the deployment config names). Seal-verified: a
        corrupt pointer raises ArtifactCorrupt instead of rebuilding
        the engine from garbage member paths."""
        if not os.path.exists(self.live_path):
            return None
        doc, _seal = artifact_lib.read_sealed_json(
            self.live_path, artifact="live"
        )
        return list(doc["member_dirs"])

    def write_live(self, member_dirs) -> None:
        os.makedirs(self.dir, exist_ok=True)
        artifact_lib.write_sealed_json(self.live_path, {
            "format": FORMAT, "version": VERSION,
            "member_dirs": list(member_dirs),
            "t": round(self._now(), 3),
        }, schema="lifecycle.live", version=VERSION)
