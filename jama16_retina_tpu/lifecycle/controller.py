"""The drift-to-retrain state machine (ISSUE 8 tentpole).

One controller drives one serving deployment through the closed loop
the ROADMAP's item 5 asks for:

    IDLE --trigger(alert)--> DRIFT_DETECTED
      --retrain--------> RETRAIN          (warm-start fine-tune, durable
                                           candidate checkpoints)
      --gates----------> GATE             (named verdicts: golden canary /
                                           profile parity / AUC floor;
                                           fail -> ROLLBACK)
      --shadow+promote-> STAGED_ROLLOUT   (ServingEngine.begin_shadow over
                                           live traffic, canary re-pin,
                                           engine.reload swap, live pointer)
      --regress-window-> WATCH            (declarative rules over the PR-5
                                           quality gauges)
      --------> COMMIT  or  ROLLBACK      (engine.rollback() re-swap to the
                                           retained previous generation)

Crash safety: every arrival is one atomic append to the on-disk
journal (lifecycle/journal.py); each step is IDEMPOTENT (retrain skips
members whose candidate checkpoints are durable, gates are pure
evaluation, promote re-applies the live pointer, rollback re-swaps),
so a controller killed at ANY state — including between a step's work
and its journal append — resumes by re-running at most the one
interrupted step and converges to the same terminal state. Proven by
killing it at every state in tests/test_lifecycle.py.

Seams: ``retrain_fn`` / ``gate_fns`` / ``watch rules`` are injectable
(tests and ``bench.py --chaos`` drive the machine off-device in
milliseconds); the defaults are the real thing — trainer.fit with
``train.init_from`` warm start, engine-scored gates over the val
split, registry-gauge watch probes. Fault seams ``lifecycle.retrain``
/ ``lifecycle.gate`` / ``lifecycle.swap`` (obs/faultinject.py) inject
failure at each phase; a gate that CANNOT run fails closed (a
candidate you could not evaluate must not ship).
"""

from __future__ import annotations

import dataclasses
import os
import time

import numpy as np
from absl import logging as absl_logging

from jama16_retina_tpu.configs import ExperimentConfig
from jama16_retina_tpu.integrity import artifact as artifact_lib
from jama16_retina_tpu.lifecycle.journal import Journal
from jama16_retina_tpu.obs import alerts as obs_alerts
from jama16_retina_tpu.obs import faultinject
from jama16_retina_tpu.obs import registry as obs_registry
from jama16_retina_tpu.obs import trace as obs_trace

STATES = (
    "IDLE", "DRIFT_DETECTED", "RETRAIN", "GATE", "STAGED_ROLLOUT",
    "WATCH", "COMMIT", "ROLLBACK",
)
TERMINAL_STATES = ("COMMIT", "ROLLBACK")
STATE_IDS = {name: i for i, name in enumerate(STATES)}


@dataclasses.dataclass(frozen=True)
class GateVerdict:
    """One named gate's typed verdict over a candidate. ``skipped``
    gates pass vacuously but say so (no artifact / no data to judge
    with) — the journal records WHY a gate did not bind, instead of a
    silent green."""

    name: str
    passed: bool
    value: "float | None" = None
    threshold: "float | None" = None
    detail: str = ""
    skipped: bool = False

    def as_dict(self) -> dict:
        return {
            "name": self.name, "passed": bool(self.passed),
            "value": (round(float(self.value), 6)
                      if self.value is not None else None),
            "threshold": (float(self.threshold)
                          if self.threshold is not None else None),
            "detail": self.detail, "skipped": bool(self.skipped),
        }


def _referable(scores: np.ndarray) -> np.ndarray:
    """Ensemble-averaged scores -> referable probability [n] (the one
    scalar every gate compares on), for either head."""
    s = np.asarray(scores, np.float64)
    if s.ndim == 2:
        from jama16_retina_tpu.eval import metrics

        s = np.asarray(
            metrics.referable_probs_from_multiclass(s), np.float64
        )
    return s.ravel()


class LifecycleController:
    """One deployment's lifecycle state machine over a crash-safe
    journal.

    ``engine``: the live ServingEngine (None only for fully seam-
    injected uses — the defaults for gate/rollout/rollback need one).
    ``data_dir``: the dataset root (fresh training data + the val
    split the gates score). ``live_member_dirs``: the deployment's
    configured checkpoint set — the fallback identity of "the live
    model" before the first promote writes the journal's live pointer.
    ``runlog``: a RunLog to append ``lifecycle`` records to (the
    serving session's own log, so obs_report renders the timeline);
    None with a workdir opens one lazily on first write.
    """

    def __init__(
        self,
        cfg: ExperimentConfig,
        workdir: str,
        *,
        engine=None,
        data_dir: str = "",
        live_member_dirs=None,
        registry: "obs_registry.Registry | None" = None,
        runlog=None,
        retrain_fn=None,
        gate_fns=None,
        sleep=time.sleep,
    ):
        self.cfg = cfg
        self.lc = cfg.lifecycle
        self.workdir = workdir
        self.dir = os.path.join(workdir, "lifecycle")
        # Cascade-aware rollout (ISSUE 10): a CascadeEngine unwraps to
        # its ENSEMBLE half — drift retrains, gates, shadow scoring,
        # swap, and rollback all act on the expensive stacked model,
        # while the distilled student keeps serving the cheap path
        # through every phase (the cascade's probs() reads the
        # ensemble's live generation handle on each escalation, so a
        # promote is visible to cascade traffic the same atomic swap
        # it is to direct traffic). The student itself is retrained
        # offline (train.distill_from against the new ensemble) and
        # replaced by constructing a fresh cascade.
        self.cascade = None
        if (engine is not None and hasattr(engine, "student")
                and hasattr(engine, "ensemble")):
            self.cascade = engine
            engine = engine.ensemble
        self.engine = engine
        self.data_dir = data_dir
        self._live_fallback = (
            list(live_member_dirs) if live_member_dirs else None
        )
        self.registry = (
            registry if registry is not None
            else (engine.registry if engine is not None
                  else obs_registry.default_registry())
        )
        self._log = runlog
        self._owns_log = False
        self._retrain_fn = retrain_fn or _default_retrain
        self._gate_fns = gate_fns  # None = the default engine gates
        self._sleep = sleep
        self.journal = Journal(self.dir, terminal_states=TERMINAL_STATES)
        self._watch_rules = [
            obs_alerts.parse_rule(r) for r in self.lc.watch_rules
        ]
        for r in self._watch_rules:
            if r.metric.startswith("rate("):
                # Watch probes are stateless single-snapshot checks
                # (obs_alerts.rule_holds); a rate() form would resolve
                # to no-data and read as vacuously healthy — the one
                # failure mode a regression watch must not have.
                raise ValueError(
                    f"lifecycle.watch_rules entry {r.name!r}: rate() "
                    "needs snapshot history, which the WATCH probe "
                    "does not keep — watch a plain counter/gauge "
                    "threshold instead"
                )
            if r.for_seconds:
                # Same loud-refusal stance for the `for` clause: the
                # probe has no continuous-hold state, so the latching
                # protection the operator asked for would silently
                # become fire-on-first-sample.
                raise ValueError(
                    f"lifecycle.watch_rules entry {r.name!r}: the "
                    "'for N' clause needs continuous-hold tracking the "
                    "WATCH probe does not keep — use "
                    "lifecycle.watch_probes/watch_interval_s for "
                    "sustained evidence instead"
                )
        # The candidate generation handle is CACHED between GATE and
        # STAGED_ROLLOUT (same residency scores the gates and the
        # shadow); it is pure in-memory acceleration — a resumed
        # controller rebuilds it from the journaled candidate dirs.
        self._candidate = None
        self._gate_data = None
        reg = self.registry
        self._g_state = reg.gauge(
            "serve.lifecycle.state",
            help="lifecycle controller state: "
                 + " ".join(f"{i}={n}" for n, i in STATE_IDS.items())
                 + " [fleet:max]",
        )
        self._c_transitions = reg.counter(
            "lifecycle.transitions",
            help="journaled lifecycle state transitions (all states)",
        )
        self._c_by_state = {
            s: reg.counter(
                f"lifecycle.transition.{s}",
                help=f"lifecycle arrivals at {s}",
            )
            for s in STATES[1:]
        }
        self._c_retrains = reg.counter(
            "lifecycle.retrains",
            help="warm-start retrain phases completed (candidate "
                 "checkpoint sets made durable)",
        )
        self._c_gate_rejects = reg.counter(
            "lifecycle.gate_rejects",
            help="candidates rejected at GATE (live model kept serving)",
        )
        self._c_promotes = reg.counter(
            "lifecycle.promotes",
            help="candidates promoted live via staged rollout",
        )
        self._c_rollbacks = reg.counter(
            "lifecycle.rollbacks",
            help="cycles that ended in ROLLBACK (gate reject or "
                 "post-swap regression)",
        )
        self._c_commits = reg.counter(
            "lifecycle.commits",
            help="cycles that ended in COMMIT (candidate retained live)",
        )
        self._c_step_errors = reg.counter(
            "lifecycle.step_errors",
            help="lifecycle steps that raised (journal unadvanced; the "
                 "step retries on the next drive)",
        )
        self._g_state.set(STATE_IDS.get(self.state, 0))
        if engine is not None:
            self.ensure_live()

    # -- identity ----------------------------------------------------------

    @property
    def state(self) -> str:
        return self.journal.state or "IDLE"

    def live_member_dirs(self) -> "list[str] | None":
        """The checkpoint set that IS the live model right now: the
        journal's live pointer once a promote/rollback wrote one, else
        the deployment's configured set."""
        live = self.journal.read_live()
        if live is not None:
            return live
        if self._live_fallback is not None:
            return list(self._live_fallback)
        if self.engine is not None and self.engine._gen.member_dirs:
            return list(self.engine._gen.member_dirs)
        return None

    def ensure_live(self) -> bool:
        """Reconcile the engine with the journal's live pointer — the
        resume half of crash-safe promotion: a swap is durable as the
        pointer file, and re-applying it is an idempotent reload.
        Returns True when a reload was applied."""
        live = self.journal.read_live()
        if live is None or self.engine is None:
            return False
        cur = self.engine._gen.member_dirs
        if cur is not None and list(cur) == list(live):
            return False
        absl_logging.info(
            "lifecycle resume: engine serves %s but the live pointer "
            "names %s — reloading", cur, live,
        )
        self.engine.reload(live)
        return True

    # -- trigger (the AlertManager on_fire seam) ---------------------------

    def on_alert(self, info: dict) -> bool:
        """``AlertManager(on_fire=controller.on_alert)``: a firing rule
        whose reason is in lifecycle.trigger_reasons opens a cycle —
        alerts become actions. Refuses (False) while a cycle is open
        (one rollout at a time) or for non-trigger reasons."""
        if not self.lc.enabled:
            return False
        if info.get("reason") not in self.lc.trigger_reasons:
            return False
        return self.trigger(
            reason=info.get("reason", "unknown"), rule=info.get("rule"),
            value=info.get("value"), threshold=info.get("threshold"),
        )

    def trigger(self, reason: str = "manual", **detail) -> bool:
        """Open a cycle at DRIFT_DETECTED. The entry snapshots the
        CURRENT live checkpoint set — the identity ROLLBACK restores
        and RETRAIN warm-starts from, pinned before anything moves."""
        if self.journal.cycle_open():
            absl_logging.warning(
                "lifecycle trigger (%s) ignored: cycle %d is still at "
                "%s", reason, self.journal.cycle, self.state,
            )
            return False
        live = self.live_member_dirs()
        # Distributed-trace seam (ISSUE 15): the trigger mints the
        # cycle's serializable trace context into the journal entry, so
        # every later step — possibly executed by a DIFFERENT process
        # (--watch supervisor, one-shot --step) — stamps its events
        # with the same trace_id and the stitched fleet trace shows one
        # cycle across pid lanes. A caller-supplied wire dict (the
        # lifecycle_run --trigger CLI) wins over minting.
        trace_wire = detail.pop("trace", None)
        if trace_wire is None:
            trace_wire = obs_trace.new_context().wire()
        self._arrive(
            "DRIFT_DETECTED", cycle=self.journal.cycle + 1,
            reason=reason, live_member_dirs=live, trace=trace_wire,
            **{k: v for k, v in detail.items() if v is not None},
        )
        return True

    # -- driving -----------------------------------------------------------

    def step(self) -> "dict | None":
        """Execute ONE transition (the operator ``--step`` unit): do
        the current state's work idempotently, then append the arrival
        it produced. Returns the new journal entry, or None when there
        is nothing to do (idle / terminal). A step that raises leaves
        the journal unadvanced — re-driving retries exactly that step."""
        state = self.state
        if state == "IDLE" or state in TERMINAL_STATES:
            return None
        # The cycle's trace context (ISSUE 15), recovered from the
        # DRIFT_DETECTED entry — minted by whichever process triggered
        # (on_fire seam, lifecycle_run --trigger). The step's work is
        # wrapped in a `lifecycle.<state>` complete event carrying its
        # trace_id and runs under the ambient context, so a RETRAIN's
        # trainer spans (and anything below them) belong to the cycle.
        ctx = self._cycle_context()
        tracer = obs_trace.default_tracer()
        args = ({"trace_id": ctx.trace_id, "state": state}
                if ctx is not None else {"state": state})
        try:
            with obs_trace.use_context(ctx), \
                    tracer.trace(f"lifecycle.{state.lower()}", args=args):
                if state == "DRIFT_DETECTED":
                    return self._step_retrain()
                if state == "RETRAIN":
                    return self._step_gate()
                if state == "GATE":
                    gate = self.journal.find("GATE")
                    if gate and not gate["passed"]:
                        return self._step_rollback("gate_rejected")
                    return self._step_rollout()
                if state == "STAGED_ROLLOUT":
                    return self._step_watch()
                if state == "WATCH":
                    watch = self.journal.find("WATCH")
                    if watch and not watch["healthy"]:
                        return self._step_rollback("watch_regression")
                    return self._step_commit()
        except Exception:
            self._c_step_errors.inc()
            raise
        raise AssertionError(f"unreachable lifecycle state {state!r}")

    def _cycle_context(self):
        """The open cycle's TraceContext from its DRIFT_DETECTED entry
        (None for legacy journals written before contexts existed)."""
        trigger = self.journal.find("DRIFT_DETECTED")
        if not trigger:
            return None
        return obs_trace.TraceContext.from_wire(trigger.get("trace"))

    def run(self, max_steps: int = 16) -> str:
        """Drive to a terminal state (the ``--watch`` supervisor's
        inner loop); returns the terminal state. ``max_steps`` bounds
        runaway (the longest cycle is 6 transitions)."""
        for _ in range(max_steps):
            if self.step() is None:
                break
        return self.state

    # -- the steps ---------------------------------------------------------

    def _arrive(self, state: str, cycle: "int | None" = None,
                **payload) -> dict:
        entry = self.journal.append(state, cycle=cycle, **payload)
        self._g_state.set(STATE_IDS[state])
        self._c_transitions.inc()
        self._c_by_state[state].inc()
        obs_trace.default_tracer().instant(
            "lifecycle.transition",
            args={"state": state, "cycle": entry["cycle"],
                  "seq": entry["seq"]},
        )
        if self._log is None and self.workdir:
            from jama16_retina_tpu.utils.logging import RunLog

            self._log = RunLog(self.workdir)
            self._owns_log = True
        if self._log is not None:
            self._log.write("lifecycle", **{
                k: v for k, v in entry.items()
                if k not in ("live_member_dirs", "member_dirs")
            })
        absl_logging.info(
            "lifecycle: cycle %d -> %s", entry["cycle"], state
        )
        return entry

    def _candidate_root(self) -> str:
        return os.path.join(
            self.dir, f"candidate-{self.journal.cycle:04d}"
        )

    def _step_retrain(self) -> dict:
        faultinject.check("lifecycle.retrain")
        member_dirs = self._retrain_fn(self, self._candidate_root())
        self._c_retrains.inc()
        return self._arrive(
            "RETRAIN", cycle=self.journal.cycle,
            member_dirs=list(member_dirs), n_members=len(member_dirs),
            # Training-data provenance (ISSUE 20): the rawshard
            # manifest (path + content digest) the cycle trained from,
            # when one exists — the link `audit_query trace` renders
            # between a served score and its training data.
            data_dir=self.data_dir or None,
            data_manifest=self._data_manifest(),
        )

    def _data_manifest(self) -> "dict | None":
        """The train-split rawshard manifest identity for this cycle's
        data_dir (data.rawshard_dir wins, then the size-suffixed
        default location), or None — advisory lineage, never a step
        failure."""
        if not self.data_dir:
            return None
        try:
            from jama16_retina_tpu.data import rawshard
            from jama16_retina_tpu.integrity import (
                artifact as artifact_lib,
            )

            dcfg = self.cfg.data
            shard_dir = (
                getattr(dcfg, "rawshard_dir", "")
                or rawshard.default_shard_dir(
                    self.data_dir, self.cfg.model.image_size
                )
            )
            path = rawshard.manifest_path(shard_dir, "train")
            if not os.path.exists(path):
                return None
            return {"path": path,
                    "sha256": artifact_lib.sha256_file(path)}
        except Exception:  # noqa: BLE001 - lineage is advisory here
            return None

    def _step_gate(self) -> dict:
        member_dirs = self.journal.find("RETRAIN")["member_dirs"]
        try:
            faultinject.check("lifecycle.gate")
            if self._gate_fns is not None:
                fns = self._gate_fns
            else:
                if self.engine is None:
                    raise RuntimeError(
                        "default gates need a ServingEngine; pass "
                        "gate_fns= or an engine"
                    )
                fns = [gate_golden_canary, gate_profile_parity,
                       gate_auc_floor]
            # warm=True: the gates only need scores, but this handle is
            # REUSED by _step_rollout's shadow session, whose contract
            # is that a sampled live request never eats a candidate
            # compile — pay every bucket's warm-up here, off the
            # request path.
            self._candidate = (
                self.engine.prepare_candidate(member_dirs, warm=True)
                if self.engine is not None else None
            )
            verdicts = [fn(self, self._candidate) for fn in fns]
        except Exception as e:  # noqa: BLE001 - gates fail CLOSED
            # A gate that cannot run must not ship the candidate: the
            # failure becomes a failing verdict, the cycle proceeds to
            # ROLLBACK, the live model keeps serving.
            absl_logging.error(
                "lifecycle gate evaluation failed (failing closed): "
                "%s: %s", type(e).__name__, e,
            )
            verdicts = [GateVerdict(
                name="gate_error", passed=False,
                detail=f"{type(e).__name__}: {e}",
            )]
        passed = all(v.passed for v in verdicts)
        if not passed:
            self._c_gate_rejects.inc()
            self._candidate = None
        return self._arrive(
            "GATE", cycle=self.journal.cycle, passed=passed,
            verdicts=[v.as_dict() for v in verdicts],
        )

    def _step_rollout(self) -> dict:
        engine = self.engine
        if engine is None:
            raise RuntimeError("STAGED_ROLLOUT needs a ServingEngine")
        member_dirs = self.journal.find("RETRAIN")["member_dirs"]
        candidate = self._candidate
        if candidate is None:  # resumed controller: rebuild from dirs
            candidate = engine.prepare_candidate(member_dirs, warm=True)
        if engine.shadow_report() is not None:
            # A session abandoned by a step interrupted mid-rollout in
            # THIS process; its evidence died with the interruption.
            engine.end_shadow()
        faultinject.check("lifecycle.swap")
        engine.begin_shadow(
            candidate=candidate, fraction=self.lc.shadow_fraction
        )
        deadline = time.monotonic() + self.lc.shadow_wait_s
        while True:
            rep = engine.shadow_report()
            if rep is None:
                # A concurrent reload/rollback (another driver, an ops
                # script) cleared the session: this rollout's baseline
                # died — abort the step; the journal holds at GATE and
                # the next drive restarts the rollout cleanly.
                raise RuntimeError(
                    "shadow session cleared by a concurrent "
                    "reload/rollback — rollout aborted; re-drive to "
                    "retry against the new live generation"
                )
            if rep["requests"] >= self.lc.shadow_requests:
                break
            if time.monotonic() >= deadline:
                absl_logging.warning(
                    "lifecycle shadow window timed out at %s — "
                    "promoting on partial evidence", rep,
                )
                break
            self._sleep(0.02)
        # Re-pin the golden canary to the CANDIDATE before the swap:
        # a retrained model legitimately moves the pinned scores, and
        # reload()'s byte-stability gate (plus the post-swap WATCH
        # rules) must judge the model being shipped, not the one being
        # replaced. The previous reference is backed up for ROLLBACK.
        repin = self._repin_canary(candidate)
        try:
            report = engine.end_shadow(promote=True)
        except Exception:
            # The swap failed AFTER the canary was re-pinned to the
            # candidate: the OLD model keeps serving, so the old
            # reference must be the truth again — otherwise every
            # cadence canary run until the retry fires false
            # quality_drift alerts against the wrong pinned scores.
            if repin:
                self._restore_canary()
            raise
        reload_info = report.pop("reload")
        self.journal.write_live(member_dirs)
        self._candidate = None
        self._c_promotes.inc()
        return self._arrive(
            "STAGED_ROLLOUT", cycle=self.journal.cycle,
            generation=reload_info["generation"], shadow=report,
            canary_repinned=repin,
        )

    def _run_live_canary(self) -> None:
        """Refresh the golden-canary gauges against the LIVE generation
        before a watch probe reads them: the gauge otherwise holds the
        last cadence run's verdict — of the PRE-swap model (stale 1.0
        makes the watch vacuous; a latched 0 from the triggering drift
        would roll back every healthy canary-triggered promote)."""
        from jama16_retina_tpu.eval import metrics

        engine = self.engine
        q = getattr(engine, "quality", None) if engine is not None \
            else None
        if q is None or q.canary is None:
            return
        q.run_canary(lambda imgs: metrics.ensemble_average(
            list(engine.member_probs(imgs))
        ))

    def _step_watch(self) -> dict:
        fired: list = []
        probes = 0
        for i in range(max(1, self.lc.watch_probes)):
            if i:
                self._sleep(self.lc.watch_interval_s)
            self._run_live_canary()
            snap = self.registry.snapshot()
            probes += 1
            fired = [
                r.name for r in self._watch_rules
                if obs_alerts.rule_holds(r, snap)
            ]
            if fired:
                break
        healthy = not fired
        return self._arrive(
            "WATCH", cycle=self.journal.cycle, healthy=healthy,
            probes=probes, fired=fired,
            rules=[r.name for r in self._watch_rules],
        )

    def _step_commit(self) -> dict:
        rollout = self.journal.find("STAGED_ROLLOUT")
        self._c_commits.inc()
        self._gate_data = None  # cycle over: release the eval rows
        if self.engine is not None and hasattr(self.engine,
                                              "release_retained"):
            # The watch judged the rollout healthy: holding the
            # outgoing generation's device residency until the
            # rollback window expires buys nothing now.
            self.engine.release_retained()
        return self._arrive(
            "COMMIT", cycle=self.journal.cycle,
            generation=rollout["generation"] if rollout else None,
        )

    def _step_rollback(self, cause: str) -> dict:
        restored = None
        rollout = self.journal.find("STAGED_ROLLOUT")
        trigger = self.journal.find("DRIFT_DETECTED")
        prev_dirs = (trigger or {}).get("live_member_dirs")
        if rollout is not None:
            # A swap happened this cycle: the DURABLE half of the
            # undo — the live pointer naming the pre-cycle set again —
            # happens first and unconditionally (a controller resumed
            # without an engine must still stop the regressed
            # candidate being what the next process serves).
            if prev_dirs:
                self.journal.write_live(prev_dirs)
            # The canary artifact's undo is durable bookkeeping too —
            # it must happen with or without an in-process engine, and
            # BEFORE any reload fallback (the gate judges the restored
            # reference).
            self._restore_canary()
            if self.engine is not None:
                # Put the previous model back in-process too —
                # instantly off the retained generation when the
                # window holds, else a full reload from the pre-cycle
                # checkpoint set the trigger entry pinned.
                from jama16_retina_tpu.serve.engine import (
                    RollbackUnavailable,
                )

                try:
                    restored = self.engine.rollback()
                except RollbackUnavailable as e:
                    if not prev_dirs:
                        raise RuntimeError(
                            "rollback needs the pre-cycle checkpoint "
                            "set but the trigger entry pinned none"
                        ) from e
                    absl_logging.warning(
                        "instant rollback unavailable (%s); reloading "
                        "the pre-cycle checkpoint set", e,
                    )
                    restored = self.engine.reload(prev_dirs)
                else:
                    if not prev_dirs and self.engine._gen.member_dirs:
                        # The trigger entry pinned no pre-cycle set
                        # (journal-only trigger with no --ckpt): the
                        # restored generation's own provenance is the
                        # durable truth the pointer must record —
                        # otherwise the next process would rebuild
                        # from the regressed candidate.
                        self.journal.write_live(
                            list(self.engine._gen.member_dirs)
                        )
        # rollout None: nothing was promoted — the live model never
        # stopped serving; rollback is the cycle's terminal
        # bookkeeping.
        self._candidate = None
        self._gate_data = None  # cycle over: release the eval rows
        self._c_rollbacks.inc()
        return self._arrive(
            "ROLLBACK", cycle=self.journal.cycle, cause=cause,
            swapped=rollout is not None,
            restored_generation=(
                restored.get("generation") if restored else None
            ),
        )

    # -- canary custody across promote/rollback ----------------------------

    def _canary_backup_path(self) -> str:
        return os.path.join(
            self.dir, f"canary-pre-{self.journal.cycle:04d}.npz"
        )

    def _repin_canary(self, candidate) -> bool:
        """Score the golden set through the candidate and make those
        scores the pinned reference (in-memory + the on-disk artifact
        when one is configured), backing up the previous reference for
        ROLLBACK. Returns whether a re-pin happened. Idempotent: a
        crash between re-pin and swap re-runs this with identical
        scores (same state, same program)."""
        from jama16_retina_tpu.obs import quality as quality_lib

        engine = self.engine
        q = engine.quality if engine is not None else None
        canary = q.canary if q is not None else None
        if canary is None or candidate is None:
            return False
        scores = self._canary_scores(candidate)
        backup = self._canary_backup_path()
        if canary.reference is not None and not os.path.exists(backup):
            quality_lib.save_canary(
                backup, canary.images, scores=canary.reference
            )
        canary.reference = scores
        path = self.cfg.obs.quality.canary_path
        if path:
            quality_lib.save_canary(path, canary.images, scores=scores)
        return True

    def _restore_canary(self) -> bool:
        """Undo ``_repin_canary`` from its backup (ROLLBACK path): the
        previous model is live again, so the previous pinned scores are
        the truth again — the DURABLE artifact is restored even when
        this controller has no engine (a resumed engineless rollback
        must not leave the next serving process loading the rejected
        candidate's reference and false-alerting forever)."""
        from jama16_retina_tpu.obs import quality as quality_lib

        backup = self._canary_backup_path()
        if not os.path.exists(backup):
            return False
        images, ref = quality_lib.load_canary_file(backup)
        path = self.cfg.obs.quality.canary_path
        if path:
            quality_lib.save_canary(path, images, scores=ref)
        engine = self.engine
        q = engine.quality if engine is not None else None
        canary = q.canary if q is not None else None
        if canary is not None:
            canary.reference = ref
            canary._g_ok.set(1.0)  # the restored model matches again
        return True

    # -- gate data ---------------------------------------------------------

    def _gate_eval_data(self):
        """(images, grades) of the val split for the parity/AUC gates,
        decoded through the data plane's own machinery (bounded by
        lifecycle.gate_eval_rows) and cached for THIS CYCLE only — the
        array is released at the cycle's terminal state, so a
        long-lived --watch supervisor neither pins gigabytes of host
        RAM between cycles nor judges a later cycle against stale
        rows. None when no data_dir/split exists — those gates then
        skip, loudly."""
        cycle = self.journal.cycle
        if self._gate_data is not None and self._gate_data[0] == cycle:
            return self._gate_data[1]
        self._gate_data = None
        if not self.data_dir:
            return None
        from jama16_retina_tpu.data import tfrecord
        from jama16_retina_tpu.data.grain_pipeline import (
            ParallelDecoder,
            TFRecordIndex,
            resolve_decode_workers,
        )

        try:
            paths = tfrecord.list_split(self.data_dir, "val")
        except (FileNotFoundError, ValueError):
            return None
        if not paths:
            return None
        index = TFRecordIndex(paths)
        n = len(index)
        if self.lc.gate_eval_rows > 0:
            n = min(n, self.lc.gate_eval_rows)
        # A detached registry: gate-time decode counters must not bleed
        # into the serving session's data-plane telemetry (and its
        # quarantine burn-rate alert).
        dec = ParallelDecoder(
            index, self.cfg.model.image_size,
            workers=resolve_decode_workers(0),
            registry=obs_registry.Registry(),
        )
        try:
            batch = dec.decode_batch(range(n))
        finally:
            dec.close()
        self._gate_data = (
            cycle, (batch["image"], np.asarray(batch["grade"]))
        )
        return self._gate_data[1]

    def _score_gen(self, gen, images: np.ndarray) -> np.ndarray:
        """Referable probabilities [n] through one generation — the
        scalar the parity/AUC gates compare on (either head)."""
        from jama16_retina_tpu.eval import metrics

        return _referable(metrics.ensemble_average(
            list(self.engine.member_probs(images, _gen=gen))
        ))

    def _canary_scores(self, gen) -> np.ndarray:
        """Golden-set scores through one generation in the ENGINE'S
        canary convention — raw ensemble-averaged output, raveled
        ([n] binary, [n*C] multi) — NOT referable-collapsed: the
        pinned reference, the reload gate, and every cadence canary
        run all use this shape, and a lifecycle that compared or
        re-pinned in another shape would mismatch every multi-head
        cycle."""
        from jama16_retina_tpu.eval import metrics

        return np.asarray(metrics.ensemble_average(
            list(self.engine.member_probs(
                self.engine.quality.canary.images, _gen=gen
            ))
        ), np.float64).ravel()


# ---------------------------------------------------------------------------
# Default seams: warm-start retrain + the three named gates
# ---------------------------------------------------------------------------


def _default_retrain(ctl: LifecycleController, cand_root: str) -> list:
    """Warm-start fine-tune every live member on fresh data
    (trainer.fit with train.init_from; the RETRAIN phase's real
    implementation). Idempotent per member: a durable candidate (its
    RETRAIN_DONE marker written after fit returned) is reused on
    resume, and fit's own train.resume continues a member interrupted
    mid-run — kill -9 during RETRAIN repeats no completed work."""
    from jama16_retina_tpu import trainer

    live = ctl.live_member_dirs()
    if not live:
        raise RuntimeError(
            "RETRAIN needs the live checkpoint set (live_member_dirs= "
            "or a journal live pointer)"
        )
    if not ctl.data_dir:
        raise RuntimeError("RETRAIN needs data_dir= (fresh training data)")
    cfg = ctl.cfg
    steps = ctl.lc.retrain_steps or cfg.train.steps
    cycle = ctl.journal.cycle
    out = []
    for m, src in enumerate(live):
        dst = os.path.join(cand_root, f"member_{m:02d}")
        marker = os.path.join(dst, "RETRAIN_DONE.json")
        if os.path.exists(marker):
            out.append(dst)
            continue
        mcfg = cfg.replace(train=dataclasses.replace(
            cfg.train, init_from=src, steps=steps, resume=True,
        ))
        result = trainer.fit(
            mcfg, ctl.data_dir, dst,
            seed=cfg.train.seed + 1000 * (cycle + 1) + m,
        )
        artifact_lib.write_sealed_json(marker, {
            "cycle": cycle, "init_from": src, "steps": steps,
            "best_auc": result.get("best_auc"),
            "t": round(time.time(), 3),
        }, schema="lifecycle.retrain_marker", version=1)
        out.append(dst)
    return out


def gate_golden_canary(ctl: LifecycleController,
                       candidate) -> GateVerdict:
    """Sanity bound on the golden set: |candidate - pinned reference|
    must stay under lifecycle.gate_canary_max_dev. Loose by design —
    a fine-tuned model moves scores; a degenerate candidate (random
    divergence, collapsed head) moves them wildly."""
    q = ctl.engine.quality if ctl.engine is not None else None
    canary = q.canary if q is not None else None
    if canary is None or canary.reference is None:
        return GateVerdict(
            name="golden_canary", passed=True, skipped=True,
            detail="no canary artifact configured/pinned",
        )
    scores = ctl._canary_scores(candidate)
    ref = np.asarray(canary.reference, np.float64).ravel()
    if scores.shape != ref.shape:
        return GateVerdict(
            name="golden_canary", passed=False,
            detail=f"score shape {scores.shape} vs pinned {ref.shape}",
        )
    dev = float(np.max(np.abs(scores - ref)))
    thr = float(ctl.lc.gate_canary_max_dev)
    return GateVerdict(
        name="golden_canary", passed=dev <= thr, value=dev,
        threshold=thr,
    )


def gate_profile_parity(ctl: LifecycleController,
                        candidate) -> GateVerdict:
    """Debiased PSI of the candidate's val-split score histogram vs
    the loaded reference profile — the same statistic the online drift
    monitor publishes, applied pre-swap."""
    from jama16_retina_tpu.obs import quality as quality_lib

    q = ctl.engine.quality if ctl.engine is not None else None
    profile = q.profile if q is not None else None
    if profile is None:
        return GateVerdict(
            name="profile_parity", passed=True, skipped=True,
            detail="no reference profile loaded",
        )
    data = ctl._gate_eval_data()
    if data is None:
        return GateVerdict(
            name="profile_parity", passed=True, skipped=True,
            detail="no val split available to score",
        )
    images, _ = data
    scores = ctl._score_gen(candidate, images)
    counts = quality_lib.bin_counts(scores, int(profile["bins"]))
    value = quality_lib.psi_debiased(
        np.asarray(profile["score_hist"], np.float64), counts
    )
    thr = float(ctl.lc.gate_parity_psi_max)
    if thr < 0:
        thr = float(ctl.cfg.obs.quality.psi_alert)
    return GateVerdict(
        name="profile_parity", passed=value <= thr, value=value,
        threshold=thr,
    )


def gate_auc_floor(ctl: LifecycleController, candidate) -> GateVerdict:
    """Operating-point floor: candidate val AUC >= live val AUC -
    lifecycle.gate_auc_floor_delta, both scored on the same rows
    through the same engine path."""
    from jama16_retina_tpu.eval import metrics

    data = ctl._gate_eval_data()
    if data is None:
        return GateVerdict(
            name="auc_floor", passed=True, skipped=True,
            detail="no val split available to score",
        )
    images, grades = data
    labels = (np.asarray(grades) >= 2).astype(np.float64)
    if not (0.0 < labels.mean() < 1.0):
        return GateVerdict(
            name="auc_floor", passed=True, skipped=True,
            detail="val split is single-class; AUC undefined",
        )
    auc_cand = metrics.roc_auc(labels, ctl._score_gen(candidate, images))
    auc_live = metrics.roc_auc(
        labels, ctl._score_gen(ctl.engine._gen, images)
    )
    delta = float(ctl.lc.gate_auc_floor_delta)
    return GateVerdict(
        name="auc_floor", passed=auc_cand >= auc_live - delta,
        value=float(auc_cand), threshold=float(auc_live - delta),
        detail=f"live_auc={auc_live:.6f}",
    )
