"""Self-healing model lifecycle (ISSUE 8): the drift-to-retrain
flywheel that closes ROADMAP item 5.

PR 5 *detects* drift (debiased-PSI gauges, golden canary, declarative
alert rules) and PR 6 built the swap *mechanism* (atomic
``engine.reload()`` with a canary gate); this package supplies the
missing controller: a journaled state machine that turns a firing
alert into retrain -> gate -> staged rollout -> watch -> commit or
rollback, crash-safe at every step.

  * ``journal``    — the atomic on-disk transition journal (tmp +
    rename discipline; a controller killed at ANY state resumes
    without repeating side effects).
  * ``controller`` — LifecycleController: the state machine itself,
    with seams for every expensive phase (retrain_fn / gate fns /
    watch rules) so tests and the chaos harness drive it off-device.

Operator surface: ``scripts/lifecycle_run.py`` (one-shot ``--step``
and supervising ``--watch``), the ``serve.lifecycle.state`` gauge +
``lifecycle.*`` counters, the Lifecycle section of
``scripts/obs_report.py``, and docs/RELIABILITY.md §Lifecycle.
"""

from jama16_retina_tpu.lifecycle.controller import (
    GateVerdict,
    LifecycleController,
    STATES,
    TERMINAL_STATES,
)
from jama16_retina_tpu.lifecycle.journal import Journal

__all__ = [
    "GateVerdict",
    "Journal",
    "LifecycleController",
    "STATES",
    "TERMINAL_STATES",
]
