"""Pallas fused serve-side preprocess (ISSUE 16 tentpole b).

The serving host stage hands the engine uint8 rows, the engine
normalizes them in-model (``augment.normalize``), and — when quality
monitoring is on — ``obs/quality.input_stat_values`` makes a SEPARATE
full per-pixel pass over the same batch on the host (the dominant
per-batch monitor cost, per its own call-site comment). At interactive
batch sizes that host pass is a real slice of p99.

This kernel is the serve-side twin of the train-side
``fused_normalize_color_jitter`` (ops/pallas_augment.py): ONE pass over
the uint8 batch streams out

  * the normalized float32 rows (``u8 * (1/127.5) - 1`` — the serving
    step's input distribution), and
  * the raw per-image statistic accumulators (per-channel pixel sums +
    the global sum of squares) that ``stats_from_sums`` turns into the
    exact ``INPUT_STATS`` vocabulary the quality monitor bins
    (mean_r/mean_g/mean_b/std/brightness over x = u8/255).

Layout mirrors pallas_augment: channels-first ``[B, 3, P]`` padded to
the lane tile; zero padding contributes zero to every accumulator, so
the true pixel count divides out exactly.

``serve_preprocess_reference`` is the pure-jnp bit-reference: it runs
the SAME chunk-sequential accumulation (a fori_loop over the kernel's
grid order), so in interpret mode on CPU the kernel is pinned
BIT-IDENTICAL to it (tests/test_pallas_serve.py) — not merely
float-close. The reference (fused off) is also the live path:
``serve/host.py prepare_images`` routes through it unless
``serve.fused_preprocess`` opts in.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

_LANE = 128
_CHUNK = 64 * _LANE  # pixels per grid step, matching pallas_augment

# Rec.601 luma weights — the same constants input_stat_values applies.
_LUMA = (0.299, 0.587, 0.114)


def _serve_kernel(x_ref, out_ref, stat_ref):
    """One grid step of image ``b``, chunk ``j``: write the normalized
    chunk and fold its raw sums into the stats accumulator (an output
    block parked on a constant index, so it persists across the j steps
    of one image and writes back when b advances)."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _reset():
        stat_ref[...] = jnp.zeros_like(stat_ref)

    # Mosaic has no direct uint8->f32 cast on TPU; stage through int32
    # (both legs are supported and exact for [0, 255]).
    raw = x_ref[0].astype(jnp.int32).astype(jnp.float32)  # [3, CHUNK]
    ch = jnp.sum(raw, axis=1, keepdims=True)              # [3, 1]
    sq = jnp.sum(raw * raw, axis=(0, 1), keepdims=True)   # [1, 1]
    stat_ref[0] += jnp.concatenate([ch, sq], axis=0)      # [4, 1]
    out_ref[0] = raw * (1.0 / 127.5) - 1.0


def stats_from_sums(sums: np.ndarray, n_pixels: int) -> np.ndarray:
    """Raw accumulators [B, 4] (sum_r, sum_g, sum_b, sum of squares
    over all channels, in uint8 units) -> [B, 4] float64 stat columns
    (mean_r, mean_g, mean_b, std) over x = u8/255 — the same
    quantities ``obs/quality.input_stat_values`` computes, derived
    from moments instead of a second pass. Shared by the kernel wrapper
    and the jnp reference so bit-identity reduces to the accumulators.

    This is a HOST numpy epilogue in float64, deliberately outside the
    jit: the moment subtraction E[x^2] - E[x]^2 is catastrophically
    cancellative in float32 for low-variance images, so a float32 std
    here would drift systematically from the float64 two-pass std the
    reference profiles (``obs/quality.input_stat_values`` via
    build_profile) were built with — shifting drift bins for exactly
    the flattest images. Float64 from the device's float32 sums keeps
    the live fused stats within histogram-bin tolerance of the host
    pass (pinned by tests at the same atol as the reference path).

    Brightness is NOT computed here either way: ``input_stats_dict``
    derives it deterministically from the mean columns."""
    s = np.asarray(sums, np.float64)
    n = float(n_pixels)
    mean_c = s[:, :3] / (255.0 * n)                       # [B, 3]
    ex = (s[:, 0] + s[:, 1] + s[:, 2]) / (255.0 * 3.0 * n)
    ex2 = s[:, 3] / (255.0 * 255.0 * 3.0 * n)
    std = np.sqrt(np.maximum(ex2 - ex * ex, 0.0))
    return np.concatenate([mean_c, std[:, None]], axis=1)


def _to_channels_first(images_u8: jnp.ndarray):
    B, H, W, _ = images_u8.shape
    P = H * W
    P_pad = -(-P // _CHUNK) * _CHUNK
    x = jnp.transpose(images_u8, (0, 3, 1, 2)).reshape(B, 3, P)
    x = jnp.pad(x, ((0, 0), (0, 0), (0, P_pad - P)))
    return x, P, P_pad


@functools.partial(jax.jit, static_argnames=("interpret",))
def _fused_core(
    images_u8: jnp.ndarray,  # [B, H, W, 3] uint8
    interpret: bool = False,
) -> "tuple[jnp.ndarray, jnp.ndarray]":
    """The jitted device pass: normalized rows + RAW float32 sums
    [B, 4]. The moment combination happens on the host, in float64
    (``stats_from_sums``) — never inside the jit, where it would run
    in float32 and cancel catastrophically for low-variance images."""
    B, H, W, _ = images_u8.shape
    x, P, P_pad = _to_channels_first(images_u8)

    out, sums = pl.pallas_call(
        _serve_kernel,
        out_shape=(
            jax.ShapeDtypeStruct((B, 3, P_pad), jnp.float32),
            jax.ShapeDtypeStruct((B, 4, 1), jnp.float32),
        ),
        grid=(B, P_pad // _CHUNK),
        in_specs=[
            pl.BlockSpec((1, 3, _CHUNK), lambda b, j: (b, 0, j)),
        ],
        out_specs=(
            pl.BlockSpec((1, 3, _CHUNK), lambda b, j: (b, 0, j)),
            # Constant index: the accumulator block lives in VMEM across
            # every j step of image b and writes back once b advances.
            pl.BlockSpec((1, 4, 1), lambda b, j: (b, 0, 0)),
        ),
        interpret=interpret,
    )(x)

    norm = jnp.transpose(out[:, :, :P].reshape(B, 3, H, W), (0, 2, 3, 1))
    return norm, sums[:, :, 0]


def fused_serve_preprocess(
    images_u8: jnp.ndarray,  # [B, H, W, 3] uint8
    interpret: bool = False,
) -> "tuple[jnp.ndarray, np.ndarray]":
    """One-HBM-pass serve preprocess: returns (normalized float32
    [B, H, W, 3] in [-1, 1], stats float64 [B, 4] — mean_r, mean_g,
    mean_b, std; host epilogue, see ``stats_from_sums``). Pinned
    bit-identical to ``serve_preprocess_reference`` in interpret
    mode."""
    _, H, W, _ = images_u8.shape
    norm, sums = _fused_core(images_u8, interpret=bool(interpret))
    return norm, stats_from_sums(np.asarray(jax.device_get(sums)), H * W)


@jax.jit
def _reference_core(
    images_u8: jnp.ndarray,  # [B, H, W, 3] uint8
) -> "tuple[jnp.ndarray, jnp.ndarray]":
    """Jitted half of the reference: same normalize expression and the
    same chunk-sequential float32 sum accumulation as the kernel's grid
    order, so interpret-mode parity is exact, not toleranced."""
    B, H, W, _ = images_u8.shape
    x, P, P_pad = _to_channels_first(images_u8)
    xf = x.astype(jnp.int32).astype(jnp.float32)  # [B, 3, P_pad]
    norm = xf[:, :, :P] * (1.0 / 127.5) - 1.0

    n_chunks = P_pad // _CHUNK

    def body(j, acc):
        raw = jax.lax.dynamic_slice(
            xf, (0, 0, j * _CHUNK), (B, 3, _CHUNK)
        )
        ch = jnp.sum(raw, axis=2)                    # [B, 3]
        sq = jnp.sum(raw * raw, axis=(1, 2))         # [B]
        return acc + jnp.concatenate([ch, sq[:, None]], axis=1)

    sums = jax.lax.fori_loop(
        0, n_chunks, body, jnp.zeros((B, 4), jnp.float32)
    )
    return (
        jnp.transpose(norm.reshape(B, 3, H, W), (0, 2, 3, 1)), sums
    )


def serve_preprocess_reference(
    images_u8: jnp.ndarray,  # [B, H, W, 3] uint8
) -> "tuple[jnp.ndarray, np.ndarray]":
    """The pure-jnp bit-reference (and the live fused-off path): the
    device half accumulates the SAME raw float32 sums in the kernel's
    chunk order, and the stats go through the SAME float64 host
    epilogue — so kernel-vs-reference bit-identity reduces to the
    accumulators."""
    _, H, W, _ = images_u8.shape
    norm, sums = _reference_core(images_u8)
    return norm, stats_from_sums(np.asarray(jax.device_get(sums)), H * W)


def input_stats_dict(stats: np.ndarray) -> dict:
    """Stats columns [n, 4] -> the ``input_stat_values``-shaped dict
    ({stat: float64 [n]}) the QualityMonitor bins. Brightness is
    derived here in float64 from the mean columns (kept out of
    ``stats_from_sums`` so the stat columns stay exactly the four
    independent moments both paths share)."""
    s = np.asarray(stats, np.float64)
    bright = s[:, 0] * _LUMA[0] + s[:, 1] * _LUMA[1] + s[:, 2] * _LUMA[2]
    return {
        "mean_r": s[:, 0],
        "mean_g": s[:, 1],
        "mean_b": s[:, 2],
        "std": s[:, 3],
        "brightness": bright,
    }
