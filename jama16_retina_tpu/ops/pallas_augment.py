"""Pallas fused color-jitter kernel (SURVEY.md N13).

The color half of the augmentation pipeline — uint8 -> [-1,1] normalize,
brightness shift, contrast scale about the per-image mean, and the YIQ
saturation/hue rotation (data/augment.py) — is algebraically one affine
map per example:

    out_c = sum_k A[c,k] * (x_k / 127.5 - 1) + o[c]

with ``A = contrast * M_chroma`` and
``o = M_chroma @ (mean * (1 - contrast) + brightness)`` (M_chroma =
YIQ2RGB @ R(hue, sat) @ RGB2YIQ). XLA emits this as several fused loops
plus a reduce; this kernel does the whole thing in ONE pass over HBM:
uint8 pixels stream through VMEM once, 9 multiply-adds per pixel on the
VPU, f32 out. Geometric augmentations (flips/transpose) are pure layout
moves and stay in XLA where they fuse with the select.

Layout: channels-first ``[B, 3, P]`` with P = H*W padded to the lane
tile, so the per-channel rows sit in sublanes and the cross-channel
combination is three row reads — no strided channel gather.

Tested against the jnp reference in interpret mode on CPU
(tests/test_pallas.py); ``fused_color_jitter`` is used by
``augment_batch(..., use_pallas=True)`` on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

_LANE = 128
_CHUNK = 64 * _LANE  # pixels per grid step; 3x64x128 f32 ≈ 96 KiB of VMEM


def _kernel(a_ref, o_ref, x_ref, out_ref):
    # Mosaic has no direct uint8->f32 cast on TPU; stage through int32
    # (both legs are supported and exact for [0, 255]).
    x = x_ref[0].astype(jnp.int32).astype(jnp.float32)  # [3, CHUNK]
    x = x * (1.0 / 127.5) - 1.0
    a = a_ref[0]  # [3, 3]
    o = o_ref[0]  # [3, 1] (kept 2-D for SMEM-free VMEM layout)
    r, g, b = x[0], x[1], x[2]
    rows = []
    for c in range(3):
        rows.append(
            jnp.clip(
                a[c, 0] * r + a[c, 1] * g + a[c, 2] * b + o[c, 0],
                -1.0,
                1.0,
            )
        )
    out_ref[0] = jnp.stack(rows, axis=0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_color_jitter(
    images_u8: jnp.ndarray,  # [B, H, W, 3] uint8
    affine: jnp.ndarray,  # [B, 3, 3] f32 — A above
    offset: jnp.ndarray,  # [B, 3] f32 — o above
    interpret: bool = False,
) -> jnp.ndarray:
    """One-HBM-pass color jitter; returns [B, H, W, 3] float32 in [-1,1]."""
    B, H, W, _ = images_u8.shape
    P = H * W
    P_pad = -(-P // _CHUNK) * _CHUNK
    x = jnp.transpose(images_u8, (0, 3, 1, 2)).reshape(B, 3, P)
    x = jnp.pad(x, ((0, 0), (0, 0), (0, P_pad - P)))

    out = pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((B, 3, P_pad), jnp.float32),
        grid=(B, P_pad // _CHUNK),
        in_specs=[
            pl.BlockSpec((1, 3, 3), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, 3, 1), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, 3, _CHUNK), lambda b, j: (b, 0, j)),
        ],
        out_specs=pl.BlockSpec((1, 3, _CHUNK), lambda b, j: (b, 0, j)),
        interpret=interpret,
    )(affine, offset[..., None], x)

    return jnp.transpose(out[:, :, :P].reshape(B, 3, H, W), (0, 2, 3, 1))


def chroma_matrix(
    saturation: jnp.ndarray,  # [B]
    hue_theta: jnp.ndarray,  # [B] radians
) -> jnp.ndarray:
    """[B, 3, 3] YIQ chroma rotation/scaling in RGB space — the
    mean-independent half of the collapsed color affine, shared by
    ``color_affine_from_params`` and the fused kernel (whose offsets
    need the in-kernel means)."""
    from jama16_retina_tpu.data.augment import _RGB2YIQ, _YIQ2RGB

    B = saturation.shape[0]
    cos = jnp.cos(hue_theta) * saturation
    sin = jnp.sin(hue_theta) * saturation
    zeros = jnp.zeros((B,))
    ones = jnp.ones((B,))
    rot = jnp.stack(
        [
            jnp.stack([ones, zeros, zeros], -1),
            jnp.stack([zeros, cos, -sin], -1),
            jnp.stack([zeros, sin, cos], -1),
        ],
        axis=-2,
    )  # [B, 3, 3]
    # Decomposed as I + Minv (rot - I) M rather than Minv rot M: when the
    # drawn params are identity (s=1, theta=0 — e.g. all color flags off),
    # rot - I is exactly zero and the affine is exactly I, independent of
    # f32 rounding in the matrix inverse. The jnp path statically skips
    # the chroma block in that case, so exactness here is what keeps the
    # two paths bit-compatible.
    eye = jnp.eye(3, dtype=rot.dtype)
    hp = jax.lax.Precision.HIGHEST
    return eye + jnp.einsum(
        "ij,bjk,kl->bil", _YIQ2RGB, rot - eye, _RGB2YIQ, precision=hp
    )


def color_affine_from_params(
    means: jnp.ndarray,  # [B, 3] per-image channel means of (x/127.5 - 1)
    brightness: jnp.ndarray,  # [B]
    contrast: jnp.ndarray,  # [B]
    saturation: jnp.ndarray,  # [B]
    hue_theta: jnp.ndarray,  # [B] radians
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Collapse the augment params into (A [B,3,3], o [B,3]).

    Matches data/augment.py exactly: v = contrast*(t - mean) + mean +
    brightness, then YIQ chroma rotation M @ v. (The jnp path computes
    the contrast mean *after* brightness, but the mean of t + b is
    mean(t) + b, so the algebra is identical.)
    """
    m_chroma = chroma_matrix(saturation, hue_theta)
    hp = jax.lax.Precision.HIGHEST
    affine = contrast[:, None, None] * m_chroma
    o_pre = means * (1.0 - contrast[:, None]) + brightness[:, None]
    offset = jnp.einsum("bij,bj->bi", m_chroma, o_pre, precision=hp)
    return affine, offset


def _fused_kernel(m_ref, cb_ref, x_ref, out_ref, acc_ref, *, n_pixels):
    """Two-phase body of ``fused_normalize_color_jitter``: phase 0
    accumulates the raw uint8 channel sums of image ``b`` into VMEM
    scratch (zero padding contributes zero, so the true-pixel count
    ``n_pixels`` divides out exactly); phase 1 derives the per-image
    mean + affine from the scratch and streams the normalized, jittered
    pixels out. The grid is sequential on TPU (and in interpret mode),
    so phase 0 of an image always completes before its phase 1 reads
    the accumulator."""
    phase = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(phase == 0)
    def _accumulate():
        @pl.when(j == 0)
        def _reset():
            acc_ref[...] = jnp.zeros_like(acc_ref)

        raw = x_ref[0].astype(jnp.int32).astype(jnp.float32)  # [3, CHUNK]
        acc_ref[...] += jnp.sum(raw, axis=1, keepdims=True)

    @pl.when(phase == 1)
    def _apply():
        # channel_means_u8 semantics: mean of (x/127.5 - 1) over the
        # TRUE pixels = sum(u8)/(P*127.5) - 1.
        mean = acc_ref[...] * (1.0 / (n_pixels * 127.5)) - 1.0  # [3, 1]
        m = m_ref[0]  # [3, 3] chroma matrix
        c = cb_ref[0, 0, 0]  # contrast
        o_pre_r = mean[0, 0] * (1.0 - c) + cb_ref[0, 1, 0]
        o_pre_g = mean[1, 0] * (1.0 - c) + cb_ref[0, 1, 0]
        o_pre_b = mean[2, 0] * (1.0 - c) + cb_ref[0, 1, 0]
        x = x_ref[0].astype(jnp.int32).astype(jnp.float32)
        x = x * (1.0 / 127.5) - 1.0
        r, g, b = x[0], x[1], x[2]
        rows = []
        for ci in range(3):
            off = (
                m[ci, 0] * o_pre_r + m[ci, 1] * o_pre_g
                + m[ci, 2] * o_pre_b
            )
            rows.append(
                jnp.clip(
                    c * (m[ci, 0] * r + m[ci, 1] * g + m[ci, 2] * b)
                    + off,
                    -1.0,
                    1.0,
                )
            )
        out_ref[0] = jnp.stack(rows, axis=0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_normalize_color_jitter(
    images_u8: jnp.ndarray,  # [B, H, W, 3] uint8
    m_chroma: jnp.ndarray,  # [B, 3, 3] f32 — chroma_matrix output
    contrast: jnp.ndarray,  # [B] f32
    brightness: jnp.ndarray,  # [B] f32
    interpret: bool = False,
) -> jnp.ndarray:
    """ISSUE 11: normalize + color jitter with the per-image contrast
    means computed IN-KERNEL — the separate ``channel_means_u8`` XLA
    reduce pass over the uint8 batch disappears, leaving one fused
    Mosaic program per batch (``train.use_pallas_fused``).

    Same math as ``channel_means_u8`` + ``color_affine_from_params`` +
    ``fused_color_jitter`` (the affine is expanded in-kernel from the
    chroma matrix, contrast, brightness, and the accumulated mean);
    parity with the jnp composition is pinned to float tolerance in
    tests/test_mixedprec.py. Returns [B, H, W, 3] float32 in [-1, 1].
    """
    B, H, W, _ = images_u8.shape
    P = H * W
    P_pad = -(-P // _CHUNK) * _CHUNK
    x = jnp.transpose(images_u8, (0, 3, 1, 2)).reshape(B, 3, P)
    x = jnp.pad(x, ((0, 0), (0, 0), (0, P_pad - P)))
    cb = jnp.stack(
        [contrast.astype(jnp.float32), brightness.astype(jnp.float32)],
        axis=1,
    )[..., None]  # [B, 2, 1]

    from jax.experimental.pallas import tpu as pltpu

    out = pl.pallas_call(
        functools.partial(_fused_kernel, n_pixels=P),
        out_shape=jax.ShapeDtypeStruct((B, 3, P_pad), jnp.float32),
        grid=(B, 2, P_pad // _CHUNK),
        in_specs=[
            pl.BlockSpec((1, 3, 3), lambda b, ph, j: (b, 0, 0)),
            pl.BlockSpec((1, 2, 1), lambda b, ph, j: (b, 0, 0)),
            pl.BlockSpec((1, 3, _CHUNK), lambda b, ph, j: (b, 0, j)),
        ],
        # Phase 0 parks the (unwritten) out block on chunk 0; the block
        # index only changes — and the buffer only writes back — after
        # phase 1 has filled it.
        out_specs=pl.BlockSpec((1, 3, _CHUNK), lambda b, ph, j: (b, 0, j * ph)),
        scratch_shapes=[pltpu.VMEM((3, 1), jnp.float32)],
        interpret=interpret,
    )(m_chroma, cb, x)

    return jnp.transpose(out[:, :, :P].reshape(B, 3, H, W), (0, 2, 3, 1))


def channel_means_u8(images_u8: jnp.ndarray) -> jnp.ndarray:
    """Per-image channel means of (x/127.5 - 1), computed with a uint8->
    f32 reduce (XLA; cheap single pass) — the kernel needs them as inputs
    because contrast is defined about the image mean."""
    return images_u8.astype(jnp.float32).mean(axis=(1, 2)) / 127.5 - 1.0
