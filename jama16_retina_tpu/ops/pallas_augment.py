"""Pallas fused color-jitter kernel (SURVEY.md N13).

The color half of the augmentation pipeline — uint8 -> [-1,1] normalize,
brightness shift, contrast scale about the per-image mean, and the YIQ
saturation/hue rotation (data/augment.py) — is algebraically one affine
map per example:

    out_c = sum_k A[c,k] * (x_k / 127.5 - 1) + o[c]

with ``A = contrast * M_chroma`` and
``o = M_chroma @ (mean * (1 - contrast) + brightness)`` (M_chroma =
YIQ2RGB @ R(hue, sat) @ RGB2YIQ). XLA emits this as several fused loops
plus a reduce; this kernel does the whole thing in ONE pass over HBM:
uint8 pixels stream through VMEM once, 9 multiply-adds per pixel on the
VPU, f32 out. Geometric augmentations (flips/transpose) are pure layout
moves and stay in XLA where they fuse with the select.

Layout: channels-first ``[B, 3, P]`` with P = H*W padded to the lane
tile, so the per-channel rows sit in sublanes and the cross-channel
combination is three row reads — no strided channel gather.

Tested against the jnp reference in interpret mode on CPU
(tests/test_pallas.py); ``fused_color_jitter`` is used by
``augment_batch(..., use_pallas=True)`` on TPU.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

_LANE = 128
_CHUNK = 64 * _LANE  # pixels per grid step; 3x64x128 f32 ≈ 96 KiB of VMEM


def _kernel(a_ref, o_ref, x_ref, out_ref):
    # Mosaic has no direct uint8->f32 cast on TPU; stage through int32
    # (both legs are supported and exact for [0, 255]).
    x = x_ref[0].astype(jnp.int32).astype(jnp.float32)  # [3, CHUNK]
    x = x * (1.0 / 127.5) - 1.0
    a = a_ref[0]  # [3, 3]
    o = o_ref[0]  # [3, 1] (kept 2-D for SMEM-free VMEM layout)
    r, g, b = x[0], x[1], x[2]
    rows = []
    for c in range(3):
        rows.append(
            jnp.clip(
                a[c, 0] * r + a[c, 1] * g + a[c, 2] * b + o[c, 0],
                -1.0,
                1.0,
            )
        )
    out_ref[0] = jnp.stack(rows, axis=0)


@functools.partial(jax.jit, static_argnames=("interpret",))
def fused_color_jitter(
    images_u8: jnp.ndarray,  # [B, H, W, 3] uint8
    affine: jnp.ndarray,  # [B, 3, 3] f32 — A above
    offset: jnp.ndarray,  # [B, 3] f32 — o above
    interpret: bool = False,
) -> jnp.ndarray:
    """One-HBM-pass color jitter; returns [B, H, W, 3] float32 in [-1,1]."""
    B, H, W, _ = images_u8.shape
    P = H * W
    P_pad = -(-P // _CHUNK) * _CHUNK
    x = jnp.transpose(images_u8, (0, 3, 1, 2)).reshape(B, 3, P)
    x = jnp.pad(x, ((0, 0), (0, 0), (0, P_pad - P)))

    out = pl.pallas_call(
        _kernel,
        out_shape=jax.ShapeDtypeStruct((B, 3, P_pad), jnp.float32),
        grid=(B, P_pad // _CHUNK),
        in_specs=[
            pl.BlockSpec((1, 3, 3), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, 3, 1), lambda b, j: (b, 0, 0)),
            pl.BlockSpec((1, 3, _CHUNK), lambda b, j: (b, 0, j)),
        ],
        out_specs=pl.BlockSpec((1, 3, _CHUNK), lambda b, j: (b, 0, j)),
        interpret=interpret,
    )(affine, offset[..., None], x)

    return jnp.transpose(out[:, :, :P].reshape(B, 3, H, W), (0, 2, 3, 1))


def color_affine_from_params(
    means: jnp.ndarray,  # [B, 3] per-image channel means of (x/127.5 - 1)
    brightness: jnp.ndarray,  # [B]
    contrast: jnp.ndarray,  # [B]
    saturation: jnp.ndarray,  # [B]
    hue_theta: jnp.ndarray,  # [B] radians
) -> tuple[jnp.ndarray, jnp.ndarray]:
    """Collapse the augment params into (A [B,3,3], o [B,3]).

    Matches data/augment.py exactly: v = contrast*(t - mean) + mean +
    brightness, then YIQ chroma rotation M @ v. (The jnp path computes
    the contrast mean *after* brightness, but the mean of t + b is
    mean(t) + b, so the algebra is identical.)
    """
    from jama16_retina_tpu.data.augment import _RGB2YIQ, _YIQ2RGB

    B = means.shape[0]
    cos = jnp.cos(hue_theta) * saturation
    sin = jnp.sin(hue_theta) * saturation
    zeros = jnp.zeros((B,))
    ones = jnp.ones((B,))
    rot = jnp.stack(
        [
            jnp.stack([ones, zeros, zeros], -1),
            jnp.stack([zeros, cos, -sin], -1),
            jnp.stack([zeros, sin, cos], -1),
        ],
        axis=-2,
    )  # [B, 3, 3]
    # Decomposed as I + Minv (rot - I) M rather than Minv rot M: when the
    # drawn params are identity (s=1, theta=0 — e.g. all color flags off),
    # rot - I is exactly zero and the affine is exactly I, independent of
    # f32 rounding in the matrix inverse. The jnp path statically skips
    # the chroma block in that case, so exactness here is what keeps the
    # two paths bit-compatible.
    eye = jnp.eye(3, dtype=rot.dtype)
    hp = jax.lax.Precision.HIGHEST
    m_chroma = eye + jnp.einsum(
        "ij,bjk,kl->bil", _YIQ2RGB, rot - eye, _RGB2YIQ, precision=hp
    )
    affine = contrast[:, None, None] * m_chroma
    o_pre = means * (1.0 - contrast[:, None]) + brightness[:, None]
    offset = jnp.einsum("bij,bj->bi", m_chroma, o_pre, precision=hp)
    return affine, offset


def channel_means_u8(images_u8: jnp.ndarray) -> jnp.ndarray:
    """Per-image channel means of (x/127.5 - 1), computed with a uint8->
    f32 reduce (XLA; cheap single pass) — the kernel needs them as inputs
    because contrast is defined about the image mean."""
    return images_u8.astype(jnp.float32).mean(axis=(1, 2)) / 127.5 - 1.0
