"""Hand-written TPU kernels (SURVEY.md N13 — optional pallas perf slot)."""
