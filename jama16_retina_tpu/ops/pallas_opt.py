"""Fused Pallas optimizer update (ISSUE 11: the raw-speed train step).

The optax adamw chain (``scale_by_adam`` -> ``add_decayed_weights`` ->
``scale_by_learning_rate``) walks the parameter tree three times and
materializes an intermediate update tree between stages — on an
HBM-bound step that is several extra passes over params-sized arrays.
This kernel does the WHOLE update in one pass per leaf: ``(param, grad,
mu, nu)`` stream through VMEM once and ``(param', mu', nu')`` stream
out, with the Adam moment math, bias correction, decoupled weight
decay, and learning-rate scale applied element-wise on the VPU.

Contract: byte-compatible with ``optax.adamw(make_schedule(tc),
weight_decay=tc.weight_decay, mask=_decay_mask)`` — the SAME opt_state
pytree structure (``ScaleByAdamState``, ``MaskedState(EmptyState)``,
``ScaleByScheduleState``) goes in and comes out, so checkpoints, resume,
and donation never see which path computed the update. Numerics are
pinned against the optax reference in tests/test_mixedprec.py
(element-wise math in the same order; float-ulp tolerance).

Gated by ``train.use_pallas_fused`` (train_lib.validate_train_knobs
restricts it to unclipped adamw); transparently interprets off-TPU like
the augment kernel, so fused configs run anywhere.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import optax
from jax.experimental import pallas as pl

from jama16_retina_tpu.configs import TrainConfig

_LANE = 128
_BLOCK_ROWS = 256  # 256x128 f32 = 128 KiB/buffer; 8 buffers ~ 1 MiB VMEM

# optax.adamw defaults — make_optimizer passes only (schedule,
# weight_decay, mask), so these are the values the optax path runs.
_B1, _B2, _EPS = 0.9, 0.999, 1e-8


def _adamw_kernel(sc_ref, p_ref, g_ref, mu_ref, nu_ref,
                  out_p, out_mu, out_nu, *, wd: float):
    """One block of the fused update. ``sc_ref`` carries the traced
    scalars: [lr, 1/(1-b1^t), 1/(1-b2^t)]; ``wd`` is the leaf's
    effective decoupled weight decay (0.0 for mask-excluded leaves —
    train_lib._decay_mask's rank<2 set), baked statically."""
    lr = sc_ref[0, 0]
    c1 = sc_ref[0, 1]
    c2 = sc_ref[0, 2]
    g = g_ref[...]
    mu = _B1 * mu_ref[...] + (1.0 - _B1) * g
    nu = _B2 * nu_ref[...] + (1.0 - _B2) * g * g
    update = (mu * c1) / (jnp.sqrt(nu * c2) + _EPS)
    p = p_ref[...]
    if wd:
        update = update + wd * p
    out_p[...] = p - lr * update
    out_mu[...] = mu
    out_nu[...] = nu


def _leaf_update(p, g, mu, nu, scalars, wd: float, interpret: bool):
    """Fused update of one leaf: flatten -> lane-tile pad -> one grid
    pass -> unpad. Zero padding is self-consistent (0 grads keep 0
    moments and 0 params at 0: sqrt(0)+eps never divides by zero)."""
    shape, n = p.shape, p.size
    rows = -(-n // _LANE)
    block_rows = min(_BLOCK_ROWS, rows)
    rows_pad = -(-rows // block_rows) * block_rows

    def prep(x):
        flat = x.reshape(-1).astype(jnp.float32)
        flat = jnp.pad(flat, (0, rows_pad * _LANE - n))
        return flat.reshape(rows_pad, _LANE)

    grid = (rows_pad // block_rows,)
    out_p, out_mu, out_nu = pl.pallas_call(
        functools.partial(_adamw_kernel, wd=wd),
        out_shape=[
            jax.ShapeDtypeStruct((rows_pad, _LANE), jnp.float32)
        ] * 3,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 3), lambda i: (0, 0)),
            pl.BlockSpec((block_rows, _LANE), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, _LANE), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, _LANE), lambda i: (i, 0)),
            pl.BlockSpec((block_rows, _LANE), lambda i: (i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, _LANE), lambda i: (i, 0))
        ] * 3,
        interpret=interpret,
    )(scalars, prep(p), prep(g), prep(mu), prep(nu))

    def unpad(x):
        return x.reshape(-1)[:n].reshape(shape)

    return unpad(out_p), unpad(out_mu), unpad(out_nu)


def fused_adamw_update(tc: TrainConfig, params, grads, opt_state):
    """The ``train.use_pallas_fused`` twin of ``tx.update`` +
    ``optax.apply_updates`` for the adamw chain: returns ``(new_params,
    new_opt_state)`` with the optax state structure preserved exactly.

    Traced scalars (schedule LR at the schedule's own count, Adam bias
    corrections at count+1) are computed once in XLA and ride into the
    kernel as a 3-vector; everything params-shaped runs in the fused
    pass."""
    from jama16_retina_tpu import train_lib

    adam, masked, sched_state = opt_state
    count_inc = optax.safe_int32_increment(adam.count)
    t = count_inc.astype(jnp.float32)
    c1 = 1.0 / (1.0 - _B1 ** t)
    c2 = 1.0 / (1.0 - _B2 ** t)
    # scale_by_learning_rate reads the schedule at ITS pre-increment
    # count (optax.scale_by_schedule semantics).
    lr = train_lib.make_schedule(tc)(sched_state.count)
    scalars = jnp.stack(
        [jnp.asarray(lr, jnp.float32), c1, c2]
    ).reshape(1, 3)

    mask = train_lib._decay_mask(params)
    interpret = jax.default_backend() != "tpu"
    wd = float(tc.weight_decay)

    out = jax.tree.map(
        lambda p, g, m, v, decayed: _leaf_update(
            p, g, m, v, scalars, wd if decayed else 0.0, interpret
        ),
        params, grads, adam.mu, adam.nu, mask,
    )

    def pick(i):
        return jax.tree.map(
            lambda t3: t3[i], out,
            is_leaf=lambda x: isinstance(x, tuple),
        )

    new_params, new_mu, new_nu = pick(0), pick(1), pick(2)
    new_state = (
        optax.ScaleByAdamState(count=count_inc, mu=new_mu, nu=new_nu),
        masked,
        optax.ScaleByScheduleState(
            count=optax.safe_int32_increment(sched_state.count)
        ),
    )
    return new_params, new_state
