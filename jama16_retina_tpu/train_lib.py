"""Training core: state, loss, optimizer, jit'd train/eval steps.

Reference equivalent: the session loop inside ``train.py`` (SURVEY.md
§3.1/R1) — forward/backward, optimizer step, periodic validation. Here
the whole step is ONE XLA program (SURVEY.md §3.4): on-device uint8
normalize+augment, bf16 forward/backward, loss, gradient mean across the
data mesh axis, optimizer update, and global-batch BatchNorm moments.
Exactly one dispatch per step; the gradient/BN all-reduces ride ICI.

Two parallel forms are provided:

  * ``make_train_step`` — the primary path: ``jax.jit`` over global
    arrays with explicit in/out shardings on a 1-axis Mesh. XLA GSPMD
    derives the gradient all-reduce, and BatchNorm statistics are
    global-batch by construction (the batch is one logical array).
  * ``make_pmap_train_step`` — the explicit-collective form (per-replica
    ``lax.pmean`` on grads, BN with ``axis_name='data'``), kept as the
    reference semantics the jit path must match; the DP≡single-device
    test in tests/test_train.py pins the two together (SURVEY.md §4.3).

Loss (reference R1): sigmoid BCE for the binary referable-DR head,
softmax CE for the 5-class ICDR head (BASELINE.json:7,9), optional label
smoothing, plus the Inception aux-head loss at weight ``aux_weight``.
"""

from __future__ import annotations

from typing import Any, Callable

import flax.struct
import jax
import jax.numpy as jnp
import optax
from jax.sharding import PartitionSpec as P
from absl import logging as absl_logging

def _shard_map(f, mesh, in_specs, out_specs, axis_names=None,
               replicate_out_axes=()):
    """jax.shard_map across jax versions: the graduated API (>= 0.4.38)
    takes ``axis_names`` = the MANUAL axes; the jax.experimental form
    takes the complement as ``auto``. ``axis_names=None`` means fully
    manual on both.

    ``replicate_out_axes``: manual axes every OUTPUT leaf is replicated
    over without being mapped in its out_spec (manual_step's 'data'
    axis). The graduated VMA checker proves that replication through the
    optimizer update on its own; the legacy check_rep inference cannot,
    so on old jax the outputs are passed through a terminal
    ``lax.pmean`` over those axes — numerically identity on
    already-replicated values, and the one terminal op the legacy
    checker accepts as proof. (check_rep=False is NOT a usable escape:
    it changes the psum-transpose rule and silently rescales the
    gradients of the in-step loss pmean.)"""
    if hasattr(jax, "shard_map"):
        kwargs = {} if axis_names is None else {"axis_names": set(axis_names)}
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )
    from jax.experimental.shard_map import shard_map

    auto = (
        frozenset() if axis_names is None
        else frozenset(mesh.axis_names) - frozenset(axis_names)
    )
    g = f
    if replicate_out_axes:
        axes = tuple(replicate_out_axes)

        def _mark(x):
            # pmean divides, promoting int leaves to float — restrict to
            # inexact leaves. Integer counters (step, optax counts) are
            # replicated-input + 1 chains the checker infers unaided.
            if jnp.issubdtype(jnp.result_type(x), jnp.inexact):
                return jax.lax.pmean(x, axes)
            return x

        def g(*args):
            return jax.tree.map(_mark, f(*args))

    return shard_map(
        g, mesh=mesh, in_specs=in_specs, out_specs=out_specs, auto=auto
    )

from jama16_retina_tpu.configs import ExperimentConfig, TrainConfig
from jama16_retina_tpu.data import augment as augment_lib
from jama16_retina_tpu.parallel import mesh as mesh_lib


class DtypeCurveRejected(RuntimeError):
    """A non-fp32 training run drifted beyond ``train.dtype_curve_tol``
    of the pinned fp32 golden curve (``train.dtype_curve_ref``) — the
    train-side mirror of serve/quantize's DtypeRejected (PR 10): a
    cheaper numerics mode must PROVE quality parity or be refused, never
    silently shipped. Raised from the eval block of the flax train
    loops; the run stops with the violating step and both AUCs named."""


class RecipeCurveRejected(RuntimeError):
    """A large-batch recipe run (LAMB / scaled LR; ISSUE 14) drifted
    beyond ``train.recipe_curve_tol`` of the pinned baseline golden
    curve (``train.recipe_curve_ref`` — a metrics.jsonl from the
    accepted reference recipe). Same fail-closed contract as
    :class:`DtypeCurveRejected`: a recipe accepted on time-to-AUC must
    prove it still REACHES the AUC — drift is refused with the
    violating step and both AUCs named, never silently shipped."""


class TrainState(flax.struct.PyTreeNode):
    step: jnp.ndarray
    params: Any
    batch_stats: Any
    opt_state: Any
    # Polyak/EMA shadow of params (None when train.ema_decay == 0): a
    # quality lever toward the AUC target (SURVEY.md §6 note) — eval and
    # checkpoints carry it; eval prefers it when present. None is an
    # empty pytree subtree, so the off case costs nothing anywhere.
    ema_params: Any = None


def make_schedule(tc: TrainConfig) -> optax.Schedule:
    if tc.lr_schedule == "constant":
        return optax.constant_schedule(tc.learning_rate)
    if tc.lr_schedule == "cosine":
        return optax.cosine_decay_schedule(tc.learning_rate, tc.steps)
    if tc.lr_schedule == "warmup_cosine":
        # Validity clamp only: warmup must fit inside the run. Honors an
        # explicit warmup_steps whenever it is feasible, and says so when
        # it is not (short smoke runs with the 500-step default).
        warmup = max(1, min(tc.warmup_steps, tc.steps - 1))
        if warmup != tc.warmup_steps:
            absl_logging.warning(
                "warmup_steps=%d does not fit in steps=%d; clamped to %d",
                tc.warmup_steps, tc.steps, warmup,
            )
        return optax.warmup_cosine_decay_schedule(
            0.0, tc.learning_rate, warmup, tc.steps
        )
    raise ValueError(f"unknown lr_schedule {tc.lr_schedule!r}")


def _decay_mask(params) -> Any:
    """Weight decay only on rank>=2 kernels — BN scales/biases and dense
    biases are excluded (standard practice; the reference's slim arg scope
    likewise regularized conv weights only)."""
    return jax.tree.map(lambda p: p.ndim >= 2, params)


def make_optimizer(tc: TrainConfig) -> optax.GradientTransformation:
    sched = make_schedule(tc)
    if tc.optimizer == "adamw":
        opt = optax.adamw(sched, weight_decay=tc.weight_decay, mask=_decay_mask)
    elif tc.optimizer == "sgdm":
        opt = optax.chain(
            optax.add_decayed_weights(tc.weight_decay, mask=_decay_mask),
            optax.sgd(sched, momentum=tc.momentum, nesterov=True),
        )
    elif tc.optimizer == "rmsprop":
        # The reference's TF-Slim era default (RECALL) was RMSProp.
        opt = optax.chain(
            optax.add_decayed_weights(tc.weight_decay, mask=_decay_mask),
            optax.rmsprop(sched, decay=0.9, eps=1.0, momentum=tc.momentum),
        )
    elif tc.optimizer == "lamb":
        # Large-batch recipe (ISSUE 14): Adam moments + per-layer trust
        # ratio ("Training EfficientNets at Supercomputer Scale",
        # PAPERS.md) so a linearly-scaled LR stays sane layerwise at
        # global batches an order of magnitude above the reference.
        # optax-native: the optimizer state is the standard optax chain
        # structure, so checkpoints/resume are optimizer-family-
        # oblivious exactly like the fused adamw path (pinned by
        # tests/test_podscale.py's 3-step parity + state-structure
        # round-trip).
        opt = optax.lamb(
            sched, weight_decay=tc.weight_decay, mask=_decay_mask
        )
    else:
        raise ValueError(f"unknown optimizer {tc.optimizer!r}")
    if tc.gradient_clip_norm > 0:
        opt = optax.chain(optax.clip_by_global_norm(tc.gradient_clip_norm), opt)
    return opt


def init_variables(model, dummy: jnp.ndarray, rng: jax.Array):
    """Jit-compiled model.init — THE one home for init semantics (rng
    collections, train=False). Eager init dispatches one tiny XLA
    executable per primitive (minutes on the axon TPU for Inception-v3);
    one compiled program is seconds."""
    init_fn = jax.jit(
        lambda r: model.init({"params": r, "dropout": r}, dummy, train=False)
    )
    return init_fn(rng)


def create_state(
    cfg: ExperimentConfig, model, rng: jax.Array
) -> tuple[TrainState, optax.GradientTransformation]:
    size = cfg.model.image_size
    dummy = jnp.zeros((2, size, size, 3), jnp.float32)
    variables = init_variables(model, dummy, rng)
    tx = make_optimizer(cfg.train)
    state = TrainState(
        step=jnp.zeros((), jnp.int32),
        params=variables["params"],
        batch_stats=variables["batch_stats"],
        opt_state=tx.init(variables["params"]),
        # EMA shadow starts AT the init params (no debias term needed).
        ema_params=(
            jax.tree.map(jnp.copy, variables["params"])
            if cfg.train.ema_decay > 0 else None
        ),
    )
    return state, tx


def _bf16_params(params):
    """bfloat16 CAST of the float32 master weights — the mixed-precision
    forward/backward view (train.dtype=bf16). Only inexact leaves cast;
    the master tree is untouched (the optimizer keeps updating it in
    float32). Loss-scale-free: bf16 keeps float32's exponent range, so
    gradients cannot under/overflow the way fp16 ones do."""
    return jax.tree.map(
        lambda p: (
            p.astype(jnp.bfloat16)
            if jnp.issubdtype(p.dtype, jnp.floating) else p
        ),
        params,
    )


def _f32_grads(grads):
    """Gradients back to float32 before the optimizer — the other half
    of the master-weight discipline (a bf16 Adam moment would quantize
    the update direction every step)."""
    return jax.tree.map(
        lambda g: (
            g.astype(jnp.float32)
            if jnp.issubdtype(g.dtype, jnp.floating) else g
        ),
        grads,
    )


def validate_train_knobs(tc: TrainConfig) -> None:
    """Loud validation of the raw-speed knobs (ISSUE 11) shared by every
    step factory — a typo'd dtype or an unsupported fused-optimizer
    combination must refuse at construction, not mistrain silently."""
    if tc.dtype not in ("fp32", "bf16"):
        raise ValueError(
            f"unknown train.dtype {tc.dtype!r} (want fp32|bf16)"
        )
    if tc.accum_steps < 1:
        raise ValueError(
            f"train.accum_steps={tc.accum_steps} must be >= 1"
        )
    if tc.use_pallas_fused:
        if tc.optimizer != "adamw":
            raise ValueError(
                "train.use_pallas_fused implements the fused optimizer "
                f"update for adamw only (got {tc.optimizer!r}); unset "
                "the flag or switch optimizers"
            )
        if tc.gradient_clip_norm > 0:
            raise ValueError(
                "train.use_pallas_fused cannot compose with "
                "train.gradient_clip_norm (the fused kernel replaces "
                "the whole optax chain; the clip transform would be "
                "silently dropped) — disable one of the two"
            )


def global_batch(cfg: ExperimentConfig) -> int:
    """The recipe batch the optimizer sees per update: data.batch_size
    — which factors as accum_steps × per-forward device batch ×
    data-axis ways (train.accum_steps splits it into micro-batches
    inside the one jit step, the mesh's data axis shards each
    micro-batch across devices). THE one home for the definition the
    large-batch LR rule scales against."""
    return int(cfg.data.batch_size)


def resolve_large_batch(cfg: ExperimentConfig, mesh=None) -> ExperimentConfig:
    """Linear LR scaling tied to the global batch (ISSUE 14;
    ``train.lr_scale_ref_batch``): effective peak LR = learning_rate ×
    (global_batch / ref_batch), the Goyal-et-al. rule the large-batch
    literature (PAPERS.md) pairs with LAMB and a warmup schedule.

    A PURE function of (cfg, mesh) applied once at fit entry — resume
    re-derives the identical effective LR, and the factorization
    (accum × device batch × data ways) is logged so a recipe change is
    traceable in the run log. 0 (the default) returns cfg untouched:
    every existing pin rides the byte-identical config."""
    ref = int(cfg.train.lr_scale_ref_batch)
    if ref <= 0:
        return cfg
    gb = global_batch(cfg)
    scale = gb / ref
    ways = 1
    if mesh is not None:
        axis = mesh_lib._batch_axis(mesh)
        ways = int(mesh.shape[axis])
    accum = max(1, int(cfg.train.accum_steps))
    eff_lr = cfg.train.learning_rate * scale
    absl_logging.info(
        "large-batch recipe: global batch %d (= %d accum × %d device "
        "batch × %d data ways), LR %g × %.3g -> %g (%s)",
        gb, accum, gb // (accum * ways), ways,
        cfg.train.learning_rate, scale, eff_lr, cfg.train.optimizer,
    )
    if scale != 1.0 and cfg.train.lr_schedule not in (
        "warmup_cosine",
    ):
        absl_logging.warning(
            "lr_scale_ref_batch scaled the peak LR %.3gx under "
            "lr_schedule=%s — scaled-LR recipes want "
            "warmup_cosine (a cold start at the scaled LR is the "
            "classic large-batch divergence mode)",
            scale, cfg.train.lr_schedule,
        )
    import dataclasses

    return dataclasses.replace(
        cfg, train=dataclasses.replace(cfg.train, learning_rate=eff_lr)
    )


def _labels_from_grades(grades: jnp.ndarray, head: str) -> jnp.ndarray:
    if head == "binary":
        # ICDR grade >= 2 -> referable DR (reference R3 binning).
        return (grades >= 2).astype(jnp.float32)
    return grades.astype(jnp.int32)


def _head_loss(logits: jnp.ndarray, labels: jnp.ndarray, head: str,
               smoothing: float, mask: jnp.ndarray | None) -> jnp.ndarray:
    if head == "binary":
        target = labels * (1.0 - smoothing) + 0.5 * smoothing
        per_ex = optax.sigmoid_binary_cross_entropy(logits[:, 0], target)
    else:
        onehot = jax.nn.one_hot(labels, logits.shape[-1])
        if smoothing > 0:
            onehot = optax.smooth_labels(onehot, smoothing)
        per_ex = optax.softmax_cross_entropy(logits, onehot)
    if mask is None:
        return per_ex.mean()
    return (per_ex * mask).sum() / jnp.maximum(mask.sum(), 1.0)


def _probs(logits: jnp.ndarray, head: str) -> jnp.ndarray:
    if head == "binary":
        return jax.nn.sigmoid(logits[:, 0])
    return jax.nn.softmax(logits, axis=-1)


def _distill_loss(logits: jnp.ndarray, soft: jnp.ndarray,
                  head: str) -> jnp.ndarray:
    """Soft-target loss against the teacher ensemble's averaged scores
    (TrainConfig.distill_from; ISSUE 10 cascade): the binary head's BCE
    accepts a probability target directly, the multi head trains on the
    teacher's full [B, C] distribution. Label smoothing is deliberately
    NOT applied — the teacher's scores already carry the softness the
    student is meant to absorb."""
    if head == "binary":
        return optax.sigmoid_binary_cross_entropy(
            logits[:, 0], soft
        ).mean()
    return optax.softmax_cross_entropy(logits, soft).mean()


def loss_fn(params, batch_stats, model, images, grades, dropout_rng,
            cfg: ExperimentConfig, train: bool, soft=None):
    labels = _labels_from_grades(grades, cfg.model.head)
    variables = {"params": params, "batch_stats": batch_stats}
    if train:
        (logits, aux), mutated = model.apply(
            variables, images, train=True, mutable=["batch_stats"],
            rngs={"dropout": dropout_rng},
        )
        new_stats = mutated["batch_stats"]
    else:
        logits, aux = model.apply(variables, images, train=False)
        new_stats = batch_stats
    if cfg.train.dtype == "bf16":
        # Mixed precision stops at the head: the loss reduction runs in
        # float32 (bf16's 8-bit mantissa is too coarse for log-prob
        # sums). A no-op on the fp32 path, where the heads already emit
        # float32 — so the existing bit-identity pins never see it.
        logits = logits.astype(jnp.float32)
        if aux is not None:
            aux = aux.astype(jnp.float32)
    if soft is not None:
        # Distillation (train.distill_from): the student's target is the
        # teacher's soft score, hard grades untouched (they still ride
        # the batch for eval-side AUC).
        loss = _distill_loss(logits, soft, cfg.model.head)
        if aux is not None:
            loss = loss + cfg.model.aux_weight * _distill_loss(
                aux, soft, cfg.model.head
            )
        return loss, (logits, new_stats)
    smoothing = cfg.train.label_smoothing
    loss = _head_loss(logits, labels, cfg.model.head, smoothing, None)
    if aux is not None:
        loss = loss + cfg.model.aux_weight * _head_loss(
            aux, labels, cfg.model.head, smoothing, None
        )
    return loss, (logits, new_stats)


def _step_impl(state: TrainState, batch: dict, base_key: jax.Array,
               model, cfg: ExperimentConfig, augment_key_extra=None,
               loss_axis: "str | None" = None):
    """Shared body for the jit and pmap step forms.

    ``loss_axis`` (the shard_map manual-data form): pmean the scalar loss
    over that axis INSIDE the differentiated function, yielding the
    global-batch gradient directly — under ``jax.shard_map`` a collective
    in the forward (the axis_name BN moments) makes the raw local-loss
    grads come back already cross-shard-summed (psum-self-transpose
    semantics; a post-grad pmean then over-counts by the axis size — a
    bug this option exists to prevent, pinned by
    test_manual_data_step_matches_auto_data). Under ``jax.pmap`` the AD
    semantics differ and the classic local-grads-then-pmean recipe of
    make_pmap_train_step is exact (pinned by TestDPEquivalence); the two
    recipes are NOT interchangeable across the two tracers."""
    debug = cfg.train.debug
    if debug:
        # chex asserts under --debug (SURVEY.md §5.2): trace-time
        # shape/dtype pins on the step's input contract.
        import chex

        chex.assert_rank(batch["image"], 4)
        chex.assert_type(batch["image"], jnp.uint8)
        chex.assert_rank(batch["grade"], 1)
        chex.assert_equal_shape_prefix(
            [batch["image"], batch["grade"]], 1
        )
        chex.assert_axis_dimension(
            batch["image"], 1, cfg.model.image_size
        )
    key = jax.random.fold_in(base_key, state.step)
    if augment_key_extra is not None:
        key = jax.random.fold_in(key, augment_key_extra)
    aug_key, dropout_key = jax.random.split(key)
    images = augment_lib.augment_batch(
        aug_key, batch["image"], cfg.data, debug=debug,
        fused=cfg.train.use_pallas_fused,
    )
    if debug:
        import chex

        chex.assert_type(images, jnp.float32)
        chex.assert_equal_shape([images, batch["image"]])

    # Teacher soft targets ride the batch dict when distillation is on
    # (trainer wraps the stream); absent key = the hard-label default,
    # so every other step form is byte-for-byte unchanged.
    soft = batch.get("soft")

    fn = loss_fn
    if loss_axis is not None:
        def fn(params, batch_stats, model, images, grades, dropout_rng,
               cfg, train, soft=None):
            loss, aux = loss_fn(
                params, batch_stats, model, images, grades, dropout_rng,
                cfg, train, soft=soft,
            )
            return jax.lax.pmean(loss, loss_axis), aux

    grad_fn = jax.value_and_grad(fn, has_aux=True)
    # Mixed precision (train.dtype=bf16; ISSUE 11): forward/backward
    # differentiate a bf16 CAST of the params; the float32 masters in
    # ``state`` are what the optimizer updates. fp32 leaves the tree
    # untouched, so the existing golden pins ride the identical program.
    params = (
        _bf16_params(state.params) if cfg.train.dtype == "bf16"
        else state.params
    )
    accum = cfg.train.accum_steps
    if accum <= 1:
        (loss, (logits, new_stats)), grads = grad_fn(
            params, state.batch_stats, model, images, batch["grade"],
            dropout_key, cfg, True, soft,
        )
        return loss.astype(jnp.float32), logits, new_stats, _f32_grads(grads)

    # Gradient accumulation (train.accum_steps): the RECIPE batch was
    # augmented above in one draw (identical pixels to accum=1); it now
    # splits into ``accum`` sequential micro-batches inside this same
    # program — per-forward activation memory drops by accum× while the
    # optimizer still sees one recipe-batch update. Grads accumulate in
    # float32 regardless of train.dtype (master-weight discipline);
    # BatchNorm normalizes by micro-batch moments (ghost batch norm)
    # and its running stats thread through the scan in micro order.
    n = images.shape[0]
    if n % accum != 0:
        raise ValueError(
            f"train.accum_steps={accum} must divide the batch size "
            f"{n} evenly"
        )
    micro = n // accum

    def _split(x):
        return x.reshape((accum, micro) + x.shape[1:])

    xs = (
        _split(images),
        _split(batch["grade"]),
        None if soft is None else _split(soft),
        jax.random.split(dropout_key, accum),
    )
    zero_grads = jax.tree.map(
        lambda p: jnp.zeros(p.shape, jnp.float32), params
    )

    def body(carry, x):
        stats, acc = carry
        imgs_m, grades_m, soft_m, dk = x
        (l, (_, new_st)), g = grad_fn(
            params, stats, model, imgs_m, grades_m, dk, cfg, True, soft_m,
        )
        acc = jax.tree.map(
            lambda a, gi: a + gi.astype(jnp.float32) * (1.0 / accum),
            acc, g,
        )
        return (new_st, acc), l.astype(jnp.float32)

    (new_stats, grads), losses = jax.lax.scan(
        body, (state.batch_stats, zero_grads), xs
    )
    # Equal-size micros: the mean of micro-mean losses IS the recipe-
    # batch mean loss, and the accumulated grads are its gradient —
    # pinned N×micro ≡ 1×full-batch in tests/test_mixedprec.py.
    return losses.mean(), None, new_stats, grads


def _apply_update(
    state: TrainState, grads, new_stats, tx, tc: TrainConfig
) -> TrainState:
    ema_decay = tc.ema_decay
    if tc.use_pallas_fused:
        # Fused optimizer update (ISSUE 11; ops/pallas_opt.py): one
        # kernel pass per leaf over (param, grad, mu, nu) replaces the
        # optax tree-map chain. Same math, same opt_state structure —
        # checkpoints and resume are oblivious (pinned vs optax in
        # tests/test_mixedprec.py). validate_train_knobs already
        # restricted this path to unclipped adamw.
        from jama16_retina_tpu.ops import pallas_opt

        new_params, new_opt = pallas_opt.fused_adamw_update(
            tc, state.params, grads, state.opt_state
        )
    else:
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        new_params = optax.apply_updates(state.params, updates)
    ema = state.ema_params
    if ema is not None and ema_decay > 0:
        ema = jax.tree.map(
            lambda e, p: e * ema_decay + p * (1.0 - ema_decay),
            ema, new_params,
        )
    return TrainState(
        step=state.step + 1,
        params=new_params,
        batch_stats=new_stats,
        opt_state=new_opt,
        ema_params=ema,
    )


def _mesh_devices(mesh) -> int:
    return mesh.size if mesh is not None else 1


def _pallas_safe_cfg(cfg: ExperimentConfig, mesh, context: str):
    """Route augmentation off the Mosaic kernel on multi-device meshes.

    Mosaic (pallas-TPU) kernels cannot be automatically partitioned by
    GSPMD (jax raises NotImplementedError at lowering), so any step
    compiled over a >1-device mesh must use the jnp augment composition
    instead — same math (ops/pallas_augment.py is pinned against it),
    and XLA fuses and partitions the jnp form freely. Single-device
    programs (every bench/artifact on this one-chip host) keep the
    kernel. Logged so a multi-chip run's ~2% end-to-end delta is
    traceable to this routing.

    ``train.use_pallas_fused`` (ISSUE 11) routes off under exactly the
    same condition — the fused normalize+augment and fused optimizer
    kernels are Mosaic programs too."""
    pallas_on = cfg.data.use_pallas or cfg.train.use_pallas_fused
    if not (pallas_on and _mesh_devices(mesh) > 1):
        return cfg
    import dataclasses

    absl_logging.info(
        "%s: use_pallas/use_pallas_fused routed to the jnp/optax "
        "compositions on a %d-device mesh (Mosaic kernels cannot be "
        "auto-partitioned)",
        context, _mesh_devices(mesh),
    )
    return dataclasses.replace(
        cfg,
        data=dataclasses.replace(cfg.data, use_pallas=False),
        train=dataclasses.replace(cfg.train, use_pallas_fused=False),
    )


def make_train_step(
    cfg: ExperimentConfig, model, tx, mesh=None, donate: bool = True
) -> Callable:
    """The primary jit path over global arrays (SURVEY.md §3.4).

    With ``mesh``: state replicated, batch sharded on dim 0; XLA GSPMD
    inserts the gradient all-reduce (grads of replicated params w.r.t. a
    sharded batch loss) and BN sees the global batch. Donation keeps the
    replicated state buffer in place across steps; pass ``donate=False``
    under jax_debug_nans, whose op-by-op re-execution needs the inputs
    to still be alive.
    """
    validate_train_knobs(cfg.train)
    cfg = _pallas_safe_cfg(cfg, mesh, "train step")

    def step(state: TrainState, batch: dict, base_key: jax.Array):
        loss, logits, new_stats, grads = _step_impl(
            state, batch, base_key, model, cfg
        )
        new_state = _apply_update(
            state, grads, new_stats, tx, cfg.train
        )
        return new_state, {"loss": loss}

    donate_argnums = (0,) if donate else ()
    if mesh is None:
        return jax.jit(step, donate_argnums=donate_argnums)
    repl = mesh_lib.replicated(mesh)
    data = mesh_lib.batch_sharding(mesh)
    return jax.jit(
        step,
        in_shardings=(repl, data, repl),
        out_shardings=(repl, repl),
        donate_argnums=donate_argnums,
    )


def aot_compile_step(step_fn, *args,
                     program: str = "train_step",
                     ) -> "tuple[Callable, float | None]":
    """AOT-compile a jitted step at these exact args; returns
    ``(callable, flops_per_call | None)``.

    The train loops compile through this instead of first-dispatch jit
    so the compiled program's cost_analysis FLOPs are available for
    free (one compile either way — AOT and dispatch share the
    persistent compilation cache when ``--jit_cache_dir`` is set, and
    the dispatch path is simply never taken afterwards). Those FLOPs
    give the throughput clock its physics ceiling, the same guard
    bench.py applies to every published rate (utils/physics.py).

    The compile is timed into the device plane's compile ledger and the
    program registers in the program ledger under ``program`` (ISSUE
    19): the ledger entry is the ONE cost_analysis parse the trainer's
    physics ceiling AND the MFU gauges both read — the returned FLOPs
    are exactly ``entry.flops``, so the two can never disagree.

    Any failure falls back to the jit dispatch path with FLOPs unknown
    (the clock then publishes unguarded, exactly round-3 behavior).
    Shapes are static by design, so later calls can never miss the
    compiled signature.
    """
    from jama16_retina_tpu.obs import device as device_lib

    try:
        with device_lib.compile_timed(program):
            compiled = step_fn.lower(*args).compile()
    except Exception as e:  # pragma: no cover - environment-dependent
        import logging

        logging.getLogger(__name__).warning(
            "AOT compile unavailable (%s: %s); falling back to jit "
            "dispatch, throughput clock unguarded", type(e).__name__, e)
        return step_fn, None
    # register swallows cost_analysis failures internally (entry costs
    # just stay None): they must not discard the finished executable —
    # re-dispatching through jit would compile the whole step a second
    # time (~40-80 s for the flagship without a persistent cache).
    entry = device_lib.program_ledger().register(program, compiled=compiled)
    return compiled, entry.flops


def make_pmap_train_step(cfg: ExperimentConfig, model, tx, axis: str = "data"):
    """Explicit-collective DP form (SURVEY.md N7): per-replica grads are
    ``lax.pmean``'d; the model must be built with ``axis_name=axis`` so BN
    moments psum over replicas (N8). Used by tests to pin the jit path's
    semantics; state is replicated per-device, batch is [n_dev, B/n_dev, ...].
    """
    validate_train_knobs(cfg.train)

    def step(state: TrainState, batch: dict, base_key: jax.Array):
        loss, logits, new_stats, grads = _step_impl(
            state, batch, base_key, model, cfg,
            augment_key_extra=jax.lax.axis_index(axis),
        )
        grads = jax.lax.pmean(grads, axis)
        loss = jax.lax.pmean(loss, axis)
        new_state = _apply_update(
            state, grads, new_stats, tx, cfg.train
        )
        return new_state, {"loss": loss}

    # state/batch are per-device stacked; the PRNG key is broadcast.
    return jax.pmap(step, axis_name=axis, in_axes=(0, 0, None))


def eval_params(state: TrainState):
    """The params eval scores with: the EMA shadow when carried (it is
    the paper-quality model of record; train keeps optimizing the raw
    params), else the raw params. THE one copy of this preference —
    every backend/entry point must score the same weights for the same
    checkpoint."""
    return state.params if state.ema_params is None else state.ema_params


def _eval_probs(
    state: TrainState, images: jnp.ndarray, model, cfg: ExperimentConfig
) -> jnp.ndarray:
    """Normalized images -> per-example probabilities for ONE model.

    With ``cfg.eval.tta``, flip-averaged TTA stacks the 4 views on a
    leading axis and ``lax.map``s so the backbone is traced/compiled ONCE
    (4 sequential passes), not inlined 4x into one giant program.
    """
    variables = {"params": eval_params(state), "batch_stats": state.batch_stats}

    def forward(x):
        logits, _ = model.apply(variables, x, train=False)
        return _probs(logits, cfg.model.head)

    if not cfg.eval.tta:
        return forward(images)
    views = jnp.stack([
        images,
        images[:, :, ::-1],
        images[:, ::-1, :],
        images[:, ::-1, ::-1],
    ])
    probs = jax.lax.map(forward, views)
    return probs.mean(axis=0)


def make_eval_step(cfg: ExperimentConfig, model, mesh=None) -> Callable:
    """Masked forward pass -> per-example probabilities (SURVEY.md §3.2).

    Returns host-gatherable probs; padding rows (mask==0) are kept in the
    output and must be trimmed by the caller — that keeps the jit shape
    static across the final partial batch.
    """

    def step(state: TrainState, batch: dict):
        return _eval_probs(
            state, augment_lib.normalize(batch["image"]), model, cfg
        )

    if mesh is None:
        return jax.jit(step)
    repl = mesh_lib.replicated(mesh)
    data = mesh_lib.batch_sharding(mesh)
    return jax.jit(step, in_shardings=(repl, data), out_shardings=repl)


# ---------------------------------------------------------------------------
# Member-parallel ensemble training (TrainConfig.ensemble_parallel)
# ---------------------------------------------------------------------------
#
# The reference trains its k-model ensemble as k sequential runs (R11).
# The members are INDEPENDENT replicas — no communication between them
# ever — so TPU-natively they stack on a leading member axis: one vmapped
# XLA program trains all k at once, and on a ('member', 'data') mesh
# (mesh_lib.make_ensemble_mesh) GSPMD shards the stacked arrays across
# chips with zero cross-member collectives. Single-chip the stacked step
# measures ~parity with sequential members (bench
# `ensemble4_parallel_speedup` — weight/optimizer HBM traffic scales
# with members); the payoff is on pods, where member groups train with
# fewer DP ways each (higher per-chip batch, docs/PERF.md) and no
# allreduce crosses member groups.
#
# Semantics vs the sequential driver: member m keeps its seed
# (train.seed + m) for init/augment/dropout — identical marginal
# randomness — but all members see ONE batch stream (seed = train.seed)
# instead of k independently shuffled streams. Ensemble diversity in
# this protocol comes overwhelmingly from init and augmentation draws;
# the sequential driver remains available (and is the paper-parity
# form) by leaving ensemble_parallel off.


def stack_member_keys(seeds: "list[int]", mesh=None) -> jax.Array:
    """[k] stacked PRNG key vector, one key per member seed — the vmapped
    twin of the sequential driver's ``base_key = jax.random.key(seed)``.
    The ONE home for member-key construction: create_ensemble_state's
    init keys and the train loop's base keys must come from the same
    expression or member m's stream diverges from a sequential run.

    With ``mesh``, the keys are computed INSIDE a jit with member-axis
    out-shardings — on multi-host meshes a host-built stacked array
    cannot be device_put to a sharding spanning non-addressable devices,
    but a jit closing over the host seeds can produce it directly.
    ``vmap(jax.random.key)`` over uint32 seeds equals the stacked
    per-seed keys (threefry seeding's high word is zero for both;
    pinned by tests/test_ensemble_parallel.py's stacked≡sequential run).
    """
    if mesh is None:
        return jnp.stack([jax.random.key(int(s)) for s in seeds])
    import numpy as np

    seeds_arr = np.asarray([int(s) for s in seeds], np.uint32)
    return jax.jit(
        lambda: jax.vmap(jax.random.key)(jnp.asarray(seeds_arr)),
        out_shardings=mesh_lib.member_sharding(mesh),
    )()


def create_ensemble_state(
    cfg: ExperimentConfig, model, seeds: "list[int]", mesh=None
) -> tuple[TrainState, optax.GradientTransformation]:
    """Stacked TrainState: every leaf gains a leading [k] member dim.

    Member m's slice is bit-identical to ``create_state`` under seed
    ``seeds[m]`` (the vmapped init consumes the same per-member key).

    With ``mesh``, the whole state is built in ONE jit with member-axis
    out-shardings: the init computes directly into the member-sharded
    global layout — each host initializes only its members, and no
    host-side stacked copy exists (required on multi-host meshes, where
    device_put cannot place host arrays across processes).
    """
    size = cfg.model.image_size
    dummy = jnp.zeros((2, size, size, 3), jnp.float32)
    tx = make_optimizer(cfg.train)
    import numpy as np

    seeds_arr = np.asarray([int(s) for s in seeds], np.uint32)

    def build():
        keys = jax.vmap(jax.random.key)(jnp.asarray(seeds_arr))
        variables = jax.vmap(
            lambda r: model.init(
                {"params": r, "dropout": r}, dummy, train=False
            )
        )(keys)
        return TrainState(
            step=jnp.zeros((len(seeds),), jnp.int32),
            params=variables["params"],
            batch_stats=variables["batch_stats"],
            opt_state=jax.vmap(tx.init)(variables["params"]),
            ema_params=(
                jax.tree.map(jnp.copy, variables["params"])
                if cfg.train.ema_decay > 0 else None
            ),
        )

    if mesh is None:
        state = jax.jit(build)()
    else:
        state = jax.jit(
            build, out_shardings=mesh_lib.member_sharding(mesh)
        )()
    return state, tx


def unstack_member(state: TrainState, m: int) -> TrainState:
    """Member m's single-model TrainState (for per-member checkpoints —
    the on-disk layout stays identical to the sequential driver's)."""
    return jax.tree.map(lambda x: x[m], state)


def make_ensemble_train_step(
    cfg: ExperimentConfig, model, tx, mesh=None, donate: bool = True,
    manual_data: bool = False,
) -> Callable:
    """One XLA program advancing all k stacked members one step.

    ``base_keys`` is the [k] key vector (member m's key = the sequential
    driver's base key under seed+m); each member folds its own key with
    its own step counter, so augmentation and dropout draws are
    independent across members exactly as in k separate runs. With a
    ('member', 'data') mesh, state shards P('member') on the stacked dim
    and the batch P('data') on dim 0 — every chip holds k/member_size
    members and sees the batch rows of its data-axis block.

    ``manual_data`` (TrainConfig.ensemble_manual_data) makes the data
    axis manual too: the whole step runs under ``jax.shard_map`` with
    BOTH mesh axes manual, so every collective is explicit — one
    ``lax.pmean`` for weight grads + loss, and the model's ``axis_name=
    'data'`` BatchNorm pmeans its moments (the caller MUST build the
    model with ``axis_name='data'``; make_pmap_train_step semantics,
    now per member). Nothing is left to GSPMD's partitioner, which on
    big meshes otherwise emits generic activation collectives (the
    n>16 CPU-dryrun wall; docs/MULTIHOST.md). Augment/dropout draws
    fold in the data-shard index exactly like the pmap reference form,
    so draws differ from the auto-data path's global-batch draws —
    same distribution, different stream (both are valid training
    randomness; parity tests compare with augmentation off).
    """
    validate_train_knobs(cfg.train)
    if cfg.train.use_pallas_fused:
        # The stacked-member vmap would have to batch every Mosaic
        # kernel launch (vmap-of-pallas_call); the fused path is a
        # single-model step optimization — refuse rather than ship an
        # untested lowering.
        raise ValueError(
            "train.use_pallas_fused is a single-model step path; the "
            "member-parallel ensemble step vmaps the whole step and "
            "cannot batch the Mosaic kernels — unset one of the two"
        )
    cfg = _pallas_safe_cfg(cfg, mesh, "ensemble train step")
    if manual_data:
        if mesh is None or "data" not in mesh.axis_names:
            raise ValueError(
                "manual_data needs a ('member', 'data') mesh"
            )
        if getattr(model, "axis_name", None) != "data":
            raise ValueError(
                "manual_data runs BatchNorm inside a manual data axis: "
                "build the model with models.build(cfg.model, "
                "axis_name='data') so its moments pmean over the mesh"
            )
        if cfg.data.use_pallas:
            # Even on a 1-device mesh: Mosaic out_shapes are rejected by
            # the shard_map VMA checker (same reason _pallas_safe_cfg
            # exists for >1-device GSPMD meshes).
            import dataclasses

            cfg = dataclasses.replace(
                cfg, data=dataclasses.replace(cfg.data, use_pallas=False)
            )

    def step(state: TrainState, batch: dict, base_keys: jax.Array):
        def one(st, bk):
            loss, _, new_stats, grads = _step_impl(st, batch, bk, model, cfg)
            return (
                _apply_update(st, grads, new_stats, tx, cfg.train),
                loss,
            )

        new_state, losses = jax.vmap(one)(state, base_keys)
        return new_state, {"loss": losses}

    donate_argnums = (0,) if donate else ()
    if mesh is None:
        return jax.jit(step, donate_argnums=donate_argnums)

    def manual_step(state: TrainState, batch: dict, base_keys: jax.Array):
        # BOTH axes manual. Each shard holds k/member_size whole members
        # and its data-block's batch rows; per member: local fwd/bwd of
        # the loss pmean'd over 'data' INSIDE the grad (loss_axis — the
        # gradient all-reduce rides the loss pmean's backward psum; a
        # post-grad pmean would double-count, see _step_impl), BN
        # moments pmean'd inside the model. The only collectives in the
        # program are those pmeans — exactly what a real pod runs over
        # ICI, nothing partitioner-derived.
        def shard_fn(st_local, batch_local, keys_local):
            def one(st, bk):
                loss, _, new_stats, grads = _step_impl(
                    st, batch_local, bk, model, cfg,
                    augment_key_extra=jax.lax.axis_index("data"),
                    loss_axis="data",
                )
                return (
                    _apply_update(
                        st, grads, new_stats, tx, cfg.train
                    ),
                    loss,
                )

            new_st, losses = jax.vmap(one)(
                st_local, jax.random.wrap_key_data(keys_local)
            )
            return new_st, {"loss": losses}

        # Keys cross the shard_map boundary as RAW uint32 data
        # (key_data/wrap_key_data round-trip, numerically identity):
        # older jax partitioners reject extended PRNG-key dtypes at a
        # manual-axis boundary ("tile assignment dimensions ... different
        # than the input rank" on u32[k,2]).
        return _shard_map(
            shard_fn, mesh=mesh,
            in_specs=(P("member"), P("data"), P("member")),
            out_specs=(P("member"), P("member")),
            replicate_out_axes=("data",),
        )(state, batch, jax.random.key_data(base_keys))

    def sharded_step(state: TrainState, batch: dict, base_keys: jax.Array):
        # The member axis is MANUAL (jax.shard_map): each member-shard
        # vmaps only its local members (the unsharded ``step`` above,
        # reused verbatim so the two paths cannot diverge), whose
        # weights live whole on the shard — under plain GSPMD, XLA's
        # batched-conv strategy instead ALL-GATHERS the member-stacked
        # kernels every step (~1300 extra all-gathers at
        # ('member':2,'data':8); docs/MULTIHOST.md
        # §Ensemble-collectives). The data axis stays automatic, so the
        # batch-dim BN reductions and weight grads compile to the same
        # data-axis all-reduces as the single-model jit step. ``batch``
        # is closed over rather than passed through: it is unsharded on
        # the manual axis ('data' is auto), which closure capture
        # expresses exactly.
        # Same raw-key-data boundary crossing as manual_step (older jax
        # partitioners reject key dtypes at manual-axis boundaries).
        return _shard_map(
            lambda st_local, keys_local: step(
                st_local, batch, jax.random.wrap_key_data(keys_local)
            ),
            mesh=mesh, axis_names={"member"},
            in_specs=(P("member"), P("member")),
            out_specs=(P("member"), P("member")),
        )(state, jax.random.key_data(base_keys))

    # A 1-device mesh gains nothing from manual axes and would lose the
    # Mosaic augment kernel (see _pallas_safe_cfg) — keep the plain
    # vmapped jit there (this host's bench/artifact form); the
    # shard_map form engages exactly where its gathers-elimination
    # matters, on real multi-device meshes.
    if manual_data:
        # Also on 1-device meshes: the model's axis_name='data' BN needs
        # the manual axis in scope (sizes are 1, the pmeans are no-ops).
        step_fn = manual_step
    elif _mesh_devices(mesh) == 1:
        step_fn = step
    else:
        step_fn = sharded_step
    member = mesh_lib.member_sharding(mesh)
    data = mesh_lib.batch_sharding(mesh)
    # Metrics stay MEMBER-SHARDED whenever one process owns the whole
    # mesh: every shard is addressable, device_get assembles [k] on host
    # with no collective at all. The replicated form (a [k]-float
    # all-gather) exists ONLY because multi-host device_get needs fully-
    # addressable arrays — and that all-gather was this repo's one
    # scale-fragile collective (XLA's CPU AllGatherThunk aborts natively
    # at 16 fake devices; a 20 s rendezvous stall at 8 — VERDICT r3
    # weak #4), so it is paid only where it is load-bearing.
    metric_sharding = (
        mesh_lib.replicated(mesh) if jax.process_count() > 1 else member
    )
    return jax.jit(
        step_fn,
        in_shardings=(member, data, member),
        out_shardings=(member, metric_sharding),
        donate_argnums=donate_argnums,
    )


def stack_states(states: "list[TrainState]") -> TrainState:
    """Stack k restored single-member TrainStates into the stacked [k]
    layout (the inverse of ``unstack_member``) — the serving engine's
    restore-once path (serve/engine.py): k member checkpoints become ONE
    device-resident parameter tree, scored by one stacked forward per
    batch instead of k restore+forward passes.

    ``opt_state`` is dropped (None): serving never steps the optimizer,
    and k stacked Adam moments would roughly triple the ensemble's HBM
    residency for nothing. Members must agree on whether they carry an
    EMA shadow (same run protocol); a mismatch fails loudly as a pytree
    structure error rather than silently scoring mixed weights.
    """
    if not states:
        raise ValueError("need at least one member state")
    states = [s.replace(opt_state=None) for s in states]
    return jax.tree.map(lambda *xs: jnp.stack(xs), *states)


def make_serving_step(
    cfg: ExperimentConfig, model, mesh=None, member_parallel: bool = False,
    param_transform: "Callable | None" = None,
) -> Callable:
    """Stacked-state forward for the serving engine (serve/engine.py):
    ``(stacked state [k], {'image': u8[B,S,S,3]}) -> probs [k, B(, C)]``.

    ``member_parallel=False`` (default): members run under ``lax.map`` —
    still ONE dispatch per batch (the k passes live inside the program;
    no host round-trip or re-restore between members), and each member's
    loop-body computation compiles to the same program a single-member
    ``make_eval_step`` runs, so member m's rows are BIT-IDENTICAL to the
    sequential restore+forward path at the same batch shape (pinned by
    tests/test_serve.py — the serving rewire's parity contract; the
    vmapped form batches convs across members, which reassociates and
    drifts at float-ulp level on some arch/shape/dtype combos).

    ``member_parallel=True``: ``vmap`` over members — the
    make_ensemble_eval_step body, float-equivalent (rtol ~2e-5), higher
    arithmetic intensity when members are small. Serving meshes here are
    DATA meshes (state replicated, batch sharded on dim 0, like
    make_eval_step); member-axis sharding stays the training-side
    make_ensemble_eval_step's job.

    ``param_transform`` (ISSUE 10 serve.dtype): applied to the stacked
    state INSIDE the one serving program — the int8 path's dequantize
    (serve/quantize.py), so device residency stays int8+scales and the
    dequant fuses into the forward instead of costing a second dispatch.
    None (the default) leaves the program byte-identical to before the
    hook existed.

    Member-sharded serving (ISSUE 14): a ('member', data) mesh
    (mesh_lib.make_serve_mesh with ``parallel.member_axis_size`` > 1)
    shards the STACKED state across the member axis — each device
    group forwards only its local members (manual member axis via
    shard_map, reusing the same ``step`` body so the two paths cannot
    diverge; the same gathers-elimination rationale as
    make_ensemble_eval_step), while batch rows shard over the data
    axis. This is what finally amortizes ensemble serving across a pod
    slice: k members on an m-way member axis pay k/m member-forwards
    of wall-clock per batch.

    Same EMA/TTA semantics as every other eval surface (_eval_probs).
    """
    cfg = _pallas_safe_cfg(cfg, mesh, "serving step")
    member_sharded = mesh_lib.has_member_axis(mesh)

    def step(state: TrainState, batch: dict):
        if param_transform is not None:
            state = param_transform(state)
        images = augment_lib.normalize(batch["image"])

        def fwd(st):
            return _eval_probs(st, images, model, cfg)

        # A member-sharded mesh serves the vmapped member form per
        # shard regardless of serve.member_parallel: it IS the
        # pod-serving form that flag documents (float-equivalent, not
        # bit-equal — the lax.map scan body is rejected by the manual-
        # axis partitioner), and the engine's bit-identity pins all
        # ride mesh-less / data-mesh engines, which keep lax.map.
        if member_parallel or member_sharded:
            return jax.vmap(fwd)(state)
        return jax.lax.map(fwd, state)

    if mesh is None:
        return jax.jit(step)
    data = mesh_lib.batch_sharding(mesh)
    if member_sharded:
        def member_sharded_step(state: TrainState, batch: dict):
            # Manual member axis: local member weights forward locally
            # (the shard's k/m members under vmap) instead of being
            # all-gathered by XLA's batched-conv strategy; the data
            # axis stays automatic so batch-row sharding compiles to
            # the same programs the 1-D serving mesh runs.
            return _shard_map(
                lambda st_local: step(st_local, batch),
                mesh=mesh, axis_names={"member"},
                in_specs=(P("member"),), out_specs=P("member"),
            )(state)

        member = mesh_lib.member_sharding(mesh)
        probs_sharding = (
            mesh_lib.replicated(mesh) if jax.process_count() > 1
            else member
        )
        return jax.jit(
            member_sharded_step,
            in_shardings=(member, data), out_shardings=probs_sharding,
        )
    repl = mesh_lib.replicated(mesh)
    return jax.jit(step, in_shardings=(repl, data), out_shardings=repl)


def make_ensemble_eval_step(cfg: ExperimentConfig, model, mesh=None) -> Callable:
    """Stacked eval: (stacked state, batch) -> probs [k, B(, C)] — all k
    members forward the same batch in one program (the eval twin of
    make_ensemble_train_step; same EMA/TTA semantics as _eval_probs)."""

    def step(state: TrainState, batch: dict):
        images = augment_lib.normalize(batch["image"])
        return jax.vmap(lambda st: _eval_probs(st, images, model, cfg))(state)

    if mesh is None:
        return jax.jit(step)

    def sharded_step(state: TrainState, batch: dict):
        # Manual member axis for the same reason as the train step:
        # local member weights forward locally instead of being
        # all-gathered by the batched-conv strategy. Reuses the
        # unsharded ``step`` so the two paths cannot diverge.
        return _shard_map(
            lambda st_local: step(st_local, batch),
            mesh=mesh, axis_names={"member"},
            in_specs=(P("member"),), out_specs=P("member"),
        )(state)

    # Same 1-device routing as the train step.
    step_fn = step if _mesh_devices(mesh) == 1 else sharded_step
    member = mesh_lib.member_sharding(mesh)
    data = mesh_lib.batch_sharding(mesh)
    # Probs [k, B] member-sharded on dim 0 when single-process (fully
    # addressable, device_get assembles with zero collectives);
    # replicated ONLY on multi-host, where the all-gather is what makes
    # the host fetch possible (same rationale as the train step's
    # metric_sharding above).
    probs_sharding = (
        mesh_lib.replicated(mesh) if jax.process_count() > 1 else member
    )
    return jax.jit(
        step_fn, in_shardings=(member, data), out_shardings=probs_sharding,
    )
