"""Fleet-scope ingest autotuning: one tuner arbitrating for N consumers.

The PR-7 ``IngestAutotuner`` closed the loop for ONE process: its own
StallClock window in, its own knobs out. Under the disaggregated
service the signals split across processes — stall attribution lives
in each CONSUMER (time blocked waiting for a batch frame) while the
knobs live in the SERVER (decode pool width, per-consumer run-ahead
depth). This module merges them without touching the tuner's policy:

  * consumers report ``(window_sec, input_wait_sec)`` tumbling windows
    over the control channel (protocol ``stats`` frames);
  * the server MERGES one fleet window per cadence: window length =
    the longest reported window, input-wait fraction = the WORST
    consumer's — a shared decode plane must feed its hungriest client,
    and the max is the only merge under which "no consumer starves"
    is the tuner's fixed point;
  * the merged window feeds the SAME pure ``decide()`` via the same
    ``IngestAutotuner.observe`` (decoder-busy and spill fractions are
    read server-side from the decoder pool's own counters), so every
    hysteresis/ratchet/budget-clamp guarantee PR 7 pinned holds
    unchanged at fleet scope — one decision stream, N beneficiaries.

Every applied adjustment rides the existing ``data.autotune.*``
counters/gauges/trace events; the server publishes its registry over
the PR-15 fleet segment bus, so ``obs_report`` on the fleet dir shows
the arbitration next to each consumer's own telemetry.
"""

from __future__ import annotations

import threading

from jama16_retina_tpu.data import autotune as autotune_lib


class FleetIngestTuner:
    """Wraps one ``IngestAutotuner`` behind per-consumer stall reports.

    ``report()`` is called from consumer serve threads; a merged
    ``observe`` fires once every attached consumer has contributed a
    window (or a consumer detached — stale peers must not gate the
    loop forever). Thread-safe; decisions stay serialized under one
    lock so the pure state threading is exactly the single-process
    tuner's."""

    def __init__(self, tuner: "autotune_lib.IngestAutotuner"):
        self.tuner = tuner
        self.knobs = tuner.knobs
        self._lock = threading.Lock()
        self._pending: dict[str, tuple[float, float]] = {}
        self._attached: set[str] = set()
        self.windows_merged = 0

    def attach(self, consumer_id: str) -> None:
        with self._lock:
            self._attached.add(consumer_id)

    def detach(self, consumer_id: str) -> None:
        with self._lock:
            self._attached.discard(consumer_id)
            self._pending.pop(consumer_id, None)

    def report(self, consumer_id: str, window_sec: float,
               input_wait_sec: float) -> tuple:
        """One consumer window. Returns the adjustments applied by the
        merged observe this report completed, or () when the fleet
        window is still filling."""
        with self._lock:
            if consumer_id not in self._attached:
                return ()
            self._pending[consumer_id] = (
                max(0.0, float(window_sec)),
                max(0.0, float(input_wait_sec)),
            )
            if not self._attached <= set(self._pending):
                return ()
            window, wait = merge_windows(list(self._pending.values()))
            self._pending.clear()
            self.windows_merged += 1
            return self.tuner.observe(window, wait)


def merge_windows(
    windows: "list[tuple[float, float]]",
) -> tuple[float, float]:
    """[(window_sec, input_wait_sec)] -> one (window_sec,
    input_wait_sec) fleet window: longest wall window, worst consumer's
    WAIT FRACTION re-expressed over it. Pure (graftlint purity scope) —
    the merge is part of the decision function's determinism
    guarantee."""
    if not windows:
        return 0.0, 0.0
    wall = max(w for w, _ in windows)
    worst_frac = max(
        (min(1.0, wait / w) if w > 0 else 0.0) for w, wait in windows
    )
    return wall, worst_frac * wall
