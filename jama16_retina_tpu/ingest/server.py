"""The ingest server: one decode plane behind a unix control socket.

Architecture (one process, one thread per consumer plus the acceptor):

  * ``_SharedStream`` — per stream SPEC (split, seed, batch_size,
    image_size, capacity_rows): ONE decoder (the tiered/rawshard stack
    the in-process loaders use) + the pure ``_TierPlan`` index
    bookkeeping + a small decoded-batch cache. Batch ``step`` is
    computed EXACTLY as ``tiered_pipeline.host_reference_batches``
    computes it — ``decode_batch(concat(res_ids, str_ids))`` — which is
    what makes the served stream bit-identical (post-decode) to the
    in-process tiered path at the same seed. Same-spec consumers share
    the stream: the cache turns the second consumer's pulls into hits,
    so decode is paid once per batch, not once per consumer (the
    ``pipeline_fed_served_x2`` bench row's whole claim, and the
    resume-without-re-decode drill's mechanism).
  * per-consumer serve loop — fills the consumer's shared-memory ring
    up to the live stage-depth knob, announces slots over the socket,
    and advances the consumer's sealed lease journal on every credit.
    A dead socket (kill -9) takes the same exit path as a clean
    ``detach``: flush the lease, free the ring.
  * ``FleetIngestTuner`` — consumers report stall windows over the
    control channel; one merged window per cadence drives the PR-7
    ``decide()`` policy over the server's decode pool and stage depth
    (fleettune.py), published over the PR-15 fleet bus when
    ``obs.fleet_dir`` is set.

Fault sites: ``ingest.attach`` fires in the attach handler (an armed
error refuses the attach with a typed ``error`` frame — the client
raises, nothing half-attached survives), ``ingest.ring.write`` fires
before each slot write (an armed error drops that consumer's
connection — the consumer's reattach path is the recovery under test;
a latency plan widens the in-flight window for kill drills), and
``ingest.decode`` fires inside the timed cache-miss decode (a latency
plan throttles the decode plane — the ``decode_bound`` verdict drill's
injection point, ISSUE 18).

Provenance (ISSUE 18): with ``ingest.provenance`` on (default), every
slot is stamped — before its ``batch`` frame — with {seq, step, decode
wall, cache hit, accumulated credit wait, write time, wire-format
trace context}, so the consumer can tile its measured input-wait into
``ingest.batch.*`` segments and stitched traces link the server lane
to the consumer lane causally.
"""

from __future__ import annotations

import collections
import dataclasses
import os
import socket
import threading
import time

import numpy as np
from absl import logging

from jama16_retina_tpu.data import tiered_pipeline
from jama16_retina_tpu.ingest import protocol
from jama16_retina_tpu.ingest.fleettune import FleetIngestTuner
from jama16_retina_tpu.ingest.leases import LeaseJournal
from jama16_retina_tpu.ingest.ring import BatchRing
from jama16_retina_tpu.obs import faultinject
from jama16_retina_tpu.obs import registry as obs_registry
from jama16_retina_tpu.obs import trace as trace_lib

# Decoded-batch cache per stream, in batches: covers each consumer's
# ring run-ahead plus the skew between near-lockstep consumers; beyond
# it a straggler re-decodes (counted on ingest.decode.batches), which
# is correct, just not free.
CACHE_BATCHES = 8
# Serve-loop poll cadence: how long a consumer thread waits for a
# credit/stats frame before re-checking fill work and shutdown.
_POLL_S = 0.05


@dataclasses.dataclass(frozen=True)
class StreamSpec:
    """Everything that determines the pure (seed, step) batch plan."""

    split: str
    seed: int
    batch_size: int
    image_size: int
    capacity_rows: int

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class _SharedStream:
    """One decoder + plan + decoded-batch cache for one StreamSpec."""

    def __init__(self, spec: StreamSpec, decoder, reg, knobs=None):
        self.spec = spec
        self.decoder = decoder
        self.plan = tiered_pipeline._TierPlan(
            len(decoder), spec.batch_size, spec.capacity_rows, spec.seed
        )
        self._knobs = knobs
        self._lock = threading.Lock()
        self._cache: "collections.OrderedDict[int, dict]" = \
            collections.OrderedDict()
        self._c_decoded = reg.counter(
            "ingest.decode.batches",
            help="unique batch decodes the serve plane paid (cache "
                 "misses); the no-re-decode drills assert deltas of "
                 "this ledger",
        )
        self._c_hits = reg.counter(
            "ingest.cache.hits",
            help="served batches satisfied from the decoded-batch cache "
                 "(a second consumer or a resume re-pull; decode paid "
                 "once)",
        )
        self._h_decode = reg.histogram(
            "ingest.decode.batch_s",
            help="seconds to decode one served batch (cache misses "
                 "only)",
        )

    def batch(self, step: int) -> "tuple[dict, bool]":
        """``(host_batch, cache_hit)`` for ``step`` — the batch is
        bit-identical to ``host_reference_batches`` at the same spec,
        by construction: same plan, same id order, same decoder
        contract. The hit flag feeds the slot's provenance stamp (a
        consumer's wait on a hit is dwell/credit, never decode)."""
        with self._lock:
            hit = self._cache.get(step)
            if hit is not None:
                self._cache.move_to_end(step)
                self._c_hits.inc()
                return hit, True
            if self._knobs is not None:
                self.decoder.set_workers(self._knobs.decode_workers)
            res_ids, str_ids = self.plan.batch_indices(step)
            t0 = time.perf_counter()
            # Inside the timed window: an armed latency plan on this
            # site inflates the measured decode wall exactly like a
            # slow decode pool would (the decode_bound drill).
            faultinject.check("ingest.decode")
            host = self.decoder.decode_batch(
                np.concatenate([res_ids, str_ids]).astype(np.int64)
            )
            self._h_decode.observe(time.perf_counter() - t0)
            self._c_decoded.inc()
            self._cache[step] = host
            while len(self._cache) > CACHE_BATCHES:
                self._cache.popitem(last=False)
            return host, False

    def close(self) -> None:
        self.decoder.close()


def _build_decoder(data_dir: str, split: str, image_size: int, cfg,
                   workers: int):
    """The decode stage the server hosts, chosen like the in-process
    loaders choose it: ``data.loader=rawshard`` serves the transcoded
    shards (decode paid offline), anything else the TFRecord parse
    path. Quarantine semantics ride along unchanged."""
    from jama16_retina_tpu.data.grain_pipeline import (
        ParallelDecoder,
        TFRecordIndex,
    )

    if cfg.data.loader == "rawshard":
        from jama16_retina_tpu.data import rawshard

        shard_dir = (
            cfg.data.rawshard_dir or
            rawshard.default_shard_dir(data_dir, image_size)
        )
        rs = rawshard.RawShardSplit(
            shard_dir, split, image_size=image_size, source_dir=data_dir
        )
        return rawshard.RawShardDecoder(
            rs, workers=workers, quarantine=cfg.data.quarantine_bad_records
        )
    from jama16_retina_tpu.data import tfrecord

    index = TFRecordIndex(tfrecord.list_split(data_dir, split))
    return ParallelDecoder(
        index, image_size, workers=workers,
        quarantine=cfg.data.quarantine_bad_records,
    )


class IngestServer:
    """The disaggregated decode plane. ``start()`` runs the acceptor in
    a daemon thread (tests, bench); ``serve_forever()`` blocks
    (scripts/ingest_server.py)."""

    def __init__(self, data_dir: str, cfg, socket_path: "str | None" = None,
                 registry=None):
        self.data_dir = data_dir
        self.cfg = cfg
        self.socket_path = socket_path or cfg.ingest.socket_path
        if not self.socket_path:
            raise ValueError(
                "the ingest server needs ingest.socket_path (the unix "
                "control socket consumers attach through)"
            )
        self.lease_dir = cfg.ingest.lease_dir or os.path.join(
            os.path.dirname(os.path.abspath(self.socket_path)), "leases"
        )
        self._reg = (
            registry if registry is not None
            else obs_registry.default_registry()
        )
        self._lock = threading.Lock()
        self._streams: dict[StreamSpec, _SharedStream] = {}
        # Live lease journals by consumer id: while the server runs,
        # the in-memory position is EXACT (advanced on every credit),
        # so a kill -9'd consumer reattaches precisely where it died —
        # the on-disk seal (lagging <= lease_flush_every) only matters
        # across a SERVER restart.
        self._leases: dict[str, LeaseJournal] = {}
        self._running = False
        self._listener: "socket.socket | None" = None
        self._threads: list[threading.Thread] = []
        self._consumers = 0

        # Fleet-scope tuner (data.autotune=true): the PR-7 policy over
        # the server's own decode pool, fed by merged consumer windows.
        self.knobs = None
        self.fleet_tuner = None
        if cfg.data.autotune:
            from jama16_retina_tpu.data import autotune as autotune_lib

            knobs, tuner = autotune_lib.for_config(
                cfg, mesh=None, registry=self._reg
            )
            self.knobs = knobs
            self.fleet_tuner = FleetIngestTuner(tuner)

        self._bus = None
        try:
            from jama16_retina_tpu.obs import fleet as fleet_lib

            self._bus = fleet_lib.bus_for(cfg, "ingest",
                                          registry=self._reg)
        except Exception as e:  # pragma: no cover - bus is optional
            logging.warning("ingest fleet bus unavailable: %s", e)

        self._g_consumers = self._reg.gauge(
            "ingest.consumers",
            help="consumers currently attached to the ingest server "
                 "[fleet:max]",
        )
        self._c_attaches = self._reg.counter(
            "ingest.attaches",
            help="consumer attaches accepted since server start "
                 "(reattaches after a kill count again)",
        )
        self._c_resumes = self._reg.counter(
            "ingest.lease.resumes",
            help="attaches that resumed from a lease journal position "
                 "> 0 instead of step 0",
        )
        self._c_batches = self._reg.counter(
            "ingest.batches_served",
            help="batches announced to consumers over shared-memory "
                 "rings, all consumers",
        )
        self._c_rows = self._reg.counter(
            "ingest.rows_served",
            help="rows of those batches (batches_served x batch_size)",
        )
        self._g_inflight = self._reg.gauge(
            "ingest.ring.inflight",
            help="ring slots currently filled and uncredited, summed "
                 "over consumers (the service's live run-ahead)",
        )
        self._h_credit = self._reg.histogram(
            "ingest.credit.wait_s",
            help="seconds the server spent blocked with a FULL ring "
                 "waiting for a consumer credit (backpressure: the "
                 "consumer is the bottleneck, not decode)",
        )
        self._inflight_total = 0

        # v2 provenance stamping (ISSUE 18): one monotonic seq across
        # all consumers + a fresh TraceContext per stamped slot.
        # Disabled == the slots stay zeroed (consumers read None) and
        # the pump loop pays one branch per batch.
        self._provenance = bool(cfg.ingest.provenance)
        self._prov_seq = 0

        # /metrics + /healthz for the ingest role (ISSUE 18 satellite):
        # the server was the only fleet role without the PR-15 HTTP
        # endpoint. The snapshotter lives next to the control socket;
        # progress() is batches served, so /healthz freshness means
        # "the decode plane is actually feeding someone".
        self._snap = None
        if cfg.obs.enabled and cfg.obs.http_port > 0:
            from jama16_retina_tpu.obs import device as device_lib
            from jama16_retina_tpu.obs import export as export_lib

            self._snap = export_lib.Snapshotter(
                self._reg,
                workdir=os.path.dirname(os.path.abspath(self.socket_path)),
                every_s=cfg.obs.flush_every_s,
                # Device plane (ISSUE 19): the ingest role's flushes
                # carry the ring owner-ledger gauges too.
                device=device_lib.monitor_for(cfg, registry=self._reg),
            )
            self._snap.serve_http(cfg.obs.http_port)

    # -- lifecycle ----------------------------------------------------

    def start(self) -> "IngestServer":
        os.makedirs(os.path.dirname(os.path.abspath(self.socket_path)),
                    exist_ok=True)
        os.makedirs(self.lease_dir, exist_ok=True)
        if os.path.exists(self.socket_path):
            os.unlink(self.socket_path)
        self._listener = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._listener.bind(self.socket_path)
        self._listener.listen(16)
        self._listener.settimeout(0.2)
        with self._lock:
            self._running = True
        t = threading.Thread(target=self._accept_loop,
                             name="jama16-ingest-accept", daemon=True)
        t.start()
        self._threads.append(t)
        if self._bus is not None or self._snap is not None:
            tb = threading.Thread(target=self._bus_loop,
                                  name="jama16-ingest-bus", daemon=True)
            tb.start()
            self._threads.append(tb)
        logging.info("ingest server listening on %s (leases under %s)",
                     self.socket_path, self.lease_dir)
        return self

    def serve_forever(self) -> None:
        self.start()
        try:
            while True:
                time.sleep(0.5)
        except KeyboardInterrupt:  # pragma: no cover - operator ^C
            pass
        finally:
            self.close()

    def close(self) -> None:
        with self._lock:
            self._running = False
        if self._listener is not None:
            try:
                self._listener.close()
            except OSError:  # pragma: no cover
                pass
        for t in list(self._threads):
            t.join(timeout=5.0)
        if self._snap is not None:
            try:
                self._snap.close()
            except Exception:  # pragma: no cover - final flush only
                pass
        with self._lock:
            streams, self._streams = dict(self._streams), {}
        for s in streams.values():
            s.close()
        if os.path.exists(self.socket_path):
            try:
                os.unlink(self.socket_path)
            except OSError:  # pragma: no cover
                pass

    # -- internals ----------------------------------------------------

    def _alive(self) -> bool:
        with self._lock:
            return self._running

    def _accept_loop(self) -> None:
        while self._alive():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            t = threading.Thread(target=self._serve_consumer, args=(conn,),
                                 name="jama16-ingest-consumer", daemon=True)
            t.start()
            self._threads.append(t)

    def _bus_loop(self) -> None:
        while self._alive():
            time.sleep(1.0)
            if self._bus is not None:
                try:
                    self._bus.publish(
                        self._reg.snapshot(),
                        heartbeat={"consumers": self._consumers})
                except Exception as e:  # pragma: no cover - keep serving
                    logging.warning("ingest bus publish failed: %s", e)
            if self._snap is not None:
                try:
                    self._snap.progress(int(self._c_batches.value))
                    self._snap.maybe_flush()
                except Exception as e:  # pragma: no cover - keep serving
                    logging.warning("ingest snapshot failed: %s", e)

    def _stream_for(self, spec: StreamSpec) -> _SharedStream:
        with self._lock:
            stream = self._streams.get(spec)
            if stream is None:
                workers = (
                    self.knobs.decode_workers if self.knobs is not None
                    else self._resolve_workers()
                )
                decoder = _build_decoder(
                    self.data_dir, spec.split, spec.image_size, self.cfg,
                    workers,
                )
                if spec.batch_size > len(decoder):
                    raise ValueError(
                        f"batch_size={spec.batch_size} exceeds split "
                        f"{spec.split!r} n={len(decoder)}"
                    )
                stream = _SharedStream(spec, decoder, self._reg,
                                       knobs=self.knobs)
                self._streams[spec] = stream
                logging.info(
                    "ingest stream %s: %d records, %d resident + %d "
                    "streamed rows/batch", spec, len(decoder),
                    stream.plan.res_pb, stream.plan.str_pb,
                )
            return stream

    def _resolve_workers(self) -> int:
        from jama16_retina_tpu.data.grain_pipeline import (
            resolve_decode_workers,
        )

        return resolve_decode_workers(self.cfg.data.decode_workers)

    def _lease_for(self, cid: str,
                   spec: StreamSpec) -> "tuple[LeaseJournal, bool]":
        """The live journal for ``cid`` (reattach shares the exact
        in-memory position), or a fresh one when none exists or the
        consumer attached with a DIFFERENT spec (then ``load()`` is the
        arbiter — it refuses a spec-mismatched on-disk journal)."""
        with self._lock:
            lease = self._leases.get(cid)
            if lease is not None and lease.spec == {
                k: spec.as_dict()[k] for k in lease.spec
            }:
                return lease, False
            lease = LeaseJournal(
                self.lease_dir, cid, spec.as_dict(),
                flush_every=self.cfg.ingest.lease_flush_every,
                registry=self._reg,
            )
            self._leases[cid] = lease
            return lease, True

    def _stage_depth(self) -> int:
        if self.knobs is not None:
            return self.knobs.stage_depth
        return tiered_pipeline.resolve_stage_depth(self.cfg.data)

    def _serve_consumer(self, conn: socket.socket) -> None:
        cid = "<unattached>"
        ring = None
        lease = None
        attached = False
        try:
            conn.settimeout(self.cfg.ingest.attach_timeout_s)
            msg = protocol.recv_msg(conn)
            if msg is None or msg.get("type") != "attach":
                return
            # Protocol skew check BEFORE anything side-effecting: a v1
            # client would compute different slot offsets (no provenance
            # region), so the only safe answer is a typed refusal.
            peer = int(msg.get("protocol", 1))
            if peer != protocol.PROTOCOL_VERSION:
                protocol.send_msg(conn, {
                    "type": "error", "code": "version_mismatch",
                    "message": (
                        f"ingest protocol mismatch: server speaks v"
                        f"{protocol.PROTOCOL_VERSION}, consumer spoke "
                        f"v{peer} — the v2 slot layout carries a "
                        f"provenance region; redeploy the older side"),
                })
                return
            try:
                faultinject.check("ingest.attach")
                cid = str(msg["consumer_id"])
                spec = StreamSpec(
                    split=str(msg["split"]), seed=int(msg["seed"]),
                    batch_size=int(msg["batch_size"]),
                    image_size=int(msg["image_size"]),
                    capacity_rows=int(msg["capacity_rows"]),
                )
                stream = self._stream_for(spec)
                lease, fresh = self._lease_for(cid, spec)
                if msg.get("start_step") is None:
                    # `fresh` means no live journal for this cid: the
                    # sealed on-disk position is all we have (server
                    # restart). Otherwise the in-memory lease is exact.
                    start = lease.load() if fresh else lease.consumed_through
                    if start:
                        self._c_resumes.inc()
                        logging.info(
                            "ingest consumer %s resumes at step %d from "
                            "its lease journal", cid, start,
                        )
                else:
                    # An explicit start (trainer resume from its own
                    # checkpoint step) overrides the journal — adopt it
                    # so the lease tracks the authoritative position.
                    start = int(msg["start_step"])
                    lease.reset_to(start)
                ring = BatchRing(
                    spec.batch_size, spec.image_size,
                    self.cfg.ingest.ring_slots, create=True,
                )
            except Exception as e:
                protocol.send_msg(conn, {"type": "error",
                                         "message": f"{type(e).__name__}: {e}"})
                raise
            protocol.send_msg(conn, {
                "type": "attached",
                "protocol": protocol.PROTOCOL_VERSION,
                "shm_name": ring.name,
                "n_slots": ring.n_slots, "slot_bytes": ring.slot_bytes,
                "batch_size": spec.batch_size,
                "image_size": spec.image_size, "start_step": start,
                "n_records": stream.plan.n,
                "steps_per_epoch": stream.plan.steps,
            })
            self._c_attaches.inc()
            attached = True
            with self._lock:
                self._consumers += 1
                self._g_consumers.set(self._consumers)
            if self.fleet_tuner is not None:
                self.fleet_tuner.attach(cid)
            c_rows_consumer = self._reg.counter(
                f"ingest.consumer.{_metric_id(cid)}.rows",
                help="decoded rows served to this one consumer "
                     "(per-consumer share of ingest.rows_served)",
            )
            self._pump(conn, stream, ring, lease, c_rows_consumer)
        except Exception as e:
            logging.warning("ingest consumer %s dropped: %s: %s", cid,
                            type(e).__name__, e)
        finally:
            if lease is not None:
                try:
                    lease.flush()
                except OSError as e:  # pragma: no cover - disk full etc
                    logging.warning("ingest lease flush for %s failed: %s",
                                    cid, e)
            if attached:
                with self._lock:
                    self._consumers = max(0, self._consumers - 1)
                    self._g_consumers.set(self._consumers)
            if self.fleet_tuner is not None:
                self.fleet_tuner.detach(cid)
            if ring is not None:
                ring.close()
            try:
                conn.close()
            except OSError:  # pragma: no cover
                pass

    def _pump(self, conn, stream, ring, lease, c_rows_consumer) -> None:
        """The per-consumer serve loop: fill free slots to the live
        stage depth, then wait for credit/stats frames. Runs until the
        consumer detaches, dies, or the server stops."""
        free = collections.deque(range(ring.n_slots))
        inflight: dict[int, int] = {}
        try:
            self._pump_loop(conn, stream, ring, lease, c_rows_consumer,
                            free, inflight)
        finally:
            # The consumer is gone (detach, kill, or fault): its
            # uncredited slots leave the live run-ahead gauge.
            if inflight:
                with self._lock:
                    self._inflight_total -= len(inflight)
                    self._g_inflight.set(self._inflight_total)

    def _stamp(self, ring, slot, step, decode_s, cache_hit,
               credit_wait_s) -> None:
        """Write one provenance record + (on a miss) one server-lane
        trace span, causally linked through the stamped trace id."""
        ctx = trace_lib.new_context()
        with self._lock:
            self._prov_seq += 1
            seq = self._prov_seq
        ring.write_provenance(slot, {
            "v": protocol.PROTOCOL_VERSION, "seq": seq, "step": step,
            "decode_s": round(decode_s, 6),
            "cache_hit": 1 if cache_hit else 0,
            "credit_wait_s": round(credit_wait_s, 6),
            "t_write_unix": round(time.time(), 6),
            "trace": ctx.wire(),
        })
        if not cache_hit:
            tr = trace_lib.default_tracer()
            if tr.enabled:
                t1 = time.perf_counter()
                tr.complete("ingest.decode.batch", t1 - decode_s, t1,
                            {"trace_id": ctx.trace_id, "step": step})

    def _pump_loop(self, conn, stream, ring, lease, c_rows_consumer,
                   free, inflight) -> None:
        next_step = lease.consumed_through
        conn.settimeout(_POLL_S)
        # Credit waits accumulate between slot writes and ride on the
        # NEXT stamped slot: that is the batch whose availability the
        # full ring actually delayed.
        credit_wait_pending = 0.0
        while self._alive():
            target = max(1, min(ring.n_slots, self._stage_depth()))
            while free and len(inflight) < target:
                slot = free.popleft()
                t_b0 = time.perf_counter()
                batch, cache_hit = stream.batch(next_step)
                t_b1 = time.perf_counter()
                faultinject.check("ingest.ring.write")
                ring.write(slot, batch["image"], batch["grade"])
                if self._provenance:
                    self._stamp(ring, slot, next_step, t_b1 - t_b0,
                                cache_hit, credit_wait_pending)
                credit_wait_pending = 0.0
                inflight[slot] = next_step
                try:
                    protocol.send_msg(conn, {"type": "batch", "slot": slot,
                                             "step": next_step})
                except OSError:
                    # Consumer closed while we were filling. Its final
                    # credits may still sit in the socket buffer —
                    # drain them so the lease lands on the last batch
                    # it actually consumed, not the last one we saw.
                    self._drain_credits(conn, lease, inflight)
                    return
                self._c_batches.inc()
                self._c_rows.inc(stream.plan.batch)
                c_rows_consumer.inc(stream.plan.batch)
                next_step += 1
                with self._lock:
                    self._inflight_total += 1
                    self._g_inflight.set(self._inflight_total)
            ring_full = not free
            t0 = time.perf_counter()
            try:
                msg = protocol.recv_msg(conn)
            except socket.timeout:
                if ring_full:
                    waited = time.perf_counter() - t0
                    self._h_credit.observe(waited)
                    credit_wait_pending += waited
                continue
            if msg is None:
                return  # EOF: consumer gone (kill -9 or close)
            if ring_full:
                waited = time.perf_counter() - t0
                self._h_credit.observe(waited)
                credit_wait_pending += waited
            kind = msg.get("type")
            if kind == "credit":
                self._credit(lease, free, inflight, msg)
            elif kind == "stats" and self.fleet_tuner is not None:
                self.fleet_tuner.report(
                    lease.consumer_id,
                    float(msg.get("window_sec", 0.0)),
                    float(msg.get("input_wait_sec", 0.0)),
                )
            elif kind == "detach":
                return

    def _credit(self, lease, free, inflight, msg) -> None:
        slot = int(msg["slot"])
        step = inflight.pop(slot, None)
        if step is None:
            return
        if free is not None:
            free.append(slot)
        lease.advance(step)
        with self._lock:
            self._inflight_total -= 1
            self._g_inflight.set(self._inflight_total)

    def _drain_credits(self, conn, lease, inflight) -> None:
        """Read whatever the departed consumer left in the socket
        buffer (in-order before its EOF): credits advance the lease,
        anything else is ignored. Returns on EOF or timeout."""
        while True:
            try:
                msg = protocol.recv_msg(conn)
            except (socket.timeout, OSError):
                return
            if msg is None or msg.get("type") == "detach":
                return
            if msg.get("type") == "credit":
                self._credit(lease, None, inflight, msg)


def _metric_id(consumer_id: str) -> str:
    """Consumer id -> a metric-name segment (lowercase [a-z0-9_])."""
    out = "".join(
        c if c.isalnum() else "_" for c in consumer_id.lower()
    ).strip("_")
    return out or "anon"
