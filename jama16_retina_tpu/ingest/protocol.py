"""Ingest control protocol: length-prefixed JSON frames + slot layout.

The control channel carries only SMALL messages (attach specs, slot
lifecycle, stall stats) — image payloads never touch it; they travel
through the shared-memory ring (ring.py). Framing is a 4-byte
big-endian length followed by UTF-8 JSON, the simplest format two
Python processes can speak without pickling (pickle over a socket
would also be a code-execution surface; JSON is inert).

Message types (``{"type": ...}``):

  * ``attach``   consumer -> server: {consumer_id, split, seed,
                 batch_size, image_size, capacity_rows,
                 start_step|None}. ``start_step=None`` asks the server
                 to resume from the consumer's lease journal.
  * ``attached`` server -> consumer: {shm_name, n_slots, slot_bytes,
                 batch_size, image_size, start_step, n_records,
                 steps_per_epoch} — everything the client needs to map
                 the ring and predict the stream.
  * ``batch``    server -> consumer: {slot, step} — slot is filled.
  * ``credit``   consumer -> server: {slot, step} — slot is free; the
                 lease journal advances through ``step``.
  * ``stats``    consumer -> server: {window_sec, input_wait_sec} —
                 one tumbling window of the consumer's stall
                 attribution, the fleet tuner's input.
  * ``detach``   consumer -> server: clean goodbye (flush lease, free
                 the ring). A dead socket (kill -9) is the unclean
                 twin and takes the same server path.
  * ``error``    server -> consumer: {message} — attach refused.
"""

from __future__ import annotations

import json
import socket
import struct

import numpy as np

_LEN = struct.Struct(">I")
# A control frame is a few hundred bytes; a length beyond this is a
# corrupt stream, not a big message — fail loudly instead of
# allocating it.
MAX_FRAME = 1 << 20


def send_msg(sock: socket.socket, msg: dict) -> None:
    blob = json.dumps(msg, separators=(",", ":")).encode("utf-8")
    if len(blob) > MAX_FRAME:
        raise ValueError(f"control frame too large: {len(blob)} bytes")
    sock.sendall(_LEN.pack(len(blob)) + blob)


def _recv_exact(sock: socket.socket, n: int) -> "bytes | None":
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:  # EOF: peer closed (or was killed)
            return None
        buf.extend(chunk)
    return bytes(buf)


def recv_msg(sock: socket.socket) -> "dict | None":
    """One frame, or None on EOF. ``socket.timeout`` propagates — the
    server's serve loop uses a short timeout as its poll cadence."""
    head = _recv_exact(sock, _LEN.size)
    if head is None:
        return None
    (length,) = _LEN.unpack(head)
    if length > MAX_FRAME:
        raise ValueError(f"control frame length {length} exceeds "
                         f"{MAX_FRAME}: corrupt stream")
    body = _recv_exact(sock, length)
    if body is None:
        return None
    return json.loads(body.decode("utf-8"))


# ---------------------------------------------------------------------------
# Slot layout: both sides derive identical offsets from the attach spec.
# ---------------------------------------------------------------------------


def slot_layout(batch_size: int, image_size: int) -> tuple[int, int]:
    """-> (image_bytes, slot_bytes) for one {'image','grade'} batch:
    uint8 [B,S,S,3] rows followed by int32 [B] grades, padded to a
    64-byte boundary so consecutive slots stay cache-line aligned."""
    image_bytes = batch_size * image_size * image_size * 3
    grade_bytes = batch_size * 4
    raw = image_bytes + grade_bytes
    return image_bytes, raw + ((-raw) % 64)


def slot_views(buf, slot: int, batch_size: int,
               image_size: int) -> tuple[np.ndarray, np.ndarray]:
    """(image_view, grade_view) into shared-memory ``buf`` for ``slot``
    — numpy views over the mapped bytes, no copies. The server writes
    through them; the client reads through them until it credits the
    slot."""
    image_bytes, slot_bytes = slot_layout(batch_size, image_size)
    base = slot * slot_bytes
    img = np.frombuffer(
        buf, dtype=np.uint8, count=image_bytes, offset=base
    ).reshape(batch_size, image_size, image_size, 3)
    grd = np.frombuffer(
        buf, dtype=np.int32, count=batch_size, offset=base + image_bytes
    )
    return img, grd
