"""Ingest control protocol: length-prefixed JSON frames + slot layout.

The control channel carries only SMALL messages (attach specs, slot
lifecycle, stall stats) — image payloads never touch it; they travel
through the shared-memory ring (ring.py). Framing is a 4-byte
big-endian length followed by UTF-8 JSON, the simplest format two
Python processes can speak without pickling (pickle over a socket
would also be a code-execution surface; JSON is inert).

Message types (``{"type": ...}``):

  * ``attach``   consumer -> server: {protocol, consumer_id, split,
                 seed, batch_size, image_size, capacity_rows,
                 start_step|None}. ``start_step=None`` asks the server
                 to resume from the consumer's lease journal.
                 ``protocol`` (absent == 1) must equal
                 ``PROTOCOL_VERSION`` — the slot layout changed in v2
                 (per-slot provenance region), so a version skew means
                 the two sides would disagree on byte offsets; the
                 server refuses with a typed ``version_mismatch`` error
                 instead of serving garbage.
  * ``attached`` server -> consumer: {protocol, shm_name, n_slots,
                 slot_bytes, batch_size, image_size, start_step,
                 n_records, steps_per_epoch} — everything the client
                 needs to map the ring and predict the stream.
  * ``batch``    server -> consumer: {slot, step} — slot is filled.
  * ``credit``   consumer -> server: {slot, step} — slot is free; the
                 lease journal advances through ``step``.
  * ``stats``    consumer -> server: {window_sec, input_wait_sec} —
                 one tumbling window of the consumer's stall
                 attribution, the fleet tuner's input.
  * ``detach``   consumer -> server: clean goodbye (flush lease, free
                 the ring). A dead socket (kill -9) is the unclean
                 twin and takes the same server path.
  * ``error``    server -> consumer: {message, code?} — attach
                 refused. ``code="version_mismatch"`` is the typed
                 protocol-skew refusal (ISSUE 18); clients surface it
                 as ``ProtocolVersionMismatch``.
"""

from __future__ import annotations

import json
import socket
import struct

import numpy as np

_LEN = struct.Struct(">I")
# A control frame is a few hundred bytes; a length beyond this is a
# corrupt stream, not a big message — fail loudly instead of
# allocating it.
MAX_FRAME = 1 << 20

# v2 (ISSUE 18): each slot carries a fixed provenance region after the
# grades, so slot offsets differ from v1. Both sides pin this and the
# server refuses a skewed attach — a silent mismatch would read image
# bytes as grades.
PROTOCOL_VERSION = 2


class ProtocolVersionMismatch(ConnectionError):
    """Attach refused (or reply unintelligible) because the two sides
    speak different slot layouts. Not retryable: redeploy one side."""


def send_msg(sock: socket.socket, msg: dict) -> None:
    blob = json.dumps(msg, separators=(",", ":")).encode("utf-8")
    if len(blob) > MAX_FRAME:
        raise ValueError(f"control frame too large: {len(blob)} bytes")
    sock.sendall(_LEN.pack(len(blob)) + blob)


def _recv_exact(sock: socket.socket, n: int) -> "bytes | None":
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:  # EOF: peer closed (or was killed)
            return None
        buf.extend(chunk)
    return bytes(buf)


def recv_msg(sock: socket.socket) -> "dict | None":
    """One frame, or None on EOF. ``socket.timeout`` propagates — the
    server's serve loop uses a short timeout as its poll cadence."""
    head = _recv_exact(sock, _LEN.size)
    if head is None:
        return None
    (length,) = _LEN.unpack(head)
    if length > MAX_FRAME:
        raise ValueError(f"control frame length {length} exceeds "
                         f"{MAX_FRAME}: corrupt stream")
    body = _recv_exact(sock, length)
    if body is None:
        return None
    return json.loads(body.decode("utf-8"))


# ---------------------------------------------------------------------------
# Slot layout: both sides derive identical offsets from the attach spec.
# ---------------------------------------------------------------------------


# Fixed per-slot provenance region (v2): a 4-byte big-endian length
# followed by UTF-8 JSON, zero length == "no record". 256 bytes holds
# the stamp (seq, step, decode wall, cache hit, credit wait, wire-format
# trace context) with headroom; write_provenance raises rather than
# truncating if a record ever outgrows it.
PROV_BYTES = 256


def slot_layout(batch_size: int, image_size: int) -> tuple[int, int]:
    """-> (image_bytes, slot_bytes) for one {'image','grade'} batch:
    uint8 [B,S,S,3] rows, int32 [B] grades, then the PROV_BYTES
    provenance region, padded to a 64-byte boundary so consecutive
    slots stay cache-line aligned."""
    image_bytes = batch_size * image_size * image_size * 3
    grade_bytes = batch_size * 4
    raw = image_bytes + grade_bytes + PROV_BYTES
    return image_bytes, raw + ((-raw) % 64)


def _prov_offset(slot: int, batch_size: int, image_size: int) -> int:
    image_bytes, slot_bytes = slot_layout(batch_size, image_size)
    return slot * slot_bytes + image_bytes + batch_size * 4


def write_provenance(buf, slot: int, batch_size: int, image_size: int,
                     record: "dict | None") -> None:
    """Stamp ``record`` into ``slot``'s provenance region (None clears
    it). The server calls this before announcing the slot; the write is
    a single memcpy into the already-mapped ring, which is what keeps
    stamping inside the ≤2% diagnosis overhead budget."""
    base = _prov_offset(slot, batch_size, image_size)
    if record is None:
        buf[base:base + _LEN.size] = _LEN.pack(0)
        return
    blob = json.dumps(record, separators=(",", ":")).encode("utf-8")
    if len(blob) > PROV_BYTES - _LEN.size:
        raise ValueError(
            f"provenance record {len(blob)} bytes exceeds the "
            f"{PROV_BYTES - _LEN.size}-byte slot region")
    buf[base:base + _LEN.size + len(blob)] = _LEN.pack(len(blob)) + blob


def read_provenance(buf, slot: int, batch_size: int,
                    image_size: int) -> "dict | None":
    """Recover the slot's provenance stamp, or None when the region is
    cleared/unparseable — provenance is diagnostic freight, so a bad
    stamp degrades to "no attribution", never to a failed batch."""
    base = _prov_offset(slot, batch_size, image_size)
    (length,) = _LEN.unpack(bytes(buf[base:base + _LEN.size]))
    if length == 0 or length > PROV_BYTES - _LEN.size:
        return None
    try:
        return json.loads(
            bytes(buf[base + _LEN.size:base + _LEN.size + length]
                  ).decode("utf-8"))
    except (ValueError, UnicodeDecodeError):
        return None


def slot_views(buf, slot: int, batch_size: int,
               image_size: int) -> tuple[np.ndarray, np.ndarray]:
    """(image_view, grade_view) into shared-memory ``buf`` for ``slot``
    — numpy views over the mapped bytes, no copies. The server writes
    through them; the client reads through them until it credits the
    slot."""
    image_bytes, slot_bytes = slot_layout(batch_size, image_size)
    base = slot * slot_bytes
    img = np.frombuffer(
        buf, dtype=np.uint8, count=image_bytes, offset=base
    ).reshape(batch_size, image_size, image_size, 3)
    grd = np.frombuffer(
        buf, dtype=np.int32, count=batch_size, offset=base + image_bytes
    )
    return img, grd
