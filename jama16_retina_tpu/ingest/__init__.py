"""Disaggregated ingest service (ISSUE 17): one decode plane, N
consumers.

BENCH r16 shape of the problem: raw-parse decode peaks at ~2660 img/s
while one chip's train appetite is ~1970 img/s — and every process of a
deployment (trainer, overlapped eval, lifecycle gate evals, bench,
transcode) pays that decode cost AGAIN, independently. "tf.data: A
Machine Learning Data Processing Framework" (PAPERS.md) names the end
state of a tuned input pipeline: a disaggregated data *service*. This
package is that service for our stack:

  * ``server.IngestServer`` — one process hosts the EXISTING
    rawshard/tiered/autotune machinery (``_TierPlan`` residency
    bookkeeping, ``ParallelDecoder`` worker pool, quarantine,
    telemetry) behind a unix control socket
    (``scripts/ingest_server.py`` entrypoint). Same-spec consumers
    share one decoder and one small decoded-batch cache, so a batch is
    decoded ONCE however many consumers pull it.
  * ``ring.BatchRing`` — per-consumer ``multiprocessing.shared_memory``
    slab divided into fixed-size batch slots; the server writes decoded
    rows straight into the slot (zero-copy on the row bytes — no
    pickling of image payloads) and announces it over the control
    socket; the consumer credits the slot back when done.
  * ``protocol`` — the length-prefixed JSON control frames
    (ATTACH/ATTACHED/BATCH/CREDIT/STATS/DETACH) and the slot layout
    math both sides derive from the attach spec.
  * ``leases.LeaseJournal`` — a SEALED (integrity/artifact) per-consumer
    journal of the consumed batch position: a kill -9'd consumer
    reattaches and resumes where it left off with zero re-decode, and a
    kill -9'd server restarts into the same pure (seed, step) epoch
    plan from the journals alone.
  * ``fleettune.FleetIngestTuner`` — the PR-7 ``IngestAutotuner``
    promoted to FLEET scope: consumers report their stall attribution
    over the control channel, the server merges the windows
    (input-wait = the WORST consumer's — the service must feed its
    hungriest client) and one pure ``decide()`` arbitrates
    decode_workers / stage depth for the whole plane, publishing over
    the PR-15 fleet segment bus.

Consumers opt in with ``data.loader=served`` (data/served.py), which
plugs a thin ``ServedStream`` client into the standard
``trainer._train_stream`` seam; the stream is bit-identical
(post-decode) to the in-process tiered path at the same seed — pinned
at fit() level, >1 epoch, partial residency (tests/test_ingest.py).
"""
