"""Shared-memory batch ring: the ingest service's data plane.

One ``multiprocessing.shared_memory`` slab per consumer, divided into
``n_slots`` fixed-size batch slots (protocol.slot_layout). The SERVER
creates and unlinks the slab and writes decoded rows straight into a
free slot's numpy view — the row bytes cross the process boundary with
zero serialization (no pickling of image payloads; the control socket
carries only the slot number). The CONSUMER maps the same slab
read-only-by-convention and credits a slot back over the control
socket when its batch has been consumed.

Slot lifecycle is socket-ordered, not shared-atomic: a slot the server
announced (``batch``) belongs to the consumer until its ``credit``
frame returns; the server never rewrites an uncredited slot. Unix
sockets deliver frames in order, so no memory fences beyond the kernel
boundary are needed.
"""

from __future__ import annotations

import secrets
from multiprocessing import shared_memory

import numpy as np

from jama16_retina_tpu.ingest import protocol

# Segment names THIS process created. An in-process attach (tests,
# bench: server and consumer share one interpreter) must not unregister
# the owner's tracker claim — the tracker keeps one entry per name per
# process, so the attach-side unregister below would orphan the unlink.
_OWNED_NAMES: set = set()


def _unregister_from_tracker(shm) -> None:
    """Detach this process's resource_tracker claim on an ATTACHED
    (not owned) segment: the server owns the unlink; without this the
    tracker tears the segment down when the first consumer exits and
    logs spurious leak warnings for the rest."""
    try:  # pragma: no cover - tracker internals vary across versions
        from multiprocessing import resource_tracker

        resource_tracker.unregister(shm._name, "shared_memory")
    except Exception:
        pass


class BatchRing:
    """The slab + slot views. ``create=True`` is the server side (owns
    the segment and its unlink); ``create=False`` attaches by name."""

    def __init__(self, batch_size: int, image_size: int, n_slots: int,
                 name: "str | None" = None, create: bool = True):
        self.batch = int(batch_size)
        self.image_size = int(image_size)
        self.n_slots = max(1, int(n_slots))
        _, self.slot_bytes = protocol.slot_layout(self.batch,
                                                  self.image_size)
        self._owner = bool(create)
        self._accounted = False
        if create:
            # Short random name: the kernel caps shm names well below
            # path length limits, and collisions must not alias rings.
            name = name or f"jama16-ing-{secrets.token_hex(6)}"
            self._shm = shared_memory.SharedMemory(
                name=name, create=True,
                size=self.slot_bytes * self.n_slots,
            )
            _OWNED_NAMES.add(self._shm.name)
            # Owner ledger (obs/device.py; ISSUE 19): the server's
            # rings are the ingest plane's big pinned buffers —
            # add/subtract (not set) because one server owns one ring
            # PER consumer.
            self._accounted = True
            try:
                from jama16_retina_tpu.obs import device as device_lib

                device_lib.add_hbm_owner(
                    "ingest_rings", self.slot_bytes * self.n_slots
                )
            except Exception:  # noqa: BLE001 - accounting only
                pass
        else:
            if not name:
                raise ValueError("attaching a BatchRing needs its name")
            self._shm = shared_memory.SharedMemory(name=name)
            if self._shm.name not in _OWNED_NAMES:
                _unregister_from_tracker(self._shm)
        self.name = self._shm.name

    def views(self, slot: int) -> tuple[np.ndarray, np.ndarray]:
        if not 0 <= slot < self.n_slots:
            raise IndexError(f"slot {slot} outside ring of {self.n_slots}")
        return protocol.slot_views(self._shm.buf, slot, self.batch,
                                   self.image_size)

    def write(self, slot: int, image: np.ndarray,
              grade: np.ndarray) -> None:
        """Server side: copy one decoded batch into ``slot``. The only
        copy on the whole server->consumer path for these bytes."""
        img_v, grd_v = self.views(slot)
        np.copyto(img_v, np.ascontiguousarray(image, dtype=np.uint8))
        np.copyto(grd_v, np.ascontiguousarray(grade, dtype=np.int32))

    def write_provenance(self, slot: int, record: "dict | None") -> None:
        """Server side: stamp (or clear) the slot's provenance region —
        written AFTER the rows and before the ``batch`` frame, so the
        socket-ordered lifecycle covers the stamp too."""
        if not 0 <= slot < self.n_slots:
            raise IndexError(f"slot {slot} outside ring of {self.n_slots}")
        protocol.write_provenance(self._shm.buf, slot, self.batch,
                                  self.image_size, record)

    def read_provenance(self, slot: int) -> "dict | None":
        """Consumer side: the slot's provenance stamp (None when the
        server runs with ingest.provenance=false)."""
        if not 0 <= slot < self.n_slots:
            raise IndexError(f"slot {slot} outside ring of {self.n_slots}")
        return protocol.read_provenance(self._shm.buf, slot, self.batch,
                                        self.image_size)

    def read(self, slot: int) -> dict:
        """Consumer side: one {'image','grade'} HOST batch copied out
        of the slot. A copy (not the view) is deliberate: the batch
        must outlive the credit frame that frees the slot, and jax's
        CPU backend may alias a numpy buffer it is handed — a reused
        slot under a live alias would corrupt a training batch."""
        img_v, grd_v = self.views(slot)
        return {"image": np.array(img_v), "grade": np.array(grd_v)}

    def close(self) -> None:
        # Views into self._shm.buf hold exported pointers; drop
        # everything this object created before closing the mapping.
        try:
            self._shm.close()
        except BufferError:  # pragma: no cover - a view outlived us
            pass
        if self._owner:
            _OWNED_NAMES.discard(self._shm.name)
            if self._accounted:
                # Once: close() may run again from __del__/teardown
                # paths, and a double subtract would under-count rings
                # still alive.
                self._accounted = False
                try:
                    from jama16_retina_tpu.obs import device as device_lib

                    device_lib.add_hbm_owner(
                        "ingest_rings", -(self.slot_bytes * self.n_slots)
                    )
                except Exception:  # noqa: BLE001 - accounting only
                    pass
            try:
                self._shm.unlink()
            except FileNotFoundError:  # pragma: no cover
                pass
