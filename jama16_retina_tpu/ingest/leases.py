"""Per-consumer lease journals: durable epoch-shard handoff.

One SEALED json per consumer (integrity/artifact seam, like every
durable artifact since PR 13) recording the stream spec and the batch
position the consumer has consumed through. Two crash classes, one
file:

  * kill -9'd CONSUMER: while the server lives its in-memory lease is
    exact (advanced on every credit), so a reattach with
    ``start_step=None`` resumes at the precise next batch and the
    server re-decodes NOTHING (the decode ledger
    ``ingest.decode.batches`` is the assertable proof).
  * kill -9'd SERVER: the on-disk journal lags at most
    ``ingest.lease_flush_every`` credits. A restarted server reloads
    every journal and resumes each consumer from its flushed position
    — into the SAME epoch plan, because the plan is a pure
    (seed, step) function of the spec the journal carries
    (tiered_pipeline._TierPlan; nothing else to recover).

A journal whose sealed digest fails verification is COUNTED
(integrity.corrupt.{artifact} ledger) and treated as absent — the
consumer restarts from step 0, which is slow but always correct; a
journal whose SPEC disagrees with the attach spec is a config error
and refuses loudly (resuming a different stream would silently skip
records).
"""

from __future__ import annotations

import os
import threading

from absl import logging

from jama16_retina_tpu.integrity import artifact as artifact_lib

LEASE_SCHEMA = "ingest.lease"
LEASE_VERSION = 1

# Spec keys that must match for a lease to be resumable: together they
# determine the pure (seed, step) batch plan.
SPEC_KEYS = ("split", "seed", "batch_size", "image_size", "capacity_rows")


def lease_path(lease_dir: str, consumer_id: str) -> str:
    safe = "".join(c if c.isalnum() or c in "-_." else "_"
                   for c in consumer_id)
    return os.path.join(lease_dir, f"lease-{safe}.json")


class LeaseJournal:
    """One consumer's durable stream position. ``consumed_through`` is
    the COUNT of batches credited: the next batch to serve."""

    def __init__(self, lease_dir: str, consumer_id: str, spec: dict,
                 flush_every: int = 8, registry=None):
        self.path = lease_path(lease_dir, consumer_id)
        self.consumer_id = consumer_id
        self.spec = {k: spec[k] for k in SPEC_KEYS}
        self.flush_every = max(1, int(flush_every))
        self.consumed_through = 0
        self._flushed = -1
        self._reg = registry
        # One journal is shared across a consumer's successive serve
        # threads (the server's in-memory lease cache); a reattach can
        # briefly overlap the old thread's teardown flush.
        self._lock = threading.Lock()
        os.makedirs(lease_dir, exist_ok=True)

    def load(self) -> int:
        """Recover ``consumed_through`` from disk (0 when no journal /
        corrupt journal). Spec mismatch raises — see module docstring."""
        if not os.path.exists(self.path):
            return 0
        try:
            payload, _ = artifact_lib.read_sealed_json(
                self.path, artifact="ingest.lease", registry=self._reg
            )
        except artifact_lib.ArtifactCorrupt as e:
            # read_sealed_json already counted it; start fresh rather
            # than trust a position the digest disowns.
            logging.warning(
                "ingest lease %s failed seal verification (%s) — "
                "consumer %s restarts from step 0", self.path, e,
                self.consumer_id,
            )
            return 0
        except (OSError, ValueError) as e:
            logging.warning(
                "ingest lease %s unreadable (%s) — consumer %s restarts "
                "from step 0", self.path, e, self.consumer_id,
            )
            return 0
        disk_spec = {k: payload.get(k) for k in SPEC_KEYS}
        if disk_spec != self.spec:
            raise ValueError(
                f"ingest lease {self.path} was written for spec "
                f"{disk_spec} but consumer {self.consumer_id!r} attached "
                f"with {self.spec} — a resumed stream must keep its "
                "(split, seed, batch, image_size, residency) plan; "
                "delete the lease to deliberately restart"
            )
        with self._lock:
            self.consumed_through = int(payload.get("consumed_through", 0))
            self._flushed = self.consumed_through
            return self.consumed_through

    def reset_to(self, step: int) -> None:
        """Adopt an EXPLICIT position (the trainer's checkpoint step —
        the authority that overrides whatever the journal held)."""
        with self._lock:
            self.consumed_through = int(step)

    def advance(self, step: int) -> None:
        """One credited batch: the consumer has consumed ``step``."""
        with self._lock:
            self.consumed_through = max(
                self.consumed_through, int(step) + 1
            )
            if (self.consumed_through - max(self._flushed, 0)
                    >= self.flush_every):
                self._flush_locked()

    def flush(self) -> None:
        with self._lock:
            self._flush_locked()

    def _flush_locked(self) -> None:
        if self.consumed_through == self._flushed:
            return
        artifact_lib.write_sealed_json(
            self.path,
            {
                "consumer_id": self.consumer_id,
                "consumed_through": self.consumed_through,
                **self.spec,
            },
            schema=LEASE_SCHEMA, version=LEASE_VERSION,
        )
        self._flushed = self.consumed_through
        if self._reg is not None:
            self._reg.counter(
                "ingest.lease.flushes",
                help="sealed lease-journal writes (per-consumer durable "
                     "stream position; ingest/leases.py)",
            ).inc()
