"""HBM-resident train input: the ``data.loader="hbm"`` option.

The literal form of "decoding straight into HBM" (BASELINE.json:5): the
whole decoded uint8 split is uploaded to device memory ONCE at startup,
and every train batch after that is an on-device gather — zero per-step
host→device traffic, zero host decode on the hot path. docs/PERF.md §H2D
measured why this matters here: on the axon tunnel the per-batch H2D
copy collapses to ~18 MB/s after the train executable loads, capping the
streamed pipeline at ~28-120 img/s while the chip can train at ~1300;
paying the (slow) upload once moves the steady-state rate back to the
device-only ceiling. On healthy PCIe hosts the same mode removes the
host from the steady-state entirely — useful for small/medium datasets
(EyePACS train at 299px raw uint8 is ~15 GB vs 16 GB/chip HBM on v5e,
so the fit is gated, not assumed; see ``fits_in_hbm``).

Batch selection is a pure function of (seed, step), computed ON DEVICE
inside one jit program per step:

    epoch = step // steps_per_epoch        (drop-remainder epochs)
    perm  = random.permutation(fold_in(key(seed), epoch), n)
    idx   = perm[pos : pos + batch]        (pos = in-epoch offset)

so epochs are exact global reshuffles (every record exactly once per
epoch, like the grain loader's index sampling) and resume is O(1):
``skip_batches=k`` just starts the step counter at k — the same
(seed, step) contract as the jit step's fold_in keys (SURVEY.md §5.4).

Multi-CHIP: pass a mesh and the resident dataset rows shard across the
data axis; the per-step gather is then a GSPMD collective over ICI,
which is exactly the fabric it should ride. Multi-HOST (VERDICT r3 #3):
each process decodes ONLY the rows its own devices hold and uploads
them shard-by-shard (``jax.make_array_from_callback`` over the same
row-sharded layout), after which the per-step gather program is
identical to the single-process multi-chip one. On a 1-D data mesh
that is 1/P of the decode work and host RAM per process; on a
('member', 'data') ensemble mesh the dataset is REPLICATED over the
member axis, so a process whose devices span every data-axis block
(e.g. one member row per host) still decodes and holds the full split
— size host RAM accordingly. Training through ``train.py --set
data.loader=hbm`` is pinned 2-process ≡ single-process in
tests/test_multiprocess.py.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np
from absl import logging

from jama16_retina_tpu.configs import DataConfig
from jama16_retina_tpu.data import tfrecord

# Warn-once latch for the no-bytes_limit HBM fallback below: the
# message names a per-PROCESS assumption, so repeating it per loader
# construction adds noise, not information. Tests reset it directly.
_WARNED_NO_BYTES_LIMIT = False


def _decode_rows(
    index, start: int, stop: int, image_size: int, n: "int | None" = None,
    workers: int = 1, quarantine: bool = True,
) -> tuple[np.ndarray, np.ndarray]:
    """Rows [start, stop) of a TFRecordIndex into preallocated uint8/i32
    arrays — THE decode loop, shared by the full single-process load and
    the per-shard multi-host load (the 2-process ≡ 1-process pin depends
    on both paths decoding identically). ``n``: wrap row ids past the
    true record count (the multi-host padding rows reuse leading
    records as filler). ``workers`` > 1 shards the loop across host
    cores via grain_pipeline.ParallelDecoder.decode_range, whose output
    is worker-count-invariant (disjoint preallocated slices), so the
    2-process ≡ 1-process pin survives parallel decode."""
    from jama16_retina_tpu.data.grain_pipeline import ParallelDecoder

    decoder = ParallelDecoder(
        index, image_size, workers=workers, quarantine=quarantine
    )
    try:
        return decoder.decode_range(start, stop, n=n)
    finally:
        decoder.close()


def load_split_numpy(
    data_dir: str, split: str, image_size: int, workers: int = 1,
    quarantine: bool = True,
) -> tuple[np.ndarray, np.ndarray]:
    """All records of a split, decoded on host once:
    (images u8[N,S,S,3], grades i32[N]). Reuses the grain loader's
    TF-free record index + proto decode (data/grain_pipeline.py);
    ``workers`` parallelizes the one-time decode across host cores."""
    from jama16_retina_tpu.data.grain_pipeline import TFRecordIndex

    index = TFRecordIndex(tfrecord.list_split(data_dir, split))
    n = len(index)
    if n == 0:
        raise ValueError(f"no records under {data_dir}/{split}")
    return _decode_rows(
        index, 0, n, image_size, workers=workers, quarantine=quarantine
    )


def row_bytes(image_size: int) -> int:
    """Resident bytes one record costs: uint8 pixels + an i32 grade."""
    return image_size * image_size * 3 + 4


def dataset_bytes(n: int, image_size: int) -> int:
    return n * row_bytes(image_size)


def resident_row_capacity(
    image_size: int,
    n_devices: int = 1,
    max_fraction: float = 0.6,
    budget_bytes: "int | None" = None,
    budget_base_bytes: int = 0,
) -> int:
    """How many dataset rows the HBM budget admits ACROSS the data axis
    — the partial-residency generalization of ``fits_in_hbm``'s
    all-or-nothing gate (the tiered loader pins this many rows and
    streams the rest; data/tiered_pipeline.py). ``budget_bytes``
    overrides the derivation with an explicit TOTAL resident budget
    (the tiered loader's ``tiered_resident_bytes`` knob; benches pin it
    for reproducible partial-residency measurements);
    ``budget_base_bytes`` is the ``data.hbm_budget_bytes`` per-chip
    memory-limit override the derivation consults when it does run."""
    total = (
        budget_bytes if budget_bytes is not None
        else hbm_budget_bytes(
            max_fraction, budget_base_bytes=budget_base_bytes
        ) * max(n_devices, 1)
    )
    return max(0, total // row_bytes(image_size))


def hbm_budget_bytes(max_fraction: float = 0.6,
                     budget_base_bytes: int = 0) -> int:
    """Per-chip HBM budget for the resident dataset: ``max_fraction`` of
    the device's memory limit when the runtime reports one. When it
    reports none, the operator's ``data.hbm_budget_bytes`` override
    (``budget_base_bytes`` > 0, the per-chip memory limit BEFORE the
    fraction) wins; with neither, assume the SMALLEST HBM of any
    deployed TPU core (8 GB, v2/v3) rather than the v5e's 16 — an
    optimistic assumption here is an OOM at upload time, and the
    fallback is disclosed in a log that names the knob that fixes it
    (ISSUE 7). An explicit override also beats a reported limit: the
    operator saying "budget for 16 GB" on a runtime that under-reports
    must win, and the precedence is then one rule, not two. The
    remaining fraction belongs to the model/optimizer/activations (the
    flagship step's live set is ~2 GB)."""
    import jax

    if budget_base_bytes and budget_base_bytes > 0:
        budget = int(budget_base_bytes * max_fraction)
        try:
            from jama16_retina_tpu.obs import device as device_lib

            device_lib.note_hbm_budget(budget)
        except Exception:  # noqa: BLE001 - accounting only
            pass
        return budget
    limit = None
    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats:
            limit = stats.get("bytes_limit")
    except Exception:
        pass
    if not limit:
        limit = 8 * 1024**3
        global _WARNED_NO_BYTES_LIMIT
        if not _WARNED_NO_BYTES_LIMIT:
            # Once per process (ISSUE 17 satellite): every loader
            # construction calls this, so an unconditional warning
            # fired twice per bench run and once per epoch-restart —
            # same fallback, same fix, pure noise after the first.
            _WARNED_NO_BYTES_LIMIT = True
            logging.warning(
                "device reports no bytes_limit: assuming a conservative "
                "%d GB HBM budget base (smallest deployed TPU core) — set "
                "data.hbm_budget_bytes to this chip's true per-device "
                "memory limit to override",
                limit // 1024**3,
            )
    budget = int(limit * max_fraction)
    # Cross-check seam (ISSUE 19): the device plane publishes this
    # derived per-chip budget next to MEASURED occupancy
    # (device.hbm.{derived_budget_bytes,budget_occupancy_frac}) so a
    # budget the math got wrong shows up as occupancy > 1 in telemetry
    # instead of as an OOM.
    try:
        from jama16_retina_tpu.obs import device as device_lib

        device_lib.note_hbm_budget(budget)
    except Exception:  # noqa: BLE001 - accounting only
        pass
    return budget


def fits_in_hbm(
    n: int, image_size: int, n_devices: int = 1, max_fraction: float = 0.6,
    budget_base_bytes: int = 0,
) -> bool:
    """The size gate: the dataset shards row-wise across the mesh's data
    axis, so the per-chip share must fit the per-chip budget."""
    per_chip = dataset_bytes(n, image_size) / max(n_devices, 1)
    return per_chip <= hbm_budget_bytes(
        max_fraction, budget_base_bytes=budget_base_bytes
    )


def _load_index_rows_sharded(index, n: int, image_size: int, mesh,
                             workers: int = 1, quarantine: bool = True):
    """Multi-host placement: decode ONLY this process's rows, upload
    shard-by-shard -> (images, grades) as GLOBAL row-sharded arrays of
    padded length (VERDICT r3 #3).

    Each addressable device's dim-0 block is decoded exactly once (the
    grade sharding's blocks coincide with the image sharding's, so one
    decode feeds both callbacks). Padding rows — dim-0 must divide the
    data axis — reuse leading records as filler; the batch permutation
    draws indices < n only, so they are never sampled and the gather
    program ends up IDENTICAL to the single-process multi-chip one.
    """
    import jax
    from jama16_retina_tpu.parallel import mesh as mesh_lib

    d = mesh.shape[mesh_lib._batch_axis(mesh)]
    n_pad = n + ((-n) % d)
    img_sh = mesh_lib._rank_sharding(4, mesh_lib.batch_sharding(mesh))
    g_sh = mesh_lib._rank_sharding(1, mesh_lib.batch_sharding(mesh))
    img_shape = (n_pad, image_size, image_size, 3)

    def _span(idx) -> tuple[int, int]:
        s = idx[0]
        return (s.start or 0, n_pad if s.stop is None else s.stop)

    blocks: dict[tuple[int, int], tuple] = {}
    for dev_idx in img_sh.addressable_devices_indices_map(img_shape).values():
        start, stop = _span(dev_idx)
        if (start, stop) not in blocks:
            blocks[(start, stop)] = _decode_rows(
                index, start, stop, image_size, n=n, workers=workers,
                quarantine=quarantine,
            )
    logging.info(
        "hbm loader (multi-host): process %d/%d decoded %d of %d rows",
        jax.process_index(), jax.process_count(),
        sum(b[1].shape[0] for b in blocks.values()), n_pad,
    )
    images = jax.make_array_from_callback(
        img_shape, img_sh, lambda idx: blocks[_span(idx)][0]
    )
    grades = jax.make_array_from_callback(
        (n_pad,), g_sh, lambda idx: blocks[_span(idx)][1]
    )
    return images, grades


def make_batch_fn(images, grades, batch_size: int, seed: int, mesh=None,
                  n_records: "int | None" = None):
    """jit'd ``step -> {'image','grade'}`` gather over the resident
    arrays. With a mesh, the dataset is row-sharded over the data axis
    and the output batch carries the standard batch sharding — the
    shuffle gather becomes an ICI collective under GSPMD.

    ``images``/``grades`` are host numpy (this function pads + places
    them) or already-global jax Arrays from _load_index_rows_sharded
    (multi-host; already padded — pass ``n_records`` = the TRUE record
    count so the permutation never samples the padding)."""
    import jax
    import jax.numpy as jnp

    from jama16_retina_tpu.parallel import mesh as mesh_lib

    n = int(n_records) if n_records is not None else images.shape[0]
    if batch_size > n:
        raise ValueError(f"batch_size={batch_size} exceeds dataset n={n}")
    steps_per_epoch = n // batch_size
    base = jax.random.key(seed)

    if isinstance(images, jax.Array):
        pass  # pre-placed global arrays (multi-host path)
    elif mesh is not None:
        # Row-sharding needs dim 0 divisible by the data axis; real
        # splits have arbitrary counts, so pad with leading records
        # re-used as filler. The permutation draws indices < n only —
        # padding rows are never sampled, so epoch semantics are
        # unchanged (no record lost, none duplicated).
        d = mesh.shape[mesh_lib._batch_axis(mesh)]
        pad = (-n) % d
        if pad:
            images = np.concatenate([images, images[:pad]])
            grades = np.concatenate([grades, grades[:pad]])
        data_sh = mesh_lib.batch_sharding(mesh)
        images = jax.device_put(images, data_sh)
        grades = jax.device_put(grades, data_sh)
    else:
        images = jax.device_put(images)
        grades = jax.device_put(grades)

    # The resident arrays are jit ARGUMENTS, not closure captures: a
    # multi-host global array spans non-addressable devices, which jit
    # refuses to close over (argument shardings are inferred from the
    # committed arrays either way, and an argument is not re-uploaded).
    def get_batch(imgs, grs, step):
        epoch = step // steps_per_epoch
        pos = (step % steps_per_epoch) * batch_size
        perm = jax.random.permutation(jax.random.fold_in(base, epoch), n)
        idx = jax.lax.dynamic_slice(perm, (pos,), (batch_size,))
        return {
            "image": jnp.take(imgs, idx, axis=0),
            "grade": jnp.take(grs, idx, axis=0),
        }

    if mesh is None:
        jitted = jax.jit(get_batch)
    else:
        jitted = jax.jit(
            get_batch,
            out_shardings={
                "image": mesh_lib.batch_sharding(mesh),
                "grade": mesh_lib.batch_sharding(mesh),
            },
        )
    return lambda step: jitted(images, grades, step)


def train_batches(
    data_dir: str,
    split: str,
    cfg: DataConfig,
    image_size: int,
    seed: int = 0,
    skip_batches: int = 0,
    mesh=None,
    max_fraction: float = 0.6,
) -> Iterator[dict]:
    """Drop-in twin of pipeline.train_batches yielding DEVICE-resident
    batches. ``skip_batches`` is an O(1) counter offset (pure (seed,
    step) semantics — no replay, no state files)."""
    import jax

    from jama16_retina_tpu.data.grain_pipeline import resolve_decode_workers
    from jama16_retina_tpu.parallel import mesh as mesh_lib

    workers = resolve_decode_workers(getattr(cfg, "decode_workers", 0))
    multiprocess = jax.process_count() > 1
    if multiprocess and mesh is None:
        raise ValueError(
            "data.loader='hbm' needs a mesh on multi-process launches "
            "(the resident rows shard across the mesh's data axis)"
        )
    if multiprocess:
        # Count records from the index alone (cheap: record framing, no
        # decode) so the HBM gate runs BEFORE any decode/upload work.
        from jama16_retina_tpu.data.grain_pipeline import TFRecordIndex

        index = TFRecordIndex(tfrecord.list_split(data_dir, split))
        n = len(index)
        if n == 0:
            raise ValueError(f"no records under {data_dir}/{split}")
    else:
        images, grades = load_split_numpy(
            data_dir, split, image_size, workers=workers,
            quarantine=getattr(cfg, "quarantine_bad_records", True),
        )
        n = len(images)
    # The dataset shards across the DATA axis only (replicated over any
    # 'member' axis of an ensemble mesh) — gating on total device count
    # would under-count per-chip bytes by the member-axis factor.
    n_dev = mesh.shape[mesh_lib._batch_axis(mesh)] if mesh is not None else 1
    budget_base = getattr(cfg, "hbm_budget_bytes", 0)
    if not fits_in_hbm(n, image_size, n_dev, max_fraction,
                       budget_base_bytes=budget_base):
        raise ValueError(
            f"{split} split ({dataset_bytes(n, image_size) / 1e9:.1f}"
            f" GB over {n_dev} chip(s)) exceeds the HBM-resident budget "
            f"({hbm_budget_bytes(max_fraction, budget_base_bytes=budget_base) / 1e9:.1f}"
            " GB/chip); use the tfdata or grain loader for datasets "
            "this size, or set data.hbm_budget_bytes if this chip's "
            "true memory limit is larger than the assumed base"
        )
    if multiprocess:
        images, grades = _load_index_rows_sharded(
            index, n, image_size, mesh, workers=workers,
            quarantine=getattr(cfg, "quarantine_bad_records", True),
        )
    get_batch = make_batch_fn(
        images, grades, cfg.batch_size, seed, mesh=mesh, n_records=n
    )
    # Telemetry (obs/): the hbm loader is the 100%-residency endpoint —
    # every batch row is a cache hit (an on-device gather, zero H2D).
    from jama16_retina_tpu.obs import registry as obs_registry

    reg = obs_registry.default_registry()
    reg.gauge(
        "data.hbm.resident_rows",
        help="rows of the split pinned device-resident by the hbm "
             "loader (the 100%-hit endpoint)",
    ).set(n)
    c_gather = reg.counter(
        "data.hbm.gather_batches",
        help="batches served as pure on-device gathers (zero "
             "steady-state H2D)",
    )
    step = skip_batches
    while True:
        batch = get_batch(step)
        c_gather.inc()  # before yield: the last batch is counted too
        yield batch
        step += 1
