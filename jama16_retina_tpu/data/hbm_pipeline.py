"""HBM-resident train input: the ``data.loader="hbm"`` option.

The literal form of "decoding straight into HBM" (BASELINE.json:5): the
whole decoded uint8 split is uploaded to device memory ONCE at startup,
and every train batch after that is an on-device gather — zero per-step
host→device traffic, zero host decode on the hot path. docs/PERF.md §H2D
measured why this matters here: on the axon tunnel the per-batch H2D
copy collapses to ~18 MB/s after the train executable loads, capping the
streamed pipeline at ~28-120 img/s while the chip can train at ~1300;
paying the (slow) upload once moves the steady-state rate back to the
device-only ceiling. On healthy PCIe hosts the same mode removes the
host from the steady-state entirely — useful for small/medium datasets
(EyePACS train at 299px raw uint8 is ~15 GB vs 16 GB/chip HBM on v5e,
so the fit is gated, not assumed; see ``fits_in_hbm``).

Batch selection is a pure function of (seed, step), computed ON DEVICE
inside one jit program per step:

    epoch = step // steps_per_epoch        (drop-remainder epochs)
    perm  = random.permutation(fold_in(key(seed), epoch), n)
    idx   = perm[pos : pos + batch]        (pos = in-epoch offset)

so epochs are exact global reshuffles (every record exactly once per
epoch, like the grain loader's index sampling) and resume is O(1):
``skip_batches=k`` just starts the step counter at k — the same
(seed, step) contract as the jit step's fold_in keys (SURVEY.md §5.4).

Single-process only (it is a single-host lever; multi-host slices keep
the streamed loaders whose per-process sharding is wired end-to-end).
Multi-CHIP within one process works: pass a mesh and the resident
dataset rows shard across the data axis; the per-step gather is then a
GSPMD collective over ICI, which is exactly the fabric it should ride.
"""

from __future__ import annotations

from typing import Iterator

import numpy as np

from jama16_retina_tpu.configs import DataConfig
from jama16_retina_tpu.data import tfrecord


def load_split_numpy(
    data_dir: str, split: str, image_size: int
) -> tuple[np.ndarray, np.ndarray]:
    """All records of a split, decoded on host once:
    (images u8[N,S,S,3], grades i32[N]). Reuses the grain loader's
    TF-free record index + proto decode (data/grain_pipeline.py)."""
    from jama16_retina_tpu.data.grain_pipeline import (
        TFRecordIndex,
        _decode_example,
    )

    index = TFRecordIndex(tfrecord.list_split(data_dir, split))
    n = len(index)
    if n == 0:
        raise ValueError(f"no records under {data_dir}/{split}")
    images = np.empty((n, image_size, image_size, 3), np.uint8)
    grades = np.empty((n,), np.int32)
    for i in range(n):
        row = _decode_example(index.read(i), image_size)
        images[i] = row["image"]
        grades[i] = row["grade"]
    return images, grades


def dataset_bytes(n: int, image_size: int) -> int:
    return n * image_size * image_size * 3 + 4 * n


def hbm_budget_bytes(max_fraction: float = 0.6) -> int:
    """Per-chip HBM budget for the resident dataset: ``max_fraction`` of
    the device's memory limit when the runtime reports one, else a
    conservative 16 GB v5e-class assumption. The remaining fraction
    belongs to the model/optimizer/activations (the flagship step's live
    set is ~2 GB; 0.6 leaves ~3x headroom)."""
    import jax

    limit = None
    try:
        stats = jax.local_devices()[0].memory_stats()
        if stats:
            limit = stats.get("bytes_limit")
    except Exception:
        pass
    if not limit:
        limit = 16 * 1024**3
    return int(limit * max_fraction)


def fits_in_hbm(
    n: int, image_size: int, n_devices: int = 1, max_fraction: float = 0.6
) -> bool:
    """The size gate: the dataset shards row-wise across the mesh's data
    axis, so the per-chip share must fit the per-chip budget."""
    per_chip = dataset_bytes(n, image_size) / max(n_devices, 1)
    return per_chip <= hbm_budget_bytes(max_fraction)


def make_batch_fn(images, grades, batch_size: int, seed: int, mesh=None):
    """jit'd ``step -> {'image','grade'}`` gather over the resident
    arrays. With a mesh, the dataset is row-sharded over the data axis
    and the output batch carries the standard batch sharding — the
    shuffle gather becomes an ICI collective under GSPMD."""
    import jax
    import jax.numpy as jnp

    from jama16_retina_tpu.parallel import mesh as mesh_lib

    n = images.shape[0]
    if batch_size > n:
        raise ValueError(f"batch_size={batch_size} exceeds dataset n={n}")
    steps_per_epoch = n // batch_size
    base = jax.random.key(seed)

    if mesh is not None:
        # Row-sharding needs dim 0 divisible by the data axis; real
        # splits have arbitrary counts, so pad with leading records
        # re-used as filler. The permutation draws indices < n only —
        # padding rows are never sampled, so epoch semantics are
        # unchanged (no record lost, none duplicated).
        d = mesh.shape[mesh_lib._batch_axis(mesh)]
        pad = (-n) % d
        if pad:
            images = np.concatenate([images, images[:pad]])
            grades = np.concatenate([grades, grades[:pad]])
        data_sh = mesh_lib.batch_sharding(mesh)
        images = jax.device_put(images, data_sh)
        grades = jax.device_put(grades, data_sh)
    else:
        images = jax.device_put(images)
        grades = jax.device_put(grades)

    def get_batch(step):
        epoch = step // steps_per_epoch
        pos = (step % steps_per_epoch) * batch_size
        perm = jax.random.permutation(jax.random.fold_in(base, epoch), n)
        idx = jax.lax.dynamic_slice(perm, (pos,), (batch_size,))
        return {
            "image": jnp.take(images, idx, axis=0),
            "grade": jnp.take(grades, idx, axis=0),
        }

    if mesh is None:
        return jax.jit(get_batch)
    return jax.jit(
        get_batch,
        out_shardings={
            "image": mesh_lib.batch_sharding(mesh),
            "grade": mesh_lib.batch_sharding(mesh),
        },
    )


def train_batches(
    data_dir: str,
    split: str,
    cfg: DataConfig,
    image_size: int,
    seed: int = 0,
    skip_batches: int = 0,
    mesh=None,
    max_fraction: float = 0.6,
) -> Iterator[dict]:
    """Drop-in twin of pipeline.train_batches yielding DEVICE-resident
    batches. ``skip_batches`` is an O(1) counter offset (pure (seed,
    step) semantics — no replay, no state files)."""
    import jax

    if jax.process_count() > 1:
        raise NotImplementedError(
            "data.loader='hbm' is single-process (a single-host lever); "
            "multi-host slices should use the tfdata or grain loader, "
            "whose per-process input sharding is wired end-to-end"
        )
    images, grades = load_split_numpy(data_dir, split, image_size)
    # The dataset shards across the DATA axis only (replicated over any
    # 'member' axis of an ensemble mesh) — gating on total device count
    # would under-count per-chip bytes by the member-axis factor.
    from jama16_retina_tpu.parallel import mesh as mesh_lib

    n_dev = mesh.shape[mesh_lib._batch_axis(mesh)] if mesh is not None else 1
    if not fits_in_hbm(len(images), image_size, n_dev, max_fraction):
        raise ValueError(
            f"{split} split ({dataset_bytes(len(images), image_size) / 1e9:.1f}"
            f" GB over {n_dev} chip(s)) exceeds the HBM-resident budget "
            f"({hbm_budget_bytes(max_fraction) / 1e9:.1f} GB/chip); use the "
            "tfdata or grain loader for datasets this size"
        )
    get_batch = make_batch_fn(
        images, grades, cfg.batch_size, seed, mesh=mesh
    )
    step = skip_batches
    while True:
        yield get_batch(step)
        step += 1
