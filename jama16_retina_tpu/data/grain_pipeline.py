"""Grain-based train input pipeline: the ``data.loader="grain"`` option.

The tf.data path (data/pipeline.py) resumes by deterministic REPLAY —
``skip_batches=k`` re-reads up to an epoch of records to reach position
k (SURVEY.md §5.4). This module is the O(1)-resume alternative named by
SURVEY.md N4/§5.4: grain's index-based sampling makes the pipeline
position an explicit, restorable value, and the position after k steps
is DERIVABLE (``state_at_step``) — so resume stays a pure function of
(seed, step), the same contract as the jit step's fold_in keys, with no
side-channel state files.

TPU-first consequences of index sampling over stream sampling:

  * GLOBAL shuffle per epoch (a permutation of all record indices), not
    tf.data's sliding-window approximation — better sample decorrelation
    at identical memory cost (the permutation is implicit, seed-derived).
  * Per-process sharding is exact and drop-remainder-stable via
    ``ShardOptions`` on the sampler: process p reads indices p, p+P, ...
    of the permuted stream; no coordination, no overlap.
  * Random access needs record offsets; TFRecord is a sequential format,
    so ``TFRecordIndex`` scans the length-prefixed framing once at
    startup (cheap: two small reads per record, no payload decode) and
    caches ``(path, offset, length)`` per record.
  * The train path needs NO TensorFlow graph machinery: protos are
    parsed with the protobuf runtime and JPEGs decoded by OpenCV.

Eval stays on the tf.data path (padded global batches, multi-host
batch-count alignment — see pipeline.eval_batches); eval is a rare,
epoch-bounded pass where replay cost is irrelevant.
"""

from __future__ import annotations

import json
import os
import struct
import time
from typing import Any, Iterator, Sequence

import numpy as np
from absl import logging as absl_logging

from jama16_retina_tpu.configs import DataConfig
from jama16_retina_tpu.data import tfrecord
from jama16_retina_tpu.obs import faultinject
from jama16_retina_tpu.obs import registry as obs_registry
from jama16_retina_tpu.utils import retry as retry_lib


class TFRecordIndex:
    """Random-access index over TFRecord shards.

    TFRecord framing per record: u64le payload length, u32 masked CRC of
    the length, payload, u32 masked CRC of the payload. The index stores
    payload extents only; CRCs are not verified (same stance as tf.data's
    default) — a torn file surfaces as a proto parse error instead.
    """

    def __init__(self, paths: Sequence[str]):
        import threading

        self.paths = list(paths)
        self._extents: list[tuple[int, int, int]] = []  # (path_i, off, len)
        self._files: dict[int, Any] = {}  # lazy per-shard descriptors
        self._open_lock = threading.Lock()
        for pi, path in enumerate(self.paths):
            with open(path, "rb") as f:
                off = 0
                while True:
                    header = f.read(12)
                    if not header:
                        break
                    if len(header) < 12:
                        raise ValueError(f"truncated TFRecord header in {path}")
                    (length,) = struct.unpack("<Q", header[:8])
                    self._extents.append((pi, off + 12, length))
                    off += 12 + length + 4
                    f.seek(off)

    def __len__(self) -> int:
        return len(self._extents)

    def _pread(self, pi: int, length: int, off: int) -> bytes:
        """One positioned read through the fault seam: ``tfrecord.read``
        chaos entries can raise (transient-I/O drill), add latency, or
        corrupt the returned payload (poison-record drill) — unarmed it
        costs one global read + branch."""
        fd = self._files.get(pi)
        if fd is None:
            # Locked first-open: two racing reader threads would both
            # os.open() and the loser's descriptor would leak.
            with self._open_lock:
                fd = self._files.get(pi)
                if fd is None:
                    fd = self._files[pi] = os.open(self.paths[pi], os.O_RDONLY)
        return faultinject.corrupt(
            "tfrecord.read", os.pread(fd, length, off)
        )

    def read(self, i: int) -> bytes:
        pi, off, length = self._extents[i]
        # Descriptors are cached per shard — global shuffle has no read
        # locality, so reopening per record would put an open/close
        # syscall pair on every image of the train hot path. os.pread is
        # a positioned read with no shared seek cursor: grain's reader
        # THREADS (ReadOptions defaults to a thread pool even with
        # worker_count=0) hit the same descriptor concurrently.
        # Transient-I/O absorption (ISSUE 6): up to 3 backoff retries
        # per read (utils/retry.py, counted under
        # io.retries.tfrecord.read). Still-failing reads raise the
        # original OSError — the decode layer's quarantine then owns
        # the record. retry_call's quiet-path overhead is one closure
        # frame per read, ~1000x under the decode it feeds.
        return retry_lib.retry_call(
            self._pread, pi, length, off,
            attempts=4, site="tfrecord.read",
        )

    # Keep the index picklable for grain worker processes: descriptors
    # and the lock are per-process state, recreated after unpickling.
    def __getstate__(self):
        state = self.__dict__.copy()
        state["_files"] = {}
        del state["_open_lock"]
        return state

    def __setstate__(self, state):
        import threading

        self.__dict__.update(state)
        self._open_lock = threading.Lock()

    def __del__(self):
        for fd in self.__dict__.get("_files", {}).values():
            try:
                os.close(fd)
            except OSError:
                pass


def _decode_example(payload: bytes, image_size: int) -> dict[str, Any]:
    """Serialized tf.train.Example -> {'image': u8[S,S,3], 'grade': i32}.

    Mirrors tfrecord.parse_fn (raw and JPEG encodings, bilinear resize to
    the model size when shards were written at another size) without any
    TF graph machinery on the hot path.

    Pixel parity with tf.data: BIT-EXACT for records stored at the model
    size — the layout preprocess_* writes, and what test_grain.py pins.
    The resize FALLBACK is best-effort only: cv2's INTER_LINEAR (rounds)
    and tf.image.resize (truncating cast) differ in low-order bits, so
    store shards at the training size if loaders must be interchangeable.
    """
    import cv2
    from tensorflow.core.example import example_pb2

    ex = example_pb2.Example.FromString(payload)
    feat = ex.features.feature
    raw = feat["image/raw"].bytes_list.value
    if raw and raw[0]:
        h = feat["image/height"].int64_list.value[0]
        w = feat["image/width"].int64_list.value[0]
        image = np.frombuffer(raw[0], np.uint8).reshape(h, w, 3)
    else:
        jpeg = feat["image/encoded"].bytes_list.value[0]
        bgr = cv2.imdecode(np.frombuffer(jpeg, np.uint8), cv2.IMREAD_COLOR)
        if bgr is None:
            raise ValueError("JPEG decode failed")
        image = bgr[..., ::-1]  # records are RGB-encoded (tfrecord.encode_jpeg)
    if image.shape[:2] != (image_size, image_size):
        image = cv2.resize(
            image, (image_size, image_size), interpolation=cv2.INTER_LINEAR
        )
    grade = np.int32(feat["image/grade"].int64_list.value[0])
    return {"image": np.ascontiguousarray(image), "grade": grade}


def resolve_decode_workers(requested: int) -> int:
    """DataConfig.decode_workers resolution: explicit positive counts are
    taken verbatim; 0 auto-derives from the host — one thread per core
    up to 8 (past ~8 the shared TFRecordIndex descriptors and the numpy
    stack in the batcher stop scaling), always leaving one core for the
    device-dispatch thread. A 1-vCPU host resolves to 1, which is
    exactly the pre-parallel single-stream decode."""
    if requested > 0:
        return requested
    cpus = os.cpu_count() or 1
    return max(1, min(8, cpus - 1))


class ParallelDecoder:
    """Deterministic multi-core decode stage over a TFRecordIndex.

    The single-stream ``_decode_example`` loop caps host feed at ~1.7k
    img/s on this class of host (bench host_grain_raw) while the chip
    consumes ~1.4k img/s of TRAIN STEP alone — any eval/checkpoint pause
    or faster model leaves the chip idle on ingest. This stage shards
    record decoding across a thread pool; OpenCV's JPEG decode and the
    raw-record frombuffer/resize paths all release the GIL, so threads
    scale without the pickling/startup cost of grain's worker PROCESSES.

    Determinism contract: output depends only on the record ids asked
    for, never on worker count or scheduling — ``decode_batch`` maps ids
    in order, and ``decode_range`` has each worker fill a disjoint slice
    of one preallocated array. That is what lets the tiered loader keep
    the (seed, step) resume purity the trainer relies on (the same
    contract as hbm_pipeline; _GrainStateTee is untouched because the
    grain loader keeps its own worker-process machinery).
    """

    def __init__(self, index: TFRecordIndex, image_size: int,
                 workers: int = 1,
                 registry: "obs_registry.Registry | None" = None,
                 quarantine: bool = True):
        self.index = index
        self.image_size = image_size
        self.workers = max(1, int(workers))
        # Poison-record quarantine (ISSUE 6): a payload that fails to
        # decode is counted (data.quarantined{reason}) and
        # deterministically SUBSTITUTED with the next decodable record
        # instead of re-raising on the caller thread and killing the
        # epoch. Substitution depends only on record ids, so the
        # worker-count-invariance contract holds for poisoned shards
        # too. quarantine=False restores raise-through (debugging).
        self.quarantine = bool(quarantine)
        # Worker-utilization telemetry (obs/): records decoded and the
        # SUM of per-record decode time across all worker threads.
        # utilization = busy_s / (wall * workers) — obs_report divides;
        # a pool at 10% busy means the streamed tier is starved on
        # upstream reads or consumers, not on decode CPU.
        self._registry = (
            registry if registry is not None
            else obs_registry.default_registry()
        )
        self._c_records = self._registry.counter(
            "data.decode.records",
            help="records decoded by the parallel host decode pool",
        )
        self._c_busy = self._registry.counter(
            "data.decode.busy_s",
            help="summed per-record decode seconds across pool workers; "
                 "utilization = delta / (wall x workers)",
        )
        self._c_quarantined = self._registry.counter(
            "data.quarantined",
            help="records skipped by the poison quarantine (corrupt "
                 "payload / failed decode), all reasons; the "
                 "data_quarantine alert rule reads this burn rate",
        )
        self._registry.gauge(
            "data.decode.workers",
            help="decode threads in the parallel host pool (live-"
                 "resized by the ingest autotuner)",
        ).set(self.workers)
        self._pool = None
        if self.workers > 1:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                max_workers=self.workers, thread_name_prefix="jama16-decode"
            )

    def __len__(self) -> int:
        return len(self.index)

    def set_workers(self, n: int) -> None:
        """Resize the decode pool live (the autotuner's decode_workers
        knob; data/autotune.py). Output is worker-count-invariant by
        the class contract, so this is a pure throughput adjustment.
        Caller contract: invoked BETWEEN decode calls on the consuming
        thread (the tiered fill loop polls it per batch) — never
        concurrently with an in-flight decode_batch/decode_range."""
        n = max(1, int(n))
        if n == self.workers:
            return
        old = self._pool
        self.workers = n
        if n > 1:
            from concurrent.futures import ThreadPoolExecutor

            self._pool = ThreadPoolExecutor(
                max_workers=n, thread_name_prefix="jama16-decode"
            )
        else:
            self._pool = None
        if old is not None:
            # No tasks are in flight (caller contract) — the old pool's
            # idle threads just exit.
            old.shutdown(wait=False)
        self._registry.gauge("data.decode.workers").set(n)

    def _read_decode(self, i: int, n: "int | None" = None) -> dict:
        return _decode_example(
            self.index.read(i % n if n else i), self.image_size
        )

    def _quarantine_substitute(self, i: int, n: "int | None",
                               exc: Exception) -> dict:
        """Count the poison record and return the NEXT decodable record
        (scanning forward, wrapping) — a pure function of record ids,
        so batches stay worker-count- and schedule-invariant. Raises
        only when EVERY record is undecodable (that is not a poison
        record, that is a destroyed dataset)."""
        total = n if n else len(self.index)
        reason = (
            "read_error" if isinstance(exc, OSError) else "decode_error"
        )
        self._c_quarantined.inc()
        self._registry.counter(
            f"data.quarantined.{reason}",
            help="poison records quarantined for this one reason "
                 "(decode_error/read_error)",
        ).inc()
        absl_logging.warning(
            "record %d quarantined (%s: %s); substituting the next "
            "decodable record", i, type(exc).__name__, exc,
        )
        for k in range(1, total):
            j = (i + k) % total
            try:
                return self._read_decode(j, n)
            except Exception:  # noqa: BLE001 - keep scanning
                self._c_quarantined.inc()
                continue
        raise ValueError(
            f"every record in the split failed to decode (started from "
            f"record {i}) — this is not a poison record, the dataset "
            "is destroyed"
        ) from exc

    def _decode_one(self, i: int, n: "int | None" = None) -> dict:
        if not self._registry.enabled and not self.quarantine:
            return self._read_decode(i, n)
        t0 = time.perf_counter() if self._registry.enabled else 0.0
        try:
            row = self._read_decode(i, n)
        except Exception as e:  # noqa: BLE001 - quarantine decides
            if not self.quarantine:
                raise
            row = self._quarantine_substitute(i, n, e)
        if self._registry.enabled:
            self._c_busy.inc(time.perf_counter() - t0)
            self._c_records.inc()
        return row

    def decode_batch(self, ids) -> dict:
        """ids -> {'image': u8[len(ids),S,S,3], 'grade': i32[len(ids)]},
        rows in ``ids`` order regardless of worker count."""
        ids = [int(i) for i in ids]
        if self._pool is None:
            rows = [self._decode_one(i) for i in ids]
        else:
            rows = list(self._pool.map(self._decode_one, ids))
        return _batch_dicts(rows)

    def decode_range(
        self, start: int, stop: int, n: "int | None" = None
    ) -> tuple[np.ndarray, np.ndarray]:
        """Rows [start, stop) into preallocated uint8/i32 arrays — the
        parallel form of hbm_pipeline's decode loop (each worker fills a
        disjoint slice, so the result is worker-count-invariant).
        ``n``: wrap row ids past the true record count (multi-host
        padding rows reuse leading records as filler)."""
        count = stop - start
        images = np.empty(
            (count, self.image_size, self.image_size, 3), np.uint8
        )
        grades = np.empty((count,), np.int32)

        def fill(lo: int, hi: int) -> None:
            for i in range(lo, hi):
                row = self._decode_one(i, n)
                images[i - start] = row["image"]
                grades[i - start] = row["grade"]

        if self._pool is None or count < 2 * self.workers:
            fill(start, stop)
            return images, grades
        chunk = -(-count // self.workers)  # ceil
        futures = [
            self._pool.submit(
                fill, start + w * chunk, min(start + (w + 1) * chunk, stop)
            )
            for w in range(self.workers)
        ]
        for f in futures:
            f.result()  # re-raise decode errors on the caller thread
        return images, grades

    def close(self) -> None:
        if self._pool is not None:
            self._pool.shutdown(wait=False)
            self._pool = None


class FundusSource:
    """grain RandomAccessDataSource over fundus TFRecord shards."""

    def __init__(self, data_dir: str, split: str, image_size: int):
        self.index = TFRecordIndex(tfrecord.list_split(data_dir, split))
        self.image_size = image_size

    def __len__(self) -> int:
        return len(self.index)

    def __getitem__(self, i: int) -> dict[str, Any]:
        return _decode_example(self.index.read(int(i)), self.image_size)

    def __repr__(self) -> str:  # embedded in grain's state JSON
        return f"FundusSource(n={len(self)}, size={self.image_size})"


def _batch_dicts(rows) -> dict[str, np.ndarray]:
    return {
        "image": np.stack([r["image"] for r in rows]),
        "grade": np.asarray([r["grade"] for r in rows], np.int32),
    }


def make_train_iterator(
    data_dir: str,
    split: str,
    cfg: DataConfig,
    image_size: int,
    seed: int = 0,
    process_index: int | None = None,
    process_count: int | None = None,
    worker_count: int = 0,
):
    """Infinite per-process loader of {'image': [b,S,S,3], 'grade': [b]}
    local batches (b = batch_size / P), as a grain iterator with
    get_state()/set_state(). Same yield contract as pipeline.train_batches.
    """
    import grain.python as pygrain

    from jama16_retina_tpu.data.pipeline import (
        _local_batch_size,
        _resolve_process,
    )

    p_idx, p_cnt = _resolve_process(process_index, process_count)
    local_bs = _local_batch_size(cfg.batch_size, p_cnt, "data.batch_size")
    source = FundusSource(data_dir, split, image_size)
    if len(source) == 0:
        raise ValueError(f"no records under {data_dir}/{split}")
    sampler = pygrain.IndexSampler(
        len(source),
        shard_options=pygrain.ShardOptions(
            shard_index=p_idx, shard_count=p_cnt, drop_remainder=True
        ),
        shuffle=True,
        num_epochs=None,  # infinite
        seed=seed,
    )
    try:
        batch_op = pygrain.Batch(
            local_bs, drop_remainder=True, batch_fn=_batch_dicts
        )
    except TypeError:
        # Older grain has no batch_fn; its default batching tree-stacks
        # the {'image','grade'} dict leaves, which is exactly what
        # _batch_dicts produces (np.stack images, i32 grades).
        batch_op = pygrain.Batch(local_bs, drop_remainder=True)
    loader = pygrain.DataLoader(
        data_source=source,
        sampler=sampler,
        operations=[batch_op],
        worker_count=worker_count,
    )
    return iter(loader)


def state_at_step(
    iterator, step: int, local_batch_size: int,
    process_index: int = 0, process_count: int = 1,
) -> bytes:
    """The grain state an uninterrupted run would have after ``step``
    batches — O(1) resume without saved pipeline state (SURVEY.md §5.4).

    grain's state is explicit: ``last_seen_indices`` holds GLOBAL
    sequence positions. Shard p of P enumerates positions p, p+P,
    p+2P, ... (verified empirically against get_state()), so after
    k = step * local_batch_size local records the in-process loader's
    last position is p + (k-1)*P. Deriving the state (rather than
    persisting get_state() bytes next to each checkpoint) keeps resume a
    pure function of (seed, step) — identical semantics to the tf.data
    path's skip_batches, minus the replayed decode. Defined only for
    worker_count=0 (raises otherwise): worker processes emit whole
    batches round-robin, making per-worker positions k-dependent in a
    way no closed form reproduces.
    """
    state = json.loads(iterator.get_state().decode())
    if int(state["worker_count"]) > 0:
        # Worker processes emit whole BATCHES round-robin, so per-worker
        # record consumption is uneven for arbitrary k — the even-split
        # formula below would fabricate a state no real run ever had.
        # Use get_state()/set_state() persistence for worker_count>0.
        raise NotImplementedError(
            "state_at_step derivation is defined for in-process loading "
            "(worker_count=0, the default); worker-process runs resume "
            "from the get_state() bytes the trainer persists next to "
            "each checkpoint (grain_state/<step>.json — absent here, so "
            "either this workdir predates worker-mode persistence or "
            "the state file for this step was lost)"
        )
    k = step * local_batch_size
    state["last_seen_indices"] = {
        "0": process_index + (k - 1) * process_count if k else -1
    }
    # In-process loading never advances last_worker_index.
    state["last_worker_index"] = -1
    return json.dumps(state).encode()


def train_batches(
    data_dir: str,
    split: str,
    cfg: DataConfig,
    image_size: int,
    seed: int = 0,
    process_index: int | None = None,
    process_count: int | None = None,
    skip_batches: int = 0,
    worker_count: int = 0,
    initial_state: bytes | None = None,
) -> Iterator[dict]:
    """Drop-in twin of pipeline.train_batches on the grain loader —
    ``skip_batches`` is an O(1) state restore instead of a replay.

    ``initial_state``: explicit grain iterator state to restore (the
    resume path for ``worker_count > 0``, where positions have no
    closed form — the trainer persists ``get_state()`` bytes next to
    each checkpoint and hands them back here; see state_at_step)."""
    it = make_train_iterator(
        data_dir, split, cfg, image_size, seed=seed,
        process_index=process_index, process_count=process_count,
        worker_count=worker_count,
    )
    if initial_state is not None:
        it.set_state(initial_state)
    elif skip_batches:
        from jama16_retina_tpu.data.pipeline import (
            _local_batch_size,
            _resolve_process,
        )

        p_idx, p_cnt = _resolve_process(process_index, process_count)
        local_bs = _local_batch_size(cfg.batch_size, p_cnt, "data.batch_size")
        it.set_state(
            state_at_step(it, skip_batches, local_bs, p_idx, p_cnt)
        )
    return it
