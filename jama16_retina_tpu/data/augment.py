"""On-device image augmentation in JAX (reference R5's augment stage).

The reference augments in tf.data on the host (flips + brightness /
contrast / saturation / hue jitter, SURVEY.md R5). On a 1-vCPU host that
would starve the TPU, so augmentation runs *inside* the jit'd train step
on uint8 batches already in HBM: XLA fuses the whole thing into the
input-normalization epilogue, and the host↔device transfer stays uint8
(3x smaller than f32).

All ops are shape-static and batched; randomness comes from one PRNG key
per step, split per-example — so a (step, example) pair fully determines
the augmentation, which is what makes the determinism test in
tests/test_pipeline.py possible. Fundus-specific extra: 90-degree
rotations + both flips (retinas have no canonical orientation).

Hue/saturation follow the classic YIQ-space approximation (rotation
about / scaling of the chroma plane) rather than an HSV round-trip: one
3x3 matmul per pixel, MXU-trivial, visually equivalent for small jitter.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from jama16_retina_tpu.configs import DataConfig

# RGB <-> YIQ (NTSC) matrices. The inverse is computed (in f64) rather
# than using the classic hand-rounded [[1, .956, .621], ...] constants:
# those are only a 3-decimal approximation, so the round trip
# YIQ2RGB @ RGB2YIQ lands ~2.7e-3 off identity — a visible color shift
# on every image and an irreducible gap between the sequential jnp path
# and the pallas affine-collapsed path. With the true inverse the round
# trip is identity to f32 rounding.
_RGB2YIQ_F64 = np.array(
    [
        [0.299, 0.587, 0.114],
        [0.596, -0.274, -0.322],
        [0.211, -0.523, 0.312],
    ]
)
_RGB2YIQ = jnp.asarray(_RGB2YIQ_F64, dtype=jnp.float32)
_YIQ2RGB = jnp.asarray(np.linalg.inv(_RGB2YIQ_F64), dtype=jnp.float32)


def normalize(images_u8: jnp.ndarray) -> jnp.ndarray:
    """uint8 [0,255] -> float32 [-1, 1] (Inception input convention)."""
    return images_u8.astype(jnp.float32) / 127.5 - 1.0


def _draw_params(key: jax.Array, n: int, cfg: DataConfig) -> dict:
    """All augmentation randomness in 6 batch-level draws. Per-example
    PRNG-key trees are threefry-expensive on TPU (hundreds of splits per
    batch); drawing [n]-shaped vectors once keeps the RNG cost flat."""
    k = jax.random.split(key, 6)
    lo, hi = cfg.contrast_range
    slo, shi = cfg.saturation_range
    return {
        "hflip": jax.random.bernoulli(k[0], shape=(n,)),
        "vflip": jax.random.bernoulli(k[1], shape=(n,)),
        "transpose": jax.random.bernoulli(k[2], shape=(n,)),
        "brightness": jax.random.uniform(
            k[3], (n,), minval=-cfg.brightness_delta,
            maxval=cfg.brightness_delta,
        ),
        "contrast": jax.random.uniform(k[4], (n,), minval=lo, maxval=hi),
        "sat_hue": jax.random.uniform(
            k[5], (n, 2), minval=jnp.array([slo, -cfg.hue_delta]),
            maxval=jnp.array([shi, cfg.hue_delta]),
        ),
    }


def _augment_one(img: jnp.ndarray, p: dict, cfg: DataConfig) -> jnp.ndarray:
    """img: HWC float32 in [-1, 1]; p: this example's slice of the params."""
    if cfg.flip:
        img = jnp.where(p["hflip"], img[:, ::-1], img)
        img = jnp.where(p["vflip"], img[::-1, :], img)
    if cfg.rotate and img.shape[0] == img.shape[1]:
        # A random transpose composed with the two flips above generates
        # the full dihedral group of the square — all four 90-degree
        # rotations plus reflections — as three independent coin flips.
        # One fused select instead of a 4-branch lax.switch, which under
        # vmap materializes every rotated copy of the whole batch.
        # Statically skipped for H != W: a transpose changes a rectangle's
        # shape, and the rectangle's symmetry group has no 90-degree
        # rotation — the two flips above already cover it.
        img = jnp.where(p["transpose"], jnp.swapaxes(img, 0, 1), img)

    if cfg.brightness_delta > 0:
        img = img + p["brightness"]
    lo, hi = cfg.contrast_range
    if (lo, hi) != (1.0, 1.0):
        mean = img.mean(axis=(0, 1), keepdims=True)
        img = (img - mean) * p["contrast"] + mean

    # Chroma jitter in YIQ space: saturation scales (I, Q); hue rotates them.
    # The 3x3 matmuls are pinned to full-f32 precision: on TPU the MXU
    # default is bf16 multiplicands, a ~1e-3 color error per round trip
    # that costs nothing to avoid at this size (and would otherwise make
    # the TPU jnp path diverge from CPU and from the pallas kernel).
    slo, shi = cfg.saturation_range
    if (slo, shi) != (1.0, 1.0) or cfg.hue_delta > 0:
        hp = jax.lax.Precision.HIGHEST
        yiq = jnp.matmul(img, _RGB2YIQ.T, precision=hp)
        s = p["sat_hue"][0]
        theta = p["sat_hue"][1] * (2.0 * jnp.pi)
        cos, sin = jnp.cos(theta) * s, jnp.sin(theta) * s
        i, q = yiq[..., 1], yiq[..., 2]
        yiq = jnp.stack(
            [yiq[..., 0], cos * i - sin * q, sin * i + cos * q], axis=-1
        )
        img = jnp.matmul(yiq, _YIQ2RGB.T, precision=hp)

    return jnp.clip(img, -1.0, 1.0)


def augment_batch_np(
    rng: "np.random.Generator", images_u8: np.ndarray, cfg: DataConfig
) -> np.ndarray:
    """Numpy twin of augment_batch for the host-side legacy TF backend
    (trainer.fit_tf): the SAME ops, ranges, and op order — flips /
    dihedral transpose, brightness, contrast about the per-image mean,
    and YIQ-space saturation/hue with the exact inverse matrix.

    Parity is distributional, not bitwise: draws come from numpy's
    PRNG (the caller seeds it with (seed, step) for resume
    determinism), while the TPU path derives threefry draws in-step.
    Returns float32 [-1, 1] NHWC.
    """
    imgs = images_u8.astype(np.float32) / 127.5 - 1.0
    if not cfg.augment:
        return imgs
    n = imgs.shape[0]

    def per_ex(x):
        return x[:, None, None, None]

    if cfg.flip:
        h, v = rng.random(n) < 0.5, rng.random(n) < 0.5
        imgs = np.where(per_ex(h), imgs[:, :, ::-1], imgs)
        imgs = np.where(per_ex(v), imgs[:, ::-1], imgs)
    if cfg.rotate and imgs.shape[1] == imgs.shape[2]:
        t = rng.random(n) < 0.5
        imgs = np.where(per_ex(t), np.swapaxes(imgs, 1, 2), imgs)
    if cfg.brightness_delta > 0:
        imgs = imgs + per_ex(rng.uniform(
            -cfg.brightness_delta, cfg.brightness_delta, n
        ).astype(np.float32))
    lo, hi = cfg.contrast_range
    if (lo, hi) != (1.0, 1.0):
        c = rng.uniform(lo, hi, n).astype(np.float32)
        mean = imgs.mean(axis=(1, 2), keepdims=True)
        imgs = (imgs - mean) * per_ex(c) + mean
    slo, shi = cfg.saturation_range
    if (slo, shi) != (1.0, 1.0) or cfg.hue_delta > 0:
        s = rng.uniform(slo, shi, n).astype(np.float32)
        theta = rng.uniform(-cfg.hue_delta, cfg.hue_delta, n).astype(
            np.float32
        ) * (2.0 * np.pi)
        yiq = imgs @ np.asarray(_RGB2YIQ).T
        cos = (np.cos(theta) * s)[:, None, None]
        sin = (np.sin(theta) * s)[:, None, None]
        y, i, q = yiq[..., 0], yiq[..., 1], yiq[..., 2]
        yiq = np.stack([y, cos * i - sin * q, sin * i + cos * q], axis=-1)
        imgs = yiq @ np.asarray(_YIQ2RGB).T
    return np.clip(imgs, -1.0, 1.0).astype(np.float32)


def _geometric_one(img: jnp.ndarray, p: dict, cfg: DataConfig) -> jnp.ndarray:
    if cfg.flip:
        img = jnp.where(p["hflip"], img[:, ::-1], img)
        img = jnp.where(p["vflip"], img[::-1, :], img)
    if cfg.rotate and img.shape[0] == img.shape[1]:
        img = jnp.where(p["transpose"], jnp.swapaxes(img, 0, 1), img)
    return img


def augment_batch(
    key: jax.Array,
    images_u8: jnp.ndarray,
    cfg: DataConfig,
    interpret: bool = False,
    debug: bool = False,
    fused: bool = False,
) -> jnp.ndarray:
    """uint8 NHWC batch -> augmented float32 [-1,1] batch (train path).

    ``cfg.use_pallas`` routes the color math through the fused kernel
    (ops/pallas_augment.py); geometric moves are pixel permutations and
    commute with per-pixel color ops (the contrast mean is permutation-
    invariant), so applying color first is numerically equivalent to the
    jnp path's geometric-first order.

    ``fused`` (train.use_pallas_fused; ISSUE 11) goes one step further:
    the per-image contrast means are accumulated INSIDE the kernel
    (pallas_augment.fused_normalize_color_jitter), so the separate
    channel-means reduce pass over the uint8 batch disappears too —
    normalize + color jitter is one Mosaic program. Wins over
    ``use_pallas`` when both are set.

    ``debug`` (the trainer passes train.debug, SURVEY.md §5.2): chex
    shape/dtype asserts on the contract this function silently assumes —
    trace-time only, zero compiled cost.
    """
    if debug:
        import chex

        chex.assert_rank(images_u8, 4)
        chex.assert_type(images_u8, jnp.uint8)
        chex.assert_axis_dimension(images_u8, -1, 3)
    if not cfg.augment:
        return normalize(images_u8)
    params = _draw_params(key, images_u8.shape[0], cfg)
    if cfg.use_pallas or fused:
        from jama16_retina_tpu.ops import pallas_augment as pk

        # Mosaic only lowers on TPU; on any other backend (CPU tests,
        # --device=cpu, the multichip dryrun, a GPU host) fall back to
        # the kernel's interpret mode so use_pallas configs run anywhere.
        interpret = interpret or jax.default_backend() != "tpu"

        if fused:
            imgs = pk.fused_normalize_color_jitter(
                images_u8,
                pk.chroma_matrix(
                    params["sat_hue"][:, 0],
                    params["sat_hue"][:, 1] * (2.0 * jnp.pi),
                ),
                params["contrast"],
                params["brightness"],
                interpret=interpret,
            )
        else:
            affine, offset = pk.color_affine_from_params(
                pk.channel_means_u8(images_u8),
                params["brightness"],
                params["contrast"],
                params["sat_hue"][:, 0],
                params["sat_hue"][:, 1] * (2.0 * jnp.pi),
            )
            imgs = pk.fused_color_jitter(
                images_u8, affine, offset, interpret=interpret
            )
        return jax.vmap(lambda im, p: _geometric_one(im, p, cfg))(imgs, params)
    imgs = normalize(images_u8)
    return jax.vmap(lambda im, p: _augment_one(im, p, cfg))(imgs, params)
