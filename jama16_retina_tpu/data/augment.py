"""On-device image augmentation in JAX (reference R5's augment stage).

The reference augments in tf.data on the host (flips + brightness /
contrast / saturation / hue jitter, SURVEY.md R5). On a 1-vCPU host that
would starve the TPU, so augmentation runs *inside* the jit'd train step
on uint8 batches already in HBM: XLA fuses the whole thing into the
input-normalization epilogue, and the host↔device transfer stays uint8
(3x smaller than f32).

All ops are shape-static and batched; randomness comes from one PRNG key
per step, split per-example — so a (step, example) pair fully determines
the augmentation, which is what makes the determinism test in
tests/test_pipeline.py possible. Fundus-specific extra: 90-degree
rotations + both flips (retinas have no canonical orientation).

Hue/saturation follow the classic YIQ-space approximation (rotation
about / scaling of the chroma plane) rather than an HSV round-trip: one
3x3 matmul per pixel, MXU-trivial, visually equivalent for small jitter.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from jama16_retina_tpu.configs import DataConfig

# RGB <-> YIQ (NTSC) matrices.
_RGB2YIQ = jnp.array(
    [
        [0.299, 0.587, 0.114],
        [0.596, -0.274, -0.322],
        [0.211, -0.523, 0.312],
    ],
    dtype=jnp.float32,
)
_YIQ2RGB = jnp.array(
    [
        [1.0, 0.956, 0.621],
        [1.0, -0.272, -0.647],
        [1.0, -1.106, 1.703],
    ],
    dtype=jnp.float32,
)


def normalize(images_u8: jnp.ndarray) -> jnp.ndarray:
    """uint8 [0,255] -> float32 [-1, 1] (Inception input convention)."""
    return images_u8.astype(jnp.float32) / 127.5 - 1.0


def _augment_one(key: jax.Array, img: jnp.ndarray, cfg: DataConfig) -> jnp.ndarray:
    """img: HWC float32 in [-1, 1]."""
    k = jax.random.split(key, 8)

    if cfg.flip:
        img = jnp.where(jax.random.bernoulli(k[0]), img[:, ::-1], img)
        img = jnp.where(jax.random.bernoulli(k[1]), img[::-1, :], img)
    if cfg.rotate:
        # Uniform choice of 0/90/180/270 via lax.switch (square images).
        rot = jax.random.randint(k[2], (), 0, 4)
        img = jax.lax.switch(
            rot,
            [
                lambda x: x,
                lambda x: jnp.rot90(x, 1),
                lambda x: jnp.rot90(x, 2),
                lambda x: jnp.rot90(x, 3),
            ],
            img,
        )

    if cfg.brightness_delta > 0:
        img = img + jax.random.uniform(
            k[3], (), minval=-cfg.brightness_delta, maxval=cfg.brightness_delta
        )
    lo, hi = cfg.contrast_range
    if (lo, hi) != (1.0, 1.0):
        c = jax.random.uniform(k[4], (), minval=lo, maxval=hi)
        mean = img.mean(axis=(0, 1), keepdims=True)
        img = (img - mean) * c + mean

    # Chroma jitter in YIQ space: saturation scales (I, Q); hue rotates them.
    slo, shi = cfg.saturation_range
    if (slo, shi) != (1.0, 1.0) or cfg.hue_delta > 0:
        yiq = img @ _RGB2YIQ.T
        s = jax.random.uniform(k[5], (), minval=slo, maxval=shi)
        theta = jax.random.uniform(
            k[6], (), minval=-cfg.hue_delta, maxval=cfg.hue_delta
        ) * (2.0 * jnp.pi)
        cos, sin = jnp.cos(theta) * s, jnp.sin(theta) * s
        i, q = yiq[..., 1], yiq[..., 2]
        yiq = jnp.stack(
            [yiq[..., 0], cos * i - sin * q, sin * i + cos * q], axis=-1
        )
        img = yiq @ _YIQ2RGB.T

    return jnp.clip(img, -1.0, 1.0)


def augment_batch(
    key: jax.Array, images_u8: jnp.ndarray, cfg: DataConfig
) -> jnp.ndarray:
    """uint8 NHWC batch -> augmented float32 [-1,1] batch (train path)."""
    imgs = normalize(images_u8)
    if not cfg.augment:
        return imgs
    keys = jax.random.split(key, imgs.shape[0])
    return jax.vmap(lambda k, im: _augment_one(k, im, cfg))(keys, imgs)
