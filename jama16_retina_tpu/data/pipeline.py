"""Online input pipeline: TFRecord shards -> device-resident uint8 batches.

Reference layer: ``lib/dataset`` (SURVEY.md R5) — decode, augment,
shuffle, batch 32. TPU-native split of responsibilities (SURVEY.md N4):

  host (tf.data, CPU):  shard interleave -> parse -> JPEG decode ->
                        resize-if-needed -> shuffle -> batch (uint8)
  device (XLA, in-step): normalize + augment (data/augment.py), fused
                        into the train step's program

The host→device copy is uint8 and double-buffered (``device_prefetch``)
so H2D overlaps compute — the practical form of "decoding straight into
HBM" (BASELINE.json:5) on a 1-vCPU host.

Eval pipelines pad the last partial batch and carry a validity mask so
jit sees only one batch shape (static shapes, no recompiles) while the
metrics layer sees every real example exactly once.
"""

from __future__ import annotations

import collections
from typing import Iterator

import jax
import numpy as np

from jama16_retina_tpu.configs import DataConfig
from jama16_retina_tpu.data import tfrecord


def _build_tf_dataset(paths, image_size: int, training: bool, cfg: DataConfig,
                      seed: int):
    import tensorflow as tf

    ds = tf.data.Dataset.from_tensor_slices(list(paths))
    if training:
        ds = ds.shuffle(len(paths), seed=seed, reshuffle_each_iteration=True)
    ds = ds.interleave(
        tf.data.TFRecordDataset,
        cycle_length=min(4, len(paths)),
        num_parallel_calls=tf.data.AUTOTUNE,
        deterministic=not training,
    )
    parse = tfrecord.parse_fn()

    def to_features(serialized):
        image, grade, _ = parse(serialized)
        # decode_jpeg's static shape is unknown inside tf.data, so the
        # size check must be a dynamic tf.cond — a Python `if` on
        # image.shape would always take the resize branch, paying a
        # float round-trip per record even for correctly sized shards.
        shape = tf.shape(image)
        image = tf.cond(
            tf.logical_and(
                tf.equal(shape[0], image_size), tf.equal(shape[1], image_size)
            ),
            lambda: image,
            lambda: tf.cast(
                tf.image.resize(image, (image_size, image_size), method="bilinear"),
                tf.uint8,
            ),
        )
        image = tf.ensure_shape(image, (image_size, image_size, 3))
        return image, grade

    ds = ds.map(to_features, num_parallel_calls=tf.data.AUTOTUNE)
    return ds


def train_batches(
    data_dir: str,
    split: str,
    cfg: DataConfig,
    image_size: int,
    seed: int = 0,
) -> Iterator[dict]:
    """Infinite shuffled uint8 batches: {'image': [B,S,S,3], 'grade': [B]}."""
    import tensorflow as tf

    paths = tfrecord.list_split(data_dir, split)
    ds = _build_tf_dataset(paths, image_size, True, cfg, seed)
    ds = ds.shuffle(cfg.shuffle_buffer, seed=seed).repeat()
    ds = ds.batch(cfg.batch_size, drop_remainder=True)
    ds = ds.prefetch(cfg.prefetch_batches)
    for image, grade in ds.as_numpy_iterator():
        yield {"image": image, "grade": grade}


def eval_batches(
    data_dir: str,
    split: str,
    batch_size: int,
    image_size: int,
) -> Iterator[dict]:
    """One epoch of padded batches: {'image', 'grade', 'mask'} — mask=0 rows
    are padding and must be dropped after host gather."""
    paths = tfrecord.list_split(data_dir, split)
    ds = _build_tf_dataset(paths, image_size, False, DataConfig(), seed=0)
    ds = ds.batch(batch_size, drop_remainder=False)
    for image, grade in ds.as_numpy_iterator():
        n = image.shape[0]
        if n < batch_size:
            pad = batch_size - n
            image = np.concatenate(
                [image, np.zeros((pad, *image.shape[1:]), image.dtype)], axis=0
            )
            grade = np.concatenate([grade, np.zeros((pad,), grade.dtype)], axis=0)
        mask = (np.arange(batch_size) < n).astype(np.float32)
        yield {"image": image, "grade": grade, "mask": mask}


def device_prefetch(
    it: Iterator[dict], sharding=None, size: int = 2
) -> Iterator[dict]:
    """Move batches to device ahead of consumption (double-buffering).

    With a ``NamedSharding(mesh, P('data'))`` the put is the global-array
    scatter across the mesh's data axis; with None it targets the default
    device. jax.device_put is async — the queue depth of ``size`` is what
    lets H2D copies run behind the current step's compute.
    """
    queue: collections.deque = collections.deque()

    def put(batch: dict) -> dict:
        if sharding is None:
            return jax.device_put(batch)
        return jax.tree.map(
            lambda x: jax.device_put(x, _shard_for(x, sharding)), batch
        )

    def _shard_for(x, sharding):
        # Rank-aware: batch-dim sharding for arrays, replicated for scalars.
        import jax.sharding as jsh

        if not hasattr(sharding, "spec"):
            return sharding
        ndim = np.ndim(x)
        spec = list(sharding.spec) + [None] * max(0, ndim - len(sharding.spec))
        return jsh.NamedSharding(sharding.mesh, jsh.PartitionSpec(*spec[:ndim]))

    for batch in it:
        queue.append(put(batch))
        if len(queue) > size:
            yield queue.popleft()
    while queue:
        yield queue.popleft()
