"""Online input pipeline: TFRecord shards -> device-resident uint8 batches.

Reference layer: ``lib/dataset`` (SURVEY.md R5) — decode, augment,
shuffle, batch 32. TPU-native split of responsibilities (SURVEY.md N4):

  host (tf.data, CPU):  shard interleave -> parse -> JPEG decode ->
                        resize-if-needed -> shuffle -> batch (uint8)
  device (XLA, in-step): normalize + augment (data/augment.py), fused
                        into the train step's program

The host→device copy is uint8 and double-buffered (``device_prefetch``)
so H2D overlaps compute — the practical form of "decoding straight into
HBM" (BASELINE.json:5) on a 1-vCPU host.

Eval pipelines pad the last partial batch and carry a validity mask so
jit sees only one batch shape (static shapes, no recompiles) while the
metrics layer sees every real example exactly once.
"""

from __future__ import annotations

import collections
import os
from typing import Iterator

import jax
import numpy as np

from jama16_retina_tpu.configs import DataConfig
from jama16_retina_tpu.data import tfrecord


def _serialized_stream(paths, training: bool, seed: int,
                       record_shard: tuple[int, int] | None = None):
    """The deterministic serialized-record stream every consumer shares:
    eval metadata passes MUST see the identical record order the decode
    stream produces (the interleave merge order is part of the
    contract), so this is the one home for it."""
    import tensorflow as tf

    ds = tf.data.Dataset.from_tensor_slices(list(paths))
    if training:
        ds = ds.shuffle(len(paths), seed=seed, reshuffle_each_iteration=True)
    # deterministic=True even for training: the batch stream must be a
    # pure function of (files, seed) so a resumed run can skip to its
    # exact position (SURVEY.md §5.4 "input-pipeline position"; see
    # train_batches skip_batches). Parallel reads still overlap — only
    # their merge order is pinned.
    ds = ds.interleave(
        tf.data.TFRecordDataset,
        cycle_length=min(4, len(paths)),
        num_parallel_calls=tf.data.AUTOTUNE,
        deterministic=True,
    )
    if record_shard is not None:
        # Stride the SERIALIZED record stream — before the parse/decode
        # map — so each host pays only 1/P of the decode work (tf.data's
        # shard-early guidance). Requires the upstream file order to be
        # identical on every process; train_batches guarantees that by
        # using the un-offset seed in this branch.
        ds = ds.shard(*record_shard)
    return ds


def _build_tf_dataset(paths, image_size: int, training: bool, cfg: DataConfig,
                      seed: int, record_shard: tuple[int, int] | None = None):
    import tensorflow as tf

    ds = _serialized_stream(paths, training, seed, record_shard)
    parse = tfrecord.parse_fn()

    def to_features(serialized):
        image, grade, name = parse(serialized)
        # decode_jpeg's static shape is unknown inside tf.data, so the
        # size check must be a dynamic tf.cond — a Python `if` on
        # image.shape would always take the resize branch, paying a
        # float round-trip per record even for correctly sized shards.
        shape = tf.shape(image)
        image = tf.cond(
            tf.logical_and(
                tf.equal(shape[0], image_size), tf.equal(shape[1], image_size)
            ),
            lambda: image,
            lambda: tf.cast(
                tf.image.resize(image, (image_size, image_size), method="bilinear"),
                tf.uint8,
            ),
        )
        image = tf.ensure_shape(image, (image_size, image_size, 3))
        return image, grade, name

    ds = ds.map(to_features, num_parallel_calls=tf.data.AUTOTUNE)
    return ds


def train_batches(
    data_dir: str,
    split: str,
    cfg: DataConfig,
    image_size: int,
    seed: int = 0,
    process_index: int | None = None,
    process_count: int | None = None,
    skip_batches: int = 0,
) -> Iterator[dict]:
    """Infinite shuffled uint8 batches: {'image': [B,S,S,3], 'grade': [B]}.

    ``skip_batches``: resume support (SURVEY.md §5.4). The stream is a
    pure function of (files, seed) — deterministic interleave + seeded
    shuffles — so skipping k batches reproduces exactly the state an
    uninterrupted run would have after k steps. The skipped records are
    still read/decoded once at startup (bounded: ~one decode pass per
    skipped epoch; raw-encoded records make this a parse, not a decode).

    Multi-host (SURVEY.md §3.5): each process reads a disjoint 1/P slice
    of the data — by whole shard files when there are enough, else by
    record striding — and yields LOCAL batches of ``batch_size / P``
    rows. ``mesh_lib.shard_batch`` / ``device_prefetch`` then assemble
    the global array, so the train step always sees the global batch.
    Defaults resolve from the jax runtime; single-process is unchanged.
    """
    import tensorflow as tf

    p_idx, p_cnt = _resolve_process(process_index, process_count)
    batch_size = _local_batch_size(cfg.batch_size, p_cnt, "data.batch_size")

    paths = tfrecord.list_split(data_dir, split)
    if p_cnt > 1 and len(paths) >= p_cnt:
        paths = paths[p_idx::p_cnt]  # file-level sharding: no wasted reads
        record_shard = None
        # Disjoint by construction (different files) — offsetting the
        # file-shuffle seed per process just decorrelates epoch orders.
        file_seed = seed + p_idx
    elif p_cnt > 1:
        # Few shard files: stride the one record stream instead. The
        # file-shuffle seed MUST be identical on every process here —
        # the strides partition positions of a single logical stream, so
        # differently-ordered streams would overlap/drop records.
        record_shard = (p_cnt, p_idx)
        file_seed = seed
    else:
        record_shard = None
        file_seed = seed
    # The post-shard record shuffle may always be process-offset: its
    # input is already this process's disjoint slice.
    shuffle_seed = seed + p_idx if p_cnt > 1 else seed
    ds = _build_tf_dataset(
        paths, image_size, True, cfg, file_seed, record_shard=record_shard
    )
    # Train drops the name early: strings cannot go to device, and the
    # step reads only image/grade.
    ds = ds.map(lambda image, grade, name: (image, grade))
    ds = ds.shuffle(cfg.shuffle_buffer, seed=shuffle_seed).repeat()
    ds = ds.batch(batch_size, drop_remainder=True)
    if skip_batches:
        ds = ds.skip(skip_batches)
    ds = ds.prefetch(cfg.prefetch_batches)
    for image, grade in ds.as_numpy_iterator():
        yield {"image": image, "grade": grade}


def _resolve_process(
    process_index: int | None, process_count: int | None
) -> tuple[int, int]:
    if process_count is None:
        import jax

        return jax.process_index(), jax.process_count()
    return process_index or 0, process_count


def _local_batch_size(global_batch: int, p_cnt: int, what: str) -> int:
    if global_batch % p_cnt:
        raise ValueError(
            f"{what}={global_batch} not divisible by process_count={p_cnt}"
        )
    return global_batch // p_cnt


def eval_batches(
    data_dir: str,
    split: str,
    batch_size: int,
    image_size: int,
    process_index: int | None = None,
    process_count: int | None = None,
) -> Iterator[dict]:
    """One epoch of padded batches: {'image', 'grade', 'mask'} — mask=0 rows
    are padding and must be dropped after host gather.

    Multi-host: every process enumerates the SAME deterministic global
    batch sequence (identical file list, no shuffle) so all hosts make
    the same number of jit dispatches — differing counts would deadlock
    the collective runtime. 'image' is this process's local row block
    (rows [p*B/P, (p+1)*B/P) of the global batch, matching the
    process-major layout ``shard_batch`` assembles); 'grade' and 'mask'
    stay GLOBAL — they are host-side metadata for the metrics layer,
    which sees replicated global probabilities. Eval decode is paid on
    every host; eval runs are rare and correctness-critical, train is
    where per-process sharding saves decode (train_batches).
    """
    p_idx, p_cnt = _resolve_process(process_index, process_count)
    local = _local_batch_size(batch_size, p_cnt, "eval.batch_size")
    paths = tfrecord.list_split(data_dir, split)
    ds = _build_tf_dataset(paths, image_size, False, DataConfig(), seed=0)
    ds = ds.batch(batch_size, drop_remainder=False)
    for image, grade, name in ds.as_numpy_iterator():
        n = image.shape[0]
        if n < batch_size:
            pad = batch_size - n
            image = np.concatenate(
                [image, np.zeros((pad, *image.shape[1:]), image.dtype)], axis=0
            )
            grade = np.concatenate([grade, np.zeros((pad,), grade.dtype)], axis=0)
            name = np.concatenate([name, np.full((pad,), b"", name.dtype)], axis=0)
        mask = (np.arange(batch_size) < n).astype(np.float32)
        yield {
            "image": image[p_idx * local:(p_idx + 1) * local],
            "grade": grade,
            # 'name' is host metadata like grade/mask (global rows) — it
            # feeds --save_probs per-image exports, never the device.
            "name": name,
            "mask": mask,
        }


_METADATA_CACHE: dict = {}


def read_split_metadata(
    data_dir: str, split: str
) -> tuple[np.ndarray, np.ndarray]:
    """(grades [n] i32, names [n] bytes) in the SAME record order the
    decode stream yields (shared _serialized_stream) — a parse-only
    pass, no image decode, so it is cheap enough to run on every host
    (the point of sharded eval is to split the DECODE).

    Memoized per (dir, split): the k-model × frequent-eval protocol that
    motivates sharded eval would otherwise re-parse the whole split on
    every eval call; eval splits are immutable for the life of a run."""
    import tensorflow as tf

    key = (os.path.realpath(data_dir), split)
    if key in _METADATA_CACHE:
        return _METADATA_CACHE[key]
    spec = {
        "image/grade": tf.io.FixedLenFeature([], tf.int64),
        "image/name": tf.io.FixedLenFeature([], tf.string, default_value=""),
    }
    ds = _serialized_stream(
        tfrecord.list_split(data_dir, split), False, 0
    ).map(
        lambda s: tf.io.parse_single_example(s, spec),
        num_parallel_calls=tf.data.AUTOTUNE,
        deterministic=True,
    )
    grades, names = [], []
    for f in ds.as_numpy_iterator():
        grades.append(int(f["image/grade"]))
        names.append(f["image/name"])
    result = (
        np.asarray(grades, np.int32),
        np.asarray(names, object) if names else np.zeros((0,), object),
    )
    _METADATA_CACHE[key] = result
    return result


def eval_batches_sharded(
    data_dir: str,
    split: str,
    batch_size: int,
    image_size: int,
    process_index: int | None = None,
    process_count: int | None = None,
) -> Iterator[dict]:
    """Multi-host eval where each process DECODES only 1/P of the
    records (eval.sharded; VERDICT r2 weak #4) — the unsharded
    eval_batches pays the full decode on every host, which under the
    k-model × eval-every-500-steps protocol multiplies host decode by
    P×k.

    Records are stride-sharded BEFORE decode (process p decodes records
    p, p+P, ...), so the assembled global batch is a known PERMUTATION
    of the record order: assembled row ``p*(B/P) + i`` of batch k holds
    record ``p + (k*B/P + i)*P`` (process-major blocks, matching
    ``shard_batch``'s assembly). Metadata ('grade'/'name'/'mask') is
    emitted already aligned to that assembled order from a cheap
    parse-only pass, so the metrics layer is oblivious to the
    permutation. Every process still yields the same number of batches
    (dispatch-count alignment). Single-process this degenerates to the
    identity permutation and plain local decode.
    """
    p_idx, p_cnt = _resolve_process(process_index, process_count)
    local = _local_batch_size(batch_size, p_cnt, "eval.batch_size")
    grades, names = read_split_metadata(data_dir, split)
    n = len(grades)
    if n == 0:
        return  # same as the unsharded path: no records, no batches
    n_batches = -(-n // batch_size)  # ceil

    paths = tfrecord.list_split(data_dir, split)
    ds = _build_tf_dataset(
        paths, image_size, False, DataConfig(), seed=0,
        record_shard=(p_cnt, p_idx) if p_cnt > 1 else None,
    )
    ds = ds.map(lambda image, grade, name: image)
    ds = ds.batch(local, drop_remainder=False)
    it = ds.as_numpy_iterator()

    # Assembled-order record ids per batch: block p rows i -> p+(kb+i)*P.
    block = np.arange(local)
    for k in range(n_batches):
        imgs = next(it, None)
        if imgs is None:
            imgs = np.zeros((0, image_size, image_size, 3), np.uint8)
        if imgs.shape[0] < local:
            pad = local - imgs.shape[0]
            imgs = np.concatenate(
                [imgs, np.zeros((pad, *imgs.shape[1:]), imgs.dtype)]
            )
        rec = np.concatenate([
            p + (k * local + block) * p_cnt for p in range(p_cnt)
        ])
        valid = rec < n
        safe = np.minimum(rec, max(n - 1, 0))
        yield {
            "image": imgs,
            "grade": np.where(valid, grades[safe], 0).astype(np.int32),
            "name": np.asarray([
                names[r] if v else b"" for r, v in zip(safe, valid)
            ]),
            "mask": valid.astype(np.float32),
        }


def staged_put(x, sharding):
    """Per-shard H2D staging: device_put each device's dim-0 block
    separately and assemble the global array from the single-device
    pieces. Every per-shard put is async, so the copies for a batch can
    overlap the running train step at SHARD granularity — the runtime
    can start feeding device 0's block while device 3's is still being
    sliced — instead of gating on one whole-batch transfer
    (tf.data's overlapped-prefetch guidance, arXiv:2101.12127, applied
    to the put side). Falls back to a plain sharded put whenever the
    layout is not the simple single-process dim-0 case (scalars,
    replicated specs, multi-process) — and for DEVICE-BORN arrays
    (hbm/tiered loader batches), where np.asarray would be a blocking
    D2H fetch followed by a pointless re-upload."""
    sh = (
        _rank_sharding_for(x, sharding)
        if hasattr(sharding, "spec") else sharding
    )
    if isinstance(x, jax.Array):
        return jax.device_put(x, sh)
    x = np.asarray(x)
    if (
        jax.process_count() > 1
        or not hasattr(sh, "spec")
        or x.ndim == 0
        or not any(s is not None for s in sh.spec)
    ):
        return jax.device_put(x, sh)
    shape = x.shape
    arrays = [
        jax.device_put(x[idx], dev)
        for dev, idx in sh.addressable_devices_indices_map(shape).items()
    ]
    return jax.make_array_from_single_device_arrays(shape, sh, arrays)


def _rank_sharding_for(x, sharding):
    from jama16_retina_tpu.parallel import mesh as mesh_lib

    return mesh_lib._rank_sharding(np.ndim(x), sharding)


def device_prefetch(
    it: Iterator[dict], sharding=None, size: int = 2,
    full_local: bool = False, per_shard: bool = False, knobs=None,
) -> Iterator[dict]:
    """Move batches to device ahead of consumption (double-buffering).

    With a ``NamedSharding(mesh, P('data'))`` the put is the global-array
    scatter across the mesh's data axis; with None it targets the default
    device. jax.device_put is async — the queue depth of ``size`` is what
    lets H2D copies run behind the current step's compute.

    ``full_local``: each process's iterator yields the FULL global batch
    (not its 1/P row block) and placement slices each device's shard from
    it — the member-parallel driver's assembly, whose ('member','data')
    device layout interleaves data columns across processes (see
    mesh_lib.place_full_local).

    ``per_shard``: stage the single-process sharded put per device block
    (``staged_put``) so the H2D copies overlap the train step at shard
    granularity (DataConfig.stage_per_shard).

    ``knobs`` (data/autotune.Knobs): when present, the queue depth is
    the live ``prefetch_depth`` knob polled each iteration instead of
    the static ``size`` — the ingest autotuner's prefetch control
    (data.autotune). Depth is pure run-ahead: batch contents and order
    are untouched, only how far ahead their H2D copies are issued.
    """
    from jama16_retina_tpu.obs import registry as obs_registry

    # Staged-H2D depth telemetry: how many dispatched batches sit ahead
    # of the one being yielded. In this synchronous generator the fill
    # discipline keeps it at `size` structurally — the gauge surfaces
    # the EFFECTIVE depth config (incl. the drain tail) in snapshots;
    # host-can't-keep-up shows as trainer input_wait_sec, not here.
    g_depth = obs_registry.default_registry().gauge(
        "data.prefetch.depth",
        help="batches staged ahead of the one being yielded in "
             "device_prefetch (the effective run-ahead config)",
    )
    queue: collections.deque = collections.deque()
    multiprocess = jax.process_count() > 1

    def put(batch: dict) -> dict:
        if sharding is None:
            return jax.device_put(batch)

        def one(x):
            sh = _shard_for(x, sharding)
            # is_equivalent_to, not ==: P('data') and P('data',None,...)
            # describe the same placement but compare unequal.
            if isinstance(x, jax.Array) and x.sharding.is_equivalent_to(
                    sh, x.ndim):
                # Already a correctly-sharded global array — the hbm
                # loader's batches are born on device (multi-host: NOT
                # fully addressable, so both host-assembly paths below
                # would be wrong, not just wasteful). Checked before the
                # full_local branch so the member-parallel driver can
                # also ride the hbm loader on multi-host.
                return x
            if full_local and multiprocess:
                from jama16_retina_tpu.parallel import mesh as mesh_lib

                return mesh_lib.place_full_local(x, sharding)
            # full_local single-process falls through: plain sharded puts
            # are equivalent there.
            if multiprocess and np.ndim(x):
                # Local rows -> global array (see mesh_lib.shard_batch).
                return jax.make_array_from_process_local_data(sh, np.asarray(x))
            if per_shard:
                return staged_put(x, sh)
            return jax.device_put(x, sh)

        return jax.tree.map(one, batch)

    def _shard_for(x, sharding):
        # Rank-aware: batch-dim sharding for arrays, replicated for scalars.
        if not hasattr(sharding, "spec"):
            return sharding
        from jama16_retina_tpu.parallel import mesh as mesh_lib

        return mesh_lib._rank_sharding(np.ndim(x), sharding)

    # HBM owner ledger (obs/device.py; ISSUE 19): the staged run-ahead
    # owns queue-depth x batch-bytes of device residency. Per-batch
    # bytes are measured ONCE (first staged batch — shapes are static);
    # the per-yield cost is one integer multiply + dict set.
    from jama16_retina_tpu.obs import device as device_lib

    batch_bytes: "int | None" = None

    def _note_runahead(n_staged: int) -> None:
        if batch_bytes is not None:
            device_lib.set_hbm_owner(
                "staged_runahead", n_staged * batch_bytes
            )

    for batch in it:
        queue.append(put(batch))
        if batch_bytes is None:
            try:
                batch_bytes = device_lib.tree_device_bytes(queue[0])
            except Exception:  # noqa: BLE001 - accounting only
                batch_bytes = 0
        depth = size if knobs is None else knobs.prefetch_depth
        # `while`, not `if`: a live depth DECREASE must let the queue
        # drain below the old level (each generator pull then serves
        # from the queue without appending until the new depth holds).
        while len(queue) > depth:
            g_depth.set(len(queue) - 1)
            _note_runahead(len(queue) - 1)
            yield queue.popleft()
    while queue:
        g_depth.set(len(queue) - 1)
        _note_runahead(len(queue) - 1)
        yield queue.popleft()
    device_lib.clear_hbm_owner("staged_runahead")
