"""TFRecord schema + sharded writers/readers (SURVEY.md N3/N4, reference R5).

The reference's offline preprocessing emits partitioned image sets that
its ``lib/dataset`` tf.data pipeline consumes (BASELINE.json:5 "the
existing TFRecord pipeline"). Here the on-disk contract is explicit:

    image/encoded  bytes   JPEG
    image/grade    int64   ICDR grade 0..4 (binary label derived online)
    image/name     bytes   source image id (debugging / dedup)

Files are sharded ``<split>-00007-of-00016.tfrecord`` so tf.data can
interleave reads across shards. TF runs CPU-only here; it never touches
the TPU (SURVEY.md §2.3).
"""

from __future__ import annotations

import os
from typing import Iterable, Iterator, Sequence

import numpy as np


def _tf():
    # Deferred import: TF costs ~12s on this 1-vCPU host; pure-numpy users
    # of the package (e.g. the metrics layer) never pay it.
    import tensorflow as tf

    return tf


def shard_path(out_dir: str, split: str, shard: int, num_shards: int) -> str:
    return os.path.join(
        out_dir, f"{split}-{shard:05d}-of-{num_shards:05d}.tfrecord"
    )


def make_example(jpeg_bytes: bytes, grade: int, name: str = ""):
    tf = _tf()
    feat = {
        "image/encoded": tf.train.Feature(
            bytes_list=tf.train.BytesList(value=[jpeg_bytes])
        ),
        "image/grade": tf.train.Feature(
            int64_list=tf.train.Int64List(value=[int(grade)])
        ),
        "image/name": tf.train.Feature(
            bytes_list=tf.train.BytesList(value=[name.encode()])
        ),
    }
    return tf.train.Example(features=tf.train.Features(feature=feat))


def write_shards(
    records: Iterable[tuple[bytes, int, str]],
    out_dir: str,
    split: str,
    num_shards: int,
) -> list[str]:
    """Round-robin the (jpeg, grade, name) stream into ``num_shards`` files."""
    tf = _tf()
    os.makedirs(out_dir, exist_ok=True)
    paths = [shard_path(out_dir, split, i, num_shards) for i in range(num_shards)]
    writers = [tf.io.TFRecordWriter(p) for p in paths]
    try:
        for i, (jpeg, grade, name) in enumerate(records):
            ex = make_example(jpeg, grade, name)
            writers[i % num_shards].write(ex.SerializeToString())
    finally:
        for w in writers:
            w.close()
    return paths


def encode_jpeg(image_u8: np.ndarray, quality: int = 92) -> bytes:
    """RGB uint8 -> JPEG bytes via OpenCV (BGR on disk handled here)."""
    import cv2

    ok, buf = cv2.imencode(
        ".jpg", image_u8[..., ::-1], [int(cv2.IMWRITE_JPEG_QUALITY), quality]
    )
    if not ok:
        raise ValueError("JPEG encode failed")
    return bytes(buf)


def write_synthetic_split(
    out_dir: str,
    split: str,
    n: int,
    image_size: int = 299,
    num_shards: int = 4,
    seed: int = 0,
) -> list[str]:
    """Test/bench fixture: synthetic fundus images -> real TFRecord shards,
    so the whole online pipeline is exercised byte-identically to how it
    would run on preprocessed EyePACS (SURVEY.md §4 fixtures)."""
    from jama16_retina_tpu.data import synthetic

    images, grades = synthetic.make_dataset(
        n, synthetic.SynthConfig(image_size=image_size), seed=seed
    )

    def gen() -> Iterator[tuple[bytes, int, str]]:
        for i in range(n):
            yield encode_jpeg(images[i]), int(grades[i]), f"{split}_{seed}_{i:05d}"

    return write_shards(gen(), out_dir, split, num_shards)


def list_split(data_dir: str, split: str) -> list[str]:
    import glob

    paths = sorted(glob.glob(os.path.join(data_dir, f"{split}-*.tfrecord")))
    if not paths:
        raise FileNotFoundError(
            f"no TFRecord shards for split {split!r} in {data_dir!r} — run "
            "preprocessing (preprocess_eyepacs.py) or the synthetic fixture "
            "writer first"
        )
    return paths


FEATURE_SPEC = {
    "image/encoded": "bytes",
    "image/grade": "int64",
    "image/name": "bytes",
}


def parse_fn():
    """Returns a tf.data map fn: serialized Example -> (image_u8, grade, name)."""
    tf = _tf()
    spec = {
        "image/encoded": tf.io.FixedLenFeature([], tf.string),
        "image/grade": tf.io.FixedLenFeature([], tf.int64),
        "image/name": tf.io.FixedLenFeature([], tf.string, default_value=""),
    }

    def parse(serialized):
        f = tf.io.parse_single_example(serialized, spec)
        image = tf.io.decode_jpeg(f["image/encoded"], channels=3)
        return image, tf.cast(f["image/grade"], tf.int32), f["image/name"]

    return parse


def count_records(paths: Sequence[str]) -> int:
    tf = _tf()
    n = 0
    for _ in tf.data.TFRecordDataset(list(paths)):
        n += 1
    return n
