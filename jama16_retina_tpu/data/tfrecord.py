"""TFRecord schema + sharded writers/readers (SURVEY.md N3/N4, reference R5).

The reference's offline preprocessing emits partitioned image sets that
its ``lib/dataset`` tf.data pipeline consumes (BASELINE.json:5 "the
existing TFRecord pipeline"). Here the on-disk contract is explicit:

    image/encoded  bytes   JPEG (empty when the record is raw-encoded)
    image/raw      bytes   raw uint8 HWC pixels (empty when JPEG-encoded)
    image/height   int64   raw height (0 for JPEG records)
    image/width    int64   raw width (0 for JPEG records)
    image/grade    int64   ICDR grade 0..4 (binary label derived online)
    image/name     bytes   source image id (debugging / dedup)
    image/quality  float   gradability score in [0,1] from preprocessing
                           (fundus.gradability_stats; -1 = not computed,
                           e.g. legacy shards or synthetic fixtures)

Two encodings, chosen at preprocessing time:

  * ``jpeg`` — compact (~30 KB/img at 299px), but each training epoch
    pays a host JPEG decode per image. On this 1-vCPU host that caps the
    feed rate far below what the chip consumes (measured by bench.py).
  * ``raw``  — pre-decoded uint8 (268 KB/img at 299px, ~9x disk). The
    hot path becomes a memcpy-parse; decode is paid ONCE offline. This
    is the practical form of "decoding straight into HBM"
    (BASELINE.json:5) when the host is CPU-starved.

Files are sharded ``<split>-00007-of-00016.tfrecord`` so tf.data can
interleave reads across shards. TF runs CPU-only here; it never touches
the TPU (SURVEY.md §2.3).
"""

from __future__ import annotations

import os
from typing import Iterable, Iterator, Sequence

import numpy as np


def _tf():
    # Deferred import: TF costs ~12s on this 1-vCPU host; pure-numpy users
    # of the package (e.g. the metrics layer) never pay it.
    import tensorflow as tf

    return tf


def shard_path(out_dir: str, split: str, shard: int, num_shards: int) -> str:
    return os.path.join(
        out_dir, f"{split}-{shard:05d}-of-{num_shards:05d}.tfrecord"
    )


def make_example(jpeg_bytes: bytes, grade: int, name: str = "",
                 quality: float = -1.0):
    tf = _tf()
    feat = {
        "image/encoded": tf.train.Feature(
            bytes_list=tf.train.BytesList(value=[jpeg_bytes])
        ),
        "image/grade": tf.train.Feature(
            int64_list=tf.train.Int64List(value=[int(grade)])
        ),
        "image/name": tf.train.Feature(
            bytes_list=tf.train.BytesList(value=[name.encode()])
        ),
        "image/quality": tf.train.Feature(
            float_list=tf.train.FloatList(value=[float(quality)])
        ),
    }
    return tf.train.Example(features=tf.train.Features(feature=feat))


def make_raw_example(image_u8: np.ndarray, grade: int, name: str = "",
                     quality: float = -1.0):
    """Pre-decoded record: uint8 HWC pixels stored verbatim (see module
    docstring for the jpeg/raw trade-off)."""
    tf = _tf()
    h, w, c = image_u8.shape
    if c != 3 or image_u8.dtype != np.uint8:
        raise ValueError(f"expected uint8 HW3, got {image_u8.dtype} {image_u8.shape}")
    feat = {
        "image/raw": tf.train.Feature(
            bytes_list=tf.train.BytesList(value=[image_u8.tobytes()])
        ),
        "image/height": tf.train.Feature(int64_list=tf.train.Int64List(value=[h])),
        "image/width": tf.train.Feature(int64_list=tf.train.Int64List(value=[w])),
        "image/grade": tf.train.Feature(
            int64_list=tf.train.Int64List(value=[int(grade)])
        ),
        "image/name": tf.train.Feature(
            bytes_list=tf.train.BytesList(value=[name.encode()])
        ),
        "image/quality": tf.train.Feature(
            float_list=tf.train.FloatList(value=[float(quality)])
        ),
    }
    return tf.train.Example(features=tf.train.Features(feature=feat))


def write_shards(
    records: Iterable[tuple[bytes, int, str]],
    out_dir: str,
    split: str,
    num_shards: int,
) -> list[str]:
    """Round-robin the (jpeg, grade, name) stream into ``num_shards`` files."""
    return write_example_shards(
        (make_example(j, g, n) for j, g, n in records), out_dir, split, num_shards
    )


def write_example_shards(
    examples: Iterable,
    out_dir: str,
    split: str,
    num_shards: int,
) -> list[str]:
    """Round-robin pre-built tf.train.Examples (or their already-
    serialized bytes — what the preprocess worker pool ships across
    processes) into ``num_shards`` files."""
    tf = _tf()
    os.makedirs(out_dir, exist_ok=True)
    paths = [shard_path(out_dir, split, i, num_shards) for i in range(num_shards)]
    writers = [tf.io.TFRecordWriter(p) for p in paths]
    try:
        for i, ex in enumerate(examples):
            # deterministic=True keeps proto-map field order stable
            # across processes (byte-identical shards at any --workers).
            data = (ex if isinstance(ex, bytes)
                    else ex.SerializeToString(deterministic=True))
            writers[i % num_shards].write(data)
    finally:
        for w in writers:
            w.close()
    return paths


def encode_jpeg(image_u8: np.ndarray, quality: int = 92) -> bytes:
    """RGB uint8 -> JPEG bytes via OpenCV (BGR on disk handled here)."""
    import cv2

    ok, buf = cv2.imencode(
        ".jpg", image_u8[..., ::-1], [int(cv2.IMWRITE_JPEG_QUALITY), quality]
    )
    if not ok:
        raise ValueError("JPEG encode failed")
    return bytes(buf)


def write_synthetic_split(
    out_dir: str,
    split: str,
    n: int,
    image_size: "int | None" = None,
    num_shards: int = 4,
    seed: int = 0,
    encoding: str = "jpeg",
    label_noise: float = 0.0,
    synth_cfg=None,
    grade_marginals=None,
) -> list[str]:
    """Test/bench fixture: synthetic fundus images -> real TFRecord shards,
    so the whole online pipeline is exercised byte-identically to how it
    would run on preprocessed EyePACS (SURVEY.md §4 fixtures).

    ``label_noise`` flips each stored grade across the referable
    boundary with that probability (image still rendered from the true
    grade) — see synthetic.flip_binary_labels for why this is the
    fixture's difficulty control. The flip stream is derived from
    ``seed`` independently of the render stream, so the same seed with
    and without noise yields byte-identical images.

    ``image_size`` defaults to 299 when neither it nor ``synth_cfg`` is
    given. Passing BOTH with disagreeing sizes raises: letting
    ``synth_cfg.image_size`` silently win writes shards at an unexpected
    resolution that only surfaces later as loader shape errors
    (ADVICE r5).

    ``synth_cfg`` (a synthetic.SynthConfig) and ``grade_marginals``
    (length-5 probability
    vector replacing synthetic.GRADE_MARGINALS) exist to write
    DISTRIBUTION-SHIFTED datasets — subtler lesions, different
    referable prevalence — for the cross-dataset threshold-transfer
    protocol (BASELINE.json:8's EyePACS→Messidor-2 clause;
    scripts/cross_dataset_transfer.py)."""
    from jama16_retina_tpu.data import synthetic

    if (synth_cfg is not None and image_size is not None
            and synth_cfg.image_size != image_size):
        raise ValueError(
            f"write_synthetic_split got synth_cfg.image_size="
            f"{synth_cfg.image_size} but image_size={image_size} — pass "
            "one or the other (records would silently be written at the "
            "synth_cfg size)"
        )
    cfg = synth_cfg or synthetic.SynthConfig(
        image_size=299 if image_size is None else image_size
    )
    images, grades = synthetic.make_dataset(
        n, cfg, seed=seed, grade_marginals=grade_marginals
    )
    if label_noise:
        grades = synthetic.flip_binary_labels(
            grades, label_noise,
            np.random.default_rng([seed, synthetic.FLIP_STREAM_KEY]),
        )

    def gen() -> Iterator:
        for i in range(n):
            name = f"{split}_{seed}_{i:05d}"
            if encoding == "raw":
                yield make_raw_example(images[i], int(grades[i]), name)
            else:
                yield make_example(encode_jpeg(images[i]), int(grades[i]), name)

    return write_example_shards(gen(), out_dir, split, num_shards)


def list_split(data_dir: str, split: str) -> list[str]:
    import glob

    paths = sorted(glob.glob(os.path.join(data_dir, f"{split}-*.tfrecord")))
    if not paths:
        raise FileNotFoundError(
            f"no TFRecord shards for split {split!r} in {data_dir!r} — run "
            "preprocessing (preprocess_eyepacs.py) or the synthetic fixture "
            "writer first"
        )
    return paths


FEATURE_SPEC = {
    "image/encoded": "bytes",
    "image/grade": "int64",
    "image/name": "bytes",
}


def parse_fn():
    """Returns a tf.data map fn: serialized Example -> (image_u8, grade, name).

    Handles both encodings per record: raw records reshape a byte string
    (memcpy-cheap), JPEG records decode. The branch is a dynamic tf.cond
    because shards of either encoding may be mixed in one directory."""
    tf = _tf()
    spec = {
        "image/encoded": tf.io.FixedLenFeature([], tf.string, default_value=""),
        "image/raw": tf.io.FixedLenFeature([], tf.string, default_value=""),
        "image/height": tf.io.FixedLenFeature([], tf.int64, default_value=0),
        "image/width": tf.io.FixedLenFeature([], tf.int64, default_value=0),
        "image/grade": tf.io.FixedLenFeature([], tf.int64),
        "image/name": tf.io.FixedLenFeature([], tf.string, default_value=""),
    }

    def parse(serialized):
        f = tf.io.parse_single_example(serialized, spec)
        image = tf.cond(
            tf.strings.length(f["image/raw"]) > 0,
            lambda: tf.reshape(
                tf.io.decode_raw(f["image/raw"], tf.uint8),
                tf.stack(
                    [tf.cast(f["image/height"], tf.int32),
                     tf.cast(f["image/width"], tf.int32), 3]
                ),
            ),
            # INTEGER_ACCURATE (islow DCT): bit-exact with OpenCV's
            # decoder, so for records stored at the model size the
            # tf.data and grain loaders yield IDENTICAL pixel streams
            # (tests/test_grain.py pins this; the resize fallback for
            # mis-sized shards is best-effort — see grain_pipeline).
            # ~15% slower than the fast default; the host still outruns
            # the chip (docs/PERF.md) and raw encoding bypasses decode.
            lambda: tf.io.decode_jpeg(
                f["image/encoded"], channels=3, dct_method="INTEGER_ACCURATE"
            ),
        )
        return image, tf.cast(f["image/grade"], tf.int32), f["image/name"]

    return parse


def _transient_read_errors():
    """The exception classes a whole-pass TFRecord read retries on
    (ISSUE 6): filesystem/network hiccups — tf's UnavailableError (GCS/
    NFS flaps surface as this) plus OSError. DataLossError is
    deliberately NOT here: a torn/corrupt shard does not get better on
    retry; it must raise (or be quarantined by the per-record decode
    layer)."""
    tf = _tf()
    return (tf.errors.UnavailableError, OSError)


def read_quality_by_name(paths: Sequence[str]) -> dict[bytes, float]:
    """-> {image/name: image/quality} for every record, without touching
    pixels (a light parse over the serialized stream). Used by evaluate's
    ``--save_probs`` to join the preprocessing gradability score onto
    per-image predictions (docs/QUALITY.md step 4: do misses correlate
    with low-quality captures?). Records written before the quality
    feature existed come back as -1.0. Transient read failures retry
    with bounded backoff (utils/retry.py)."""
    from jama16_retina_tpu.utils import retry as retry_lib

    tf = _tf()
    spec = {
        "image/name": tf.io.FixedLenFeature([], tf.string, default_value=""),
        "image/quality": tf.io.FixedLenFeature(
            [], tf.float32, default_value=-1.0
        ),
    }

    def _read() -> dict[bytes, float]:
        out: dict[bytes, float] = {}
        ds = tf.data.TFRecordDataset(list(paths)).map(
            lambda s: tf.io.parse_single_example(s, spec),
            num_parallel_calls=tf.data.AUTOTUNE,
        )
        for f in ds.as_numpy_iterator():
            out[f["image/name"]] = float(f["image/quality"])
        return out

    return retry_lib.retry_call(
        _read, attempts=3, retry_on=_transient_read_errors(),
        site="tfrecord.quality_scan",
    )


def count_records(paths: Sequence[str]) -> int:
    from jama16_retina_tpu.utils import retry as retry_lib

    tf = _tf()

    def _count() -> int:
        n = 0
        for _ in tf.data.TFRecordDataset(list(paths)):
            n += 1
        return n

    return retry_lib.retry_call(
        _count, attempts=3, retry_on=_transient_read_errors(),
        site="tfrecord.count",
    )
