"""Synthetic fundus-image generator (test/bench fixture, SURVEY.md §4).

No EyePACS/Messidor data exists in this environment (SURVEY.md §2.3), so
tests and benchmarks run on procedurally generated fundus-like images: a
bright circular retina disc on black background, an optic-disc highlight,
vessel-like arcs, and — crucially — ICDR-grade-correlated lesions
(microaneurysm dots / hemorrhage blobs) whose count scales with grade.
That correlation makes the binary referable-DR task *learnable*, so
integration tests can assert real AUC lift rather than just loss motion.

Pure numpy; cv2 only used by callers that want JPEG bytes.
"""

from __future__ import annotations

import dataclasses

import numpy as np


# make_dataset's default grade marginals [0.55, 0.15, 0.15, 0.08, 0.07];
# P(referable) = P(grade >= 2). Callers publishing noisy_auc_ceiling
# (scripts/time_to_auc.py) read this instead of re-deriving it so the
# published ceiling cannot drift from the data actually written.
GRADE_MARGINALS = (0.55, 0.15, 0.15, 0.08, 0.07)
REFERABLE_PREVALENCE = float(sum(GRADE_MARGINALS[2:]))

# Stream-key suffix deriving a split's label-flip rng from its seed
# (np.random.default_rng([seed, FLIP_STREAM_KEY])) — independent of the
# render stream, shared by tfrecord.write_synthetic_split and any caller
# regenerating the flipped labels from the seed alone.
FLIP_STREAM_KEY = 0x0F11


@dataclasses.dataclass(frozen=True)
class SynthConfig:
    image_size: int = 299
    min_radius_frac: float = 0.40  # fundus radius as fraction of image size
    max_radius_frac: float = 0.48
    lesions_per_grade: int = 6
    lesion_radius: int = 3


def _disc_mask(
    yy: np.ndarray, xx: np.ndarray, cx: float, cy: float, r: float
) -> np.ndarray:
    """Disc mask over precomputed coordinate grids (built once per image —
    rebuilding mgrid for each of the ~30 lesions dominated fixture time)."""
    return ((xx - cx) ** 2 + (yy - cy) ** 2) <= r * r


def render_fundus(
    rng: np.random.Generator, grade: int, cfg: SynthConfig
) -> np.ndarray:
    """Render one uint8 RGB fundus-like image for an ICDR grade in [0, 4]."""
    s = cfg.image_size
    img = np.zeros((s, s, 3), dtype=np.float32)

    yy, xx = np.mgrid[0:s, 0:s]
    r = rng.uniform(cfg.min_radius_frac, cfg.max_radius_frac) * s
    cx = s / 2 + rng.uniform(-0.03, 0.03) * s
    cy = s / 2 + rng.uniform(-0.03, 0.03) * s
    disc = _disc_mask(yy, xx, cx, cy, r)

    # Retina base color: orange-red with radial shading.
    dist = np.sqrt((xx - cx) ** 2 + (yy - cy) ** 2) / max(r, 1.0)
    shade = np.clip(1.0 - 0.35 * dist, 0.0, 1.0)
    base = np.array([0.82, 0.42, 0.18], dtype=np.float32)
    base = base * rng.uniform(0.85, 1.15, size=3)
    img[disc] = (shade[disc, None] * base[None, :]) * 255.0

    # Optic disc: bright yellowish circle off-center.
    od_r = r * rng.uniform(0.10, 0.14)
    od_cx = cx + rng.choice([-1, 1]) * r * 0.55
    od_cy = cy + rng.uniform(-0.15, 0.15) * r
    od = _disc_mask(yy, xx, od_cx, od_cy, od_r) & disc
    img[od] = np.array([235.0, 210.0, 140.0], dtype=np.float32)

    # Vessel-like dark arcs from the optic disc.
    n_vessels = rng.integers(3, 6)
    t = np.linspace(0, 1, 220)
    for _ in range(n_vessels):
        ang = rng.uniform(0, 2 * np.pi)
        curve = rng.uniform(-2.0, 2.0)
        px = od_cx + t * r * 1.6 * np.cos(ang + curve * t)
        py = od_cy + t * r * 1.6 * np.sin(ang + curve * t)
        pts = np.stack([py, px], axis=1).astype(np.int64)
        ok = (
            (pts[:, 0] >= 0) & (pts[:, 0] < s) & (pts[:, 1] >= 0) & (pts[:, 1] < s)
        )
        pts = pts[ok]
        inside = disc[pts[:, 0], pts[:, 1]]
        pts = pts[inside]
        for dy in (-1, 0, 1):
            yyv = np.clip(pts[:, 0] + dy, 0, s - 1)
            img[yyv, pts[:, 1]] *= 0.55

    # Grade-correlated lesions: dark red dots (count ~ grade), plus pale
    # exudate blobs for grades >= 3. This is the learnable signal.
    n_lesions = int(grade) * cfg.lesions_per_grade + int(rng.integers(0, 3))
    for _ in range(n_lesions):
        ang = rng.uniform(0, 2 * np.pi)
        rad = rng.uniform(0.1, 0.9) * r
        lx, ly = cx + rad * np.cos(ang), cy + rad * np.sin(ang)
        lr = cfg.lesion_radius * rng.uniform(0.7, 1.6)
        lm = _disc_mask(yy, xx, lx, ly, lr) & disc
        img[lm] = np.array([95.0, 18.0, 12.0], dtype=np.float32)
    if grade >= 3:
        for _ in range(int(grade)):
            ang = rng.uniform(0, 2 * np.pi)
            rad = rng.uniform(0.2, 0.8) * r
            lx, ly = cx + rad * np.cos(ang), cy + rad * np.sin(ang)
            lm = _disc_mask(yy, xx, lx, ly, cfg.lesion_radius * 2.2) & disc
            img[lm] = np.array([230.0, 220.0, 160.0], dtype=np.float32)

    # Sensor noise.
    img += rng.normal(0.0, 4.0, size=img.shape).astype(np.float32)
    return np.clip(img, 0, 255).astype(np.uint8)


def make_dataset(
    n: int,
    cfg: SynthConfig | None = None,
    grades: np.ndarray | None = None,
    seed: int = 0,
    grade_marginals=None,
) -> tuple[np.ndarray, np.ndarray]:
    """Generate (images[n,s,s,3] uint8, grades[n] int32). Grade marginals
    roughly follow EyePACS's skew toward grade 0 unless `grades` given.

    ``grade_marginals`` replaces GRADE_MARGINALS in the grade draw (the
    distribution-shift knob behind scripts/cross_dataset_transfer.py)
    while keeping the one-stream discipline: the draw stays FIRST on
    the seed's rng and rendering continues on the same stream, so
    labels and render noise never share stream positions — and
    marginals == GRADE_MARGINALS reproduces the default path
    byte-identically."""
    cfg = cfg or SynthConfig()
    rng = np.random.default_rng(seed)
    if grades is None:
        grades = sample_grades(n, rng, grade_marginals)
    grades = np.asarray(grades, dtype=np.int32)
    images = np.stack([render_fundus(rng, int(g), cfg) for g in grades])
    return images, grades


def binary_labels(grades: np.ndarray) -> np.ndarray:
    """ICDR grade -> binary referable-DR label (grade >= 2 referable),
    the reference's grade binning (SURVEY.md R3, BASELINE.json:7)."""
    return (np.asarray(grades) >= 2).astype(np.int32)


def sample_grades(
    n: int, rng: np.random.Generator, marginals=None
) -> np.ndarray:
    """The grade draw make_dataset performs FIRST on its rng — exposed so
    callers can reproduce a split's grades from its seed without paying
    for image rendering (scripts/time_to_auc.py regenerates the val
    grades this way to compute the realized noisy-AUC ceiling).
    ``marginals`` defaults to GRADE_MARGINALS; a custom vector must be
    5 probabilities summing to 1."""
    if marginals is None:
        marginals = GRADE_MARGINALS
    marg = np.asarray(marginals, np.float64)
    if marg.shape != (5,) or np.any(marg < 0) or not np.isclose(
        marg.sum(), 1.0
    ):
        raise ValueError(
            f"grade_marginals must be 5 probabilities summing to 1, "
            f"got {marginals!r}"
        )
    # Normalize residue inside our (looser) isclose gate: rng.choice's
    # own sum check is ~1e-8-tight and would raise a generic numpy
    # error for hand-typed marginals that pass the named check above.
    return rng.choice(5, size=n, p=list(marg / marg.sum()))


def flip_binary_labels(
    grades: np.ndarray, p: float, rng: np.random.Generator
) -> np.ndarray:
    """Symmetric label noise across the referable boundary.

    With probability ``p`` per image, move the STORED grade to the other
    side of the binary boundary (referable -> 1, non-referable -> 2) so
    the binary label flips while the image still renders its true grade.
    This is the fixture's difficulty control: the clean lesion-count
    task is perfectly separable (measured AUC saturates at 1.0), so a
    crossing of any sub-1.0 target says nothing about how close to
    optimal the recipe is. Noisy labels cap the MEASURED val/test AUC at
    ``noisy_auc_ceiling(p, prevalence)`` — a target near that ceiling is
    only crossable by a near-Bayes-optimal model.
    """
    grades = np.asarray(grades, dtype=np.int32).copy()
    flip = rng.random(grades.shape[0]) < p
    pos = grades >= 2
    grades[flip & pos] = 1
    grades[flip & ~pos] = 2
    return grades


def noisy_auc_ceiling(p: float, prevalence: float) -> float:
    """EXPECTED AUC of the best noise-blind scorer against labels
    flipped with probability ``p``.

    The Bayes scorer ranks every true-positive image above every true
    negative and cannot order images within a true class (flips are
    label-only and independent of the image). With
    ``a = P(true+ | noisy+)`` and ``b = P(true+ | noisy-)`` (Bayes on
    flip rate ``p`` and true prevalence ``prevalence``), a
    noisy-positive/noisy-negative pair is correctly ordered when the
    noisy+ is truly positive and the noisy- truly negative, and is a
    coin flip when both fall in the same true class:

        E[AUC] = a(1-b) + 0.5 * (a*b + (1-a)(1-b))

    This is a ceiling IN EXPECTATION, not almost surely: the
    within-true-class coin flips make any single measured AUC fluctuate
    around it (sd ~0.004 on a 512-image split at p=0.01), and
    best-over-evals selection rides that fluctuation — a near-Bayes
    model's best-of-run val AUC typically lands ~1 sd ABOVE this value
    (observed in docs/time_to_auc_noise_r4.json: max 0.9883 vs expected
    0.9836). Pinned against Monte Carlo in tests/test_synthetic.py.
    """
    q = prevalence
    a = (1 - p) * q / ((1 - p) * q + p * (1 - q))
    b = p * q / (p * q + (1 - p) * (1 - q))
    return a * (1 - b) + 0.5 * (a * b + (1 - a) * (1 - b))


def realized_noisy_auc_ceiling(
    true_y: np.ndarray, noisy_y: np.ndarray
) -> float:
    """noisy_auc_ceiling's expectation computed on THIS finite label
    draw (population quantities replaced by realized counts — on a
    256-image val split the two can differ by ~0.01, enough to flip
    whether a near-ceiling target is crossable at all). Same
    expectation-not-almost-sure caveat as noisy_auc_ceiling."""
    true_y = np.asarray(true_y).astype(bool)
    noisy_y = np.asarray(noisy_y).astype(bool)
    pp = float(np.sum(noisy_y & true_y))    # noisy+, true+
    pn = float(np.sum(noisy_y & ~true_y))   # noisy+, true-
    np_ = float(np.sum(~noisy_y & true_y))  # noisy-, true+
    nn = float(np.sum(~noisy_y & ~true_y))  # noisy-, true-
    pos, neg = pp + pn, np_ + nn
    if pos == 0 or neg == 0:
        raise ValueError("need at least one noisy-positive and one "
                         "noisy-negative label")
    return (pp * nn + 0.5 * (pp * np_ + pn * nn)) / (pos * neg)
