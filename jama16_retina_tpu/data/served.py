"""``data.loader="served"``: the disaggregated ingest service's client.

A thin stream over the shared-memory ring the ingest server
(jama16_retina_tpu/ingest/) fills: attach over the unix control
socket, map the ring, then yield one {'image','grade'} HOST batch per
``batch`` frame — the standard loader contract, so the trainer's
``device_prefetch`` moves batches exactly as it does for tfdata/grain
and the train loops never see which loader is underneath.

Bit-identity: the client sends the SAME residency spec the in-process
tiered loader would derive (``resident_row_capacity`` over the same
budget knobs), and the server computes each batch exactly as
``tiered_pipeline.host_reference_batches`` does — so a fit() over
``served`` consumes the identical post-decode batch sequence as the
same seed over ``tiered``/``rawshard`` (pinned in
tests/test_ingest.py, >1 epoch, partial residency).

Stall attribution: the client measures its own blocked-in-recv time
and reports tumbling ``(window_sec, input_wait_sec)`` windows over the
control channel — the fleet tuner's per-consumer input
(ingest/fleettune.py).

Crash semantics: ``skip_batches=None`` asks the server to resume from
this consumer's lease journal (kill -9 reattach, zero re-decode); the
trainer always passes its explicit checkpoint step instead, which
overrides the journal (the checkpoint is the authority on training
position).
"""

from __future__ import annotations

import os
import socket
import time
from typing import Iterator

from absl import logging

from jama16_retina_tpu.ingest import protocol
from jama16_retina_tpu.ingest.ring import BatchRing

# Report a stats window to the fleet tuner every N batches: frequent
# enough to steer within a bench window, rare enough to stay invisible
# next to a decode.
STATS_EVERY = 8


class ServedStream:
    """One attached consumer. Iterate for host batches; ``close()``
    (or exhaust/GC) detaches cleanly. Not thread-safe — one stream per
    consuming loop, like every other loader iterator."""

    def __init__(self, socket_path: str, consumer_id: str, split: str,
                 seed: int, batch_size: int, image_size: int,
                 capacity_rows: int, start_step: "int | None" = 0,
                 attach_timeout_s: float = 30.0):
        if not socket_path:
            raise ValueError(
                "data.loader='served' needs ingest.socket_path — the "
                "unix socket of a running scripts/ingest_server.py"
            )
        self.consumer_id = consumer_id
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.settimeout(attach_timeout_s)
        try:
            self._sock.connect(socket_path)
        except OSError as e:
            self._sock.close()
            raise ConnectionError(
                f"no ingest server at {socket_path!r} ({e}) — start one "
                "with scripts/ingest_server.py or switch data.loader"
            ) from None
        protocol.send_msg(self._sock, {
            "type": "attach", "consumer_id": consumer_id, "split": split,
            "seed": int(seed), "batch_size": int(batch_size),
            "image_size": int(image_size),
            "capacity_rows": int(capacity_rows),
            "start_step": None if start_step is None else int(start_step),
        })
        reply = protocol.recv_msg(self._sock)
        if reply is None:
            self._sock.close()
            raise ConnectionError(
                f"ingest server at {socket_path!r} closed during attach"
            )
        if reply.get("type") == "error":
            self._sock.close()
            raise RuntimeError(
                f"ingest attach refused: {reply.get('message')}"
            )
        if reply.get("type") != "attached":
            self._sock.close()
            raise RuntimeError(f"unexpected attach reply: {reply}")
        self.start_step = int(reply["start_step"])
        self.n_records = int(reply["n_records"])
        self.steps_per_epoch = int(reply["steps_per_epoch"])
        self._ring = BatchRing(
            int(reply["batch_size"]), int(reply["image_size"]),
            int(reply["n_slots"]), name=reply["shm_name"], create=False,
        )
        self._closed = False
        self._since_stats = 0
        self._window_t0 = time.perf_counter()
        self._window_wait = 0.0
        logging.info(
            "served loader: consumer %s attached at step %d (%d records, "
            "%d steps/epoch, ring of %d slots)", consumer_id,
            self.start_step, self.n_records, self.steps_per_epoch,
            int(reply["n_slots"]),
        )

    def __iter__(self) -> Iterator[dict]:
        return self

    def __next__(self) -> dict:
        if self._closed:
            raise StopIteration
        t0 = time.perf_counter()
        try:
            msg = protocol.recv_msg(self._sock)
        except socket.timeout:
            raise TimeoutError(
                "ingest server stopped feeding (no batch frame within "
                "the attach timeout) — check the server process"
            ) from None
        self._window_wait += time.perf_counter() - t0
        if msg is None:
            # Server closed the stream (shutdown or an injected
            # ingest.ring.write fault killed this consumer's pump).
            self.close(detach=False)
            raise ConnectionError(
                "ingest server dropped the connection mid-stream — "
                "reattach (the lease journal resumes this consumer "
                "without re-decode)"
            )
        if msg.get("type") != "batch":
            raise RuntimeError(f"unexpected frame mid-stream: {msg}")
        slot = int(msg["slot"])
        batch = self._ring.read(slot)
        # Credit immediately: read() copied the rows out, so the slot
        # can refill behind the train step right away.
        protocol.send_msg(self._sock, {"type": "credit", "slot": slot,
                                       "step": int(msg["step"])})
        self._since_stats += 1
        if self._since_stats >= STATS_EVERY:
            now = time.perf_counter()
            protocol.send_msg(self._sock, {
                "type": "stats",
                "window_sec": now - self._window_t0,
                "input_wait_sec": self._window_wait,
            })
            self._window_t0 = now
            self._window_wait = 0.0
            self._since_stats = 0
        return batch

    def close(self, detach: bool = True) -> None:
        if self._closed:
            return
        self._closed = True
        if detach:
            try:
                protocol.send_msg(self._sock, {"type": "detach"})
            except OSError:  # pragma: no cover - server already gone
                pass
        try:
            self._sock.close()
        finally:
            self._ring.close()


def capacity_rows_for(cfg, mesh=None, max_fraction: float = 0.6) -> int:
    """The resident-row capacity the SPEC carries — derived exactly as
    the in-process tiered loader derives it (same budget knobs, same
    mesh width), so a served consumer and an in-process tiered run at
    the same config plan identical batches."""
    from jama16_retina_tpu.data.hbm_pipeline import resident_row_capacity

    n_dev = 1
    if mesh is not None:
        from jama16_retina_tpu.parallel import mesh as mesh_lib

        n_dev = mesh.shape[mesh_lib._batch_axis(mesh)]
    return resident_row_capacity(
        cfg.model.image_size, n_dev, max_fraction,
        budget_bytes=(
            cfg.data.tiered_resident_bytes
            if cfg.data.tiered_resident_bytes >= 0 else None
        ),
        budget_base_bytes=cfg.data.hbm_budget_bytes,
    )


def train_batches(cfg, seed: int = 0, skip_batches: "int | None" = 0,
                  mesh=None, consumer_id: "str | None" = None,
                  split: str = "train") -> Iterator[dict]:
    """The trainer seam: a ServedStream dressed as the standard loader
    generator (host {'image','grade'} batches; ``device_prefetch``
    moves them). The stream detaches when the generator is closed."""
    stream = ServedStream(
        cfg.ingest.socket_path,
        consumer_id=(
            consumer_id or cfg.ingest.consumer_id or f"pid{os.getpid()}"
        ),
        split=split, seed=seed, batch_size=cfg.data.batch_size,
        image_size=cfg.model.image_size,
        capacity_rows=capacity_rows_for(cfg, mesh=mesh),
        start_step=skip_batches,
        attach_timeout_s=cfg.ingest.attach_timeout_s,
    )
    try:
        yield from stream
    finally:
        stream.close()
