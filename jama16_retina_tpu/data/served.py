"""``data.loader="served"``: the disaggregated ingest service's client.

A thin stream over the shared-memory ring the ingest server
(jama16_retina_tpu/ingest/) fills: attach over the unix control
socket, map the ring, then yield one {'image','grade'} HOST batch per
``batch`` frame — the standard loader contract, so the trainer's
``device_prefetch`` moves batches exactly as it does for tfdata/grain
and the train loops never see which loader is underneath.

Bit-identity: the client sends the SAME residency spec the in-process
tiered loader would derive (``resident_row_capacity`` over the same
budget knobs), and the server computes each batch exactly as
``tiered_pipeline.host_reference_batches`` does — so a fit() over
``served`` consumes the identical post-decode batch sequence as the
same seed over ``tiered``/``rawshard`` (pinned in
tests/test_ingest.py, >1 epoch, partial residency).

Stall attribution: the client measures its own blocked-in-recv time
and reports tumbling ``(window_sec, input_wait_sec)`` windows over the
control channel — the fleet tuner's per-consumer input
(ingest/fleettune.py).

Causal attribution (ISSUE 18): each slot arrives with the server's
provenance stamp, and ``__next__`` tiles its measured wait into
``ingest.batch.{credit_wait,decode|cache,ring_dwell,read}`` trace
segments with shared boundary timestamps (the PR-4 batcher
discipline: segment sums are pinned against the measured wall).
``min()``-clamping the server-reported credit/decode walls against the
wait keeps attribution causal: a full-ring credit stall absorbs the
wait first (more slots would have hidden the decode), then decode,
and the residue is ring dwell. The wait lands on the
``ingest.batch.wait_s`` histogram with the batch's trace id as its
exemplar, so a slow-step dump names the exact batch that stalled it.

Crash semantics: ``skip_batches=None`` asks the server to resume from
this consumer's lease journal (kill -9 reattach, zero re-decode); the
trainer always passes its explicit checkpoint step instead, which
overrides the journal (the checkpoint is the authority on training
position).
"""

from __future__ import annotations

import os
import socket
import time
from typing import Iterator

from absl import logging

from jama16_retina_tpu.ingest import protocol
from jama16_retina_tpu.ingest.ring import BatchRing
from jama16_retina_tpu.obs import registry as obs_registry
from jama16_retina_tpu.obs import trace as trace_lib

# Report a stats window to the fleet tuner every N batches: frequent
# enough to steer within a bench window, rare enough to stay invisible
# next to a decode.
STATS_EVERY = 8


class ServedStream:
    """One attached consumer. Iterate for host batches; ``close()``
    (or exhaust/GC) detaches cleanly. Not thread-safe — one stream per
    consuming loop, like every other loader iterator."""

    def __init__(self, socket_path: str, consumer_id: str, split: str,
                 seed: int, batch_size: int, image_size: int,
                 capacity_rows: int, start_step: "int | None" = 0,
                 attach_timeout_s: float = 30.0, registry=None):
        if not socket_path:
            raise ValueError(
                "data.loader='served' needs ingest.socket_path — the "
                "unix socket of a running scripts/ingest_server.py"
            )
        self.consumer_id = consumer_id
        self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        self._sock.settimeout(attach_timeout_s)
        try:
            self._sock.connect(socket_path)
        except OSError as e:
            self._sock.close()
            raise ConnectionError(
                f"no ingest server at {socket_path!r} ({e}) — start one "
                "with scripts/ingest_server.py or switch data.loader"
            ) from None
        protocol.send_msg(self._sock, {
            "type": "attach", "protocol": protocol.PROTOCOL_VERSION,
            "consumer_id": consumer_id, "split": split,
            "seed": int(seed), "batch_size": int(batch_size),
            "image_size": int(image_size),
            "capacity_rows": int(capacity_rows),
            "start_step": None if start_step is None else int(start_step),
        })
        reply = protocol.recv_msg(self._sock)
        if reply is None:
            self._sock.close()
            raise ConnectionError(
                f"ingest server at {socket_path!r} closed during attach"
            )
        if reply.get("type") == "error":
            self._sock.close()
            if reply.get("code") == "version_mismatch":
                raise protocol.ProtocolVersionMismatch(
                    str(reply.get("message")))
            raise RuntimeError(
                f"ingest attach refused: {reply.get('message')}"
            )
        if reply.get("type") != "attached":
            self._sock.close()
            raise RuntimeError(f"unexpected attach reply: {reply}")
        # A pre-v2 server replies without a protocol field — its slot
        # layout has no provenance region, so mapping its ring with v2
        # offsets would shear every batch. Refuse, typed.
        if int(reply.get("protocol", 1)) != protocol.PROTOCOL_VERSION:
            self._sock.close()
            raise protocol.ProtocolVersionMismatch(
                f"ingest server speaks protocol v"
                f"{int(reply.get('protocol', 1))}, this client v"
                f"{protocol.PROTOCOL_VERSION} — redeploy the older side"
            )
        self.start_step = int(reply["start_step"])
        self.n_records = int(reply["n_records"])
        self.steps_per_epoch = int(reply["steps_per_epoch"])
        self._ring = BatchRing(
            int(reply["batch_size"]), int(reply["image_size"]),
            int(reply["n_slots"]), name=reply["shm_name"], create=False,
        )
        self._closed = False
        self._since_stats = 0
        self._window_t0 = time.perf_counter()
        self._window_wait = 0.0
        reg = registry if registry is not None \
            else obs_registry.default_registry()
        self._h_wait = reg.histogram(
            "ingest.batch.wait_s",
            help="seconds one served-consumer __next__ spent blocked "
                 "for + reading a batch; exemplar = the stamped batch "
                 "trace id, so slow-step dumps name the stalling batch",
        )
        # Last batch's tiling, for the segment-sum pin tests:
        # {'input_wait_s', 'read_s', 'segments': {name: seconds}} where
        # the non-read segments tile input_wait_s exactly.
        self._last_tiling: "dict | None" = None
        logging.info(
            "served loader: consumer %s attached at step %d (%d records, "
            "%d steps/epoch, ring of %d slots)", consumer_id,
            self.start_step, self.n_records, self.steps_per_epoch,
            int(reply["n_slots"]),
        )

    def __iter__(self) -> Iterator[dict]:
        return self

    def __next__(self) -> dict:
        if self._closed:
            raise StopIteration
        t0 = time.perf_counter()
        try:
            msg = protocol.recv_msg(self._sock)
        except socket.timeout:
            raise TimeoutError(
                "ingest server stopped feeding (no batch frame within "
                "the attach timeout) — check the server process"
            ) from None
        t_recv = time.perf_counter()
        self._window_wait += t_recv - t0
        if msg is None:
            # Server closed the stream (shutdown or an injected
            # ingest.ring.write fault killed this consumer's pump).
            self.close(detach=False)
            raise ConnectionError(
                "ingest server dropped the connection mid-stream — "
                "reattach (the lease journal resumes this consumer "
                "without re-decode)"
            )
        if msg.get("type") != "batch":
            raise RuntimeError(f"unexpected frame mid-stream: {msg}")
        slot = int(msg["slot"])
        batch = self._ring.read(slot)
        # Provenance must be read BEFORE the credit frame frees the
        # slot — a credited slot can refill (and restamp) immediately.
        prov = self._ring.read_provenance(slot)
        t_done = time.perf_counter()
        # Credit immediately after: read() copied the rows out, so the
        # slot can refill behind the train step right away.
        protocol.send_msg(self._sock, {"type": "credit", "slot": slot,
                                       "step": int(msg["step"])})
        self._attribute(prov, int(msg["step"]), t0, t_recv, t_done)
        self._since_stats += 1
        if self._since_stats >= STATS_EVERY:
            now = time.perf_counter()
            protocol.send_msg(self._sock, {
                "type": "stats",
                "window_sec": now - self._window_t0,
                "input_wait_sec": self._window_wait,
            })
            self._window_t0 = now
            self._window_wait = 0.0
            self._since_stats = 0
        return batch

    def _attribute(self, prov, step, t0, t_recv, t_done) -> None:
        """Tile [t0, t_done] into the ``ingest.batch.*`` segments from
        the slot's provenance stamp. Shared boundary timestamps keep
        the tiling exact: credit wait from t0, then decode (or cache
        lookup), then ring dwell as the residue up to the recv return,
        then the slot read. No stamp -> the wait is still observed,
        just unattributed (no segments)."""
        wait_recv = t_recv - t0
        read_s = t_done - t_recv
        if prov is None:
            self._h_wait.observe(wait_recv + read_s)
            self._last_tiling = None
            return
        cache_hit = bool(prov.get("cache_hit"))
        credit = min(max(0.0, float(prov.get("credit_wait_s", 0.0))),
                     wait_recv)
        decode = min(max(0.0, float(prov.get("decode_s", 0.0))),
                     wait_recv - credit)
        dwell = wait_recv - credit - decode
        trace_id = (prov.get("trace") or {}).get("trace_id")
        self._h_wait.observe(wait_recv + read_s, exemplar=trace_id)
        fill = "ingest.batch.cache" if cache_hit else "ingest.batch.decode"
        self._last_tiling = {
            "input_wait_s": wait_recv, "read_s": read_s,
            "trace_id": trace_id,
            "segments": {"ingest.batch.credit_wait": credit, fill: decode,
                         "ingest.batch.ring_dwell": dwell,
                         "ingest.batch.read": read_s},
        }
        tr = trace_lib.default_tracer()
        if tr.enabled:
            args = {"trace_id": trace_id, "step": step,
                    "seq": prov.get("seq"), "cache_hit": int(cache_hit)}
            b1 = t0 + credit
            b2 = b1 + decode
            tr.complete("ingest.batch.credit_wait", t0, b1, args)
            tr.complete(fill, b1, b2, args)
            tr.complete("ingest.batch.ring_dwell", b2, t_recv, args)
            tr.complete("ingest.batch.read", t_recv, t_done, args)

    def close(self, detach: bool = True) -> None:
        if self._closed:
            return
        self._closed = True
        if detach:
            try:
                protocol.send_msg(self._sock, {"type": "detach"})
                # Drain to the server's EOF before closing: the pump
                # reads our frames strictly in order, so its close
                # (after the detach) proves every credit ahead of it
                # was processed through the normal serve path. Closing
                # first instead turns the pump's next batch send into
                # a connection reset mid-credit — the lease still
                # lands (the server drains credits on the error path)
                # but the run-ahead decode behind the torn-off credit
                # is skipped, which the decode-once ledger drills
                # would read as nondeterministic.
                self._sock.settimeout(5.0)
                while protocol.recv_msg(self._sock) is not None:
                    pass
            except OSError:  # pragma: no cover - server already gone
                pass
        try:
            self._sock.close()
        finally:
            self._ring.close()


def capacity_rows_for(cfg, mesh=None, max_fraction: float = 0.6) -> int:
    """The resident-row capacity the SPEC carries — derived exactly as
    the in-process tiered loader derives it (same budget knobs, same
    mesh width), so a served consumer and an in-process tiered run at
    the same config plan identical batches."""
    from jama16_retina_tpu.data.hbm_pipeline import resident_row_capacity

    n_dev = 1
    if mesh is not None:
        from jama16_retina_tpu.parallel import mesh as mesh_lib

        n_dev = mesh.shape[mesh_lib._batch_axis(mesh)]
    return resident_row_capacity(
        cfg.model.image_size, n_dev, max_fraction,
        budget_bytes=(
            cfg.data.tiered_resident_bytes
            if cfg.data.tiered_resident_bytes >= 0 else None
        ),
        budget_base_bytes=cfg.data.hbm_budget_bytes,
    )


def train_batches(cfg, seed: int = 0, skip_batches: "int | None" = 0,
                  mesh=None, consumer_id: "str | None" = None,
                  split: str = "train") -> Iterator[dict]:
    """The trainer seam: a ServedStream dressed as the standard loader
    generator (host {'image','grade'} batches; ``device_prefetch``
    moves them). The stream detaches when the generator is closed."""
    stream = ServedStream(
        cfg.ingest.socket_path,
        consumer_id=(
            consumer_id or cfg.ingest.consumer_id or f"pid{os.getpid()}"
        ),
        split=split, seed=seed, batch_size=cfg.data.batch_size,
        image_size=cfg.model.image_size,
        capacity_rows=capacity_rows_for(cfg, mesh=mesh),
        start_step=skip_batches,
        attach_timeout_s=cfg.ingest.attach_timeout_s,
    )
    try:
        yield from stream
    finally:
        stream.close()
