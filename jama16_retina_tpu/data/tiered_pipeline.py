"""Tiered streaming ingest: the ``data.loader="tiered"`` option.

BENCH r05 shape of the problem: the streamed train path reaches ~10% of
device compute (pipeline_fed 139.5 vs device_only 1397.8 img/s/chip)
while the all-resident hbm loader reaches ~94% (pipeline_fed_hbm
1310.8) — but hbm_pipeline is all-or-nothing: one record over the
budget (``fits_in_hbm``) and throughput cliffs from 1311 to 139. This
module makes the degradation a RAMP instead of a cliff, with three
layers (the tf.data input-pipeline playbook, arXiv:2101.12127, applied
to a JAX loader):

  1. PARALLEL HOST DECODE — the streamed tier's records are decoded by
     grain_pipeline.ParallelDecoder, a multi-thread decode stage whose
     output is worker-count-invariant (data.decode_workers; auto from
     host cores). Replaces the single-stream decode that caps host feed
     at ~1.7k img/s.
  2. HBM SPILL CACHE — as many rows as the budget admits
     (hbm_pipeline.resident_row_capacity; data.tiered_resident_bytes)
     are decoded once and pinned device-resident, row-sharded over the
     mesh's data axis exactly like the hbm loader. Every batch mixes a
     fixed quota of resident rows (an on-device gather) with streamed
     rows, so per-step H2D shrinks proportionally to residency.
  3. OVERLAPPED H2D STAGING — streamed rows are uploaded with
     pipeline.staged_put (per-shard async copies) and the loader keeps
     ``data.stage_depth`` batches decoded + dispatched ahead of
     consumption, so host decode and H2D for step k+depth run behind
     step k's compute.

Batch composition is STATIC per run: with s = n // batch_size steps per
epoch and R pinnable rows, every batch holds
``res_pb = min(B, R // s)`` resident rows and ``B - res_pb`` streamed
rows — static shapes, so one jit program serves every step (no
recompiles at tier boundaries). The resident tier is records
[0, res_pb*s) in index order; each epoch permutes each tier internally
with a (seed, tier, epoch)-seeded numpy stream, so the whole batch
sequence is a pure function of (seed, step) at a fixed residency:
resume is the same O(1) counter offset as the hbm loader
(``skip_batches``), no state files, and the grain loader's
_GrainStateTee machinery is untouched. Epoch semantics: at partial
residency, resident records appear exactly once per epoch and
streamed records at most once with the per-epoch drop rotating under
the reshuffle; at full residency (budget admits all n rows) every
record is pinned and the n % B epoch drop rotates — the hbm loader's
exact semantics. No record is ever excluded permanently: whenever any
row stays unpinned, plan_residency reserves at least one streamed
slot per batch so the unpinnable remainder keeps rotating through
training.

Residency endpoints degenerate exactly: 100% → every batch is a pure
on-device gather (the hbm loader's steady state); 0% → the pure
streamed path (``streamed_batches`` IS ``train_batches`` at budget 0).
``host_reference_batches`` recomputes the planned batch sequence from
first principles (plan -> record ids -> direct decode, no staging/jit
machinery), giving bench.py and the tests an INDEPENDENT sequence to
hold the loader's device plumbing bit-identical to.
"""

from __future__ import annotations

import collections
import time
from typing import Iterator

import numpy as np
from absl import logging

from jama16_retina_tpu.configs import DataConfig
from jama16_retina_tpu.data import tfrecord
from jama16_retina_tpu.data.hbm_pipeline import (
    resident_row_capacity,
    row_bytes,
)
from jama16_retina_tpu.obs import registry as obs_registry


def plan_residency(
    n: int, batch_size: int, capacity_rows: int
) -> tuple[int, int, int]:
    """-> (steps_per_epoch, resident_rows_per_batch, n_resident_pinned).

    Full residency (capacity >= n): pin ALL n rows and take res_pb = B —
    batch_indices then draws from a per-epoch permutation of n, so the
    n % B epoch drop ROTATES exactly like the hbm loader's.

    Partial residency: ``res_pb = min(B, capacity // steps)`` is the
    largest per-batch resident quota whose epoch consumption
    (res_pb * steps) both fits the capacity and never exceeds the
    pinned set; the streamed tier is always feasible because
    steps * batch_size <= n. res_pb is additionally capped at B-1
    whenever any row stays unpinned: a batch with NO streamed slot
    would exclude the unpinnable remainder from training PERMANENTLY
    (the streamed tier is what rotates it), which a one-row quota
    prevents at negligible cost. Only ``res_pb * steps`` rows are
    actually pinned — capacity beyond what a whole epoch can consume
    buys nothing, so it is left to the model.
    """
    if batch_size > n:
        raise ValueError(f"batch_size={batch_size} exceeds dataset n={n}")
    steps = n // batch_size
    capacity_rows = max(0, capacity_rows)
    if capacity_rows >= n:
        return steps, batch_size, n
    res_pb = min(batch_size, capacity_rows // steps)
    if res_pb == batch_size:
        res_pb = batch_size - 1
    return steps, res_pb, res_pb * steps


def host_spill_plan(n_padded: int, process_count: int) -> list:
    """The cross-host sharded spill plan (ISSUE 14): process-major
    contiguous ``[lo, hi)`` blocks of the PADDED resident set — host p
    decodes and stages exactly its addressable shard, never the whole
    resident tier (on a pod each host would otherwise burn
    process_count× the decode work and host RAM staging rows whose
    device copies it cannot even address).

    ``n_padded`` is the resident row count already padded to the
    mesh's data-axis size (``_place_resident``'s rule), so block
    boundaries are device-block aligned: the union of the per-host
    blocks IS the single-host resident set, disjoint and in order —
    the content-invariance contract pinned by tests/test_podscale.py.
    A pure function of its arguments (graftlint-deterministic)."""
    if process_count < 1:
        raise ValueError(f"process_count must be >= 1, got {process_count}")
    if n_padded % process_count:
        raise ValueError(
            f"{n_padded} padded resident rows do not split across "
            f"{process_count} process(es); pad to the data-axis size "
            "first (_place_resident's rule — every host owns an equal "
            "device-aligned block)"
        )
    per = n_padded // process_count
    return [(p * per, (p + 1) * per) for p in range(process_count)]


def host_spill_ids(n_res: int, n_padded: int, process_index: int,
                   process_count: int) -> np.ndarray:
    """Global record ids host ``process_index`` stages: its
    ``host_spill_plan`` block, with padding rows (>= n_res) wrapping
    onto leading records exactly like the single-host pad
    (``_place_resident``'s wraparound rule), so the padded global
    array's contents are invariant to how many hosts staged it."""
    lo, hi = host_spill_plan(n_padded, process_count)[process_index]
    return (np.arange(lo, hi) % max(n_res, 1)).astype(np.int64)


def stage_resident(decoder, n_res: int, mesh, process_index=None,
                   process_count=None):
    """Decode + pin the resident tier, per-host sharded (ISSUE 14).

    Single-process (the historical path, bit-identical): one
    ``decode_range`` + ``_place_resident``. Multi-process: each host
    decodes only its ``host_spill_ids`` block and contributes it via
    ``jax.make_array_from_process_local_data`` — the spill cache's
    device layout is identical to the single-host placement (row-
    sharded dim 0, process-major), only the staging work is sharded.
    ``process_index``/``process_count`` default to the jax runtime's
    (tests pass them explicitly to drive the plan single-process)."""
    import jax

    from jama16_retina_tpu.parallel import mesh as mesh_lib

    P = jax.process_count() if process_count is None else process_count
    p = jax.process_index() if process_index is None else process_index
    if mesh is None or P <= 1:
        images, grades = decoder.decode_range(0, n_res)
        return _place_resident(images, grades, mesh)
    if mesh_lib.has_member_axis(mesh):
        # Rows shard over the DATA axis only — a >1-way member axis
        # REPLICATES every row across member groups, so a host whose
        # devices sit in one member row addresses ALL rows of its data
        # columns, not a disjoint 1/P block: the per-host plan below
        # cannot express that layout (make_array_from_process_local_data
        # would mis-assemble it). Refuse loudly; full-local placement
        # (mesh_lib.place_full_local / the hbm loader) is the
        # member-mesh road.
        raise ValueError(
            "the cross-host sharded spill plan needs a data-only mesh "
            "(rows replicate across a >1-way member axis, so no "
            "disjoint per-host row block exists) — use the hbm loader "
            "or a pure data mesh for multi-process tiered residency"
        )
    d = int(mesh.shape[mesh_lib._batch_axis(mesh)])
    n_padded = n_res + ((-n_res) % d)
    ids = host_spill_ids(n_res, n_padded, p, P)
    host = decoder.decode_batch(ids)
    sharding = mesh_lib.batch_sharding(mesh)
    return _note_resident_owner((
        jax.make_array_from_process_local_data(sharding, host["image"]),
        jax.make_array_from_process_local_data(sharding, host["grade"]),
    ))


def _epoch_perm(seed: int, epoch: int, tier: int, n: int) -> np.ndarray:
    """Deterministic per-(tier, epoch) permutation of [0, n) — a numpy
    stream seeded on (seed, tier, epoch) via SeedSequence (the same
    derivation fit_tf uses for per-step augment draws), host-computable
    (the loader must know which records to DECODE, unlike the hbm
    loader's on-device permutation) and independent of worker count."""
    return np.random.default_rng([seed, tier, epoch]).permutation(n)


class _TierPlan:
    """Index bookkeeping for one (n, batch_size, residency) layout."""

    def __init__(self, n: int, batch_size: int, capacity_rows: int,
                 seed: int):
        self.n = n
        self.batch = batch_size
        self.steps, self.res_pb, self.n_res = plan_residency(
            n, batch_size, capacity_rows
        )
        self.str_pb = batch_size - self.res_pb
        self.n_str = n - self.n_res
        self.seed = seed
        self._perms: dict[tuple[int, int], np.ndarray] = {}

    def _perm(self, tier: int, epoch: int, n: int) -> np.ndarray:
        key = (tier, epoch)
        if key not in self._perms:
            # Keep only the current epoch's pair of perms (+ the next
            # epoch's while the staging queue straddles the boundary).
            for k in [k for k in self._perms if k[1] < epoch - 1]:
                del self._perms[k]
            self._perms[key] = _epoch_perm(self.seed, epoch, tier, n)
        return self._perms[key]

    def batch_indices(self, step: int) -> tuple[np.ndarray, np.ndarray]:
        """Global record ids for batch ``step``:
        (resident_ids [res_pb], streamed_ids [str_pb])."""
        epoch, b = divmod(step, self.steps)
        res = np.zeros((0,), np.int64)
        if self.res_pb:
            perm = self._perm(0, epoch, self.n_res)
            res = perm[b * self.res_pb:(b + 1) * self.res_pb]
        streamed = np.zeros((0,), np.int64)
        if self.str_pb:
            perm = self._perm(1, epoch, self.n_str)
            streamed = self.n_res + perm[b * self.str_pb:(b + 1) * self.str_pb]
        return res, streamed


def _note_resident_owner(placed):
    """Register the pinned resident tier's per-device footprint with
    the HBM owner ledger (obs/device.py; ISSUE 19) — one measurement at
    placement, pass-through return."""
    try:
        from jama16_retina_tpu.obs import device as device_lib

        device_lib.set_hbm_owner(
            "tiered_resident", device_lib.tree_device_bytes(placed)
        )
    except Exception:  # noqa: BLE001 - accounting only
        pass
    return placed


def _place_resident(images: np.ndarray, grades: np.ndarray, mesh):
    """Pin the resident tier on device, row-sharded over the data axis
    (hbm_pipeline.make_batch_fn's placement rule: pad dim 0 to the data
    axis size with leading records as filler; gather indices stay below
    the true count, so padding is never sampled)."""
    import jax

    from jama16_retina_tpu.parallel import mesh as mesh_lib

    if mesh is None:
        return _note_resident_owner(
            (jax.device_put(images), jax.device_put(grades))
        )
    d = mesh.shape[mesh_lib._batch_axis(mesh)]
    pad = (-len(images)) % d
    if pad:
        # Wraparound indexing, not images[:pad]: a resident set SMALLER
        # than the pad (tiny res_pb on a wide mesh) must still fill
        # every padding row or dim 0 stays non-divisible by the axis.
        idx = np.arange(len(images) + pad) % len(images)
        images = images[idx]
        grades = grades[idx]
    sh = mesh_lib.batch_sharding(mesh)
    return _note_resident_owner(
        (jax.device_put(images, sh), jax.device_put(grades, sh))
    )


def _make_combine_fn(res_images, res_grades, res_pb: int, str_pb: int,
                     mesh):
    """One jit program per run: (res_idx, str_imgs, str_grades) ->
    {'image': [B,...], 'grade': [B]} — resident gather concatenated with
    the staged streamed rows, emitted under the standard batch sharding.
    Static res_pb/str_pb keep the shapes fixed for every step."""
    import jax
    import jax.numpy as jnp

    from jama16_retina_tpu.parallel import mesh as mesh_lib

    out_shardings = None
    if mesh is not None:
        out_shardings = {
            "image": mesh_lib.batch_sharding(mesh),
            "grade": mesh_lib.batch_sharding(mesh),
        }

    if res_pb and str_pb:
        def combine(imgs, grs, res_idx, str_imgs, str_grs):
            return {
                "image": jnp.concatenate(
                    [jnp.take(imgs, res_idx, axis=0), str_imgs]
                ),
                "grade": jnp.concatenate(
                    [jnp.take(grs, res_idx, axis=0), str_grs]
                ),
            }
    elif res_pb:
        def combine(imgs, grs, res_idx):
            return {
                "image": jnp.take(imgs, res_idx, axis=0),
                "grade": jnp.take(grs, res_idx, axis=0),
            }
    else:
        def combine(str_imgs, str_grs):
            # jnp.asarray under out_shardings: the scatter of the staged
            # host rows into the standard batch layout.
            return {"image": jnp.asarray(str_imgs),
                    "grade": jnp.asarray(str_grs)}

    jitted = (
        jax.jit(combine, out_shardings=out_shardings)
        if out_shardings is not None else jax.jit(combine)
    )

    def run(res_idx, str_imgs, str_grs):
        if res_pb and str_pb:
            return jitted(res_images, res_grades, res_idx, str_imgs, str_grs)
        if res_pb:
            return jitted(res_images, res_grades, res_idx)
        return jitted(str_imgs, str_grs)

    return run


def resolve_stage_depth(cfg: DataConfig) -> int:
    return cfg.stage_depth if cfg.stage_depth > 0 else max(
        2, cfg.prefetch_batches
    )


def train_batches(
    data_dir: str,
    split: str,
    cfg: DataConfig,
    image_size: int,
    seed: int = 0,
    skip_batches: int = 0,
    mesh=None,
    max_fraction: float = 0.6,
    knobs=None,
    decoder_factory=None,
) -> Iterator[dict]:
    """Drop-in twin of pipeline.train_batches yielding DEVICE-resident
    batches whose rows mix the HBM-resident and streamed tiers.
    ``skip_batches`` is an O(1) counter offset (pure (seed, step)
    semantics, same contract as the hbm loader).

    ``knobs`` (data/autotune.Knobs): live decode_workers/stage_depth
    the fill loop polls between batches — the ingest autotuner's
    control surface. Both knobs are content-invariant (ParallelDecoder
    worker invariance; stage depth is pure run-ahead), so a tuned run's
    batch sequence is identical to a hand-set one.

    ``decoder_factory`` (``(workers, quarantine) -> decoder``): swap
    the record-decode stage while keeping ALL of this loader's
    machinery — the residency plan, staging, combine jit, quarantine
    substitution and telemetry. The decoder contract is
    grain_pipeline.ParallelDecoder's surface (``__len__``,
    ``decode_batch``, ``decode_range``, ``set_workers``, ``close``).
    data/rawshard.py plugs its ahead-of-time-transcoded shards in
    here."""
    import jax

    from jama16_retina_tpu.data.grain_pipeline import (
        ParallelDecoder,
        TFRecordIndex,
        resolve_decode_workers,
    )
    from jama16_retina_tpu.parallel import mesh as mesh_lib

    workers = (
        knobs.decode_workers if knobs is not None
        else resolve_decode_workers(cfg.decode_workers)
    )
    if decoder_factory is None:
        index = TFRecordIndex(tfrecord.list_split(data_dir, split))
        decoder = ParallelDecoder(
            index, image_size, workers=workers,
            quarantine=cfg.quarantine_bad_records,
        )
    else:
        decoder = decoder_factory(workers, cfg.quarantine_bad_records)
    n = len(decoder)
    if n == 0:
        raise ValueError(f"no records under {data_dir}/{split}")

    n_dev = (
        mesh.shape[mesh_lib._batch_axis(mesh)] if mesh is not None else 1
    )
    capacity = resident_row_capacity(
        image_size, n_dev, max_fraction,
        budget_bytes=(
            cfg.tiered_resident_bytes
            if cfg.tiered_resident_bytes >= 0 else None
        ),
        budget_base_bytes=getattr(cfg, "hbm_budget_bytes", 0),
    )
    plan = _TierPlan(n, cfg.batch_size, capacity, seed)

    if jax.process_count() > 1 and plan.str_pb:
        # The STREAMED tier stays single-process (its per-batch host
        # decode has no per-process row block under this plan); the
        # fully-resident case proceeds below with the cross-host
        # sharded spill plan — each host stages only its addressable
        # shard (stage_resident / host_spill_plan, ISSUE 14), so
        # data.hbm_budget_bytes governs each host's own staging.
        raise ValueError(
            "data.loader='tiered' at PARTIAL residency is "
            "single-process — raise the budget until the split is "
            "fully resident (the spill plan then shards staging "
            "across hosts), or use the hbm/grain/tfdata loaders on "
            "multi-process launches"
        )

    logging.info(
        "tiered loader: %d/%d rows HBM-resident (%.0f%%, %.1f MB over %d "
        "chip(s)), %d resident + %d streamed rows per batch, %d decode "
        "worker(s)",
        plan.n_res, n, 100.0 * plan.n_res / n,
        plan.n_res * row_bytes(image_size) / 1e6, n_dev,
        plan.res_pb, plan.str_pb, workers,
    )

    # Telemetry (obs/): per-batch tier composition as HIT/SPILL counters
    # — a resident row is an HBM-cache hit (on-device gather, zero H2D),
    # a streamed row is the spill that pays decode + upload — plus the
    # staging-queue depth gauge (the effective decode+H2D run-ahead this
    # loader sustains; the synchronous fill keeps it at the configured
    # depth, so host-side starvation surfaces as trainer input_wait_sec
    # and in decode_batch_s, not as a sagging depth).
    reg = obs_registry.default_registry()
    c_hit = reg.counter(
        "data.tiered.resident_rows",
        help="batch rows served from the resident HBM tier (cache "
             "hits: on-device gather, zero H2D)",
    )
    c_spill = reg.counter(
        "data.tiered.streamed_rows",
        help="batch rows streamed through host decode + staged H2D "
             "(spills); hit rate = resident / (resident + streamed)",
    )
    g_depth = reg.gauge(
        "data.tiered.stage_depth",
        help="the tiered loader's staging-queue depth (decode+H2D "
             "run-ahead; the data.stage_depth target)",
    )
    h_decode = reg.histogram(
        "data.tiered.decode_batch_s",
        help="streamed-tier decode seconds per batch",
    )
    reg.gauge(
        "data.tiered.resident_rows_pinned",
        help="rows the HBM budget admitted into the resident tier",
    ).set(plan.n_res)
    g_host_spill = reg.gauge(
        "data.tiered.host_spill_rows",
        help="resident-tier rows THIS host decoded and staged (the "
             "cross-host sharded spill plan's addressable shard; "
             "single-process = the whole resident set)",
    )

    res_images = res_grades = None
    if plan.n_res:
        res_images, res_grades = stage_resident(decoder, plan.n_res, mesh)
        if jax.process_count() > 1:
            n_padded = plan.n_res + ((-plan.n_res) % n_dev)
            lo, hi = host_spill_plan(n_padded, jax.process_count())[
                jax.process_index()
            ]
            g_host_spill.set(hi - lo)
        else:
            g_host_spill.set(plan.n_res)
    combine = _make_combine_fn(
        res_images, res_grades, plan.res_pb, plan.str_pb, mesh
    )
    sharding = mesh_lib.batch_sharding(mesh) if mesh is not None else None

    from jama16_retina_tpu.data import pipeline as pipeline_lib

    def make_batch(step: int) -> dict:
        res_idx, str_ids = plan.batch_indices(step)
        c_hit.inc(plan.res_pb)
        c_spill.inc(plan.str_pb)
        str_imgs = str_grs = None
        if plan.str_pb:
            t0 = time.perf_counter()
            host = decoder.decode_batch(str_ids)
            h_decode.observe(time.perf_counter() - t0)
            if sharding is not None and plan.str_pb % n_dev == 0:
                # Per-shard staged upload: each device's block is an
                # independent async copy behind the running step.
                str_imgs = pipeline_lib.staged_put(host["image"], sharding)
                str_grs = pipeline_lib.staged_put(host["grade"], sharding)
            else:
                # Streamed quota not divisible by the data axis (or no
                # mesh): a replicated put; GSPMD reshards inside combine.
                str_imgs = jax.device_put(host["image"])
                str_grs = jax.device_put(host["grade"])
        dev_idx = None
        if plan.res_pb:
            dev_idx = np.asarray(res_idx, np.int32)
        return combine(dev_idx, str_imgs, str_grs)

    depth = resolve_stage_depth(cfg)
    queue: collections.deque = collections.deque()
    step = skip_batches
    try:
        while True:
            if knobs is not None:
                # Live knob poll (one lock + int read each): a raised
                # stage depth fills deeper on the next iteration, a
                # lowered one just lets the queue drain to the new
                # level; worker resizes land between decode calls.
                decoder.set_workers(knobs.decode_workers)
                depth = knobs.stage_depth
            while len(queue) <= depth:
                queue.append(make_batch(step + len(queue)))
            g_depth.set(len(queue))
            yield queue.popleft()
            step += 1
    finally:
        decoder.close()


def host_reference_batches(
    data_dir: str,
    split: str,
    cfg: DataConfig,
    image_size: int,
    seed: int = 0,
    skip_batches: int = 0,
    capacity_rows: int = 0,
) -> Iterator[dict]:
    """The batch sequence ``train_batches`` MUST produce, recomputed
    from first principles: same _TierPlan index selection, but rows are
    decoded directly to host arrays in batch order — no residency
    placement, no staging, no combine jit. An independent oracle for
    the loader's device plumbing (bench.py's zero-budget fallback check
    and tests/test_tiered.py compare against it bit for bit)."""
    from jama16_retina_tpu.data.grain_pipeline import (
        ParallelDecoder,
        TFRecordIndex,
    )

    index = TFRecordIndex(tfrecord.list_split(data_dir, split))
    n = len(index)
    plan = _TierPlan(n, cfg.batch_size, capacity_rows, seed)
    decoder = ParallelDecoder(
        index, image_size, workers=1,
        quarantine=cfg.quarantine_bad_records,
    )
    step = skip_batches
    try:
        while True:
            res_ids, str_ids = plan.batch_indices(step)
            yield decoder.decode_batch(
                np.concatenate([res_ids, str_ids]).astype(np.int64)
            )
            step += 1
    finally:
        decoder.close()


def streamed_batches(
    data_dir: str,
    split: str,
    cfg: DataConfig,
    image_size: int,
    seed: int = 0,
    skip_batches: int = 0,
    mesh=None,
) -> Iterator[dict]:
    """The pure streamed tier as a standalone loader: parallel host
    decode + staged upload, nothing resident. By construction this IS
    ``train_batches`` with a zero HBM budget — the bit-identical
    fallback the acceptance bench asserts."""
    import dataclasses

    return train_batches(
        data_dir, split,
        dataclasses.replace(cfg, tiered_resident_bytes=0),
        image_size, seed=seed, skip_batches=skip_batches, mesh=mesh,
    )
