"""Closed-loop ingest autotuner: the ``data.autotune=true`` option.

ISSUE 7 tentpole — the layer that turns the PR-3 observability stack
from a reporting surface into a CONTROL surface. The streamed train
path feeds ~10% of device compute (BENCH_r05: pipeline_fed 139.5 vs
device_only 1397.8 img/s/chip) and every signal needed to close that
gap is already exported (trainer ``input_wait_sec`` stall attribution,
``data.decode.busy_s`` decoder utilization, tiered hit/spill counters)
— as is every knob a tuner would turn (``data.decode_workers``,
``data.stage_depth``, ``data.prefetch_batches``). This module closes
the loop the way "tf.data: A Machine Learning Data Processing
Framework" (PAPERS.md) closes it for tf.data: a lightweight controller
observes tumbling windows of those signals and adjusts the knobs
online.

Design constraints, in order:

  * TIMING-ONLY KNOBS. The tuner changes WHEN data arrives, never WHAT
    arrives: every tunable knob is content-invariant by the loaders'
    own contracts (``ParallelDecoder`` output is worker-count-
    invariant; stage/prefetch depth are pure run-ahead). A run with
    ``data.autotune=true`` therefore produces bit-identical batches —
    and bit-identical eval metrics — to the same seed with hand-set
    knobs (pinned in tests/test_autotune.py). Residency
    (``tiered_resident_bytes``) is deliberately NOT a live knob: the
    tiered plan derives batch COMPOSITION from it, so turning it
    mid-run would change record selection and break the (seed, step)
    resume purity.
  * DETERMINISTIC DECISIONS. ``decide()`` is a pure function of
    (window stats, current knobs, limits, controller state) — same
    stats in, same adjustments out, which is what lets the convergence
    tests pin exact decision sequences.
  * BUDGET-SAFE. The run-ahead knobs pin streamed batches in device
    memory (staged H2D buffers). Their total is clamped so the staged
    bytes never exceed ``Limits.hbm_headroom_bytes`` — by default the
    same 10%-of-HBM-budget discipline the eval cache applies
    (trainer._eval_cache_for), on top of the 60% the spill cache may
    already hold resident. The clamp is the FIRST rule in ``decide``:
    a violated budget is corrected before any hill-climbing happens,
    and no increase is ever issued past the cap.
  * NON-OSCILLATING. Hill-climb with hysteresis: increases need the
    input-wait fraction above ``HIGH_WATER``; decays need
    ``QUIET_WINDOWS`` consecutive windows below ``LOW_WATER``; the
    band between them holds still. A decay that starves the very next
    window is REVERTED and the reverted value becomes that knob's
    ratchet floor — it is never decayed below again, so a stationary
    workload converges and stays converged (pinned in
    tests/test_autotune.py).
  * DISABLED == NOTHING. ``data.autotune=false`` builds no Knobs and
    no tuner; the loaders' poll sites cost one ``is not None`` branch
    per batch (pinned in tests/test_bench_guard.py).

Every applied adjustment is counted (``data.autotune.adjustments`` +
``data.autotune.<knob>``), mirrored into a ``data.autotune.<knob>``
gauge (current value), and emitted as a ``data.autotune.<knob>``
instant trace event carrying {old, new, reason} — so a trajectory file
or blackbox dump shows exactly WHY the feed rate moved.
"""

from __future__ import annotations

import dataclasses
import os
import threading

from absl import logging as absl_logging

from jama16_retina_tpu.obs import registry as obs_registry
from jama16_retina_tpu.obs import trace as obs_trace

# --- Policy constants (module-level so tests pin against the shipped
# values; see the module docstring for the roles) -----------------------
HIGH_WATER = 0.10     # input-wait fraction: above = the chip is starved
LOW_WATER = 0.02      # below = the pipeline is comfortably ahead
BUSY_HIGH = 0.75      # decoder-pool utilization: above = decode-bound
QUIET_WINDOWS = 3     # consecutive quiet windows before one decay step
MIN_WINDOW_S = 0.05   # shorter windows carry no usable signal
MAX_STAGE_DEPTH = 16  # hard ceilings for the run-ahead knobs — past
MAX_PREFETCH = 8      # these, more queue is latency, not throughput
MAX_WORKERS_CAP = 16  # decode threads stop scaling past the shared
                      # TFRecordIndex descriptors (grain_pipeline)


@dataclasses.dataclass(frozen=True)
class WindowStats:
    """The signals of one tumbling window, normalized.

    ``input_wait_frac``: fraction of the window the trainer spent
    blocked in ``next(batches)`` (StallClock ``input_wait_sec`` /
    ``window_sec``). ``decoder_busy_frac``: ``data.decode.busy_s``
    delta / (window * workers) — the ParallelDecoder pool utilization.
    ``spill_frac``: streamed-row fraction of the window's rows (tiered
    hit/spill counter deltas; 1.0 when the loader keeps nothing
    resident, so the whole batch is staged H2D).
    """

    window_sec: float
    input_wait_frac: float
    decoder_busy_frac: float
    spill_frac: float = 1.0


@dataclasses.dataclass(frozen=True)
class Limits:
    """Knob bounds + the HBM staging headroom the clamp enforces."""

    min_decode_workers: int = 1
    max_decode_workers: int = 8
    min_stage_depth: int = 1
    max_stage_depth: int = MAX_STAGE_DEPTH
    min_prefetch_depth: int = 1
    max_prefetch_depth: int = MAX_PREFETCH
    # Total device bytes the staged run-ahead may pin (streamed rows of
    # stage_depth + prefetch_depth batches). <= 0 disables the clamp
    # (no budget known — e.g. pure-host tests).
    hbm_headroom_bytes: int = 0
    # Device bytes one full batch costs when fully streamed.
    batch_bytes: int = 0


@dataclasses.dataclass(frozen=True)
class ControlState:
    """Controller memory threaded through ``decide`` — explicit state
    keeps the decision function pure (and the tests' sequences exact)."""

    quiet_windows: int = 0
    # Ratchet floors learned from reverted decays: a decay that starved
    # the next window is undone and its old value becomes the floor.
    stage_floor: int = 0
    prefetch_floor: int = 0
    # The single decay issued last window, as (knob, old_value) — the
    # revert target if that decay turns out to have caused starvation.
    last_decay: tuple = ()


@dataclasses.dataclass(frozen=True)
class Adjustment:
    knob: str   # "decode_workers" | "stage_depth" | "prefetch_depth"
    old: int
    new: int
    reason: str


class Knobs:
    """Thread-safe live knob values.

    The loaders POLL these between batches (tiered fill loop, prefetch
    queue) and the tuner writes them from the trainer thread at window
    boundaries — a knob read is one lock + attribute read, a knob that
    does not exist for a loader is simply never polled. All three are
    content-invariant (module docstring), so concurrent adjustment is
    a pure timing perturbation.
    """

    __slots__ = ("_lock", "_v")

    FIELDS = ("decode_workers", "stage_depth", "prefetch_depth")

    def __init__(self, decode_workers: int, stage_depth: int,
                 prefetch_depth: int):
        self._lock = threading.Lock()
        self._v = {
            "decode_workers": int(decode_workers),
            "stage_depth": int(stage_depth),
            "prefetch_depth": int(prefetch_depth),
        }

    @property
    def decode_workers(self) -> int:
        with self._lock:
            return self._v["decode_workers"]

    @property
    def stage_depth(self) -> int:
        with self._lock:
            return self._v["stage_depth"]

    @property
    def prefetch_depth(self) -> int:
        with self._lock:
            return self._v["prefetch_depth"]

    def get(self, knob: str) -> int:
        with self._lock:
            return self._v[knob]

    def set(self, knob: str, value: int) -> None:
        if knob not in self._v:
            raise KeyError(knob)
        with self._lock:
            self._v[knob] = int(value)

    def as_dict(self) -> dict:
        with self._lock:
            return dict(self._v)


def staged_cap(limits: Limits, spill_frac: float) -> "int | None":
    """Max total run-ahead (stage_depth + prefetch_depth) the HBM
    headroom admits. The headroom is budgeted against the loaders'
    FILL PEAK, not the nominal depths: the tiered fill loop holds up
    to stage_depth+1 batches while filling and device_prefetch holds
    prefetch_depth+1 at its append point, so depths summing to C pin
    C+2 batches at peak — the cap subtracts those 2 in-flight batches
    so the byte guarantee holds at the worst instant. Only the
    STREAMED fraction of a batch is staged (resident rows are an
    on-device gather, never re-uploaded), so the cap scales inversely
    with spill_frac; a fully resident stream (spill_frac 0) stages
    nothing and has no cap. None = no cap (headroom unknown or
    nothing staged). Never below 2: one batch in flight plus one
    being built is the minimum that overlaps at all — at pathological
    headrooms this floor wins over the budget (a pipeline that cannot
    hold two batches cannot run at all).
    """
    if limits.hbm_headroom_bytes <= 0 or limits.batch_bytes <= 0:
        return None
    per_batch = limits.batch_bytes * min(max(spill_frac, 0.0), 1.0)
    if per_batch <= 0:
        return None
    return max(2, int(limits.hbm_headroom_bytes // per_batch) - 2)


def decide(
    stats: WindowStats, knobs: dict, limits: Limits, state: ControlState
) -> tuple[tuple[Adjustment, ...], ControlState]:
    """One window's decision: (adjustments, next state). PURE — the
    whole policy lives here so determinism is checkable by calling it.

    Rule order (first match wins):
      1. HBM budget clamp (hard constraint — corrects violations and
         is also consulted before any increase).
      2. Starved + a decay issued last window: revert it and ratchet.
      3. Starved: raise the bottleneck knob by one — decode workers
         when the pool is saturated, else staging depth, else prefetch
         depth, else workers as the last resort.
      4. Quiet for QUIET_WINDOWS: decay ONE run-ahead knob by one
         (stage first: it pins HBM), respecting ratchet floors. Worker
         threads are never decayed — an idle thread parks on the pool
         queue and costs nothing, unlike pinned device buffers.
      5. Dead band: hold still.
    """
    if stats.window_sec < MIN_WINDOW_S:
        return (), state
    w = int(knobs["decode_workers"])
    s = int(knobs["stage_depth"])
    p = int(knobs["prefetch_depth"])
    cap = staged_cap(limits, stats.spill_frac)
    adjs: list[Adjustment] = []

    # 1) Budget clamp — the tuner must never hold the staging queue
    # over the headroom the spill cache's budget discipline leaves it.
    if cap is not None and s + p > cap:
        s0, p0 = s, p
        while s + p > cap and s > limits.min_stage_depth:
            s -= 1
        while s + p > cap and p > limits.min_prefetch_depth:
            p -= 1
        if s != s0:
            adjs.append(Adjustment("stage_depth", s0, s, "hbm_budget"))
        if p != p0:
            adjs.append(Adjustment("prefetch_depth", p0, p, "hbm_budget"))
        return tuple(adjs), dataclasses.replace(
            state, quiet_windows=0, last_decay=()
        )

    starved = stats.input_wait_frac > HIGH_WATER
    quiet = stats.input_wait_frac < LOW_WATER

    if starved:
        if state.last_decay:
            # 2) The decay last window caused this starvation: undo it
            # and never decay that knob below the reverted value again.
            knob, old = state.last_decay
            adjs.append(Adjustment(knob, knobs[knob], old, "decay_reverted"))
            floors = {}
            if knob == "stage_depth":
                floors["stage_floor"] = old
            elif knob == "prefetch_depth":
                floors["prefetch_floor"] = old
            return tuple(adjs), dataclasses.replace(
                state, quiet_windows=0, last_decay=(), **floors
            )
        # 3) Hill-climb the bottleneck knob.
        room = cap is None or s + p + 1 <= cap
        if stats.decoder_busy_frac >= BUSY_HIGH and w < limits.max_decode_workers:
            adjs.append(
                Adjustment("decode_workers", w, w + 1, "decoder_saturated")
            )
        elif s < limits.max_stage_depth and room:
            adjs.append(Adjustment("stage_depth", s, s + 1, "staging_shallow"))
        elif p < limits.max_prefetch_depth and room:
            adjs.append(
                Adjustment("prefetch_depth", p, p + 1, "prefetch_shallow")
            )
        elif w < limits.max_decode_workers:
            adjs.append(
                Adjustment("decode_workers", w, w + 1, "starved_fallback")
            )
        return tuple(adjs), dataclasses.replace(
            state, quiet_windows=0, last_decay=()
        )

    if quiet:
        q = state.quiet_windows + 1
        if q < QUIET_WINDOWS:
            return (), dataclasses.replace(
                state, quiet_windows=q, last_decay=()
            )
        # 4) One decay step, floors respected.
        if s > max(limits.min_stage_depth, state.stage_floor):
            adjs.append(Adjustment("stage_depth", s, s - 1, "quiet_decay"))
            return tuple(adjs), dataclasses.replace(
                state, quiet_windows=0, last_decay=("stage_depth", s)
            )
        if p > max(limits.min_prefetch_depth, state.prefetch_floor):
            adjs.append(Adjustment("prefetch_depth", p, p - 1, "quiet_decay"))
            return tuple(adjs), dataclasses.replace(
                state, quiet_windows=0, last_decay=("prefetch_depth", p)
            )
        return (), dataclasses.replace(state, quiet_windows=q, last_decay=())

    # 5) Dead band.
    return (), dataclasses.replace(state, quiet_windows=0, last_decay=())


class IngestAutotuner:
    """Reads the live registry over tumbling windows, applies
    ``decide``'s adjustments to the shared ``Knobs``, and records every
    adjustment as counter + gauge + trace event.

    The window cadence is the CALLER's (the trainer observes at its
    log boundary, bench.py at its own window loop) — the tuner only
    needs (window_sec, input_wait_sec) from the caller's StallClock;
    the decoder/tier signals it reads itself as counter deltas.
    """

    def __init__(self, knobs: Knobs, limits: Limits,
                 registry: "obs_registry.Registry | None" = None,
                 tracer: "obs_trace.Tracer | None" = None):
        self.knobs = knobs
        self.limits = limits
        self.state = ControlState()
        self._reg = (
            registry if registry is not None
            else obs_registry.default_registry()
        )
        self._tracer = (
            tracer if tracer is not None else obs_trace.default_tracer()
        )
        # Read-side handles: the owning loaders register these with
        # their help text (grain_pipeline / tiered_pipeline); the tuner
        # only reads deltas.
        self._c_busy = self._reg.counter("data.decode.busy_s")
        self._c_hit = self._reg.counter("data.tiered.resident_rows")
        self._c_spill = self._reg.counter("data.tiered.streamed_rows")
        self._c_adjust = self._reg.counter(
            "data.autotune.adjustments",
            help="ingest-autotuner knob adjustments applied, all knobs "
                 "(data/autotune.py); per-knob counts under "
                 "data.autotune.adjust.<knob>, current values under the "
                 "data.autotune.<knob> gauges",
        )
        # Window deltas start from the counters' CURRENT values: in a
        # long-lived process (bench, notebooks) earlier work's decode
        # counts must not read as the first window's burst.
        self._prev = {
            "busy": self._c_busy.value,
            "hit": self._c_hit.value,
            "spill": self._c_spill.value,
        }
        for k in Knobs.FIELDS:
            self._reg.gauge(
                f"data.autotune.{k}",
                help="current value of this live ingest knob "
                     "(decode_workers/stage_depth/prefetch_depth)",
            ).set(knobs.get(k))

    def window_stats(self, window_sec: float,
                     input_wait_sec: float) -> WindowStats:
        """Normalize this window's registry deltas into WindowStats."""
        busy, hit, spill = (
            self._c_busy.value, self._c_hit.value, self._c_spill.value
        )
        d_busy = max(0.0, busy - self._prev["busy"])
        d_hit = max(0.0, hit - self._prev["hit"])
        d_spill = max(0.0, spill - self._prev["spill"])
        self._prev = {"busy": busy, "hit": hit, "spill": spill}
        wall = max(window_sec, 1e-9)
        workers = max(1, self.knobs.decode_workers)
        rows = d_hit + d_spill
        return WindowStats(
            window_sec=window_sec,
            input_wait_frac=min(1.0, max(0.0, input_wait_sec / wall)),
            decoder_busy_frac=min(1.0, d_busy / (wall * workers)),
            # No tier counters moving (tfdata/grain/rawshard-streamed
            # before first window, or a fully streamed plan): treat the
            # batch as fully staged — the conservative budget view.
            spill_frac=(d_spill / rows) if rows else 1.0,
        )

    def observe(self, window_sec: float,
                input_wait_sec: float) -> tuple[Adjustment, ...]:
        """One tumbling window: read signals, decide, apply, record."""
        stats = self.window_stats(window_sec, input_wait_sec)
        adjs, self.state = decide(
            stats, self.knobs.as_dict(), self.limits, self.state
        )
        for a in adjs:
            self.knobs.set(a.knob, a.new)
            self._c_adjust.inc()
            self._reg.counter(
                f"data.autotune.adjust.{a.knob}",
                help="autotuner adjustments applied to this one knob",
            ).inc()
            self._reg.gauge(f"data.autotune.{a.knob}").set(a.new)
            self._tracer.instant(
                f"data.autotune.{a.knob}",
                args={"old": a.old, "new": a.new, "reason": a.reason},
            )
            absl_logging.info(
                "autotune: %s %d -> %d (%s; input_wait %.0f%%, decoder "
                "busy %.0f%%)", a.knob, a.old, a.new, a.reason,
                100 * stats.input_wait_frac, 100 * stats.decoder_busy_frac,
            )
        return adjs


def for_config(cfg, mesh=None, registry=None, tracer=None,
               max_fraction: float = 0.6) -> tuple[Knobs, IngestAutotuner]:
    """(Knobs, tuner) for one run — the trainer/bench wiring helper.

    Initial knob values are the config's own resolved values, so an
    autotuned run STARTS exactly where a hand-set run sits and the
    tuner only moves from there. The staging headroom is 10% of the
    per-chip HBM budget across the data axis — the EXACT discipline
    the eval cache is held to (trainer._eval_cache_for gates at
    0.1 x hbm_budget_bytes at the same ``max_fraction``), on top of
    the 60% the resident tier may already pin; the
    ``data.hbm_budget_bytes`` override applies here too.
    """
    from jama16_retina_tpu.data.grain_pipeline import resolve_decode_workers
    from jama16_retina_tpu.data.hbm_pipeline import (
        hbm_budget_bytes,
        row_bytes,
    )
    from jama16_retina_tpu.data.tiered_pipeline import resolve_stage_depth

    workers0 = resolve_decode_workers(cfg.data.decode_workers)
    knobs = Knobs(
        decode_workers=workers0,
        stage_depth=resolve_stage_depth(cfg.data),
        prefetch_depth=max(1, cfg.data.prefetch_batches),
    )
    n_dev = 1
    if mesh is not None:
        from jama16_retina_tpu.parallel import mesh as mesh_lib

        n_dev = mesh.shape[mesh_lib._batch_axis(mesh)]
    budget = hbm_budget_bytes(
        max_fraction=max_fraction,
        budget_base_bytes=getattr(cfg.data, "hbm_budget_bytes", 0),
    )
    limits = Limits(
        min_decode_workers=1,
        # Never below the configured start; otherwise one thread per
        # core up to the shared-descriptor scaling cliff.
        max_decode_workers=max(
            workers0,
            min(MAX_WORKERS_CAP, max(1, (os.cpu_count() or 2) - 1)),
        ),
        hbm_headroom_bytes=int(0.1 * budget) * max(1, n_dev),
        batch_bytes=cfg.data.batch_size * row_bytes(cfg.model.image_size),
    )
    tuner = IngestAutotuner(knobs, limits, registry=registry, tracer=tracer)
    absl_logging.info(
        "autotune: enabled — start %s, worker cap %d, staging headroom "
        "%.0f MB", knobs.as_dict(), limits.max_decode_workers,
        limits.hbm_headroom_bytes / 1e6,
    )
    return knobs, tuner
