"""Ahead-of-time raw-shard transcode + loader: ``data.loader="rawshard"``.

ISSUE 7 tentpole, part two. The streamed train path pays a host JPEG
decode per image per epoch (~1692 img/s on the bench host) while the
same host parses pre-decoded raw records at ~2660 img/s and memcpys
decoded arrays far faster still. TFRecord ``raw`` encoding
(data/tfrecord.py) already moves decode offline, but keeps the
per-record proto parse and the sequential framing; this module goes the
rest of the way:

  TRANSCODE (offline, once):  TFRecord shards (JPEG or raw) ->
      resized uint8 arrays written as plain ``.npy`` shard pairs
      (images + grades) with a versioned JSON manifest. Decode/resize
      is paid exactly once, by scripts/transcode_shards.py.
  LOAD (every epoch):  each shard memory-maps (``np.load mmap_mode``);
      reading record i is a bisect + one row memcpy out of the page
      cache — no proto parse, no decode, no framing scan.

Determinism contract: the transcode decodes record i of the source
split with the SAME ``_decode_example`` + quarantine-substitution rules
the streamed tier applies online (grain_pipeline.ParallelDecoder), and
stores it at global index i. The rawshard loader therefore yields
batches BIT-IDENTICAL (post-decode) to the streamed path at the same
seed — pinned in tests/test_rawshard.py and by bench.py's
``rawshard_bit_identical_ok``. It is an encoding change, never a data
change.

The loader is ~60 lines because it reuses ALL of the tiered loader's
machinery (data/tiered_pipeline.py): ``RawShardDecoder`` subclasses
``ParallelDecoder`` overriding only the per-record read, so the
residency plan, HBM spill cache, staged H2D, poison quarantine,
autotuner knobs, and telemetry counters all apply unchanged —
``train_batches`` here is the tiered loader with a different decode
stage plugged into its ``decoder_factory`` seam.

Durability: shard writes are ATOMIC (tmp + os.replace, retried under
utils/retry.py as ``io.retries.rawshard.write``) and the manifest is
rewritten atomically after every completed shard, so an interrupted
transcode RESUMES from the last durable shard instead of restarting.
The manifest pins format version, image size, per-shard byte sizes,
and a source-file fingerprint; the loader refuses (actionably) shards
that are stale against their source or written at another size.
"""

from __future__ import annotations

import bisect
import dataclasses
import json
import os
import time
from typing import Iterator

import numpy as np
from absl import logging

from jama16_retina_tpu.configs import DataConfig
from jama16_retina_tpu.data import tfrecord
from jama16_retina_tpu.data.grain_pipeline import (
    ParallelDecoder,
    TFRecordIndex,
    resolve_decode_workers,
)
from jama16_retina_tpu.integrity import artifact as artifact_lib
from jama16_retina_tpu.utils import retry as retry_lib

MANIFEST_FORMAT = "jama16.rawshard"
MANIFEST_VERSION = 1


def manifest_path(shard_dir: str, split: str) -> str:
    return os.path.join(shard_dir, f"{split}.rawshard.json")


def default_shard_dir(data_dir: str, image_size: int) -> str:
    """Where ``data.loader=rawshard`` looks when ``data.rawshard_dir``
    is unset: a sibling of the source shards, size-suffixed so one
    dataset can carry transcodes at several training resolutions."""
    return os.path.join(data_dir, f"rawshard{image_size}")


def _shard_names(split: str, i: int, num: int) -> tuple[str, str]:
    stem = f"{split}-{i:05d}-of-{num:05d}"
    return f"{stem}.images.npy", f"{stem}.grades.npy"


def _atomic_save(path: str, arr: np.ndarray) -> str:
    """Serialize the array and publish it through the SEALED writer
    seam (integrity/artifact.atomic_write_bytes: tmp + fsync +
    os.replace, ``integrity.write`` fault sites) — a reader (or a
    resumed transcode) never sees a torn shard. Returns the sha256 of
    the written bytes (the manifest's per-shard digest, what
    ``graftfsck`` verifies against bit rot). Retried as
    ``io.retries.rawshard.write`` (utils/retry.py): transient
    filesystem hiccups are absorbed, a permanently failing write
    surfaces the original OSError."""
    import hashlib
    import io

    buf = io.BytesIO()
    np.save(buf, arr)
    # getbuffer(): a zero-copy view — ONE transient copy of the shard
    # (the serialization), not two (getvalue() would duplicate it;
    # review finding on multi-GB shard transcodes).
    blob = buf.getbuffer()
    digest = hashlib.sha256(blob).hexdigest()

    def _write() -> None:
        artifact_lib.atomic_write_bytes(path, blob)

    retry_lib.retry_call(_write, attempts=3, site="rawshard.write")
    return digest


def _atomic_write_json(path: str, obj: dict) -> None:
    artifact_lib.write_sealed_json(
        path, obj, schema="rawshard.manifest", version=MANIFEST_VERSION
    )


def source_fingerprint(paths) -> list[dict]:
    """What "the same source split" means for staleness: file names and
    byte sizes of every TFRecord shard. Name+size (not mtime) so a
    byte-identical re-copy of the dataset does not read as stale, while
    any record added/removed/rewritten does."""
    return [
        {"name": os.path.basename(p), "bytes": os.path.getsize(p)}
        for p in sorted(paths)
    ]


@dataclasses.dataclass(frozen=True)
class _ShardEntry:
    images: str
    grades: str
    start: int
    records: int
    images_bytes: int
    grades_bytes: int


def _entry_valid(shard_dir: str, e: dict) -> bool:
    """A manifest entry counts only if both files exist at the recorded
    sizes — the resume gate (a shard whose write was torn before the
    manifest update simply is not listed; one listed but later
    truncated fails this check and is rewritten)."""
    for k, size_k in (("images", "images_bytes"), ("grades", "grades_bytes")):
        p = os.path.join(shard_dir, e[k])
        if not os.path.exists(p) or os.path.getsize(p) != e[size_k]:
            return False
    return True


def transcode_split(
    data_dir: str,
    split: str,
    out_dir: "str | None" = None,
    image_size: int = 299,
    shard_records: int = 256,
    workers: int = 0,
    quarantine: bool = True,
    resume: bool = True,
) -> dict:
    """Transcode one TFRecord split into raw ``.npy`` shard pairs +
    manifest; returns the manifest dict. Idempotent and resumable:
    already-durable shards (listed in the manifest at their recorded
    sizes) are skipped on re-run; pass ``resume=False`` to rebuild from
    scratch. ``quarantine=True`` bakes the streamed tier's
    poison-record substitution into the shards (the bit-identity
    contract with a quarantining online run); ``False`` makes a poison
    source record fail the transcode loudly instead."""
    out_dir = out_dir or default_shard_dir(data_dir, image_size)
    os.makedirs(out_dir, exist_ok=True)
    src_paths = tfrecord.list_split(data_dir, split)
    index = TFRecordIndex(src_paths)
    n = len(index)
    if n == 0:
        raise ValueError(f"no records under {data_dir}/{split}")
    shard_records = max(1, int(shard_records))
    num_shards = -(-n // shard_records)  # ceil
    fp = source_fingerprint(src_paths)

    manifest = {
        "format": MANIFEST_FORMAT,
        "version": MANIFEST_VERSION,
        "split": split,
        "image_size": int(image_size),
        "num_records": n,
        "shard_records": shard_records,
        "quarantine_baked": bool(quarantine),
        "source": {"files": fp, "num_records": n},
        "shards": [],
    }
    done: dict[int, dict] = {}
    mpath = manifest_path(out_dir, split)
    if resume and os.path.exists(mpath):
        try:
            with open(mpath) as f:
                prev = json.load(f)
        except (OSError, json.JSONDecodeError):
            prev = None
        head_keys = (
            "format", "version", "split", "image_size", "num_records",
            "shard_records", "quarantine_baked", "source",
        )
        if prev and all(prev.get(k) == manifest[k] for k in head_keys):
            for e in prev.get("shards", []):
                if _entry_valid(out_dir, e):
                    done[e["start"] // shard_records] = e
            if done:
                logging.info(
                    "rawshard transcode: resuming %s/%s — %d/%d shards "
                    "already durable", out_dir, split, len(done), num_shards,
                )
        elif prev:
            logging.warning(
                "rawshard transcode: existing manifest at %s does not "
                "match this transcode's parameters/source — rebuilding "
                "all shards", mpath,
            )

    decoder = ParallelDecoder(
        index, image_size, workers=resolve_decode_workers(workers),
        quarantine=quarantine,
    )
    t0 = time.perf_counter()
    written = 0
    try:
        for i in range(num_shards):
            lo, hi = i * shard_records, min(n, (i + 1) * shard_records)
            if i in done:
                manifest["shards"].append(done[i])
                continue
            images, grades = decoder.decode_range(lo, hi)
            img_name, gr_name = _shard_names(split, i, num_shards)
            img_sha = _atomic_save(os.path.join(out_dir, img_name), images)
            gr_sha = _atomic_save(os.path.join(out_dir, gr_name), grades)
            entry = {
                "images": img_name,
                "grades": gr_name,
                "start": lo,
                "records": hi - lo,
                "images_bytes": os.path.getsize(
                    os.path.join(out_dir, img_name)
                ),
                "grades_bytes": os.path.getsize(
                    os.path.join(out_dir, gr_name)
                ),
                # Per-shard content digests (ISSUE 13): what graftfsck
                # verifies — a bit-flipped shard is detectable without
                # decoding it. The loader's hot path keeps the cheap
                # size check; fsck pays the hash.
                "images_sha256": img_sha,
                "grades_sha256": gr_sha,
            }
            manifest["shards"].append(entry)
            written += 1
            # Manifest rewritten after EVERY durable shard: the resume
            # point advances with the work, not at the end.
            _atomic_write_json(mpath, manifest)
    finally:
        decoder.close()
    _atomic_write_json(mpath, manifest)
    logging.info(
        "rawshard transcode: %s/%s -> %s: %d records, %d shards "
        "(%d written, %d reused) in %.1fs",
        data_dir, split, out_dir, n, num_shards, written,
        num_shards - written, time.perf_counter() - t0,
    )
    return manifest


class RawShardSplit:
    """Validated view over one transcoded split: manifest + lazily
    memory-mapped shard arrays.

    ``source_dir``: when the original TFRecord split is reachable, its
    fingerprint is checked against the manifest's — stale shards (the
    source changed after transcode) are refused with the command that
    fixes them. A missing source is fine: the whole point is that
    steady-state training does not need the TFRecords at all."""

    def __init__(self, shard_dir: str, split: str,
                 image_size: "int | None" = None,
                 source_dir: "str | None" = None):
        self.shard_dir = shard_dir
        self.split = split
        mpath = manifest_path(shard_dir, split)
        if not os.path.exists(mpath):
            raise FileNotFoundError(
                f"no rawshard manifest at {mpath} — transcode the split "
                f"first: python scripts/transcode_shards.py "
                f"--data_dir <tfrecord dir> --splits {split}"
                + (f" --image_size {image_size}" if image_size else "")
            )
        with open(mpath) as f:
            self.manifest = json.load(f)
        m = self.manifest
        if m.get("format") != MANIFEST_FORMAT or (
                m.get("version") != MANIFEST_VERSION):
            raise ValueError(
                f"rawshard manifest {mpath} has format/version "
                f"{m.get('format')!r}/{m.get('version')!r}; this build "
                f"reads {MANIFEST_FORMAT!r}/{MANIFEST_VERSION} — "
                "re-transcode with scripts/transcode_shards.py"
            )
        # Sealed-content verification (ISSUE 13) after the typed
        # format refusal: a bit-flipped manifest raises ArtifactCorrupt
        # (counted) before any of its values steer a training run.
        artifact_lib.verify_payload(m, mpath, artifact="rawshard",
                                    rebuild_key="rawshard.manifest")
        if image_size is not None and m["image_size"] != image_size:
            raise ValueError(
                f"rawshard split at {shard_dir} was transcoded at "
                f"{m['image_size']}px but the model wants {image_size}px "
                f"— re-transcode: python scripts/transcode_shards.py "
                f"--data_dir <tfrecord dir> --splits {split} "
                f"--image_size {image_size}"
            )
        expect = sum(e["records"] for e in m["shards"])
        if expect != m["num_records"]:
            raise ValueError(
                f"rawshard manifest {mpath} is incomplete: shards cover "
                f"{expect} of {m['num_records']} records — the transcode "
                "was interrupted; re-run scripts/transcode_shards.py "
                "(it resumes from the last durable shard)"
            )
        if source_dir is not None:
            try:
                src = tfrecord.list_split(source_dir, split)
            except FileNotFoundError:
                src = None
            if src is not None and (
                    source_fingerprint(src) != m["source"]["files"]):
                raise ValueError(
                    f"rawshard split at {shard_dir} is STALE: the source "
                    f"TFRecords under {source_dir} changed since the "
                    "transcode — re-run scripts/transcode_shards.py"
                )
        self.image_size = int(m["image_size"])
        self._entries = sorted(m["shards"], key=lambda e: e["start"])
        self._starts = [e["start"] for e in self._entries]
        self._mmaps: dict[int, tuple] = {}

    def __len__(self) -> int:
        return int(self.manifest["num_records"])

    def shard_arrays(self, j: int) -> tuple[np.ndarray, np.ndarray]:
        """(images mmap [k,S,S,3] u8, grades [k] i32) for shard j.
        mmap'd lazily and cached; rows are served out of the OS page
        cache after first touch. Opens retry as
        ``io.retries.rawshard.read``; a still-failing or mis-shaped
        shard raises for the caller's quarantine layer to own."""
        cached = self._mmaps.get(j)
        if cached is not None:
            return cached
        e = self._entries[j]

        def _open():
            imgs = np.load(
                os.path.join(self.shard_dir, e["images"]), mmap_mode="r"
            )
            grs = np.load(
                os.path.join(self.shard_dir, e["grades"]), mmap_mode="r"
            )
            return imgs, grs

        imgs, grs = retry_lib.retry_call(
            _open, attempts=3, site="rawshard.read"
        )
        want = (e["records"], self.image_size, self.image_size, 3)
        if tuple(imgs.shape) != want or grs.shape != (e["records"],):
            raise ValueError(
                f"rawshard shard {e['images']} has shape {imgs.shape} / "
                f"{grs.shape}, manifest says {want} — shard corrupt or "
                "manifest stale; re-run scripts/transcode_shards.py"
            )
        self._mmaps[j] = (imgs, grs)
        return imgs, grs

    def row(self, i: int) -> dict:
        j = bisect.bisect_right(self._starts, i) - 1
        imgs, grs = self.shard_arrays(j)
        r = i - self._starts[j]
        # Contiguous copies out of the mmap: downstream batching holds
        # rows across shard evictions / process forks.
        return {
            "image": np.ascontiguousarray(imgs[r]),
            "grade": np.int32(grs[r]),
        }


class RawShardDecoder(ParallelDecoder):
    """ParallelDecoder whose per-record read is a shard-row memcpy.

    Subclassing buys the whole contract for free: worker pool +
    ``set_workers`` (the autotuner knob — accepted for interface
    parity; row copies are memcpy-bound, so the busy counters honestly
    report a near-idle pool and the tuner raises run-ahead instead),
    poison quarantine with deterministic next-readable substitution
    (a torn/corrupt shard degrades to counted substitutions, same as a
    torn TFRecord), the worker-count-invariant ``decode_batch`` /
    ``decode_range``, and the ``data.decode.*`` telemetry the tuner's
    utilization signal reads."""

    def __init__(self, split: RawShardSplit, workers: int = 1,
                 registry=None, quarantine: bool = True):
        # ``split`` stands in for the index: quarantine's scan-forward
        # substitution only needs len(); reads go through _read_decode.
        super().__init__(
            split, split.image_size, workers=workers, registry=registry,
            quarantine=quarantine,
        )
        self._split = split

    def _read_decode(self, i: int, n: "int | None" = None) -> dict:
        return self._split.row(i % n if n else i)


def train_batches(
    data_dir: str,
    split: str,
    cfg: DataConfig,
    image_size: int,
    seed: int = 0,
    skip_batches: int = 0,
    mesh=None,
    max_fraction: float = 0.6,
    knobs=None,
) -> Iterator[dict]:
    """Drop-in twin of tiered_pipeline.train_batches reading the
    ahead-of-time transcoded shards: same residency plan, staging,
    quarantine and autotuner knobs — only the decode stage differs
    (mmap row copy instead of proto parse + JPEG decode), so the batch
    sequence is bit-identical to the tiered/streamed loaders at the
    same seed and budget."""
    from jama16_retina_tpu.data import tiered_pipeline

    shard_dir = (
        cfg.rawshard_dir if getattr(cfg, "rawshard_dir", "")
        else default_shard_dir(data_dir, image_size)
    )
    rs = RawShardSplit(
        shard_dir, split, image_size=image_size, source_dir=data_dir
    )

    def factory(workers: int, quarantine: bool) -> RawShardDecoder:
        return RawShardDecoder(rs, workers=workers, quarantine=quarantine)

    return tiered_pipeline.train_batches(
        data_dir, split, cfg, image_size, seed=seed,
        skip_batches=skip_batches, mesh=mesh, max_fraction=max_fraction,
        knobs=knobs, decoder_factory=factory,
    )
