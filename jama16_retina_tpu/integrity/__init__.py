"""Durable-state integrity (ISSUE 13).

PRs 6 and 8 made *runtime* failure a handled input; this subsystem does
the same for the system's *durable* state — the checkpointed artifacts
a production deployment actually survives on ("TensorFlow: A system for
large-scale machine learning", PAPERS.md). Three layers:

  * ``artifact.py`` — the SEALED ARTIFACT envelope every durable writer
    shares: atomic tmp+fsync+rename through one seam, schema
    name/version, environment fingerprint, and a sha256 content
    checksum verified on load (typed :class:`ArtifactCorrupt`, counted
    ``integrity.corrupt.{artifact}``);
  * ``fsck.py`` — repo-wide verification of a workdir: every artifact
    class checked for checksums, schema versions, and cross-artifact
    consistency, findings classified CORRUPT/STALE/ORPHAN/REPAIRABLE,
    with ``--repair`` rebuilding derivable artifacts and quarantining
    the rest (``scripts/graftfsck.py`` is the CLI);
  * ``retention.py`` — the unified dry-run-first GC policy: blackbox
    dumps, compile-cache bytes, telemetry JSONL, and retired lifecycle
    candidate sets, journaled per deletion and pinned to never collect
    anything reachable from ``live.json`` or an open journal cycle.

Proven by ``bench.py --chaos``'s disk-fault drills (torn write, bit
flip, truncation, ENOSPC at the ``integrity.write`` site family, plus
kill -9 inside the sealed writer) and tests/test_integrity.py.
"""

from __future__ import annotations

from jama16_retina_tpu.integrity.artifact import (  # noqa: F401
    ArtifactCorrupt,
    atomic_write_bytes,
    atomic_write_text,
    env_fingerprint,
    payload_digest,
    read_sealed_json,
    sha256_file,
    verify_sidecar,
    write_json,
    write_seal_sidecar,
    write_sealed_json,
)
from jama16_retina_tpu.integrity.fsck import (  # noqa: F401
    FsckFinding,
    FsckReport,
    fsck_workdir,
    repair_workdir,
)
from jama16_retina_tpu.integrity.retention import (  # noqa: F401
    RetentionPlan,
    apply_plan,
    plan_retention,
)
