"""The sealed-artifact envelope: ONE durable-write discipline (ISSUE 13).

Before this module, ~10 artifact formats (rawshard manifests, the
lifecycle journal + ``live.json``, serve policies, compile-cache
manifests/entries, reference profiles, canary ``.npz``, blackbox dumps,
telemetry JSONL) each hand-rolled their own atomic-rename write and
only two carried content hashes — silent on-disk corruption was
invisible until a reader crashed on it. This module is the one seam
they all share now:

  * ``write_sealed_json`` — the payload is written with an embedded
    ``__seal__`` block: seal version, schema name + version, an
    environment fingerprint, and a sha256 over the canonical payload
    JSON. The write itself is atomic (tmp in the same directory,
    fsync, ``os.replace``) and carries the ``integrity.write`` /
    ``integrity.write.commit`` fault sites, so ``bench.py --chaos``
    can inject torn writes, bit flips, truncation, and ENOSPC-style
    failures into EVERY artifact class through one seam — and a
    kill -9 between fsync and publish provably leaves no readable
    torn artifact (the tmp file is inert; readers only see the path).
  * ``read_sealed_json`` / ``verify_payload`` — the digest is verified
    on load; a mismatch raises typed :class:`ArtifactCorrupt` naming
    the file, expected/actual digest, and the rebuild command, and
    increments ``integrity.corrupt`` + ``integrity.corrupt.{artifact}``
    (the ``rate(integrity.corrupt) > 0`` alert rule's input). Files
    written before sealing existed load as "unsealed" (legacy) —
    ``graftfsck`` flags them STALE; loads do not refuse them.
  * Binary artifacts (rawshard ``.npy``, canary ``.npz``, compile-cache
    ``.jex``) seal via ``write_seal_sidecar`` / ``verify_sidecar``:
    a ``<name>.seal.json`` sealed-JSON sidecar carrying the target's
    byte size and sha256.
  * ``write_json`` / ``atomic_write_text`` — the non-sealed escape
    hatches (report files, blackbox dumps, the ``.prom`` exposition
    snapshot) so every durable write in the repo still flows through
    this module: graftlint's ``artifacts`` rule makes a bare
    ``os.replace``/``json.dump`` outside this file a finding.

The checksum cost rides WRITES (one sha256 over bytes already in
memory) and artifact LOADS, never the train/serve hot loop — pinned by
bench.py's ``integrity_overhead_pct`` guard.
"""

from __future__ import annotations

import hashlib
import json
import os
import sys

from jama16_retina_tpu.obs import faultinject

SEAL_KEY = "__seal__"
SEAL_VERSION = 1

# Rebuild commands per artifact class — what an ArtifactCorrupt error
# and the graftfsck report tell the operator. "Derivable" classes can
# be regenerated from other durable state; the rest restore from
# quarantine/ or a backup, never silently.
REBUILD = {
    "rawshard.manifest": (
        "re-run scripts/transcode_shards.py (it resumes from the last "
        "durable shard)"
    ),
    "rawshard.shard": (
        "delete the shard pair and re-run scripts/transcode_shards.py "
        "(resume rebuilds exactly the missing shards)"
    ),
    "lifecycle.journal": (
        "NOT derivable — inspect or restore from quarantine/; a fresh "
        "journal starts idle (live.json still names the serving set)"
    ),
    "lifecycle.live": (
        "NOT derivable — restore from quarantine/ or re-point at the "
        "blessed checkpoint set (scripts/lifecycle_run.py --status "
        "shows the journal's view)"
    ),
    "serve.policy": "re-derive with scripts/derive_serve_policy.py",
    "compile_cache.manifest": (
        "rm -r the cache directory and re-warm one engine construction"
    ),
    "compile_cache.entry": (
        "delete the entry (+.seal.json); the next engine warm-up "
        "recompiles and re-saves it"
    ),
    "quality.profile": "re-emit with evaluate.py --profile_out",
    "quality.canary": (
        "NOT derivable — restore from quarantine/ or re-pin with "
        "obs/quality.save_canary on the served checkpoint"
    ),
    "integrity.ledger": (
        "NOT derivable — the quarantine/GC ledger records actions "
        "already taken; move it aside"
    ),
    "integrity.fsck": "re-run scripts/graftfsck.py on the workdir",
    "audit.segment": (
        "NOT derivable — a sealed audit segment is the provenance "
        "record of already-served predictions; move it aside "
        "(quarantine) and treat its records as lost (they are counted "
        "audit.dropped only at write time, never retroactively)"
    ),
}

# Short artifact-class names (what loaders/fsck tag corruption with:
# the integrity.corrupt.{artifact} counter suffixes) -> REBUILD keys.
REBUILD_BY_CLASS = {
    "rawshard": "rawshard.shard",
    "journal": "lifecycle.journal",
    "live": "lifecycle.live",
    "policy": "serve.policy",
    "compile_cache": "compile_cache.entry",
    "profile": "quality.profile",
    "canary": "quality.canary",
    "ledger": "integrity.ledger",
    "audit": "audit.segment",
}


def rebuild_hint(artifact: str) -> str:
    return REBUILD.get(
        artifact,
        REBUILD.get(REBUILD_BY_CLASS.get(artifact, ""),
                    "inspect or restore the file"),
    )


class ArtifactCorrupt(RuntimeError):
    """A sealed artifact failed its content-checksum (or seal-schema)
    verification: the bytes on disk are not the bytes the writer
    sealed. Never absorbed silently — the message names the file, the
    expected and actual digest, and the rebuild command for the
    artifact's class."""

    def __init__(self, path: str, expected: str, actual: str,
                 artifact: str = "", detail: str = "",
                 rebuild_key: str = ""):
        self.path = path
        self.expected = expected
        self.actual = actual
        self.artifact = artifact
        rebuild = REBUILD.get(rebuild_key) or rebuild_hint(artifact)
        super().__init__(
            f"artifact {path} is CORRUPT"
            + (f" ({detail})" if detail else "")
            + f": sealed sha256 {expected} but content is {actual}"
            + (f" [{artifact}]" if artifact else "")
            + f" — {rebuild}"
        )


def env_fingerprint() -> dict:
    """What produced an artifact — deterministic per container (no
    clocks, no hostnames), so sealed writes of identical payloads are
    byte-identical and the lifecycle journal's byte-stability pins
    survive sealing."""
    import numpy as np

    return {
        "python": ".".join(str(v) for v in sys.version_info[:3]),
        "numpy": str(np.__version__),
        "platform": sys.platform,
    }


def payload_digest(payload: dict) -> str:
    """sha256 over the canonical (sorted, compact) JSON of the payload
    WITHOUT its seal — the quantity the seal pins and loads verify."""
    body = {k: v for k, v in payload.items() if k != SEAL_KEY}
    blob = json.dumps(body, sort_keys=True,
                      separators=(",", ":")).encode("utf-8")
    return hashlib.sha256(blob).hexdigest()


def sha256_file(path: str, chunk: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            b = f.read(chunk)
            if not b:
                break
            h.update(b)
    return h.hexdigest()


def count_corrupt(artifact: str, registry=None) -> None:
    """One detected corruption: ``integrity.corrupt`` (the alert rule's
    burn-rate input) plus the per-class ``integrity.corrupt.{artifact}``
    ledger."""
    from jama16_retina_tpu.obs import registry as registry_lib

    reg = registry if registry is not None \
        else registry_lib.default_registry()
    reg.counter(
        "integrity.corrupt",
        help="sealed artifacts whose content checksum (or seal sidecar) "
             "failed verification on load — any nonzero rate fires the "
             "artifact_corrupt alert rule",
    ).inc()
    reg.counter(
        f"integrity.corrupt.{artifact}",
        help="per-class corrupt-artifact detections "
             "(rawshard/journal/live/policy/compile_cache/profile/"
             "canary/ledger/audit)",
    ).inc()


# ---------------------------------------------------------------------------
# The one atomic write seam
# ---------------------------------------------------------------------------


def atomic_write_bytes(path: str, blob: bytes,
                       fsync: bool = True) -> None:
    """tmp in the same directory + fsync + ``os.replace``: a reader (or
    a process resuming after kill -9 at ANY point in here) sees either
    the old artifact or the new one, never a torn file. The
    ``integrity.write`` fault site damages/fails the payload
    (torn/bitflip/truncate/ENOSPC drills); ``integrity.write.commit``
    sits between durability and publish — a latency plan there holds
    the window open for the kill -9 drill. ``fsync=False`` keeps the
    rename-only atomicity for REGENERATED snapshots on hot paths (the
    ``.prom`` scrape file): a scraper needs never-torn, not durable —
    an fsync per telemetry flush would tax the loop for nothing."""
    blob = faultinject.corrupt("integrity.write", blob)
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(blob)
            if fsync:
                f.flush()
                os.fsync(f.fileno())
        faultinject.check("integrity.write.commit")
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def atomic_write_text(path: str, text: str, fsync: bool = True) -> None:
    """Atomic publish of a plain-text artifact (the ``telemetry.prom``
    exposition snapshot): same seam, no seal — the consumer is a
    scrape parser, not this codebase."""
    atomic_write_bytes(path, text.encode("utf-8"), fsync=fsync)


def rename(src: str, dst: str) -> None:
    """Atomic move/publish of an existing file (quarantine moves, log
    rotation). Same-filesystem ``os.replace`` semantics; centralized
    here so graftlint's ``artifacts`` rule can keep every durable
    rename inside this module."""
    os.replace(src, dst)


def write_json(path: str, obj, indent: "int | None" = 1,
               sort_keys: bool = False, default=None,
               trailing_newline: bool = False) -> None:
    """Plain (NON-atomic, unsealed) JSON write for report/dump-grade
    files — blackbox dumps, bench/report outputs, baselines. Exists so
    graftlint's ``artifacts`` rule can insist every ``json.dump`` in
    the repo flows through integrity/artifact.py: the caller chose
    plain semantics, it did not hand-roll them."""
    with open(path, "w", encoding="utf-8") as f:
        json.dump(obj, f, indent=indent, sort_keys=sort_keys,
                  default=default)
        if trailing_newline:
            f.write("\n")


# ---------------------------------------------------------------------------
# Sealed JSON artifacts
# ---------------------------------------------------------------------------


def make_seal(payload: dict, schema: str, version) -> dict:
    return {
        "seal_version": SEAL_VERSION,
        "schema": schema,
        "schema_version": version,
        "sha256": payload_digest(payload),
        "env": env_fingerprint(),
    }


def write_sealed_json(path: str, payload: dict, schema: str,
                      version) -> str:
    """Atomically publish ``payload`` with its embedded ``__seal__``.
    The payload's own keys stay at the top level (every pre-seal reader
    of these formats keeps working); the seal is one reserved key."""
    doc = dict(payload)
    doc.pop(SEAL_KEY, None)
    doc[SEAL_KEY] = make_seal(doc, schema, version)
    blob = (json.dumps(doc, indent=1, sort_keys=True) + "\n").encode()
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    atomic_write_bytes(path, blob)
    return path


def verify_payload(doc: dict, path: str, artifact: str = "",
                   registry=None, rebuild_key: str = "") -> "dict | None":
    """Verify an already-parsed sealed document IN PLACE and return its
    seal (None = legacy unsealed file — tolerated on load, flagged
    STALE by fsck). Raises :class:`ArtifactCorrupt` (and counts it) on
    a digest mismatch. Split out of :func:`read_sealed_json` so loaders
    can run their own format/version checks FIRST — a hand-bumped
    version must keep raising the loader's own typed error, not a
    digest mismatch."""
    seal = doc.pop(SEAL_KEY, None)
    if seal is None:
        return None
    actual = payload_digest(doc)
    expected = str(seal.get("sha256", ""))
    if actual != expected:
        count_corrupt(artifact or str(seal.get("schema", "unknown")),
                      registry=registry)
        raise ArtifactCorrupt(path, expected, actual, artifact=artifact,
                              rebuild_key=rebuild_key)
    return seal


def read_sealed_json(path: str, artifact: str = "",
                     registry=None) -> "tuple[dict, dict | None]":
    """(payload, seal|None) with the digest verified. OSError /
    JSONDecodeError propagate — callers keep their existing torn-file
    semantics; only a parseable-but-mismatched file is CORRUPT."""
    with open(path, encoding="utf-8") as f:
        doc = json.load(f)
    if not isinstance(doc, dict):
        raise ValueError(f"{path} is not a JSON object artifact")
    seal = verify_payload(doc, path, artifact=artifact, registry=registry)
    return doc, seal


# ---------------------------------------------------------------------------
# Sidecar seals for binary artifacts
# ---------------------------------------------------------------------------


def sidecar_path(path: str) -> str:
    return path + ".seal.json"


def write_seal_sidecar(path: str, schema: str, version,
                       extra: "dict | None" = None,
                       blob: "bytes | None" = None) -> str:
    """Seal a binary artifact that already sits at ``path``: a sealed
    JSON sidecar pins its byte size and sha256 (the digest of the FILE,
    not of JSON). Pass ``blob`` (the bytes the writer INTENDED) when
    available — the sidecar then pins the intended content, so damage
    injected into the write itself (the ``integrity.write`` chaos
    drills) is detectable instead of being sealed over. The sidecar
    itself is a sealed artifact, so a torn sidecar is detected like
    any other."""
    if blob is not None:
        size = len(blob)
        digest = hashlib.sha256(blob).hexdigest()
    else:
        size = os.path.getsize(path)
        digest = sha256_file(path)
    payload = {
        "target": os.path.basename(path),
        "bytes": size,
        "sha256": digest,
        **(extra or {}),
    }
    return write_sealed_json(sidecar_path(path), payload, schema, version)


def verify_sidecar(path: str, artifact: str = "",
                   registry=None) -> str:
    """Check a binary artifact against its seal sidecar. Returns
    ``"ok"`` (verified) or ``"unsealed"`` (no sidecar — legacy);
    raises :class:`ArtifactCorrupt` (counted) when the sidecar's
    pinned size/digest disagrees with the file, or the sidecar itself
    fails its own seal."""
    sc = sidecar_path(path)
    if not os.path.exists(sc):
        return "unsealed"
    payload, _seal = read_sealed_json(sc, artifact=artifact,
                                      registry=registry)
    want_bytes = int(payload.get("bytes", -1))
    if not os.path.exists(path) or os.path.getsize(path) != want_bytes:
        have = os.path.getsize(path) if os.path.exists(path) else -1
        count_corrupt(artifact or "sidecar", registry=registry)
        raise ArtifactCorrupt(
            path, f"{want_bytes} bytes", f"{have} bytes",
            artifact=artifact, detail="size mismatch vs seal sidecar",
        )
    actual = sha256_file(path)
    expected = str(payload.get("sha256", ""))
    if actual != expected:
        count_corrupt(artifact or "sidecar", registry=registry)
        raise ArtifactCorrupt(path, expected, actual, artifact=artifact)
    return "ok"
