"""Repo-wide workdir fsck: verify every durable artifact class
(ISSUE 13).

``fsck_workdir`` walks one workdir and verifies everything the stack
persists — sealed JSON artifacts (lifecycle journal + ``live.json``,
serve policies, rawshard manifests, compile-cache manifests, quality
profiles), seal-sidecar'd binaries (rawshard shards via their manifest
digests, canary ``.npz``, compile-cache entries), JSONL logs (torn-line
scan), blackbox dumps, and the CROSS-ARTIFACT consistency no single
loader can see: ``live.json`` members exist with a restorable
checkpoint structure, the journal's terminal state agrees with the live
pointer, rawshard manifests agree with their shards' bytes, cache
entries agree with their sidecars.

Findings are classified:

  * ``CORRUPT``    — bytes disagree with a seal/digest/size the writer
    pinned, a sealed artifact no longer parses, or a cross-referenced
    file is missing: the state is WRONG.
  * ``STALE``      — readable but outdated: unsealed legacy artifacts,
    old schema versions, an interrupted transcode's partial coverage.
    Report-only; the finding names the rebuild command.
  * ``ORPHAN``     — a file its manifest does not claim (stray shard,
    sidecar without target, dead ``.tmp`` leftovers).
  * ``REPAIRABLE`` — damage with a lossless automatic fix (torn JSONL
    lines the tolerant reader already skips).

``repair_workdir`` applies each finding's repair action: DERIVABLE
artifacts (policy, profiles, compile-cache entries/manifests, rawshard
shards with a reachable source) are deleted so their owners rebuild
them on demand — the finding names the exact rebuild command;
non-derivable ones (journal, live pointer, canary) are MOVED to
``<workdir>/quarantine/`` with a sealed, journaled ledger; torn JSONL
files are rewritten without their torn lines. Nothing named by an
in-flight lifecycle cycle or reachable from ``live.json`` is ever
touched — if the journal itself is unreadable, the whole lifecycle
directory is left alone (reported, not repaired): repairing blind is
how a half-done rollout gets destroyed.

CLI: ``scripts/graftfsck.py`` (text + ``--json``, exit 0 clean /
1 findings / 2 internal error, ``--repair``).
"""

from __future__ import annotations

import dataclasses
import json
import os
import re

from jama16_retina_tpu.integrity import artifact as artifact_lib

# Artifact classes the walk recognizes (the inventory table in
# docs/RELIABILITY.md §Durable state mirrors this list).
CLASSES = (
    "journal", "live", "policy", "profile", "canary",
    "rawshard", "compile_cache", "jsonl", "blackbox", "checkpoint",
    "ledger", "audit", "other",
)

_CANDIDATE_RE = re.compile(r"^candidate-(\d{4})$")
_TMP_RE = re.compile(r"\.tmp(\.\d+)?$")
# Sealed audit-ledger segments (obs/audit.py, ISSUE 20). The name
# pattern is shared with fleet segment streams, so the walk requires
# the canonical ``audit/`` parent for the name-based match (a torn,
# unparseable segment still classifies there); segments in a custom
# obs.audit.dir are caught by the ``kind: audit_segment`` sniff, which
# needs a parseable document.
_AUDIT_SEG_RE = re.compile(r"^seg-(\d{6})\.json$")


@dataclasses.dataclass(frozen=True)
class FsckFinding:
    """One verification failure. ``status`` is the taxonomy above;
    ``repair`` the action ``repair_workdir`` would take (``delete`` /
    ``quarantine`` / ``trim-manifest`` / ``rewrite`` / None =
    operator-only); ``detail`` says what disagreed and how to
    rebuild."""

    path: str
    artifact: str
    status: str
    detail: str
    repair: "str | None" = None

    def render(self) -> str:
        act = f" [repair: {self.repair}]" if self.repair else ""
        return f"{self.status} {self.artifact} {self.path}: " \
               f"{self.detail}{act}"

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class FsckReport:
    workdir: str
    findings: list
    checked: dict          # class -> {"count": n, "bytes": b}
    protected: list        # paths pinned by live.json / open cycle

    @property
    def clean(self) -> bool:
        return not self.findings

    def by_status(self) -> dict:
        out: dict = {}
        for f in self.findings:
            out.setdefault(f.status, []).append(f)
        return out

    def as_dict(self) -> dict:
        return {
            "workdir": self.workdir,
            "clean": self.clean,
            "findings": [f.as_dict() for f in self.findings],
            "checked": self.checked,
            "protected": sorted(self.protected),
            "counts": {s: len(fs) for s, fs in self.by_status().items()},
        }


def _rel(workdir: str, path: str) -> str:
    try:
        return os.path.relpath(path, workdir)
    except ValueError:  # pragma: no cover - cross-drive on win
        return path


def _has_checkpoint_structure(member_dir: str) -> bool:
    """Light 'restorable' probe (the deep proof is the engine restore
    the chaos drill performs): the member dir carries at least one
    step directory under best/ or latest/ (utils/checkpoint layout),
    or is itself a non-empty orbax-style directory."""
    if not os.path.isdir(member_dir):
        return False
    for sub in ("best", "latest"):
        d = os.path.join(member_dir, sub)
        if os.path.isdir(d) and any(
            s.isdigit() for s in os.listdir(d)
        ):
            return True
    # A bare checkpoint dir (tests point members at orbax roots
    # directly): any numeric step child counts.
    return any(s.isdigit() for s in os.listdir(member_dir))


def protected_paths(workdir: str) -> "tuple[set, bool]":
    """(paths pinned against repair/GC, journal_readable). Pinned:
    everything ``live.json`` names, every string an OPEN journal
    cycle's entries carry that resolves to an existing path, and the
    journal + live pointer themselves while a cycle is open. An
    unreadable journal returns journal_readable=False — callers must
    then refuse to touch the lifecycle directory at all."""
    pinned: set = set()
    lc_dir = os.path.join(workdir, "lifecycle")
    live_path = os.path.join(lc_dir, "live.json")
    journal_path = os.path.join(lc_dir, "journal.json")
    readable = True
    if os.path.exists(live_path):
        # Raw read, digest deliberately NOT verified here: pinning from
        # a possibly-corrupt pointer only ever protects MORE (and the
        # walk reports/counts the corruption separately).
        try:
            with open(live_path) as f:
                doc = json.load(f)
            for m in doc.get("member_dirs", ()):
                pinned.add(os.path.abspath(m))
        except Exception:  # noqa: BLE001 - unreadable live pointer
            readable = False
    if os.path.exists(journal_path):
        try:
            with open(journal_path) as f:
                doc = json.load(f)
            doc.pop(artifact_lib.SEAL_KEY, None)
            entries = list(doc.get("entries", ()))
        except Exception:  # noqa: BLE001 - corrupt journal
            return pinned, False
        terminal = ("COMMIT", "ROLLBACK")
        if entries and entries[-1].get("state") not in terminal:
            cycle = entries[-1].get("cycle")
            pinned.add(os.path.abspath(journal_path))
            pinned.add(os.path.abspath(live_path))
            for e in entries:
                if e.get("cycle") != cycle:
                    continue
                for v in _strings_in(e):
                    p = v if os.path.isabs(v) else os.path.join(
                        workdir, v
                    )
                    if os.path.exists(p):
                        pinned.add(os.path.abspath(p))
    return pinned, readable


def _strings_in(obj) -> list:
    out = []
    if isinstance(obj, str):
        out.append(obj)
    elif isinstance(obj, dict):
        for v in obj.values():
            out.extend(_strings_in(v))
    elif isinstance(obj, (list, tuple)):
        for v in obj:
            out.extend(_strings_in(v))
    return out


def _is_protected(path: str, pinned: set) -> bool:
    p = os.path.abspath(path)
    for root in pinned:
        if p == root or p.startswith(root + os.sep):
            return True
    return False


# ---------------------------------------------------------------------------
# Per-class checks
# ---------------------------------------------------------------------------


def _check_sealed_json(path: str, artifact: str, findings: list,
                       registry=None) -> "dict | None":
    """Parse + digest-verify one sealed JSON artifact. Returns the
    payload (seal stripped) or None after recording a finding.
    Unsealed legacy files return their payload AND record STALE."""
    try:
        doc, seal = artifact_lib.read_sealed_json(
            path, artifact=artifact, registry=registry
        )
    except artifact_lib.ArtifactCorrupt as e:
        findings.append(FsckFinding(
            path=path, artifact=artifact, status="CORRUPT",
            detail=str(e),
            repair=("delete" if artifact in _DERIVABLE else "quarantine"),
        ))
        return None
    except (OSError, ValueError) as e:
        findings.append(FsckFinding(
            path=path, artifact=artifact, status="CORRUPT",
            detail=f"unparseable ({type(e).__name__}: {e}) — "
                   + artifact_lib.REBUILD.get(
                       _REBUILD_KEY.get(artifact, ""), "inspect"),
            repair=("delete" if artifact in _DERIVABLE else "quarantine"),
        ))
        return None
    if seal is None:
        findings.append(FsckFinding(
            path=path, artifact=artifact, status="STALE",
            detail="unsealed legacy artifact (written before ISSUE 13); "
                   "rewrite by its owner seals it — "
                   + artifact_lib.REBUILD.get(
                       _REBUILD_KEY.get(artifact, ""), "rewrite"),
        ))
    return doc


# Derivable classes: repair deletes them (owners rebuild on demand).
_DERIVABLE = {"policy", "profile", "compile_cache"}

_REBUILD_KEY = {
    "journal": "lifecycle.journal",
    "live": "lifecycle.live",
    "policy": "serve.policy",
    "profile": "quality.profile",
    "canary": "quality.canary",
    "rawshard": "rawshard.manifest",
    "compile_cache": "compile_cache.manifest",
    "ledger": "integrity.ledger",
    "audit": "audit.segment",
}


def _check_rawshard(mpath: str, findings: list, checked: dict,
                    registry=None) -> None:
    shard_dir = os.path.dirname(mpath)
    m = _check_sealed_json(mpath, "rawshard", findings,
                           registry=registry)
    if m is None:
        return
    claimed: set = set()
    for e in m.get("shards", ()):
        for fk, sk, dk in (("images", "images_bytes", "images_sha256"),
                           ("grades", "grades_bytes", "grades_sha256")):
            name = e.get(fk)
            if not name:
                continue
            claimed.add(name)
            p = os.path.join(shard_dir, name)
            if not os.path.exists(p):
                findings.append(FsckFinding(
                    path=p, artifact="rawshard", status="CORRUPT",
                    detail=f"shard named by manifest {mpath} is "
                           f"missing — {artifact_lib.REBUILD['rawshard.shard']}",
                    repair="trim-manifest",
                ))
                continue
            size = os.path.getsize(p)
            checked.setdefault("rawshard", {"count": 0, "bytes": 0})
            checked["rawshard"]["count"] += 1
            checked["rawshard"]["bytes"] += size
            if size != e.get(sk):
                findings.append(FsckFinding(
                    path=p, artifact="rawshard", status="CORRUPT",
                    detail=f"shard is {size} bytes, manifest pins "
                           f"{e.get(sk)} — "
                           + artifact_lib.REBUILD["rawshard.shard"],
                    repair="trim-manifest",
                ))
                continue
            want = e.get(dk)
            if want:
                have = artifact_lib.sha256_file(p)
                if have != want:
                    artifact_lib.count_corrupt("rawshard",
                                               registry=registry)
                    findings.append(FsckFinding(
                        path=p, artifact="rawshard", status="CORRUPT",
                        detail=f"shard sha256 {have} != manifest's "
                               f"{want} (bit rot) — "
                               + artifact_lib.REBUILD["rawshard.shard"],
                        repair="trim-manifest",
                    ))
    covered = sum(int(e.get("records", 0)) for e in m.get("shards", ()))
    if covered != int(m.get("num_records", covered)):
        findings.append(FsckFinding(
            path=mpath, artifact="rawshard", status="STALE",
            detail=f"manifest covers {covered} of "
                   f"{m.get('num_records')} records (interrupted or "
                   "repaired transcode) — "
                   + artifact_lib.REBUILD["rawshard.manifest"],
        ))
    # Strays: .npy files beside a VALID manifest that it doesn't claim.
    split = str(m.get("split", ""))
    for name in sorted(os.listdir(shard_dir)):
        if (name.endswith(".npy") and name.startswith(split + "-")
                and name not in claimed):
            findings.append(FsckFinding(
                path=os.path.join(shard_dir, name), artifact="rawshard",
                status="ORPHAN",
                detail=f"shard not claimed by manifest {mpath}",
                repair="quarantine",
            ))


def _check_compile_cache(mpath: str, findings: list, checked: dict,
                         registry=None) -> None:
    cache_dir = os.path.dirname(mpath)
    m = _check_sealed_json(mpath, "compile_cache", findings,
                           registry=registry)
    if m is None:
        return
    for name in sorted(os.listdir(cache_dir)):
        p = os.path.join(cache_dir, name)
        if name.endswith(".jex"):
            checked.setdefault("compile_cache", {"count": 0, "bytes": 0})
            checked["compile_cache"]["count"] += 1
            checked["compile_cache"]["bytes"] += os.path.getsize(p)
            try:
                status = artifact_lib.verify_sidecar(
                    p, artifact="compile_cache", registry=registry
                )
            except artifact_lib.ArtifactCorrupt as e:
                findings.append(FsckFinding(
                    path=p, artifact="compile_cache", status="CORRUPT",
                    detail=str(e), repair="delete",
                ))
                continue
            if status == "unsealed":
                findings.append(FsckFinding(
                    path=p, artifact="compile_cache", status="STALE",
                    detail="entry has no seal sidecar (pre-ISSUE 13); "
                           + artifact_lib.REBUILD["compile_cache.entry"],
                ))
        elif name.endswith(".jex.seal.json"):
            target = p[: -len(".seal.json")]
            if not os.path.exists(target):
                findings.append(FsckFinding(
                    path=p, artifact="compile_cache", status="ORPHAN",
                    detail="seal sidecar without its entry",
                    repair="delete",
                ))


def _check_jsonl(path: str, findings: list, checked: dict) -> None:
    torn = 0
    total = 0
    try:
        with open(path, encoding="utf-8", errors="replace") as f:
            for line in f:
                if not line.strip():
                    continue
                total += 1
                try:
                    json.loads(line)
                except json.JSONDecodeError:
                    torn += 1
    except OSError as e:  # pragma: no cover - unreadable log
        findings.append(FsckFinding(
            path=path, artifact="jsonl", status="CORRUPT",
            detail=f"unreadable ({e})", repair="quarantine",
        ))
        return
    checked.setdefault("jsonl", {"count": 0, "bytes": 0})
    checked["jsonl"]["count"] += 1
    checked["jsonl"]["bytes"] += os.path.getsize(path)
    if torn:
        findings.append(FsckFinding(
            path=path, artifact="jsonl", status="REPAIRABLE",
            detail=f"{torn}/{total} torn JSONL line(s) (readers "
                   "tolerate them; rewrite drops them losslessly)",
            repair="rewrite",
        ))


def _check_live_cross_refs(workdir: str, findings: list,
                           registry=None) -> None:
    lc_dir = os.path.join(workdir, "lifecycle")
    live_path = os.path.join(lc_dir, "live.json")
    journal_path = os.path.join(lc_dir, "journal.json")
    members: "list | None" = None
    if os.path.exists(live_path):
        # Raw read (no digest verify): the walk already verified and
        # reported/counted a corrupt live pointer once.
        try:
            with open(live_path) as f:
                doc = json.load(f)
            doc.pop(artifact_lib.SEAL_KEY, None)
            members = [str(m) for m in doc.get("member_dirs", ())]
        except Exception:  # noqa: BLE001 - already reported by the walk
            members = None
    if members is not None:
        for m in members:
            p = m if os.path.isabs(m) else os.path.join(workdir, m)
            if not _has_checkpoint_structure(p):
                findings.append(FsckFinding(
                    path=p, artifact="checkpoint", status="CORRUPT",
                    detail=f"live.json names this member but no "
                           "restorable checkpoint structure exists "
                           "(best/, latest/, or a step dir) — the "
                           "serving engine cannot rebuild; restore the "
                           "member or re-point live.json",
                ))
    # Journal terminal state vs the live pointer: a COMMITted cycle
    # with no pointer means the promote's pointer write was lost.
    if os.path.exists(journal_path):
        try:
            with open(journal_path) as f:
                doc = json.load(f)
            doc.pop(artifact_lib.SEAL_KEY, None)
            entries = list(doc.get("entries", ()))
        except Exception:  # noqa: BLE001 - reported by the walk
            return
        if entries and entries[-1].get("state") == "COMMIT" \
                and members is None and not os.path.exists(live_path):
            findings.append(FsckFinding(
                path=journal_path, artifact="journal", status="CORRUPT",
                detail="journal's newest cycle COMMITted a promote but "
                       "live.json is missing — the blessed set is "
                       "unknown; re-point live.json at the committed "
                       "candidate (see the cycle's STAGED_ROLLOUT "
                       "entry)",
            ))


# ---------------------------------------------------------------------------
# The walk
# ---------------------------------------------------------------------------


def fsck_workdir(workdir: str, registry=None) -> FsckReport:
    """Verify every artifact class under ``workdir``. Read-only: the
    report says what repair WOULD do; ``repair_workdir`` does it."""
    workdir = os.path.abspath(workdir)
    findings: list = []
    checked: dict = {}

    def count(cls: str, path: str) -> None:
        checked.setdefault(cls, {"count": 0, "bytes": 0})
        checked[cls]["count"] += 1
        try:
            checked[cls]["bytes"] += os.path.getsize(path)
        except OSError:  # pragma: no cover
            pass

    pinned, journal_readable = protected_paths(workdir)
    for base, dirs, files in os.walk(workdir):
        dirs[:] = sorted(d for d in dirs if d != "quarantine")
        in_blackbox = os.path.basename(
            os.path.dirname(base)
        ) == "blackbox" or os.path.basename(base) == "blackbox"
        for name in sorted(files):
            path = os.path.join(base, name)
            if _TMP_RE.search(name):
                findings.append(FsckFinding(
                    path=path, artifact="other", status="ORPHAN",
                    detail="dead temp file from an interrupted atomic "
                           "write (inert: readers only see the "
                           "published path)",
                    repair="delete",
                ))
                continue
            if name.endswith(".rawshard.json"):
                count("rawshard", path)
                _check_rawshard(path, findings, checked,
                                registry=registry)
            elif name == "MANIFEST.json":
                count("compile_cache", path)
                _check_compile_cache(path, findings, checked,
                                     registry=registry)
            elif name == "journal.json":
                count("journal", path)
                _check_sealed_json(path, "journal", findings,
                                   registry=registry)
            elif name == "live.json":
                count("live", path)
                _check_sealed_json(path, "live", findings,
                                   registry=registry)
            elif name.endswith(".seal.json"):
                if name.endswith(".jex.seal.json"):
                    continue  # _check_compile_cache owns those
                target = path[: -len(".seal.json")]
                if not os.path.exists(target):
                    findings.append(FsckFinding(
                        path=path,
                        artifact=("canary" if target.endswith(".npz")
                                  else "other"),
                        status="ORPHAN",
                        detail="seal sidecar without its target",
                        repair="delete",
                    ))
            elif name.endswith(".npz"):
                count("canary", path)
                try:
                    status = artifact_lib.verify_sidecar(
                        path, artifact="canary", registry=registry
                    )
                except artifact_lib.ArtifactCorrupt as e:
                    findings.append(FsckFinding(
                        path=path, artifact="canary", status="CORRUPT",
                        detail=str(e), repair="quarantine",
                    ))
                    continue
                if status == "unsealed" and "canary" in name:
                    findings.append(FsckFinding(
                        path=path, artifact="canary", status="STALE",
                        detail="canary artifact has no seal sidecar "
                               "(pre-ISSUE 13); re-save with "
                               "obs/quality.save_canary to seal it",
                    ))
            elif (_AUDIT_SEG_RE.match(name)
                  and os.path.basename(base) == "audit"):
                # Only SEALED segments ever exist on disk (the writer
                # buffers in memory and publishes atomically), so any
                # torn/mismatched file here is damage, never a live
                # segment mid-write.
                count("audit", path)
                _check_sealed_json(path, "audit", findings,
                                   registry=registry)
            elif name.endswith(".jsonl"):
                _check_jsonl(path, findings, checked)
            elif name.endswith(".json") and not in_blackbox:
                # Sniff sealed/known JSON artifacts by content.
                try:
                    with open(path) as f:
                        doc = json.load(f)
                except (OSError, ValueError):
                    doc = None
                if not isinstance(doc, dict):
                    continue
                if doc.get("format") == "jama16.serve_policy":
                    count("policy", path)
                    _check_sealed_json(path, "policy", findings,
                                       registry=registry)
                elif doc.get("kind") == "quality_profile":
                    count("profile", path)
                    _check_sealed_json(path, "profile", findings,
                                       registry=registry)
                elif doc.get("kind") == "integrity_ledger":
                    count("ledger", path)
                    _check_sealed_json(path, "ledger", findings,
                                       registry=registry)
                elif doc.get("kind") == "audit_segment":
                    count("audit", path)
                    _check_sealed_json(path, "audit", findings,
                                       registry=registry)
            elif in_blackbox and name == "meta.json":
                count("blackbox", path)
                try:
                    with open(path) as f:
                        json.load(f)
                except (OSError, ValueError) as e:
                    findings.append(FsckFinding(
                        path=path, artifact="blackbox",
                        status="CORRUPT",
                        detail=f"dump metadata unparseable ({e})",
                        repair="quarantine",
                    ))
    _check_live_cross_refs(workdir, findings, registry=registry)
    if not journal_readable:
        # Repairing blind destroys rollout state: flag loudly.
        findings.append(FsckFinding(
            path=os.path.join(workdir, "lifecycle"), artifact="journal",
            status="CORRUPT",
            detail="the lifecycle journal (or live pointer) is "
                   "unreadable, so open-cycle protection cannot be "
                   "computed — --repair will NOT touch the lifecycle "
                   "directory; inspect it by hand",
        ))
    return FsckReport(
        workdir=workdir, findings=findings, checked=checked,
        protected=sorted(pinned),
    )


# ---------------------------------------------------------------------------
# Repair
# ---------------------------------------------------------------------------


def _append_ledger(workdir: str, actions: list) -> str:
    """Sealed, journaled quarantine/repair ledger: each repair run
    appends its actions (read-modify-write through the sealed writer,
    same discipline as the lifecycle journal)."""
    qdir = os.path.join(workdir, "quarantine")
    os.makedirs(qdir, exist_ok=True)
    path = os.path.join(qdir, "ledger.json")
    entries: list = []
    if os.path.exists(path):
        try:
            doc, _ = artifact_lib.read_sealed_json(
                path, artifact="ledger"
            )
            entries = list(doc.get("actions", ()))
        except Exception:  # noqa: BLE001 - a corrupt ledger must not
            entries = []   # block repairing everything else
    entries.extend(actions)
    artifact_lib.write_sealed_json(path, {
        "kind": "integrity_ledger", "actions": entries,
    }, schema="integrity.ledger", version=1)
    return path


def _quarantine(workdir: str, path: str) -> str:
    qdir = os.path.join(workdir, "quarantine")
    os.makedirs(qdir, exist_ok=True)
    base = os.path.basename(path)
    dst = os.path.join(qdir, base)
    n = 0
    while os.path.exists(dst):
        n += 1
        dst = os.path.join(qdir, f"{base}.{n}")
    artifact_lib.rename(path, dst)
    return dst


def _trim_manifest(shard_path: str) -> "str | None":
    """Drop the manifest entry claiming a corrupt/missing shard (and
    delete the shard pair): the manifest returns to a valid PARTIAL
    state — exactly what an interrupted transcode leaves — so
    ``transcode_shards.py`` resume rebuilds precisely the trimmed
    shards."""
    shard_dir = os.path.dirname(shard_path)
    name = os.path.basename(shard_path)
    for mname in os.listdir(shard_dir):
        if not mname.endswith(".rawshard.json"):
            continue
        mpath = os.path.join(shard_dir, mname)
        try:
            with open(mpath) as f:
                m = json.load(f)
        except (OSError, ValueError):
            continue
        m.pop(artifact_lib.SEAL_KEY, None)
        hit = [e for e in m.get("shards", ())
               if e.get("images") == name or e.get("grades") == name]
        if not hit:
            continue
        m["shards"] = [e for e in m["shards"] if e not in hit]
        for e in hit:
            for k in ("images", "grades"):
                p = os.path.join(shard_dir, e.get(k, ""))
                if e.get(k) and os.path.exists(p):
                    os.unlink(p)
        artifact_lib.write_sealed_json(
            mpath, m, schema="rawshard.manifest",
            version=m.get("version", 1),
        )
        return mpath
    return None


def repair_workdir(workdir: str, report: "FsckReport | None" = None,
                   registry=None) -> dict:
    """Apply every finding's repair action (see module docstring).
    Returns the ledger dict {actions: [...], skipped: [...]} — also
    appended to ``<workdir>/quarantine/ledger.json`` (sealed) and
    counted under ``integrity.repaired``."""
    from jama16_retina_tpu.obs import registry as registry_lib

    workdir = os.path.abspath(workdir)
    if report is None:
        report = fsck_workdir(workdir, registry=registry)
    pinned, journal_readable = protected_paths(workdir)
    reg = registry if registry is not None \
        else registry_lib.default_registry()
    c_repaired = reg.counter(
        "integrity.repaired",
        help="fsck repair actions applied (derivable artifacts "
             "deleted for on-demand rebuild, non-derivable ones "
             "quarantined, torn JSONL rewritten)",
    )
    actions: list = []
    skipped: list = []
    lc_dir = os.path.join(workdir, "lifecycle")
    trimmed: set = set()
    for f in report.findings:
        if not f.repair:
            continue
        if _is_protected(f.path, pinned):
            skipped.append({"path": f.path, "why": "protected "
                            "(live.json / open lifecycle cycle)"})
            continue
        if not journal_readable and os.path.abspath(f.path).startswith(
                os.path.abspath(lc_dir) + os.sep):
            skipped.append({"path": f.path, "why": "lifecycle journal "
                            "unreadable; repairing blind is refused"})
            continue
        if not os.path.exists(f.path) and f.repair != "trim-manifest":
            # trim-manifest's target IS allowed to be missing (a lost
            # shard): the repair edits the manifest, not the shard.
            continue
        try:
            if f.repair == "delete":
                size = os.path.getsize(f.path)
                os.unlink(f.path)
                sc = artifact_lib.sidecar_path(f.path)
                if os.path.exists(sc):
                    os.unlink(sc)
                actions.append({"action": "delete", "path": f.path,
                                "artifact": f.artifact, "bytes": size,
                                "rebuild": f.detail})
            elif f.repair == "quarantine":
                dst = _quarantine(workdir, f.path)
                # The seal sidecar travels with its binary: leaving it
                # behind would be a fresh ORPHAN finding (and the
                # quarantined file would lose its seal pairing for
                # later forensics).
                sc = artifact_lib.sidecar_path(f.path)
                sc_dst = None
                if os.path.exists(sc):
                    sc_dst = _quarantine(workdir, sc)
                actions.append({"action": "quarantine", "path": f.path,
                                "artifact": f.artifact, "moved_to": dst,
                                **({"sidecar_moved_to": sc_dst}
                                   if sc_dst else {})})
            elif f.repair == "trim-manifest":
                if f.path in trimmed:
                    continue
                mpath = _trim_manifest(f.path)
                trimmed.add(f.path)
                actions.append({"action": "trim-manifest",
                                "path": f.path, "artifact": f.artifact,
                                "manifest": mpath,
                                "rebuild": artifact_lib.REBUILD[
                                    "rawshard.shard"]})
            elif f.repair == "rewrite":
                kept: list = []
                with open(f.path, encoding="utf-8",
                          errors="replace") as fh:
                    for line in fh:
                        if not line.strip():
                            continue
                        try:
                            json.loads(line)
                            kept.append(line if line.endswith("\n")
                                        else line + "\n")
                        except json.JSONDecodeError:
                            pass
                artifact_lib.atomic_write_text(f.path, "".join(kept))
                actions.append({"action": "rewrite", "path": f.path,
                                "artifact": f.artifact,
                                "kept_lines": len(kept)})
            else:  # pragma: no cover - unknown action
                skipped.append({"path": f.path,
                                "why": f"unknown repair {f.repair!r}"})
                continue
            c_repaired.inc()
        except OSError as e:  # pragma: no cover - fs race
            skipped.append({"path": f.path, "why": f"OSError: {e}"})
    ledger = {"actions": actions, "skipped": skipped}
    if actions:
        _append_ledger(workdir, actions)
    return ledger
