"""Unified retention GC: a workdir must not grow without bound
(ISSUE 13).

Until this module NOTHING was ever garbage-collected: blackbox dumps
accumulated one-per-reason-per-run forever, compile-cache entries for
every (bucket, mesh, dtype) ever served stayed on disk, telemetry JSONL
grew monotonically, and every lifecycle cycle's candidate checkpoint
set survived its own rollback. One dry-run-first policy covers all of
it:

  * BLACKBOX — keep the newest ``obs.blackbox_keep`` dump dirs (the
    flight recorder enforces the same cap at dump time; this is the
    offline sweep for workdirs written by older code).
  * COMPILE CACHE — entry files LRU-evicted (by mtime) above
    ``integrity.cache_max_bytes``; the manifest is never collected, an
    evicted entry recompiles on the next warm-up.
  * TELEMETRY — a metrics JSONL above ``integrity.telemetry_max_bytes``
    rotates to ``<name>.1`` (older rotations and ``.prev`` files
    deleted). Offline only — never run against a live run's log.
  * FLEET — each fleet segment stream (``<role>-p<pid>/seg-*.json``,
    obs/fleet.py; ISSUE 15) is bounded to the same
    ``integrity.telemetry_max_bytes``, oldest segments first, newest
    (heartbeat-bearing) segment always kept.
  * CHECKPOINTS — retired lifecycle candidate roots
    (``lifecycle/candidate-NNNN``) and canary-pre backups of CLOSED
    cycles beyond the newest ``integrity.keep_candidate_cycles``.
    Within a checkpoint dir, orbax's own ``max_to_keep`` retention
    owns step-level GC — this layer collects whole retired sets.

THE PIN (tested): nothing reachable from ``live.json`` or named by an
OPEN journal cycle is ever planned, let alone deleted — and an
unreadable journal freezes the lifecycle/checkpoint classes entirely.

``plan_retention`` is a pure function of the filesystem state (same
state ⇒ identical plan, so the dry-run ledger and the apply ledger
match — pinned); ``apply_plan`` executes exactly the plan, appends a
sealed GC ledger at ``<workdir>/integrity/gc-ledger.json``, and counts
``integrity.gc.deleted{.class}`` / ``integrity.gc.bytes``. Driven by
``scripts/graftfsck.py --gc [--apply]``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import re

from jama16_retina_tpu.integrity import artifact as artifact_lib
from jama16_retina_tpu.integrity.fsck import _is_protected, protected_paths

_CANDIDATE_RE = re.compile(r"^candidate-(\d+)$")
_CANARY_BACKUP_RE = re.compile(r"^canary-pre-(\d+)\.npz$")


@dataclasses.dataclass(frozen=True)
class Action:
    """One planned GC action: ``kind`` is ``delete`` (file or tree) or
    ``rotate`` (JSONL size rotation)."""

    kind: str
    path: str
    cls: str    # blackbox | compile_cache | telemetry | fleet |
    #             checkpoint | audit
    bytes: int
    reason: str

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class RetentionPlan:
    workdir: str
    actions: list
    pinned: list

    @property
    def total_bytes(self) -> int:
        return sum(a.bytes for a in self.actions)

    def ledger(self) -> dict:
        """The ledger this plan implies — IDENTICAL for dry-run and
        apply by construction (apply executes exactly these actions)."""
        return {
            "workdir": self.workdir,
            "actions": [a.as_dict() for a in self.actions],
            "total_bytes": self.total_bytes,
            "pinned": sorted(self.pinned),
        }


def _audit_capture_files(seg_path: str) -> "list[str]":
    """Relative capture-file names the segment's records reference
    (what rides along when the segment is GC'd). Best-effort: a
    corrupt segment contributes nothing — graftfsck owns classifying
    it, the GC plan stays pure."""
    try:
        doc, _seal = artifact_lib.read_sealed_json(seg_path,
                                                   artifact="audit")
    except Exception:  # noqa: BLE001 - fsck's job, not the planner's
        return []
    out = []
    for rec in doc.get("records", ()):
        cap = rec.get("capture") if isinstance(rec, dict) else None
        if cap and cap.get("file"):
            out.append(cap["file"])
    return out


def _tree_bytes(path: str) -> int:
    if os.path.isfile(path):
        return os.path.getsize(path)
    total = 0
    for base, _dirs, files in os.walk(path):
        for f in files:
            try:
                total += os.path.getsize(os.path.join(base, f))
            except OSError:  # pragma: no cover
                pass
    return total


def plan_retention(workdir: str, cfg) -> RetentionPlan:
    """Compute the GC plan for ``workdir`` under ``cfg`` (an
    ExperimentConfig — reads ``cfg.integrity.*`` and
    ``cfg.obs.blackbox_keep``). Pure over the filesystem state: walks,
    sizes, and mtime order only — no clock, no randomness — so two
    plans over the same state are identical (the dry-run-equals-apply
    ledger pin)."""
    workdir = os.path.abspath(workdir)
    icfg = cfg.integrity
    actions: list = []
    pinned, journal_readable = protected_paths(workdir)

    def plan(kind: str, path: str, cls: str, reason: str) -> None:
        if _is_protected(path, pinned):
            return
        # A tree delete must also be refused when a PINNED path lives
        # INSIDE it (live.json pointing into an old candidate root —
        # deleting the parent would eat the blessed member).
        p = os.path.abspath(path)
        if any(root.startswith(p + os.sep) for root in pinned):
            return
        actions.append(Action(
            kind=kind, path=path, cls=cls, bytes=_tree_bytes(path),
            reason=reason,
        ))

    # 1) Blackbox dumps: newest obs.blackbox_keep survive.
    keep = int(cfg.obs.blackbox_keep)
    bb = os.path.join(workdir, "blackbox")
    if keep > 0 and os.path.isdir(bb):
        dumps = sorted(
            (os.path.join(bb, n) for n in os.listdir(bb)
             if os.path.isdir(os.path.join(bb, n))),
            key=lambda p: (os.path.getmtime(p), p),
        )
        for p in dumps[: max(0, len(dumps) - keep)]:
            plan("delete", p, "blackbox",
                 f"beyond obs.blackbox_keep={keep} (oldest first)")

    # 2) Compile-cache entries: LRU by mtime above cache_max_bytes.
    cap = int(icfg.cache_max_bytes)
    if cap > 0:
        for base, dirs, files in os.walk(workdir):
            dirs[:] = sorted(d for d in dirs if d != "quarantine")
            if "MANIFEST.json" not in files:
                continue
            entries = []
            for n in sorted(files):
                if n.endswith(".jex"):
                    p = os.path.join(base, n)
                    sc = artifact_lib.sidecar_path(p)
                    size = os.path.getsize(p) + (
                        os.path.getsize(sc) if os.path.exists(sc) else 0
                    )
                    entries.append((os.path.getmtime(p), p, size))
            total = sum(s for _, _, s in entries)
            for _mt, p, size in sorted(entries):
                if total <= cap:
                    break
                plan("delete", p, "compile_cache",
                     f"cache over integrity.cache_max_bytes={cap}; "
                     "LRU-evicted (recompiles on next warm-up)")
                total -= size

    # 3) Telemetry JSONL rotation. Order matters at apply time (the
    #    ledger executes in plan order): an EXISTING .1 that a planned
    #    rotation would land on is deleted BEFORE the rotate — never
    #    after, which would unlink the freshly rotated current log. A
    #    .1 whose base is NOT rotating is the one allowed rotation and
    #    is kept; .prev backups (RunLog fresh-rotation leftovers) are
    #    always superseded.
    tcap = int(icfg.telemetry_max_bytes)
    if tcap > 0:
        for base, dirs, files in os.walk(workdir):
            dirs[:] = sorted(
                d for d in dirs if d not in ("quarantine", "blackbox")
            )
            for n in sorted(files):
                p = os.path.join(base, n)
                if n.endswith(".jsonl.prev"):
                    plan("delete", p, "telemetry",
                         "superseded backup file")
                elif n.endswith(".jsonl") and os.path.getsize(p) > tcap:
                    if os.path.exists(p + ".1"):
                        plan("delete", p + ".1", "telemetry",
                             "superseded rotation (its base rotates "
                             "onto it this run)")
                    plan("rotate", p, "telemetry",
                         f"over integrity.telemetry_max_bytes={tcap}; "
                         "rotated to .1 (offline runs only — resume "
                         "best-tracking replays the fresh file)")

    # 3b) Fleet segment streams (ISSUE 15): each <role>-p<pid>/ stream
    #     under a fleet dir is bounded to telemetry_max_bytes — oldest
    #     segments deleted first (the bus's keep_segments prune is the
    #     online half; this is the offline byte-cap half, so a
    #     long-lived fleet dir with many short-lived pids stays
    #     bounded). The NEWEST segment always survives (it carries the
    #     process's heartbeat — collecting it would blind
    #     --check-heartbeats to a live process).
    if tcap > 0:
        from jama16_retina_tpu.obs import fleet as fleet_lib
        for base, dirs, files in os.walk(workdir):
            dirs[:] = sorted(
                d for d in dirs if d not in ("quarantine", "blackbox")
            )
            if not fleet_lib._PROC_DIR_RE.match(os.path.basename(base)):
                continue
            segs = sorted(
                n for n in files if fleet_lib._SEG_RE.match(n)
            )
            if not segs:
                continue
            # A live FleetBus prunes its own stream concurrently
            # (obs.fleet_keep_segments); a segment listed by os.walk
            # may be gone by stat time — already collected, skip it.
            sizes = {}
            for n in segs:
                try:
                    sizes[n] = os.path.getsize(os.path.join(base, n))
                except OSError:
                    pass
            segs = [n for n in segs if n in sizes]
            if not segs:
                continue
            total = sum(sizes.values())
            for n in segs[:-1]:  # newest always survives
                if total <= tcap:
                    break
                plan("delete", os.path.join(base, n), "fleet",
                     f"segment stream over "
                     f"integrity.telemetry_max_bytes={tcap}; oldest "
                     "segments deleted first (heartbeat-bearing newest "
                     "kept)")
                total -= sizes[n]

    # 3c) Audit-ledger segments (ISSUE 20): each ``audit/`` dir keeps
    #     its newest obs.audit.retention SEALED segments — oldest
    #     deleted first, the newest always implicitly survives
    #     (retention >= 1), and a deleted segment takes its captured
    #     input tensors with it (capture file names embed the segment
    #     number, so they are referenced by exactly one segment).
    #     retention <= 0 keeps everything (the medico-legal default is
    #     deliberately generous; pruning is an explicit opt-in).
    akeep = int(cfg.obs.audit.retention)
    if akeep > 0:
        from jama16_retina_tpu.obs import audit as audit_lib
        for base, dirs, files in os.walk(workdir):
            dirs[:] = sorted(
                d for d in dirs if d not in ("quarantine", "blackbox")
            )
            if os.path.basename(base) != "audit":
                continue
            segs = sorted(
                n for n in files if audit_lib.SEGMENT_RE.match(n)
            )
            for n in segs[: max(0, len(segs) - akeep)]:
                p = os.path.join(base, n)
                plan("delete", p, "audit",
                     f"beyond obs.audit.retention={akeep} (oldest "
                     "sealed audit segments first)")
                for cap in _audit_capture_files(p):
                    cp = os.path.join(base, cap)
                    if os.path.exists(cp):
                        plan("delete", cp, "audit",
                             "captured input tensor referenced only "
                             "by a GC'd audit segment")

    # 4) Retired lifecycle candidate sets + canary backups. An
    #    unreadable journal freezes this class: collecting candidates
    #    blind could eat a half-done rollout's work.
    lc = os.path.join(workdir, "lifecycle")
    if journal_readable and os.path.isdir(lc):
        jpath = os.path.join(lc, "journal.json")
        closed: list = []
        open_cycle = -1
        if os.path.exists(jpath):
            try:
                with open(jpath) as f:
                    doc = json.load(f)
                doc.pop(artifact_lib.SEAL_KEY, None)
                entries = list(doc.get("entries", ()))
            except Exception:  # noqa: BLE001 - raced; freeze the class
                entries = None
            if entries is None:
                return RetentionPlan(workdir=workdir, actions=actions,
                                     pinned=sorted(pinned))
            terminal = ("COMMIT", "ROLLBACK")
            by_cycle: dict = {}
            for e in entries:
                by_cycle.setdefault(e.get("cycle"), []).append(e)
            for c, es in by_cycle.items():
                if es[-1].get("state") in terminal:
                    closed.append(int(c))
                else:
                    open_cycle = int(c)
            closed.sort()
        keep_c = set(closed[-max(0, int(icfg.keep_candidate_cycles)):])
        for n in sorted(os.listdir(lc)):
            p = os.path.join(lc, n)
            m = _CANDIDATE_RE.match(n) or _CANARY_BACKUP_RE.match(n)
            if not m:
                continue
            cyc = int(m.group(1))
            if cyc == open_cycle or cyc in keep_c or cyc not in closed:
                continue
            plan("delete", p, "checkpoint",
                 f"candidate artifacts of closed cycle {cyc} beyond "
                 "integrity.keep_candidate_cycles="
                 f"{icfg.keep_candidate_cycles}")
    return RetentionPlan(workdir=workdir, actions=actions,
                         pinned=sorted(pinned))


def apply_plan(plan: RetentionPlan, registry=None) -> dict:
    """Execute EXACTLY the planned actions (the dry-run ledger is the
    apply ledger), append the sealed GC ledger, count every deletion."""
    import shutil

    from jama16_retina_tpu.obs import registry as registry_lib

    reg = registry if registry is not None \
        else registry_lib.default_registry()
    c_deleted = reg.counter(
        "integrity.gc.deleted",
        help="files/trees removed by the retention GC, all classes",
    )
    c_bytes = reg.counter(
        "integrity.gc.bytes",
        help="bytes reclaimed by the retention GC",
    )
    executed: list = []
    for a in plan.actions:
        if not os.path.exists(a.path):
            continue
        try:
            if a.kind == "rotate":
                artifact_lib.rename(a.path, a.path + ".1")
            elif os.path.isdir(a.path):
                shutil.rmtree(a.path)
            else:
                os.unlink(a.path)
                sc = artifact_lib.sidecar_path(a.path)
                if os.path.exists(sc):
                    os.unlink(sc)
        except OSError:  # pragma: no cover - fs race
            continue
        reg.counter(
            f"integrity.gc.deleted.{a.cls}",
            help="retention-GC removals per artifact class "
                 "(blackbox/compile_cache/telemetry/fleet/checkpoint/"
                 "audit)",
        ).inc()
        c_deleted.inc()
        c_bytes.inc(a.bytes)
        executed.append(a.as_dict())
    ledger = dict(plan.ledger())
    ledger["executed"] = executed
    idir = os.path.join(plan.workdir, "integrity")
    os.makedirs(idir, exist_ok=True)
    path = os.path.join(idir, "gc-ledger.json")
    prior: list = []
    if os.path.exists(path):
        try:
            doc, _ = artifact_lib.read_sealed_json(path,
                                                   artifact="ledger")
            prior = list(doc.get("runs", ()))
        except Exception:  # noqa: BLE001 - a corrupt ledger must not
            prior = []     # block the GC itself; fsck reports it
    prior.append({"actions": executed,
                  "total_bytes": ledger["total_bytes"]})
    artifact_lib.write_sealed_json(path, {
        "kind": "integrity_ledger", "runs": prior,
    }, schema="integrity.ledger", version=1)
    return ledger
