"""Backend-agnostic evaluation metrics (SURVEY.md N11, reference R8).

The reference's eval layer computes ROC-AUC and sensitivity at fixed
specificity operating points (specificity 0.87 and 0.98, BASELINE.json:8)
plus ensemble probability averaging (BASELINE.json:10). Everything here is
pure numpy on host-gathered probabilities so the same code serves any
training backend ("evaluation code is untouched", BASELINE.json:5) and is
directly checkable against scikit-learn in tests.

All functions accept 1-D numpy arrays; probabilities are P(positive).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

import numpy as np


def roc_curve(labels: np.ndarray, scores: np.ndarray):
    """ROC curve via single descending sort (O(n log n)).

    Returns (fpr, tpr, thresholds) with one point per distinct score,
    matching sklearn.metrics.roc_curve's convention of prepending the
    (0, 0) point with threshold +inf.
    """
    labels = np.asarray(labels).astype(np.float64).ravel()
    scores = np.asarray(scores).astype(np.float64).ravel()
    if labels.shape != scores.shape:
        raise ValueError("labels and scores must have the same shape")
    if labels.size == 0:
        raise ValueError(
            "roc_curve got empty input — no examples reached the metric "
            "(check eval split / mask filtering)"
        )
    if not np.all((labels == 0.0) | (labels == 1.0)):
        raise ValueError(
            "roc_curve expects binary labels in {0, 1}; got values "
            f"{np.unique(labels)[:6]} — binarize grades first "
            "(e.g. synthetic.binary_labels)"
        )
    order = np.argsort(-scores, kind="stable")
    labels = labels[order]
    scores = scores[order]

    # Cumulative TP/FP counts at each distinct-score cut.
    distinct = np.where(np.diff(scores))[0]
    cut = np.r_[distinct, labels.size - 1]
    tps = np.cumsum(labels)[cut]
    fps = (cut + 1) - tps
    p = tps[-1] if tps.size else 0.0
    n = fps[-1] if fps.size else 0.0
    if p == 0 or n == 0:
        raise ValueError("roc_curve needs at least one positive and one negative")
    tpr = np.r_[0.0, tps / p]
    fpr = np.r_[0.0, fps / n]
    thresholds = np.r_[np.inf, scores[cut]]
    return fpr, tpr, thresholds


def roc_auc(labels: np.ndarray, scores: np.ndarray) -> float:
    """Area under the ROC curve (trapezoidal; ties handled via the curve)."""
    fpr, tpr, _ = roc_curve(labels, scores)
    return float(np.trapezoid(tpr, fpr))


@dataclasses.dataclass(frozen=True)
class OperatingPoint:
    """Threshold chosen at a fixed specificity (reference operating points)."""

    target_specificity: float
    threshold: float
    sensitivity: float
    specificity: float

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def sensitivity_at_specificity(
    labels: np.ndarray, scores: np.ndarray, target_specificity: float
) -> OperatingPoint:
    """Pick the ROC threshold with specificity >= target that maximizes
    sensitivity; report achieved sens/spec at that threshold.

    This is the reference's operating-point selection (BASELINE.json:8):
    on the ROC curve, specificity = 1 - fpr, so we take the largest fpr
    with 1 - fpr >= target (ties on the curve already resolved toward
    higher tpr by construction).
    """
    fpr, tpr, thresholds = roc_curve(labels, scores)
    spec = 1.0 - fpr
    feasible = np.where(spec >= target_specificity)[0]
    if feasible.size == 0:  # unreachable: the (0,0) point has spec 1.0
        feasible = np.array([0])
    best = feasible[np.argmax(tpr[feasible])]
    return OperatingPoint(
        target_specificity=float(target_specificity),
        threshold=float(thresholds[best]),
        sensitivity=float(tpr[best]),
        specificity=float(spec[best]),
    )


def confusion_at_threshold(
    labels: np.ndarray, scores: np.ndarray, threshold: float
) -> dict:
    labels = np.asarray(labels).ravel().astype(bool)
    pred = np.asarray(scores).ravel() >= threshold
    tp = int(np.sum(pred & labels))
    fp = int(np.sum(pred & ~labels))
    fn = int(np.sum(~pred & labels))
    tn = int(np.sum(~pred & ~labels))
    return {
        "tp": tp, "fp": fp, "fn": fn, "tn": tn,
        "sensitivity": tp / max(tp + fn, 1),
        "specificity": tn / max(tn + fp, 1),
        "precision": tp / max(tp + fp, 1),
        "accuracy": (tp + tn) / max(tp + fp + fn + tn, 1),
    }


def transferred_operating_points(
    tune_labels: np.ndarray,
    tune_scores: np.ndarray,
    eval_labels: np.ndarray,
    eval_scores: np.ndarray,
    operating_specificities: Sequence[float],
    bootstrap_samples: int = 0,
    bootstrap_seed: int = 0,
) -> list[dict]:
    """The paper's operating-point protocol (JAMA 2016 / the replication):
    thresholds are chosen at fixed specificity on a TUNING split, then
    applied unchanged to the held-out eval split — reporting achieved
    sensitivity/specificity plus the full confusion there. Selecting
    thresholds on the eval split itself (sensitivity_at_specificity
    directly) is optimistically biased; both forms appear in the report
    so the bias is visible. ``bootstrap_samples > 0`` adds 95% CIs on the
    achieved sensitivity/specificity (eval-split resampling at the FIXED
    transferred threshold — these rows are the protocol's headline
    numbers, so they carry the uncertainty too).
    """
    rows = []
    for s in operating_specificities:
        op = sensitivity_at_specificity(tune_labels, tune_scores, s)
        achieved = confusion_at_threshold(eval_labels, eval_scores, op.threshold)
        row = {
            "target_specificity": float(s),
            "threshold": op.threshold,
            **achieved,
        }
        if bootstrap_samples > 0:
            thr = op.threshold

            def sens_spec(l, sc):
                c = confusion_at_threshold(l, sc, thr)
                return {"sensitivity": c["sensitivity"],
                        "specificity": c["specificity"]}

            cis = bootstrap_ci(
                eval_labels, eval_scores, sens_spec,
                bootstrap_samples, bootstrap_seed,
            )
            row["sensitivity_ci95"] = list(cis["sensitivity"])
            row["specificity_ci95"] = list(cis["specificity"])
        rows.append(row)
    return rows


def bootstrap_ci(
    labels: np.ndarray,
    scores: np.ndarray,
    stat_fn,
    n_samples: int = 2000,
    seed: int = 0,
    alpha: float = 0.05,
):
    """Percentile-bootstrap CI for any statistic of (labels, scores) —
    the replication reported 95% CIs on AUC this way.

    ``stat_fn`` may return a float (returns ``(lo, hi)``) or a dict of
    floats (returns ``{key: (lo, hi)}``, all statistics computed from
    the SAME resamples — one pass instead of one per statistic).
    Resamples that lose one class (possible on small eval sets) are
    skipped; at least half of ``n_samples`` (min 20) must survive.
    """
    labels = np.asarray(labels).ravel()
    scores = np.asarray(scores).ravel()
    rng = np.random.default_rng(seed)
    stats = []
    for _ in range(n_samples):
        idx = rng.integers(0, labels.size, labels.size)
        lab = labels[idx]
        if lab.min() == lab.max():  # one-class resample: statistic undefined
            continue
        stats.append(stat_fn(lab, scores[idx]))
    min_valid = max(20, n_samples // 2)
    if len(stats) < min_valid:
        raise ValueError(
            f"only {len(stats)}/{n_samples} bootstrap resamples were valid "
            f"(need >= {min_valid}) — eval set too small/imbalanced for a CI"
        )
    q = [alpha / 2, 1 - alpha / 2]
    if isinstance(stats[0], dict):
        return {
            k: tuple(float(v) for v in np.quantile([s[k] for s in stats], q))
            for k in stats[0]
        }
    lo, hi = np.quantile(stats, q)
    return float(lo), float(hi)


def brier_score(labels: np.ndarray, scores: np.ndarray) -> float:
    labels = np.asarray(labels, dtype=np.float64).ravel()
    scores = np.asarray(scores, dtype=np.float64).ravel()
    return float(np.mean((scores - labels) ** 2))


def expected_calibration_error(
    labels: np.ndarray, scores: np.ndarray, n_bins: int = 15
) -> float:
    """Equal-width-bin ECE: sum_b (n_b/N) * |acc_b - conf_b|. Reported
    next to Brier so miscalibration (which threshold transfer inherits)
    is visible; recalibrate externally from --save_probs if needed."""
    labels = np.asarray(labels, dtype=np.float64).ravel()
    scores = np.asarray(scores, dtype=np.float64).ravel()
    if labels.size == 0:
        raise ValueError("expected_calibration_error got empty input")
    bins = np.clip(
        (scores * n_bins).astype(np.int64), 0, n_bins - 1
    )
    ece = 0.0
    for b in range(n_bins):
        sel = bins == b
        n_b = int(sel.sum())
        if n_b == 0:
            continue
        ece += (n_b / labels.size) * abs(
            labels[sel].mean() - scores[sel].mean()
        )
    return float(ece)


def fit_temperature(
    labels: np.ndarray, probs: np.ndarray,
    lo: float = 0.05, hi: float = 20.0, iters: int = 80,
) -> float:
    """Temperature that minimizes binary NLL on a TUNING split (golden-
    section search over log T — NLL in T is unimodal for fixed logits).
    Probabilities are mapped back to logits first, so this composes with
    ensemble averaging. Apply with :func:`apply_temperature` to the EVAL
    split; never fit on the split being reported (same bias rule as
    threshold transfer).
    """
    labels = np.asarray(labels, dtype=np.float64).ravel()
    p = np.clip(np.asarray(probs, dtype=np.float64).ravel(), 1e-7, 1 - 1e-7)
    logits = np.log(p) - np.log1p(-p)

    def nll(log_t: float) -> float:
        z = logits / np.exp(log_t)
        # stable log(1+e^z): logaddexp(0, z)
        return float(np.mean(np.logaddexp(0.0, z) - labels * z))

    a, b = np.log(lo), np.log(hi)
    phi = (np.sqrt(5.0) - 1) / 2
    c, d = b - phi * (b - a), a + phi * (b - a)
    fc, fd = nll(c), nll(d)
    for _ in range(iters):
        if fc < fd:
            b, d, fd = d, c, fc
            c = b - phi * (b - a)
            fc = nll(c)
        else:
            a, c, fc = c, d, fd
            d = a + phi * (b - a)
            fd = nll(d)
    return float(np.exp((a + b) / 2))


def apply_temperature(probs: np.ndarray, temperature: float) -> np.ndarray:
    """sigmoid(logit(p) / T) elementwise."""
    p = np.clip(np.asarray(probs, dtype=np.float64), 1e-7, 1 - 1e-7)
    logits = np.log(p) - np.log1p(-p)
    return 1.0 / (1.0 + np.exp(-logits / temperature))


def ensemble_average(prob_list: Sequence[np.ndarray]) -> np.ndarray:
    """Averaged per-model probabilities (reference's "averaged logits",
    BASELINE.json:10 — the replication averaged the models' sigmoid
    outputs linearly)."""
    if not prob_list:
        raise ValueError("empty ensemble")
    stacked = np.stack([np.asarray(p, dtype=np.float64) for p in prob_list])
    return np.mean(stacked, axis=0)


# ---------------------------------------------------------------------------
# 5-class ICDR severity metrics (BASELINE.json:9 "multi:softmax")
# ---------------------------------------------------------------------------


def multiclass_accuracy(labels: np.ndarray, probs: np.ndarray) -> float:
    pred = np.argmax(np.asarray(probs), axis=-1)
    return float(np.mean(pred == np.asarray(labels).ravel()))


def confusion_matrix(labels: np.ndarray, preds: np.ndarray, num_classes: int) -> np.ndarray:
    labels = np.asarray(labels).ravel().astype(np.int64)
    preds = np.asarray(preds).ravel().astype(np.int64)
    cm = np.zeros((num_classes, num_classes), dtype=np.int64)
    np.add.at(cm, (labels, preds), 1)
    return cm


def quadratic_weighted_kappa(
    labels: np.ndarray, preds: np.ndarray, num_classes: int = 5
) -> float:
    """Quadratic-weighted Cohen's kappa — the standard ordinal agreement
    metric for ICDR grading (used by the Kaggle EyePACS competition)."""
    cm = confusion_matrix(labels, preds, num_classes).astype(np.float64)
    n = cm.sum()
    if n == 0:
        return 0.0
    idx = np.arange(num_classes, dtype=np.float64)
    w = (idx[:, None] - idx[None, :]) ** 2 / (num_classes - 1) ** 2
    row = cm.sum(axis=1)
    col = cm.sum(axis=0)
    expected = np.outer(row, col) / n
    denom = np.sum(w * expected)
    if denom == 0:
        return 0.0
    return float(1.0 - np.sum(w * cm) / denom)


def referable_probs_from_multiclass(probs: np.ndarray) -> np.ndarray:
    """Collapse 5-class ICDR probabilities to P(referable DR) = P(grade>=2),
    so binary operating-point reporting works for the multi head too."""
    probs = np.asarray(probs, dtype=np.float64)
    return probs[..., 2:].sum(axis=-1)


def evaluation_report(
    labels: np.ndarray,
    probs: np.ndarray,
    operating_specificities: Sequence[float] = (0.87, 0.98),
    bootstrap_samples: int = 0,
    bootstrap_seed: int = 0,
) -> dict:
    """The reference's final eval report shape: AUC plus one row per
    operating point (SURVEY.md §3.2), identical format for every backend.

    ``bootstrap_samples > 0`` adds 95% percentile-bootstrap intervals
    (``auc_ci95``, per-point ``sensitivity_ci95``) — the replication
    paper's uncertainty protocol, absent from the reference code."""
    labels = np.asarray(labels).ravel()
    probs = np.asarray(probs)
    if probs.ndim == 2 and probs.shape[-1] == 2:
        raise ValueError(
            "2-column probabilities are ambiguous; pass P(positive) as a "
            "1-D array for the binary head (probs[:, 1])"
        )
    if probs.ndim == 2 and probs.shape[-1] > 2:  # 5-class ICDR head
        binary_labels = (labels >= 2).astype(np.float64)
        binary_probs = referable_probs_from_multiclass(probs)
        report = {
            "accuracy": multiclass_accuracy(labels, probs),
            "quadratic_weighted_kappa": quadratic_weighted_kappa(
                labels, np.argmax(probs, axis=-1), probs.shape[-1]
            ),
        }
    else:
        binary_labels = labels.astype(np.float64)
        binary_probs = probs.ravel()
        report = {}
    report["auc"] = roc_auc(binary_labels, binary_probs)
    report["brier"] = brier_score(binary_labels, binary_probs)
    report["ece"] = expected_calibration_error(binary_labels, binary_probs)
    report["n_examples"] = int(binary_labels.size)
    # Each row: the ROC-chosen point plus the full confusion at its
    # threshold (reference R2 reports confusion at the operating points).
    report["operating_points"] = []
    for s in operating_specificities:
        op = sensitivity_at_specificity(binary_labels, binary_probs, s)
        conf = confusion_at_threshold(binary_labels, binary_probs, op.threshold)
        report["operating_points"].append({**conf, **op.as_dict()})
    if bootstrap_samples > 0:
        report["auc_ci95"] = list(bootstrap_ci(
            binary_labels, binary_probs, roc_auc, bootstrap_samples,
            bootstrap_seed,
        ))
        for row in report["operating_points"]:
            thr = row["threshold"]
            row["sensitivity_ci95"] = list(bootstrap_ci(
                binary_labels, binary_probs,
                lambda l, s: confusion_at_threshold(l, s, thr)["sensitivity"],
                bootstrap_samples, bootstrap_seed,
            ))
    return report
