from jama16_retina_tpu.eval import metrics  # noqa: F401
