"""Device mesh + sharding layout (SURVEY.md N7/N9; BASELINE.json:5).

The reference is single-process TF with no distributed layer (SURVEY.md
§1); the north star mandates data-parallel training with gradient
allreduce and cross-replica BatchNorm over ICI. TPU-natively that is:

  * one ``jax.sharding.Mesh`` over all devices with a single ``'data'``
    axis (N10: DP is the only strategy this 24M-param CNN needs; a
    model axis would be added HERE if one were ever warranted);
  * batches sharded ``P('data')`` on dim 0, parameters/optimizer state
    replicated ``P()``;
  * the train step jit'd over global arrays — XLA GSPMD turns the
    gradient mean and the global-batch BN moments into ICI all-reduces.
    No NCCL/MPI analogue exists or is needed (SURVEY.md §5.8).

Multi-host: ``initialize_distributed()`` wraps
``jax.distributed.initialize`` — a no-op single-host, the DCN bring-up
on a pod — after which ``jax.devices()`` spans all hosts and the same
mesh code scales unchanged.
"""

from __future__ import annotations

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def initialize_distributed() -> None:
    """Multi-host bring-up (SURVEY.md §3.5). Safe to call single-host."""
    if jax.process_count() > 1:
        return  # already initialized by the launcher
    try:
        jax.distributed.initialize()
    except Exception:
        # Single-host / no coordinator configured: run locally.
        pass


def make_mesh(num_devices: int = 0, axis: str = "data") -> Mesh:
    """1-D data-parallel mesh over the first ``num_devices`` devices
    (0 = all). Device order is jax.devices() order, which groups
    ICI-adjacent chips before DCN hops — collectives ride ICI first."""
    devices = jax.devices()
    if num_devices:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, have {len(devices)}"
            )
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (axis,))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Dim-0 (batch) sharding over the data axis."""
    return NamedSharding(mesh, P(mesh.axis_names[0]))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def shard_batch(batch, mesh: Mesh):
    """Place a host batch dict as global arrays sharded on dim 0."""
    sh = batch_sharding(mesh)

    def put(x):
        x = np.asarray(x)
        spec = P(mesh.axis_names[0], *([None] * (x.ndim - 1))) if x.ndim else P()
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(put, batch)
