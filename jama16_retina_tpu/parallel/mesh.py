"""Device mesh + sharding layout (SURVEY.md N7/N9; BASELINE.json:5).

The reference is single-process TF with no distributed layer (SURVEY.md
§1); the north star mandates data-parallel training with gradient
allreduce and cross-replica BatchNorm over ICI. TPU-natively that is:

  * one ``jax.sharding.Mesh`` over all devices with a single ``'data'``
    axis (N10: DP is the only strategy this 24M-param CNN needs; a
    model axis would be added HERE if one were ever warranted);
  * batches sharded ``P('data')`` on dim 0, parameters/optimizer state
    replicated ``P()``;
  * the train step jit'd over global arrays — XLA GSPMD turns the
    gradient mean and the global-batch BN moments into ICI all-reduces.
    No NCCL/MPI analogue exists or is needed (SURVEY.md §5.8).

Multi-host: ``initialize_distributed()`` wraps
``jax.distributed.initialize`` — a no-op single-host, the DCN bring-up
on a pod — after which ``jax.devices()`` spans all hosts and the same
mesh code scales unchanged.
"""

from __future__ import annotations

import os

import numpy as np

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Env vars whose presence means "a multi-process launch is configured".
# JAX's own auto-detection (cluster_detection_method) covers GKE/TPU-pod
# metadata; these cover explicit launchers. Guarding on env — NOT on
# jax.process_count(), which itself initializes a backend and always
# returns 1 before jax.distributed.initialize() has run.
_COORDINATOR_ENV_VARS = (
    "JAX_COORDINATOR_ADDRESS",
    "COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS",
)


def _multihost_env_configured() -> bool:
    if any(os.environ.get(v) for v in _COORDINATOR_ENV_VARS):
        return True
    # Cloud TPU metadata: set on every TPU VM, including single-host
    # slices (this axon environment exports 'localhost') — only a
    # multi-name list means an actual pod of workers.
    hostnames = os.environ.get("TPU_WORKER_HOSTNAMES", "")
    return "," in hostnames


def enable_persistent_compilation_cache(path: str) -> None:
    """Point XLA's persistent compilation cache at ``path`` (the CLIs'
    --jit_cache_dir). One home for the floor overrides so train.py and
    evaluate.py caches stay shareable: floors are zeroed because even the
    small eval step recompiles per ensemble member, and on the TPU the
    train step's ~80s compile is the dominant per-run fixed cost."""
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def configure_fake_cpu_devices(n: int) -> None:
    """Point jax at ``n`` fake CPU devices — the one home for the
    version-compat rule the CLIs and tests share: jax >= 0.4.38 exposes
    jax_num_cpu_devices; older jax only honors the XLA_FLAGS knob,
    which is read lazily at first backend init (so this must run before
    anything touches a backend). Callers pin jax_platforms=cpu first."""
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        flags = os.environ.get("XLA_FLAGS", "")
        if "xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + f" --xla_force_host_platform_device_count={n}"
            ).strip()


def _distributed_is_initialized() -> bool:
    """jax.distributed.is_initialized() where it exists (>= 0.4.38);
    older jax exposes the same fact as the service client's presence."""
    is_init = getattr(jax.distributed, "is_initialized", None)
    if is_init is not None:
        return bool(is_init())
    try:
        from jax._src import distributed as _dist

        return _dist.global_state.client is not None
    except Exception:
        return False


def initialize_distributed(force: bool = False) -> bool:
    """Multi-host bring-up (SURVEY.md §3.5). MUST run before any other jax
    API touches a backend — jax.distributed.initialize() after backend
    init is too late. train.py/evaluate.py call this first thing in main.

    Single-host (no coordinator env configured) this is a no-op, so the
    same entry points run unchanged on one chip. Returns True when
    distributed initialization actually ran.
    """
    if _distributed_is_initialized():
        return True
    if not force and not _multihost_env_configured():
        return False  # single-host: leave the local backend to init lazily
    addr = (
        os.environ.get("JAX_COORDINATOR_ADDRESS")
        or os.environ.get("COORDINATOR_ADDRESS")
    )
    kwargs = {}
    if addr:
        kwargs["coordinator_address"] = addr
        n, p = os.environ.get("JAX_NUM_PROCESSES"), os.environ.get("JAX_PROCESS_ID")
        # jax.distributed.initialize needs BOTH (or neither, relying on
        # cluster auto-detection). Fail here with the missing name — a
        # half-set launcher env otherwise dies with a jax-internal error
        # on some hosts while the rest block on the coordinator.
        if (n is None) != (p is None):
            missing = "JAX_NUM_PROCESSES" if n is None else "JAX_PROCESS_ID"
            raise RuntimeError(
                f"multi-host launch env is half-configured: "
                f"JAX_COORDINATOR_ADDRESS is set but {missing} is not "
                "(set both JAX_NUM_PROCESSES and JAX_PROCESS_ID, or "
                "neither if the cluster is auto-detectable)"
            )
        if n is not None:
            kwargs["num_processes"] = int(n)
            kwargs["process_id"] = int(p)
    jax.distributed.initialize(**kwargs)
    return True


def make_mesh(num_devices: int = 0, axis: str = "data") -> Mesh:
    """1-D data-parallel mesh over the first ``num_devices`` devices
    (0 = all). Device order is jax.devices() order, which groups
    ICI-adjacent chips before DCN hops — collectives ride ICI first."""
    devices = jax.devices()
    if num_devices:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, have {len(devices)}"
            )
        devices = devices[:num_devices]
    return Mesh(np.asarray(devices), (axis,))


def make_ensemble_mesh(
    n_members: int, num_devices: int = 0, member_axis_size: int = 0,
    data_axis: str = "data",
) -> Mesh:
    """2-D ``('member', data_axis)`` mesh for member-parallel ensemble
    training (trainer.fit_ensemble_parallel) and member-sharded serving
    (serve/assemble.py).

    The member axis carries INDEPENDENT replicas — stacked params shard
    across it with zero cross-member collectives (it is ensemble
    data-parallelism over seeds, not a tensor/pipeline axis; SURVEY.md
    N10's honesty note stands). ``member_axis_size`` 0 = auto:
    ``gcd(n_members, n_devices)`` — the largest count that divides both,
    so the stacked member dim and the device array always factor evenly
    (k=10 on 8 chips -> member axis 2, data axis 4, 5 members per
    member-shard). An explicit size (``parallel.member_axis_size``) is
    validated against BOTH divisibility constraints here, at mesh
    construction, instead of surfacing as an XLA uneven-sharding error
    mid-compile.
    """
    import math

    devices = jax.devices()
    if num_devices:
        if num_devices > len(devices):
            raise ValueError(
                f"requested {num_devices} devices, have {len(devices)}"
            )
        devices = devices[:num_devices]
    n = len(devices)
    if member_axis_size and member_axis_size > 0:
        member_size = int(member_axis_size)
        if n % member_size:
            raise ValueError(
                f"parallel.member_axis_size={member_size} does not "
                f"divide the {n}-device mesh"
            )
        if max(n_members, 1) % member_size:
            raise ValueError(
                f"parallel.member_axis_size={member_size} does not "
                f"divide the {n_members}-member ensemble"
            )
    else:
        member_size = math.gcd(max(n_members, 1), n)
    return Mesh(
        np.asarray(devices).reshape(member_size, n // member_size),
        ("member", data_axis),
    )


def make_serve_mesh(pc, n_members: int = 1) -> "Mesh | None":
    """The serving mesh a ParallelConfig describes (ISSUE 14;
    serve/assemble.py builds engines over it).

    ``parallel.serve_devices`` 0/1 returns None — the mesh-less
    single-device construction every predict.py bit-identity pin rides,
    byte-for-byte the pre-seam path. >1 with ``member_axis_size`` <= 1
    is a 1-D data mesh (state replicated, batch rows sharded); with
    ``member_axis_size`` > 1 it is the ('member', data_axis) mesh that
    shards the STACKED serving tree across the member axis — each
    device group holds n_members/member_axis_size members.
    """
    n = int(pc.serve_devices)
    if n <= 1:
        return None
    member = int(pc.member_axis_size)
    if member <= 1:
        return make_mesh(n, axis=pc.data_axis)
    return make_ensemble_mesh(
        n_members, num_devices=n, member_axis_size=member,
        data_axis=pc.data_axis,
    )


def mesh_fingerprint(mesh: "Mesh | None") -> dict:
    """The identity of a mesh as seen by serialized executables: device
    array shape, AXIS NAMES, and the process count of the launch
    (serve/compilecache.py folds this into the model fingerprint, so a
    resharded pod slice — same device total, different axis factoring
    or host split — refuses stale executables with the typed
    CompileCacheStale rebuild message instead of deserializing a
    program partitioned for another topology)."""
    if mesh is None:
        return {
            "shape": [1],
            "axis_names": [],
            "process_count": int(jax.process_count()),
        }
    return {
        "shape": [int(s) for s in mesh.devices.shape],
        "axis_names": [str(a) for a in mesh.axis_names],
        "process_count": int(jax.process_count()),
    }


def has_member_axis(mesh: "Mesh | None") -> bool:
    """True when the mesh carries a >1-way 'member' axis — the signal
    the serving stack keys member-sharded placement/dispatch on."""
    return (
        mesh is not None
        and "member" in mesh.axis_names
        and int(mesh.shape["member"]) > 1
    )


def _batch_axis(mesh: Mesh) -> str:
    """The mesh axis batches shard over: 'data' when present (2-D
    ensemble mesh), else the sole axis of the 1-D mesh — or, under a
    renamed ``parallel.data_axis`` on a 2-D mesh, the non-'member'
    axis (the member axis never carries batch rows)."""
    if "data" in mesh.axis_names:
        return "data"
    non_member = [a for a in mesh.axis_names if a != "member"]
    return non_member[0] if non_member else mesh.axis_names[0]


def member_sharding(mesh: Mesh) -> NamedSharding:
    """Dim-0 (stacked member) sharding over the member axis."""
    return NamedSharding(mesh, P("member"))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """Dim-0 (batch) sharding over the data axis."""
    return NamedSharding(mesh, P(_batch_axis(mesh)))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def _rank_sharding(ndim: int, sharding: NamedSharding) -> NamedSharding:
    """Extend (or trim) a sharding's spec to an array's rank — batch-dim
    sharding for arrays, replicated for scalars."""
    spec = list(sharding.spec) + [None] * max(0, ndim - len(sharding.spec))
    return NamedSharding(sharding.mesh, P(*spec[:ndim]))


def place_full_local(tree, sharding: NamedSharding):
    """Place host values that are IDENTICAL on every process as global
    arrays under (rank-extended) ``sharding``.

    Single-process: a plain device_put. Multi-process: each process
    supplies its own devices' shards from its full local copy
    (``jax.make_array_from_callback``) — the assembly for layouts where
    a process's devices do NOT own a contiguous process-major block of
    dim 0, which is exactly the ('member', 'data') ensemble mesh: its
    data columns interleave across processes, so ``shard_batch``'s
    local-rows contract cannot express them. Every process must hold the
    same full value (the member-parallel driver reads the full global
    batch on every host for this reason).
    """
    multiprocess = jax.process_count() > 1

    def put(x):
        x = np.asarray(x)
        sh = _rank_sharding(x.ndim, sharding)
        if not multiprocess:
            return jax.device_put(x, sh)
        return jax.make_array_from_callback(
            x.shape, sh, lambda idx, _x=x: _x[idx]
        )

    return jax.tree.map(put, tree)


def shard_batch(batch, mesh: Mesh):
    """Place a host batch dict as global arrays sharded on dim 0.

    Single-process: a plain sharded device_put. Multi-process: each
    process contributes its LOCAL rows (the per-process slice the input
    pipeline produced, SURVEY.md §3.5) and
    ``jax.make_array_from_process_local_data`` assembles the global
    array — global dim 0 = sum of local dims, laid out process-major
    (jax.devices() orders each process's devices contiguously).
    """
    multiprocess = jax.process_count() > 1

    axis = _batch_axis(mesh)

    def put(x):
        x = np.asarray(x)
        spec = P(axis, *([None] * (x.ndim - 1))) if x.ndim else P()
        sharding = NamedSharding(mesh, spec)
        if multiprocess and x.ndim:
            return jax.make_array_from_process_local_data(sharding, x)
        return jax.device_put(x, sharding)

    return jax.tree.map(put, batch)
