"""Parallelism layer: device mesh + sharding helpers (SURVEY.md N7-N9)."""

from jama16_retina_tpu.parallel.mesh import (  # noqa: F401
    batch_sharding,
    make_mesh,
    replicated,
    shard_batch,
)
