"""Critical-path analysis over trace events: typed bottleneck verdicts.

The observability stack up to PR 17 *collects* — metrics, traces,
stitched fleet timelines — but nothing *interprets*. This module is the
interpreter (ISSUE 18): a PURE function family over Chrome-shaped trace
events (obs/trace.Tracer.events, or obs/fleet.stitch_trace output) that
produces

  * per-request and per-train-step WATERFALLS (ordered segment
    decompositions with fractions, grouped by the ``trace_id`` the
    instrumented seams stamp into event args),
  * dominant-segment ATTRIBUTION (seconds per category over the whole
    window), and
  * a typed ``DiagnosisVerdict`` — the operator answer "what is the
    bottleneck": ``device_bound`` / ``decode_bound`` / ``credit_starved``
    / ``h2d_bound`` / ``queue_bound`` / ``balanced`` — with evidence
    fractions and the top-K slowest exemplar waterfalls attached.

Category mapping (the double-count discipline matters more than the
names):

  * ``device``  — ``trainer.dispatch`` + ``serve.request.device`` (+
    the router twin). ``serve.engine.*`` sub-spans nest INSIDE
    ``serve.request.device`` and are excluded from attribution.
  * ``decode``  — the consumer-side ``ingest.batch.{decode,cache}``
    segments. The server-lane ``ingest.decode.batch`` span is the SAME
    wall seen from the other process, so it only counts when no
    consumer-side decomposition is present. A plain ``trainer.input``
    (an in-process loader, no served decomposition) also lands here:
    input-bound IS decode-bound in this architecture's terms (tf.data's
    framing — the operator question is "feed the chip or fix the
    model").
  * ``credit``  — ``ingest.batch.credit_wait`` (the ring was full: the
    consumer, not decode, gated the server).
  * ``queue``   — ``serve.request.{queue_wait,window_fill}`` (+ router
    twin): admission/batch-formation pressure.
  * ``h2d``     — any segment whose name contains ``h2d`` (host-to-
    device transfer seams).
  * everything else (``ring_dwell``, ``read``, ``resolve``, ``pause``,
    ``save``, ...) — ``other``, plus the part of ``trainer.input`` the
    ``ingest.batch.*`` segments did not explain when both are present.

A verdict needs a dominant category: the largest of the five bound
categories must carry >= ``DOMINANT_FRACTION`` of attributed time,
else the window is ``balanced``. ``confidence`` is that dominant
fraction either way, so a gauge reader can distinguish "balanced at
0.38 device" from "balanced, nothing above 0.1".

Everything here is pure over the event list — no clocks, no I/O — so
the FlightRecorder can run it inside a dump and tests can pin verdicts
against synthetic timelines.
"""

from __future__ import annotations

import dataclasses

# Verdict -> stable numeric code for the obs.diagnosis.verdict gauge
# (alert rules compare numbers; the order is append-only). Codes 6-8
# are the ISSUE 19 device-plane refinements of ``device_bound``: when
# ``diagnose(device=...)`` gets a device summary (obs/device.py MFU +
# roofline gauges), "the device is the bottleneck" splits into WHY.
VERDICT_CODES = {
    "balanced": 0,
    "device_bound": 1,
    "decode_bound": 2,
    "credit_starved": 3,
    "h2d_bound": 4,
    "queue_bound": 5,
    "device_compute_bound": 6,
    "device_membw_bound": 7,
    "device_underutilized": 8,
}

# Category -> the verdict it argues for.
_CATEGORY_VERDICT = {
    "device": "device_bound",
    "decode": "decode_bound",
    "credit": "credit_starved",
    "h2d": "h2d_bound",
    "queue": "queue_bound",
}

# Share of attributed wall the dominant category must carry before the
# diagnosis commits to a typed verdict (below it: "balanced").
DOMINANT_FRACTION = 0.4

_DEVICE = {"trainer.dispatch", "serve.request.device",
           "serve.router.request.device"}
_DECODE = {"ingest.batch.decode", "ingest.batch.cache"}
_CREDIT = {"ingest.batch.credit_wait"}
_QUEUE = {"serve.request.queue_wait", "serve.request.window_fill",
          "serve.router.request.queue_wait"}
# Sub-spans nested inside an already-counted parent segment: counting
# them again would double the wall they share.
_NESTED_PREFIXES = ("serve.engine.",)

_REQUEST_PREFIXES = ("serve.request.", "serve.router.request.",
                     "ingest.batch.")
_STEP_PREFIX = "trainer."


def _complete_events(events) -> list:
    """The ph='X' events with a usable duration, as (name, ts_us,
    dur_s, args) tuples sorted by timestamp."""
    out = []
    for e in events or ():
        if not isinstance(e, dict) or e.get("ph") != "X":
            continue
        name = str(e.get("name", ""))
        if not name or any(name.startswith(p) for p in _NESTED_PREFIXES):
            continue
        try:
            dur_s = float(e.get("dur", 0.0)) / 1e6
            ts = float(e.get("ts", 0.0))
        except (TypeError, ValueError):
            continue
        if dur_s < 0.0:
            continue
        out.append((name, ts, dur_s, e.get("args") or {}))
    out.sort(key=lambda t: t[1])
    return out


def _category(name: str) -> str:
    if name in _DEVICE:
        return "device"
    if name in _DECODE:
        return "decode"
    if name in _CREDIT:
        return "credit"
    if name in _QUEUE:
        return "queue"
    if "h2d" in name:
        return "h2d"
    return "other"


def attribute(events) -> dict:
    """Seconds per category over the whole event window, double-count
    disciplined (module docstring): {'device','decode','credit','h2d',
    'queue','other'} -> seconds."""
    evs = _complete_events(events)
    totals = {k: 0.0 for k in ("device", "decode", "credit", "h2d",
                               "queue", "other")}
    have_consumer_ingest = any(
        n.startswith("ingest.batch.") for n, _t, _d, _a in evs
    )
    input_total = 0.0
    ingest_total = 0.0
    for name, _ts, dur_s, _args in evs:
        if name == "trainer.input":
            input_total += dur_s
            continue
        if name == "ingest.decode.batch":
            # Server lane of the same wall the consumer's
            # ingest.batch.* segments tile — only stands in when that
            # decomposition is absent (server-only traces).
            if not have_consumer_ingest:
                totals["decode"] += dur_s
            continue
        totals[_category(name)] += dur_s
        if name.startswith("ingest.batch."):
            ingest_total += dur_s
    if have_consumer_ingest:
        # The ingest.batch.* segments tile the input wait; whatever
        # trainer.input measured beyond them is loader overhead the
        # decomposition did not see.
        totals["other"] += max(0.0, input_total - ingest_total)
    else:
        totals["decode"] += input_total
    return totals


def _group_waterfalls(evs, want) -> list:
    """Group (name, ts, dur, args) tuples by args['trace_id'] for names
    ``want`` admits -> waterfall dicts, slowest first."""
    groups: dict = {}
    for name, ts, dur_s, args in evs:
        if not want(name):
            continue
        tid = args.get("trace_id")
        if not tid:
            continue
        groups.setdefault(tid, []).append((ts, name, dur_s))
    out = []
    for tid, segs in groups.items():
        segs.sort()
        total = sum(d for _ts, _n, d in segs)
        out.append({
            "trace_id": tid,
            "total_s": round(total, 6),
            "dominant": (
                max(segs, key=lambda s: s[2])[1] if segs else None
            ),
            "segments": [
                {"name": n, "dur_s": round(d, 6),
                 "frac": round(d / total, 4) if total > 0 else 0.0}
                for _ts, n, d in segs
            ],
        })
    out.sort(key=lambda w: -w["total_s"])
    return out


def request_waterfalls(events) -> list:
    """Per-request (and per-served-batch) waterfalls: the serve.request
    / router / ingest.batch segment families grouped by the trace id
    their instrumentation stamps into args, slowest first."""
    evs = _complete_events(events)
    return _group_waterfalls(
        evs, lambda n: any(n.startswith(p) for p in _REQUEST_PREFIXES)
    )


def step_waterfalls(events) -> list:
    """Per-train-step waterfalls: the ``trainer.*`` segment timeline
    split at each ``trainer.dispatch`` (one dispatch == one step; the
    segments since the previous dispatch belong to this step), slowest
    first."""
    evs = [t for t in _complete_events(events)
           if t[0].startswith(_STEP_PREFIX)]
    steps: list = []
    cur: list = []
    for name, ts, dur_s, _args in evs:
        cur.append((ts, name, dur_s))
        if name == "trainer.dispatch":
            steps.append(cur)
            cur = []
    out = []
    for i, segs in enumerate(steps):
        total = sum(d for _ts, _n, d in segs)
        out.append({
            "step_index": i,
            "total_s": round(total, 6),
            "dominant": max(segs, key=lambda s: s[2])[1],
            "segments": [
                {"name": n, "dur_s": round(d, 6),
                 "frac": round(d / total, 4) if total > 0 else 0.0}
                for _ts, n, d in segs
            ],
        })
    out.sort(key=lambda w: -w["total_s"])
    return out


@dataclasses.dataclass(frozen=True)
class DiagnosisVerdict:
    """The typed answer. ``evidence`` maps every category (including
    ``other``) to its fraction of attributed wall; ``confidence`` is
    the dominant bound category's fraction (0.0 when nothing was
    attributable)."""

    verdict: str
    code: int
    confidence: float
    evidence: dict
    totals_s: dict
    n_events: int
    request_waterfalls: list
    step_waterfalls: list
    # Device summary (obs/device.summary_from_gauges) that refined a
    # device_bound verdict into its sub-cause, or None when no device
    # plane was available (the verdict stays unrefined).
    device: "dict | None" = None

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def refine_device_verdict(device: "dict | None") -> "str | None":
    """device summary -> the typed sub-cause of ``device_bound``, or
    None when the summary cannot commit (no MFU, no roofline class).

    A memory-bandwidth-bound dominant program means more FLOP/s is not
    on the table regardless of MFU (``device_membw_bound``); a
    compute-class window at >= ``device.SATURATED_MFU`` is genuinely
    compute-saturated (``device_compute_bound``); below it the chip is
    the bottleneck only because each dispatch is too small to fill it —
    the batch-size MFU cliff (``device_underutilized``)."""
    if not device:
        return None
    if device.get("dominant_class") == "memory":
        return "device_membw_bound"
    mfu = device.get("mfu")
    if mfu is None:
        return None
    from jama16_retina_tpu.obs import device as device_lib

    if float(mfu) >= device_lib.SATURATED_MFU:
        return "device_compute_bound"
    return "device_underutilized"


def diagnose(events, top_k: int = 3,
             device: "dict | None" = None) -> DiagnosisVerdict:
    """events -> DiagnosisVerdict. Pure; an empty / unattributable
    window diagnoses ``balanced`` at confidence 0.0 rather than
    guessing. ``device`` (obs/device.summary_from_gauges) refines a
    ``device_bound`` verdict into its typed sub-cause; every other
    verdict ignores it."""
    totals = attribute(events)
    wall = sum(totals.values())
    evidence = {
        k: (round(v / wall, 4) if wall > 0 else 0.0)
        for k, v in totals.items()
    }
    best_cat, best_frac = None, 0.0
    for cat in _CATEGORY_VERDICT:
        if evidence[cat] > best_frac:
            best_cat, best_frac = cat, evidence[cat]
    if best_cat is not None and best_frac >= DOMINANT_FRACTION:
        verdict = _CATEGORY_VERDICT[best_cat]
    else:
        verdict = "balanced"
    used_device = None
    if verdict == "device_bound" and device:
        sub = refine_device_verdict(device)
        if sub is not None:
            verdict = sub
            used_device = dict(device)
    k = max(0, int(top_k))
    return DiagnosisVerdict(
        verdict=verdict,
        code=VERDICT_CODES[verdict],
        confidence=round(best_frac, 4),
        evidence=evidence,
        totals_s={k2: round(v, 6) for k2, v in totals.items()},
        n_events=len(_complete_events(events)),
        request_waterfalls=request_waterfalls(events)[:k],
        step_waterfalls=step_waterfalls(events)[:k],
        device=used_device,
    )
