"""Telemetry export: periodic JSONL snapshots + Prometheus text files.

Two consumers, one snapshot (ISSUE 3):

  * ``telemetry`` records through the existing RunLog JSONL — the
    system of record, diffable across runs, rendered by
    scripts/obs_report.py;
  * ``<workdir>/telemetry.prom`` — a Prometheus-text-format file
    rewritten atomically on every flush, scrapeable by node_exporter's
    textfile collector (or any file-based scraper) with zero coupling
    to this process's lifetime. Process p != 0 writes
    ``telemetry.p{N}.prom`` (the RunLog mirror convention).

Plus the explicit HEARTBEAT: SURVEY.md §5.3's wedged-host probe used to
be "stat the metrics.p{N}.jsonl mtime" — implicit, and blind to the
difference between a host that stopped writing and one that writes but
stopped PROGRESSING (wedged on a collective while its logging thread
stays alive). Each flush now writes a ``heartbeat`` record carrying
``step`` and ``last_progress_t`` (when the step counter last advanced),
so both failure shapes are detectable from the JSONL alone —
``scripts/obs_report.py --check-heartbeats`` is the cron/CI one-liner.
"""

from __future__ import annotations

import os
import re
import time

from jama16_retina_tpu.obs import registry as registry_lib

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    """Dotted registry names -> Prometheus metric names."""
    return _NAME_RE.sub("_", name)


def _fmt(v: float) -> str:
    if v == int(v):
        return str(int(v))
    return repr(float(v))


def _escape_help(text: str) -> str:
    """Prometheus HELP text escaping: backslash and newline only (the
    exposition-format rule; quotes are legal in HELP text)."""
    return text.replace("\\", "\\\\").replace("\n", "\\n")


def prometheus_text(snapshot: dict) -> str:
    """Render a Registry.snapshot() as Prometheus text exposition
    (counters, gauges, and cumulative-``le`` histogram series with
    ``_sum``/``_count``). Metrics registered with a ``help:`` string
    get a ``# HELP`` line before their ``# TYPE`` (the ordering strict
    scrape parsers expect; pinned in tests)."""
    help_by = snapshot.get("help", {})

    def _help_line(lines: list, name: str, prom: str) -> None:
        text = help_by.get(name)
        if text:
            lines.append(f"# HELP {prom} {_escape_help(text)}")

    lines: list[str] = []
    for name, v in sorted(snapshot.get("counters", {}).items()):
        n = _prom_name(name)
        _help_line(lines, name, n)
        lines.append(f"# TYPE {n} counter")
        lines.append(f"{n} {_fmt(v)}")
    for name, v in sorted(snapshot.get("gauges", {}).items()):
        n = _prom_name(name)
        _help_line(lines, name, n)
        lines.append(f"# TYPE {n} gauge")
        lines.append(f"{n} {_fmt(v)}")
    for name, h in sorted(snapshot.get("histograms", {}).items()):
        n = _prom_name(name)
        _help_line(lines, name, n)
        lines.append(f"# TYPE {n} histogram")
        for bound, cum in h["buckets"]:
            lines.append(f'{n}_bucket{{le="{_fmt(bound)}"}} {cum}')
        lines.append(f'{n}_bucket{{le="+Inf"}} {h["count"]}')
        lines.append(f"{n}_sum {_fmt(h['sum'])}")
        lines.append(f"{n}_count {h['count']}")
    return "\n".join(lines) + "\n"


def _jsonl_histograms(snapshot: dict) -> dict:
    """Histogram summaries for the telemetry JSONL record: quantiles +
    count/sum, WITHOUT the per-bucket series (the .prom file carries
    those; the JSONL stays one readable line per flush)."""
    return {
        name: {
            "count": h["count"],
            "sum": round(h["sum"], 6),
            "mean": round(h["mean"], 6) if h["mean"] is not None else None,
            "p50": round(h["p50"], 6) if h["p50"] is not None else None,
            "p95": round(h["p95"], 6) if h["p95"] is not None else None,
            "p99": round(h["p99"], 6) if h["p99"] is not None else None,
            # The window's slowest exemplar-tagged observation (ISSUE
            # 15): an SLO breach in this record links straight to the
            # trace_id to pull from the stitched fleet trace.
            **({"exemplar": h["exemplar"]} if h.get("exemplar") else {}),
        }
        for name, h in snapshot.get("histograms", {}).items()
    }


def _process_index() -> int:
    """jax.process_index() when a backend exists; 0 otherwise. Deferred
    and forgiving so pure-host telemetry (tests, CPU serving) never
    force-initializes an accelerator backend."""
    try:
        import jax

        return jax.process_index()
    except Exception:  # noqa: BLE001 - no backend == single process
        return 0


class Snapshotter:
    """Periodic registry snapshot -> RunLog ``telemetry`` record +
    atomic ``telemetry.prom`` rewrite + per-process ``heartbeat``.

    Pass the run's existing ``runlog`` (the trainer does) or let the
    snapshotter open its own RunLog in ``workdir`` (serving sessions,
    which have no train log) — an owned log is closed by ``close()``.

    ``progress(step)`` is the hot-path hook: two attribute writes, no
    lock (reader tolerance: a torn step/t pair is one flush stale).
    ``maybe_flush()`` flushes at most every ``every_s`` seconds —
    callers invoke it from their logging cadence, so a tight loop costs
    one ``time.time()`` per call between flushes.
    """

    def __init__(
        self,
        registry: "registry_lib.Registry | None" = None,
        workdir: str = "",
        runlog=None,
        every_s: float = 60.0,
        prom_name: str = "telemetry.prom",
        alerts=None,
        fleet=None,
        device=None,
    ):
        if not workdir and runlog is None:
            raise ValueError("Snapshotter needs a workdir and/or a runlog")
        self._registry = (
            registry if registry is not None
            else registry_lib.default_registry()
        )
        self._workdir = workdir
        self._owns_log = runlog is None
        if runlog is None:
            from jama16_retina_tpu.utils.logging import RunLog

            runlog = RunLog(workdir)
        self._log = runlog
        self.every_s = float(every_s)
        self._prom_name = prom_name
        # SLO/quality alerting (obs/alerts.py; ISSUE 5): the manager is
        # evaluated on every flush against the snapshot just taken, so
        # alert latency == telemetry cadence and `alert` records land
        # in the same JSONL as the telemetry they fired on. Assignable
        # after construction (predict.py builds the engine — and thus
        # the rules' flight recorder — after its snapshotter).
        self.alerts = alerts
        # Fleet segment bus (obs/fleet.py; ISSUE 15): when a FleetBus
        # is attached (obs.fleet_dir set — see fleet.bus_for), every
        # flush ALSO publishes a sealed telemetry segment into the
        # shared fleet dir. None = one branch per flush (the bench
        # fleet_overhead_pct contract).
        self._fleet = fleet
        # Device-utilization monitor (obs/device.py; ISSUE 19): sampled
        # FIRST in every flush so the HBM/MFU/compile gauges land in
        # the snapshot that flush exports. None = one branch per flush
        # (the bench devicemon_overhead_pct contract). Assignable after
        # construction, like ``alerts``.
        self.device = device
        self._http = None
        self._last_flush = time.time()
        self._step: "int | None" = None
        self._last_progress_t: "float | None" = None
        self.flushes = 0

    def progress(self, step: int) -> None:
        """Record forward progress (the heartbeat's payload)."""
        self._step = int(step)
        self._last_progress_t = time.time()

    def write_record(self, kind: str, **fields) -> None:
        """One custom JSONL record through the snapshotter's RunLog
        (e.g. the Router's session report as a ``router`` record —
        ISSUE 12). RunLog.write is lock-guarded, so this is safe
        against a concurrent flush cadence."""
        self._log.write(kind, **fields)

    def _prom_path(self) -> str:
        idx = _process_index()
        name = self._prom_name
        if idx != 0:
            stem, ext = os.path.splitext(name)
            name = f"{stem}.p{idx}{ext}"
        return os.path.join(self._workdir, name)

    def flush(self) -> dict:
        """Snapshot now: one ``telemetry`` + one ``heartbeat`` JSONL
        record, and (when a workdir is set) an atomic .prom rewrite.
        Returns the raw snapshot (tests read it). The flush is the ONE
        consumer that closes histogram exemplar windows — scrapes and
        dumps read without consuming."""
        if self.device is not None:
            try:
                self.device.sample(runlog=self._log)
            except Exception:  # noqa: BLE001 - telemetry must not kill a flush
                pass
        snap = self._registry.snapshot(reset_exemplars=True)
        self._log.write(
            "telemetry",
            counters={k: round(v, 6) for k, v in snap["counters"].items()},
            gauges={k: round(v, 6) for k, v in snap["gauges"].items()},
            histograms=_jsonl_histograms(snap),
        )
        self._log.write(
            "heartbeat",
            process_index=_process_index(),
            step=self._step,
            last_progress_t=(
                round(self._last_progress_t, 3)
                if self._last_progress_t is not None else None
            ),
        )
        if self.alerts is not None:
            self.alerts.evaluate(snapshot=snap, runlog=self._log)
        if self._fleet is not None:
            self._fleet.publish(snap, heartbeat={
                "step": self._step,
                "last_progress_t": (
                    round(self._last_progress_t, 3)
                    if self._last_progress_t is not None else None
                ),
                "flushes": self.flushes + 1,
            })
        if self._workdir:
            path = self._prom_path()
            os.makedirs(self._workdir, exist_ok=True)
            # Atomic publish through the shared sealed-writer seam
            # (integrity/artifact.py — unsealed text: the consumer is
            # a scrape parser): a scraper never reads a half-written
            # file.
            from jama16_retina_tpu.integrity import artifact as artifact_lib

            # fsync=False: the snapshot regenerates every flush — a
            # scraper needs never-torn (the rename), not durable.
            artifact_lib.atomic_write_text(path, prometheus_text(snap),
                                           fsync=False)
        self._last_flush = time.time()
        self.flushes += 1
        return snap

    def maybe_flush(self) -> "dict | None":
        if time.time() - self._last_flush >= self.every_s:
            return self.flush()
        return None

    def serve_http(self, port: int, max_age_s: float = 300.0):
        """Opt-in stdlib HTTP endpoint (ISSUE 15 satellite): start an
        ObsHttp server (obs/httpd.py) bound to this snapshotter's
        registry + heartbeat state — ``/metrics`` serves the live
        Prometheus text, ``/healthz`` the heartbeat freshness with the
        same 0/1/2 semantics as ``--check-heartbeats``. Bind failures
        are logged, never raised (a busy port must not kill the run).
        Returns the server (its ``.port`` resolves port 0), or None."""
        from absl import logging as absl_logging

        from jama16_retina_tpu.obs import httpd

        try:
            self._http = httpd.ObsHttp(
                self._registry, port, snapshotter=self,
                max_age_s=max_age_s,
            )
        except OSError as e:
            absl_logging.error(
                "obs http endpoint failed to bind port %d: %s", port, e
            )
            return None
        return self._http

    def close(self) -> None:
        """Final flush + close the owned RunLog (never one the caller
        passed in — the trainer closes its own log after this)."""
        self.flush()
        if self._http is not None:
            self._http.close()
            self._http = None
        if self._owns_log:
            self._log.close()
