"""Deterministic fault injection at named seams (ISSUE 6).

Recovery paths that only run when production breaks are recovery paths
that have never run. This module makes failure an INPUT: the data
plane, checkpoint restore, and the serving engine each call
``check(site)`` (or ``corrupt(site, data)``) at their seam, and a
``FaultPlan`` armed for that site injects the configured fault — an
exception on exactly the Nth call, added latency, or corrupted bytes —
deterministically, so tests/test_faults.py and ``bench.py --chaos``
drive every recovery path on demand.

``SITES`` below is the CANONICAL declared-site registry — the one
vocabulary the seams, plan specs, bench --chaos, and
docs/RELIABILITY.md's tables all resolve against. ``arm()`` and
``plan_from_spec()`` validate every plan against it with a
did-you-mean, so a typo'd chaos plan refuses loudly instead of
silently never firing; graftlint's ``faults`` rule pins the
code/docs populations to it statically (ISSUE 9).

Zero overhead unarmed — the contract the bench guard pins: every seam
reads ONE module-level global and branches; no dict lookup, no lock,
no allocation happens until a plan is armed. Arming is process-global
(``arm()``/``disarm()``) because the seams live across threads (the
batcher worker, decode pools); per-site call counting under the plan's
lock only costs anything while a plan is live.

Plans come from code (tests), from a JSON spec string/file
(``plan_from_spec``), or from the ``JAMA16_FAULTS`` environment
variable (``plan_from_env`` — how ``bench.py --chaos`` and operators
arm a real process). Spec shape, one entry per site:

    {"tfrecord.read": {"kind": "error", "on_calls": [3],
                       "error": "OSError", "message": "injected"},
     "host.decode":   {"kind": "latency", "on_calls": [1, 2],
                       "delay_s": 0.05},
     "ckpt.restore":  {"kind": "corrupt", "on_calls": [1]}}

``on_calls`` are 1-based per-site call ordinals — raise-on-Nth-call
semantics, exactly reproducible run to run. ``"every": N`` fires on
every Nth call instead (sustained-rot mode for the quarantine-rate
alert). ``max_fires`` bounds total injections per site (default
unbounded).
"""

from __future__ import annotations

import json
import os
import threading
import time
from dataclasses import dataclass, field

from absl import logging as absl_logging

# The canonical declared-site registry (ISSUE 9): every fault site the
# codebase fires, every plan key an operator may arm, and every site
# docs/RELIABILITY.md's failure matrix names. Adding a seam REQUIRES a
# row here (graftlint faults.unknown-site otherwise); a row whose seam
# disappears is flagged the other way (faults.never-fired).
SITES = {
    "tfrecord.read": "TFRecordIndex.read payload read "
                     "(data/grain_pipeline.py)",
    "host.decode": "serve/host per-image file read before fundus "
                   "normalization",
    "ckpt.restore": "Checkpointer.restore (utils/checkpoint.py)",
    "ckpt.save": "Checkpointer save write — fires in Checkpointer.save/"
                 "save_latest before the orbax write, on whichever "
                 "thread runs it (the train loop, or the AsyncSaver "
                 "worker under train.async_save); latency plans widen "
                 "the in-flight-save window for kill drills",
    "engine.dispatch": "ServingEngine per-chunk dispatch "
                       "(serve/engine.py)",
    "serve.router.dispatch": "Router per-bin replica dispatch "
                             "(serve/router.py; an injected failure "
                             "kills the replica — its bins retry on "
                             "siblings with typed accounting, zero "
                             "dropped requests)",
    "serve.compile_cache.load": "persistent AOT compile-cache entry "
                                "deserialize (serve/compilecache.py; a "
                                "failed load degrades to a counted "
                                "recompile, never a failed request)",
    "trainer.step": "the trainer loops' per-step boundary",
    "lifecycle.retrain": "LifecycleController RETRAIN phase entry",
    "lifecycle.gate": "LifecycleController GATE evaluation (an injected "
                      "error FAILS CLOSED: candidate rejected, cycle "
                      "rolls back)",
    "lifecycle.swap": "LifecycleController STAGED_ROLLOUT promote "
                      "(lifecycle/controller.py)",
    "integrity.write": "sealed-artifact payload seam (integrity/"
                       "artifact.atomic_write_bytes — EVERY durable "
                       "writer: rawshard manifests+shards, lifecycle "
                       "journal/live.json, serve policy, compile-cache "
                       "manifest/entries, profiles, canary): corrupt-"
                       "family kinds (torn/bitflip/truncate) damage "
                       "the serialized blob, error kinds fail the "
                       "write ENOSPC-style",
    "integrity.write.commit": "between the sealed writer's tmp-file "
                              "fsync and its atomic os.replace publish "
                              "— a latency plan holds the window open "
                              "for the kill -9 torn-write drill "
                              "(integrity/artifact.py)",
    "ingest.attach": "ingest-server consumer attach handler (ingest/"
                     "server.py; an injected error refuses the attach "
                     "with a typed error frame — the consumer raises, "
                     "nothing half-attached survives server-side)",
    "ingest.ring.write": "before each shared-memory ring slot write in "
                         "the ingest server's per-consumer serve loop "
                         "(ingest/server.py; an injected error drops "
                         "that consumer's connection — its lease-"
                         "journal reattach is the recovery under test; "
                         "latency plans widen the in-flight window for "
                         "kill drills)",
    "audit.seal": "audit-ledger segment seal on the writer thread "
                  "(obs/audit.py _seal, ahead of the sealed-artifact "
                  "publish; an injected error loses exactly that "
                  "segment's records — counted audit.seal_errors + "
                  "audit.dropped, serving unaffected; corrupt-family "
                  "kinds ride integrity.write underneath and leave a "
                  "torn segment for graftfsck to classify)",
    "ingest.decode": "inside the ingest server's timed cache-miss batch "
                     "decode (ingest/server.py _SharedStream.batch; a "
                     "latency plan throttles the decode plane so the "
                     "stamped decode wall — and the consumer's "
                     "ingest.batch.decode segment — inflate exactly "
                     "like a slow pool: the decode_bound verdict "
                     "drill's injection point, ISSUE 18)",
}

# Error classes a JSON spec may name. Deliberately small: injected
# faults should look like the real faults the seams handle (transient
# I/O, corrupt payloads, cancellation), not arbitrary types.
_ERRORS = {
    "OSError": OSError,
    "IOError": IOError,
    "ValueError": ValueError,
    "RuntimeError": RuntimeError,
    "TimeoutError": TimeoutError,
}


class InjectedFault(RuntimeError):
    """Default exception for kind="error" entries that name no class —
    unambiguous in logs/dumps: this failure was asked for."""


# Every plan kind. The corrupt FAMILY (data-damaging kinds delivered
# via ``corrupt()`` at data-carrying seams) models the disk-fault
# taxonomy of ISSUE 13's drills: "corrupt" (legacy: truncate-to-half +
# XOR), "torn" (only a prefix of the bytes land — a non-atomic write
# interrupted mid-flight), "bitflip" (one flipped bit mid-payload —
# silent media rot a size check cannot see), "truncate" (the tail is
# lost — a filesystem that acknowledged bytes it never wrote).
# kind="error" with error="OSError" is the ENOSPC-style write failure.
_KINDS = ("error", "latency", "corrupt", "torn", "bitflip", "truncate")
_CORRUPT_KINDS = ("corrupt", "torn", "bitflip", "truncate")


def _damage(kind: str, data: bytes) -> bytes:
    """Deterministic byte damage per corrupt-family kind (no RNG: the
    same plan always produces the same corpse, so fsck/test assertions
    can pin exactly what the reader must detect)."""
    if len(data) == 0:
        return data
    if kind == "torn":
        return data[: max(1, len(data) // 3)]
    if kind == "bitflip":
        i = len(data) // 2
        return data[:i] + bytes([data[i] ^ 0x01]) + data[i + 1:]
    if kind == "truncate":
        return data[: max(1, (len(data) * 3) // 4)]
    # legacy "corrupt": truncate to half and XOR-flip every byte
    half = data[: max(1, len(data) // 2)]
    return bytes(b ^ 0xFF for b in half)


@dataclass
class FaultSite:
    """One site's fault configuration inside a FaultPlan."""

    kind: str = "error"            # error | latency | corrupt
    on_calls: tuple = ()           # 1-based ordinals that fire
    every: int = 0                 # fire on every Nth call (0 = off)
    error: str = ""                # _ERRORS key; "" -> InjectedFault
    message: str = "injected fault"
    delay_s: float = 0.0           # latency kind: seconds to add
    max_fires: int = 0             # 0 = unbounded
    calls: int = 0                 # mutable: per-site call count
    fires: int = 0                 # mutable: injections delivered

    def should_fire(self) -> bool:
        """Call-counted decision; caller holds the plan lock."""
        self.calls += 1
        if self.max_fires and self.fires >= self.max_fires:
            return False
        hit = self.calls in self.on_calls or (
            self.every > 0 and self.calls % self.every == 0
        )
        if hit:
            self.fires += 1
        return hit

    def make_error(self) -> BaseException:
        cls = _ERRORS.get(self.error, InjectedFault)
        return cls(f"{self.message} (injected, call {self.calls})")


@dataclass
class FaultPlan:
    """A named-site fault schedule. Immutable site set after
    construction; per-site call counters mutate under ``_lock`` (seams
    fire from decode pools and the batcher worker concurrently)."""

    sites: dict = field(default_factory=dict)  # site -> FaultSite
    _lock: threading.Lock = field(default_factory=threading.Lock,
                                  repr=False)

    def site(self, name: str) -> "FaultSite | None":
        return self.sites.get(name)

    def counts(self) -> dict:
        """{site: {'calls': n, 'fires': m}} — what --chaos reports."""
        with self._lock:
            return {
                name: {"calls": s.calls, "fires": s.fires}
                for name, s in self.sites.items()
            }

    def validate_sites(self) -> None:
        """Every site of this plan must be declared in ``SITES`` —
        raises with a did-you-mean otherwise. A plan naming a site the
        code never fires is a chaos drill that silently tests nothing
        (ISSUE 9 satellite)."""
        import difflib

        for name in self.sites:
            if name in SITES:
                continue
            close = difflib.get_close_matches(name, sorted(SITES), n=1)
            hint = f"; did you mean {close[0]!r}?" if close else ""
            raise ValueError(
                f"unknown fault site {name!r}{hint} (declared sites: "
                f"{', '.join(sorted(SITES))}) — an unknown site would "
                "never fire; pass allow_unknown=True only to test the "
                "fault machinery itself"
            )


def plan_from_spec(spec: "str | dict",
                   allow_unknown: bool = False) -> FaultPlan:
    """A FaultPlan from the JSON spec shape in the module docstring.
    ``spec`` may be the JSON text itself, a path to a JSON file, or an
    already-parsed dict. Unknown keys/kinds — and, unless
    ``allow_unknown``, site names outside ``SITES`` — raise: a
    half-understood chaos plan silently not injecting is the one
    failure mode a fault harness must not have."""
    if isinstance(spec, str):
        if os.path.exists(spec):
            with open(spec) as f:
                spec = json.load(f)
        else:
            spec = json.loads(spec)
    if not isinstance(spec, dict):
        raise ValueError(f"fault spec must be a JSON object, got {spec!r}")
    sites = {}
    allowed = {"kind", "on_calls", "every", "error", "message",
               "delay_s", "max_fires"}
    for name, entry in spec.items():
        unknown = set(entry) - allowed
        if unknown:
            raise ValueError(
                f"fault site {name!r}: unknown keys {sorted(unknown)} "
                f"(allowed: {sorted(allowed)})"
            )
        kind = entry.get("kind", "error")
        if kind not in _KINDS:
            raise ValueError(
                f"fault site {name!r}: unknown kind {kind!r} "
                f"(want {'|'.join(_KINDS)})"
            )
        err = entry.get("error", "")
        if err and err not in _ERRORS:
            raise ValueError(
                f"fault site {name!r}: unknown error class {err!r} "
                f"(allowed: {sorted(_ERRORS)})"
            )
        sites[name] = FaultSite(
            kind=kind,
            on_calls=tuple(int(c) for c in entry.get("on_calls", ())),
            every=int(entry.get("every", 0)),
            error=err,
            message=str(entry.get("message", "injected fault")),
            delay_s=float(entry.get("delay_s", 0.0)),
            max_fires=int(entry.get("max_fires", 0)),
        )
    plan = FaultPlan(sites=sites)
    if not allow_unknown:
        plan.validate_sites()
    return plan


ENV_VAR = "JAMA16_FAULTS"


def plan_from_env() -> "FaultPlan | None":
    """The environment-driven arming path (operators / --chaos child
    processes): ``JAMA16_FAULTS`` holds the JSON spec or a file path."""
    raw = os.environ.get(ENV_VAR, "")
    if not raw:
        return None
    return plan_from_spec(raw)


# THE unarmed-cost contract: seams read this one global and branch.
_active: "FaultPlan | None" = None


def arm(plan: "FaultPlan | str | dict | None",
        allow_unknown: bool = False) -> "FaultPlan | None":
    """Install ``plan`` process-wide (str/dict specs are parsed);
    returns the previous plan so tests can restore it. ``None``
    disarms. Site names are validated against ``SITES`` (did-you-mean
    on a miss) unless ``allow_unknown`` — arming an undeclared site is
    a drill that silently injects nothing."""
    global _active
    prev = _active
    if plan is not None:
        if not isinstance(plan, FaultPlan):
            plan = plan_from_spec(plan, allow_unknown=allow_unknown)
        elif not allow_unknown:
            plan.validate_sites()
    _active = plan
    if plan is not None:
        absl_logging.warning(
            "FAULT INJECTION ARMED at sites %s", sorted(plan.sites)
        )
    return prev


def disarm() -> None:
    arm(None)


def active_plan() -> "FaultPlan | None":
    return _active


def arm_from_env_or_config(config_spec: str = "") -> None:
    """The run-entry arming rule (trainer._obs_begin_run, ServingEngine
    construction): the JAMA16_FAULTS env var wins, else the config's
    ``obs.fault_plan`` spec, else leave whatever is armed alone (tests
    arm programmatically before building the engine/trainer)."""
    env = plan_from_env()
    if env is not None:
        arm(env)
    elif config_spec:
        arm(plan_from_spec(config_spec))


def check(site: str) -> None:
    """The seam hook. Unarmed: one global read + one branch. Armed:
    count the call under the plan lock and deliver the configured
    fault — raise (kind=error), sleep (kind=latency), or nothing here
    (kind=corrupt is delivered via ``corrupt()``, which data-carrying
    seams call instead)."""
    plan = _active
    if plan is None:
        return
    s = plan.site(site)
    if s is None:
        return
    with plan._lock:
        fire = s.should_fire()
    if not fire:
        return
    if s.kind == "latency":
        time.sleep(s.delay_s)
        return
    if s.kind == "error":
        raise s.make_error()
    # A corrupt-family kind at a non-data seam: nothing to corrupt;
    # treat as an error so the plan is never silently inert.
    raise s.make_error()


def corrupt(site: str, data: bytes) -> bytes:
    """Data-carrying seam hook (TFRecord payloads, image bytes, sealed
    artifact blobs): returns ``data`` untouched unless an armed
    corrupt-family entry fires, in which case the bytes are
    deterministically damaged per the kind — "corrupt" (truncate-to-
    half + XOR), "torn", "bitflip", "truncate" (see ``_damage``) — so
    parsers downstream see a genuinely corrupt payload, not a magic
    sentinel. kind="error"/"latency" entries behave exactly like
    ``check``."""
    plan = _active
    if plan is None:
        return data
    s = plan.site(site)
    if s is None:
        return data
    with plan._lock:
        fire = s.should_fire()
    if not fire:
        return data
    if s.kind == "latency":
        time.sleep(s.delay_s)
        return data
    if s.kind == "error":
        raise s.make_error()
    return _damage(s.kind, data)
