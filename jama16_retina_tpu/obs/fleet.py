"""Fleet observability plane: cross-process telemetry segments, the
kind-correct aggregator, stitched traces, and fleet-scope SLO rules
(ISSUE 15).

PRs 12-14 made the runtime a multi-process FLEET (router replicas over
an EscalationPool, the lifecycle ``--watch`` supervisor, GSPMD
multi-host trainers) while the PR-3/4/5 observability stack stayed
strictly per-process: one registry, one JSONL, one ``.prom``, one ring
tracer per workdir. Nobody could answer "what is the fleet's p99" or
"which process wedged" from one place. This module is that place — the
distributed-runtime monitoring discipline of "TensorFlow: a system for
large-scale machine learning" (PAPERS.md) applied to our own stack:

  * **Segment bus** — every :class:`~jama16_retina_tpu.obs.export.
    Snapshotter` additionally publishes SEALED telemetry segments
    (riding the PR-13 ``integrity/artifact`` seam: atomic, digest-
    verified) into a shared ``obs.fleet_dir``. One directory per
    process — ``<fleet_dir>/<role>-p<pid>/`` — holding a bounded
    stream of ``seg-NNNNNN.json`` snapshots (each tagged with role /
    pid / host index / heartbeat) plus an atomically-rewritten
    ``trace.json`` with the process's current event rings and the
    wall-clock epoch that aligns them across processes.
  * **Kind-correct aggregation** — :func:`merge_snapshots`: counters
    SUM, fixed-bucket histograms merge BUCKET-EXACT (identical bounds
    ⇒ cumulative series add; quantiles recomputed from the merged
    series — never averaged), gauges keep their per-process series AND
    a fleet reduction the metric's help string declares
    (``[fleet:sum|max|min|mean|last]``, default sum). Pinned by the
    property ``merged == sum/merge of the per-process snapshots``
    (tests/test_fleet.py).
  * **Fleet-scope SLO rules** — :func:`evaluate_fleet` replays the
    merged snapshot TIMELINE through the ordinary alert grammar (so
    ``for S`` latching and ``rate()`` work over fleet history) and
    evaluates the multi-window ``burn(bad/total, LONG, SHORT)``
    burn-rate form (obs/alerts.py) over merged counter deltas — rules
    a single process can never fire, because no single process holds
    the fleet totals. Firing transitions write the standard ``alert``
    record (``<fleet_dir>/fleet.jsonl``) and a blackbox dump through
    the PR-4 FlightRecorder, deduped across aggregator invocations by
    a sealed state artifact (``fleet-alerts.json``).
  * **Stitched traces** — :func:`stitch_trace` merges every process's
    published rings into ONE Chrome trace with per-process pid lanes,
    wall-clock aligned via each tracer's ``epoch_unix``
    (``obs_report --trace-out`` over a fleet dir).

Retention: the per-process segment streams are bounded twice — the bus
prunes beyond ``obs.fleet_keep_segments`` at publish time, and
``integrity/retention.py`` enforces ``integrity.telemetry_max_bytes``
per stream offline (the blackbox_keep dual-enforcement precedent).
"""

from __future__ import annotations

import glob
import json
import os
import re
import time

from jama16_retina_tpu.integrity import artifact as artifact_lib
from jama16_retina_tpu.obs import registry as registry_lib

SEGMENT_SCHEMA = "obs.fleet_segment"
SEGMENT_VERSION = 1
STATE_SCHEMA = "obs.fleet_alerts"
STATE_VERSION = 1

# <fleet_dir>/<role>-p<pid>/ — role sanitized to this alphabet so the
# directory name round-trips through the regex below.
_ROLE_RE = re.compile(r"[^a-z0-9_-]")
_PROC_DIR_RE = re.compile(r"^([a-z0-9_-]+)-p(\d+)$")
_SEG_RE = re.compile(r"^seg-(\d+)\.json$")

# Gauge fleet-reduction declared in the metric's help string:
# "... [fleet:max]" — absent means sum (queue depths, in-flight rows,
# resident counts all add across processes; the exceptions declare
# themselves).
_REDUCTION_RE = re.compile(r"\[fleet:(sum|max|min|mean|last)\]")

# How many merged timeline points rule replay walks (newest kept): the
# long burn window bounds how much history is USEFUL; this bounds how
# much is read.
TIMELINE_KEEP = 256

# A stream whose newest segment is older than this stops contributing
# its GAUGES to the merge (a dead server's frozen queue depth is not a
# current level — left in, it would keep a fleet threshold rule firing
# forever off a dead pid, and a restarted process's new stream would
# double-count it). Cumulative counters and histograms STAY in the
# fleet totals: the rows that dead process served did happen, and a
# frozen counter contributes zero to every rate()/burn() delta.
STALE_GAUGES_AFTER_S = 900.0


def _without_gauges(snapshot: dict) -> dict:
    out = dict(snapshot)
    out["gauges"] = {}
    return out


def sanitize_role(role: str) -> str:
    return _ROLE_RE.sub("_", (role or "proc").lower()) or "proc"


def process_dir(fleet_dir: str, role: str, pid: "int | None" = None) -> str:
    pid = os.getpid() if pid is None else int(pid)
    return os.path.join(fleet_dir, f"{sanitize_role(role)}-p{pid}")


def is_fleet_dir(path: str) -> bool:
    """Does ``path`` look like a fleet dir (vs an ordinary workdir)?
    True when any immediate subdirectory is a segment stream."""
    if not os.path.isdir(path):
        return False
    for n in os.listdir(path):
        if _PROC_DIR_RE.match(n) and glob.glob(
                os.path.join(path, n, "seg-*.json")):
            return True
    return False


class FleetBus:
    """One process's publisher half of the segment bus.

    Constructed by :func:`bus_for` (None when the fleet plane is off —
    the Snapshotter then pays ONE branch per flush, the bench
    ``fleet_overhead_pct`` contract). ``publish`` is driven from the
    Snapshotter's flush cadence; a publish failure is counted
    (``obs.fleet.publish_errors``) and logged, never raised into the
    flush — losing one fleet segment must not take telemetry down.
    """

    def __init__(self, fleet_dir: str, role: str,
                 registry: "registry_lib.Registry | None" = None,
                 tracer=None, keep_segments: int = 64,
                 host_index: "int | None" = None):
        from jama16_retina_tpu.obs import trace as trace_lib

        self.fleet_dir = fleet_dir
        self.role = sanitize_role(role)
        self.pid = os.getpid()
        self.dir = process_dir(fleet_dir, self.role, self.pid)
        self.keep_segments = max(1, int(keep_segments))
        self._registry = (registry if registry is not None
                          else registry_lib.default_registry())
        self._tracer = (tracer if tracer is not None
                        else trace_lib.default_tracer())
        self._host_index = host_index
        # Resume the stream: a process running several sequential runs
        # (ensemble members) keeps ONE monotone segment sequence.
        self._seq = 0
        if os.path.isdir(self.dir):
            for n in os.listdir(self.dir):
                m = _SEG_RE.match(n)
                if m:
                    self._seq = max(self._seq, int(m.group(1)))
        self._c_segments = self._registry.counter(
            "obs.fleet.segments",
            help="sealed telemetry segments this process published to "
                 "the fleet dir (obs.fleet_dir)",
        )
        self._c_errors = self._registry.counter(
            "obs.fleet.publish_errors",
            help="fleet-segment publish failures swallowed so the "
                 "telemetry flush survives (disk full, permissions)",
        )

    def _host(self) -> int:
        if self._host_index is not None:
            return int(self._host_index)
        try:
            import jax

            return jax.process_index()
        except Exception:  # noqa: BLE001 - no backend == single host
            return 0

    def publish(self, snapshot: dict, heartbeat: "dict | None" = None) -> None:
        """One sealed segment (+ the trace rewrite) into this process's
        stream; prunes beyond ``keep_segments``. Never raises."""
        try:
            self._seq += 1
            payload = {
                "kind": "fleet_segment",
                "role": self.role,
                "pid": self.pid,
                "host_index": self._host(),
                "seq": self._seq,
                "t": round(time.time(), 3),
                "heartbeat": dict(heartbeat or {}),
                "snapshot": {
                    "counters": snapshot.get("counters", {}),
                    "gauges": snapshot.get("gauges", {}),
                    "histograms": snapshot.get("histograms", {}),
                    "help": snapshot.get("help", {}),
                },
            }
            os.makedirs(self.dir, exist_ok=True)
            artifact_lib.write_sealed_json(
                os.path.join(self.dir, f"seg-{self._seq:06d}.json"),
                payload, schema=SEGMENT_SCHEMA, version=SEGMENT_VERSION,
            )
            self._prune()
            if self._tracer.enabled:
                self._publish_trace()
            self._c_segments.inc()
        except Exception as e:  # noqa: BLE001 - flush must survive
            self._c_errors.inc()
            try:
                from absl import logging as absl_logging

                absl_logging.error(
                    "fleet segment publish failed (%s): %s: %s",
                    self.dir, type(e).__name__, e,
                )
            except Exception:  # pragma: no cover - logging itself broke
                pass

    def _publish_trace(self) -> None:
        """Atomic rewrite of this process's current event rings with
        the wall-clock epoch the stitcher aligns on. Regenerated every
        flush (rings are overwrite-oldest), so rename-only atomicity —
        no fsync on the flush path (the .prom precedent)."""
        doc = {
            "meta": {
                "role": self.role,
                "pid": self.pid,
                "epoch_unix": round(self._tracer.epoch_unix, 6),
            },
            "traceEvents": self._tracer.events(),
        }
        artifact_lib.atomic_write_text(
            os.path.join(self.dir, "trace.json"),
            json.dumps(doc), fsync=False,
        )

    def _prune(self) -> None:
        segs = sorted(
            n for n in os.listdir(self.dir) if _SEG_RE.match(n)
        )
        for n in segs[: max(0, len(segs) - self.keep_segments)]:
            try:
                os.unlink(os.path.join(self.dir, n))
            except OSError:  # pragma: no cover - racing GC
                pass


def bus_for(cfg, role: str, registry=None, tracer=None) -> "FleetBus | None":
    """The FleetBus one wiring site hangs on its Snapshotter, or None
    when the fleet plane is off (``obs.fleet_dir`` empty or obs
    disabled) — the disabled path is one ``is not None`` branch per
    flush. ``obs.fleet_role`` overrides the site's default role."""
    if not cfg.obs.enabled or not cfg.obs.fleet_dir:
        return None
    return FleetBus(
        cfg.obs.fleet_dir,
        role=cfg.obs.fleet_role or role,
        registry=registry, tracer=tracer,
        keep_segments=cfg.obs.fleet_keep_segments,
    )


# ---------------------------------------------------------------------------
# Reading
# ---------------------------------------------------------------------------


def read_segments(proc_dir: str, registry=None) -> "tuple[list, list]":
    """(segments sorted by seq, corrupt file basenames). A corrupt
    segment is SKIPPED (and counted through the integrity machinery by
    read_sealed_json) — one torn segment must not blind the aggregator
    to the rest of the stream."""
    segs, corrupt = [], []
    if not os.path.isdir(proc_dir):
        return segs, corrupt
    for n in sorted(os.listdir(proc_dir)):
        if not _SEG_RE.match(n):
            continue
        p = os.path.join(proc_dir, n)
        try:
            doc, _seal = artifact_lib.read_sealed_json(
                p, artifact="fleet_segment", registry=registry
            )
            segs.append(doc)
        except artifact_lib.ArtifactCorrupt:
            corrupt.append(n)
        except (OSError, ValueError):
            corrupt.append(n)
    segs.sort(key=lambda s: int(s.get("seq", 0)))
    return segs, corrupt


def read_fleet(fleet_dir: str, registry=None) -> dict:
    """{(role, pid): {"segments": [...], "corrupt": [...], "dir": path}}
    for every segment stream under ``fleet_dir``."""
    out: dict = {}
    if not os.path.isdir(fleet_dir):
        return out
    for n in sorted(os.listdir(fleet_dir)):
        m = _PROC_DIR_RE.match(n)
        if not m:
            continue
        p = os.path.join(fleet_dir, n)
        if not os.path.isdir(p):
            continue
        segs, corrupt = read_segments(p, registry=registry)
        if segs or corrupt:
            out[(m.group(1), int(m.group(2)))] = {
                "segments": segs, "corrupt": corrupt, "dir": p,
            }
    return out


# ---------------------------------------------------------------------------
# Kind-correct merge
# ---------------------------------------------------------------------------


def gauge_reduction(help_text: "str | None") -> str:
    """The fleet reduction a gauge's help string declares
    (``[fleet:max]`` etc.); sum when undeclared — levels like queue
    depth, in-flight rows, and resident counts add across processes."""
    if help_text:
        m = _REDUCTION_RE.search(help_text)
        if m:
            return m.group(1)
    return "sum"


def _merge_histogram(hists: list) -> "dict | None":
    """Bucket-exact merge of same-name histogram snapshots: identical
    bounds ⇒ the cumulative series (and sum/count) add elementwise,
    and quantiles are recomputed from the MERGED series with the same
    rank interpolation obs/registry.py applies — never averaged across
    processes (an average of p99s is not a p99). Mismatched bounds
    return None (the caller keeps them per-process and says so)."""
    bounds = [tuple(b for b, _c in h.get("buckets", ())) for h in hists]
    if len(set(bounds)) != 1:
        return None
    merged_bounds = bounds[0]
    cum = [0] * len(merged_bounds)
    total = 0
    s = 0.0
    exemplar = None
    for h in hists:
        for i, (_b, c) in enumerate(h.get("buckets", ())):
            cum[i] += int(c)
        total += int(h.get("count", 0))
        s += float(h.get("sum", 0.0))
        ex = h.get("exemplar")
        if ex and ex.get("value") is not None and (
                exemplar is None or ex["value"] > exemplar["value"]):
            exemplar = dict(ex)

    def quantile(q: float):
        if not total:
            return None
        target = q * total
        prev_cum, lo = 0, 0.0
        for bound, c_cum in zip(merged_bounds, cum):
            c = c_cum - prev_cum
            if c and c_cum >= target:
                frac = (target - prev_cum) / c
                return lo + (bound - lo) * frac
            prev_cum, lo = c_cum, bound
        return merged_bounds[-1] if merged_bounds else None

    return {
        "count": total,
        "sum": s,
        "mean": (s / total) if total else None,
        "p50": quantile(0.5),
        "p95": quantile(0.95),
        "p99": quantile(0.99),
        "buckets": list(zip(merged_bounds, cum)),
        "exemplar": exemplar,
    }


def merge_snapshots(snaps: "list[tuple[str, dict]]") -> dict:
    """THE aggregator: ``[(proc_key, Registry.snapshot()), ...]`` →
    one merged snapshot with kind-correct semantics. Counters sum;
    histograms merge bucket-exact (mismatched bounds land in
    ``unmerged_histograms`` per process instead of being mangled);
    gauges carry BOTH the help-declared fleet reduction (``gauges``)
    and the per-process series (``gauge_series``). ``help`` is the
    union (first writer wins). Pinned by the merged==sum property test.
    """
    out: dict = {
        "counters": {}, "gauges": {}, "gauge_series": {},
        "histograms": {}, "unmerged_histograms": {}, "help": {},
    }
    hist_groups: dict = {}
    gauge_groups: dict = {}
    for proc, snap in snaps:
        for name, v in snap.get("counters", {}).items():
            out["counters"][name] = out["counters"].get(name, 0.0) + v
        for name, v in snap.get("gauges", {}).items():
            gauge_groups.setdefault(name, []).append((proc, float(v)))
        for name, h in snap.get("histograms", {}).items():
            hist_groups.setdefault(name, []).append((proc, h))
        for name, text in snap.get("help", {}).items():
            out["help"].setdefault(name, text)
    for name, group in gauge_groups.items():
        values = [v for _p, v in group]
        red = gauge_reduction(out["help"].get(name))
        if red == "max":
            fleet = max(values)
        elif red == "min":
            fleet = min(values)
        elif red == "mean":
            fleet = sum(values) / len(values)
        elif red == "last":
            fleet = values[-1]
        else:
            fleet = sum(values)
        out["gauges"][name] = fleet
        out["gauge_series"][name] = {p: v for p, v in group}
    for name, group in hist_groups.items():
        merged = _merge_histogram([h for _p, h in group])
        if merged is not None:
            out["histograms"][name] = merged
        else:
            out["unmerged_histograms"][name] = {p: h for p, h in group}
    return out


def _segment_at(segments: list, t: float) -> "dict | None":
    """Newest segment published at or before ``t`` (None = the process
    had not published yet)."""
    best = None
    for seg in segments:
        if float(seg.get("t", 0.0)) <= t:
            best = seg
        else:
            break
    return best


def merged_timeline(fleet: dict, keep: int = TIMELINE_KEEP,
                    stale_after_s: float = STALE_GAUGES_AFTER_S) -> list:
    """[(t, merged_snapshot), ...] oldest-first: one merged fleet
    instant per distinct segment timestamp (each process contributes
    its newest segment at or before that instant; a contribution older
    than ``stale_after_s`` at that instant keeps its cumulative
    counters/histograms but loses its point-in-time gauges — see
    STALE_GAUGES_AFTER_S). This is the replay input for ``for S``
    latching and rate()/burn() forms — fleet-level alert evaluation
    needs fleet-level HISTORY, which is exactly what the segment
    streams keep and a single .prom snapshot does not."""
    times = sorted({
        float(seg.get("t", 0.0))
        for proc in fleet.values() for seg in proc["segments"]
    })
    times = times[-max(1, int(keep)):] if times else []
    out = []
    for t in times:
        snaps = []
        for (role, pid), proc in sorted(fleet.items()):
            seg = _segment_at(proc["segments"], t)
            if seg is None:
                continue
            snap = seg.get("snapshot", {})
            if t - float(seg.get("t", 0.0)) > stale_after_s:
                snap = _without_gauges(snap)
            snaps.append((f"{role}-p{pid}", snap))
        if snaps:
            out.append((t, merge_snapshots(snaps)))
    return out


def fleet_meta(fleet: dict, now: "float | None" = None,
               stale_after_s: float = STALE_GAUGES_AFTER_S) -> dict:
    """Per-process meta table from an already-read fleet dict (one
    read serves report + meta — the aggregator must not re-read and
    re-hash every sealed segment per section)."""
    now = time.time() if now is None else now
    meta = {}
    for (role, pid), proc in sorted(fleet.items()):
        key = f"{role}-p{pid}"
        if proc["segments"]:
            newest = proc["segments"][-1]
            meta[key] = {
                "role": role, "pid": pid,
                "host_index": newest.get("host_index"),
                "seq": newest.get("seq"),
                "t": newest.get("t"),
                "heartbeat": newest.get("heartbeat", {}),
                "segments": len(proc["segments"]),
                "corrupt": proc["corrupt"],
                "stale": (now - float(newest.get("t") or 0.0)
                          > stale_after_s),
            }
        elif proc["corrupt"]:
            meta[key] = {
                "role": role, "pid": pid, "segments": 0,
                "corrupt": proc["corrupt"],
            }
    return meta


def fleet_snapshot(fleet_dir: str, registry=None,
                   now: "float | None" = None,
                   stale_after_s: float = STALE_GAUGES_AFTER_S,
                   fleet: "dict | None" = None) -> "tuple[dict, dict]":
    """(merged latest snapshot, per-process meta) — the ``--fleet``
    report's payload. A stream whose newest segment is older than
    ``stale_after_s`` keeps its cumulative counters/histograms in the
    merge but NOT its gauges (marked ``stale`` in the meta). Pass a
    pre-read ``fleet`` dict to skip the second read."""
    now = time.time() if now is None else now
    if fleet is None:
        fleet = read_fleet(fleet_dir, registry=registry)
    snaps = []
    for (role, pid), proc in sorted(fleet.items()):
        if not proc["segments"]:
            continue
        newest = proc["segments"][-1]
        snap = newest.get("snapshot", {})
        if now - float(newest.get("t") or 0.0) > stale_after_s:
            snap = _without_gauges(snap)
        snaps.append((f"{role}-p{pid}", snap))
    return merge_snapshots(snaps), fleet_meta(
        fleet, now=now, stale_after_s=stale_after_s
    )


# ---------------------------------------------------------------------------
# Fleet heartbeats
# ---------------------------------------------------------------------------


def check_fleet_heartbeats(fleet_dir: str, max_age_s: float,
                           now: "float | None" = None) -> "tuple[int, str]":
    """The fleet twin of obs_report's --check-heartbeats: 0 every
    process fresh, 1 any stale/wedged — the message names EXACTLY the
    sick process (role + pid) and stays quiet about the healthy
    remainder — 2 no segments at all (blind)."""
    now = time.time() if now is None else now
    fleet = read_fleet(fleet_dir)
    _merged, meta = fleet_snapshot(fleet_dir, now=now, fleet=fleet)
    procs = {k: m for k, m in meta.items() if m.get("segments")}
    if not procs:
        return 2, f"no fleet segments under {fleet_dir}"
    # Memory-pressure blame (ISSUE 19): each process's newest published
    # device.hbm.headroom_frac gauge, read from the SAME segments the
    # freshness verdict uses — a stale-or-wedged process that is also
    # out of HBM gets named as memory-pressured (the usual reason an
    # allocator-thrashing process stops heartbeating).
    headroom: "dict[str, float]" = {}
    for (role, pid), proc in fleet.items():
        if not proc.get("segments"):
            continue
        gauges = (proc["segments"][-1].get("snapshot") or {}).get(
            "gauges", {}
        )
        h = gauges.get("device.hbm.headroom_frac")
        if h is not None:
            headroom[f"{role}-p{pid}"] = float(h)

    def _pressure(key: str) -> str:
        from jama16_retina_tpu.obs import device as device_lib

        h = headroom.get(key)
        if h is not None and h < device_lib.HBM_PRESSURE_HEADROOM:
            return f" [HBM headroom {h:.1%} — memory-pressured]"
        return ""

    stale = []
    for key, m in sorted(procs.items()):
        age = now - float(m.get("t") or 0.0)
        if age > max_age_s:
            stale.append(
                f"{key}: last segment {age:.0f}s old "
                f"(> {max_age_s:.0f}s){_pressure(key)}"
            )
            continue
        prog = (m.get("heartbeat") or {}).get("last_progress_t")
        if prog and now - float(prog) > max_age_s:
            stale.append(
                f"{key}: segments fresh but no step progress for "
                f"{now - float(prog):.0f}s (> {max_age_s:.0f}s) — "
                f"wedged?{_pressure(key)}"
            )
    if stale:
        return 1, "\n".join(stale)
    return 0, "\n".join(
        f"{key}: ok (step {(m.get('heartbeat') or {}).get('step')}, "
        f"segment {now - float(m.get('t') or 0.0):.0f}s old)"
        f"{_pressure(key)}"
        for key, m in sorted(procs.items())
    )


# ---------------------------------------------------------------------------
# Stitched traces
# ---------------------------------------------------------------------------


def stitch_trace(fleet_dir: str) -> list:
    """ONE Chrome trace from every process's published rings: each
    process's events keep their pid lane; timestamps shift from the
    process-private perf_counter epoch onto a shared axis via the
    published ``epoch_unix`` (earliest process = t 0). Per-lane
    ``process_name`` metadata events label the lanes ``role-p<pid>``
    so Perfetto reads like the fleet table."""
    sources = []
    if not os.path.isdir(fleet_dir):
        return []
    for n in sorted(os.listdir(fleet_dir)):
        if not _PROC_DIR_RE.match(n):
            continue
        p = os.path.join(fleet_dir, n, "trace.json")
        if not os.path.exists(p):
            continue
        try:
            with open(p, encoding="utf-8") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        meta = doc.get("meta", {})
        events = [e for e in doc.get("traceEvents", ())
                  if isinstance(e, dict)]
        if events:
            sources.append((meta, events))
    if not sources:
        return []
    base = min(float(m.get("epoch_unix", 0.0)) for m, _e in sources)
    out = []
    for meta, events in sources:
        shift_us = (float(meta.get("epoch_unix", 0.0)) - base) * 1e6
        pid = int(meta.get("pid", 0))
        out.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": f"{meta.get('role', 'proc')}-p{pid}"},
        })
        for e in events:
            ev = dict(e)
            ev["ts"] = round(float(e.get("ts", 0.0)) + shift_us, 3)
            out.append(ev)
    out.sort(key=lambda e: (e.get("ph") != "M", e.get("ts", 0.0)))
    return out


# ---------------------------------------------------------------------------
# Fleet-scope rule evaluation (plain grammar replay + burn-rate form)
# ---------------------------------------------------------------------------


def _counter_delta(timeline: list, name: str, window_s: float,
                   now: float) -> "tuple[float, float] | None":
    """(delta, dt) of a merged counter over the trailing window, read
    off the merged timeline (newest point minus the newest point at or
    before ``now - window_s``; shorter history uses what exists).
    None = fewer than two points carry the counter (no rate yet)."""
    pts = [(t, snap["counters"][name]) for t, snap in timeline
           if name in snap.get("counters", {})]
    if len(pts) < 2:
        return None
    t1, v1 = pts[-1]
    cutoff = now - window_s
    t0, v0 = pts[0]
    for t, v in pts:
        if t <= cutoff:
            t0, v0 = t, v
        else:
            break
    if t1 <= t0:
        return None
    return (v1 - v0, t1 - t0)


def evaluate_burn(timeline: list, rule, now: "float | None" = None) -> dict:
    """One multi-window burn-rate evaluation over the merged timeline.

    The SRE multi-window discipline: the bad/total ratio must breach
    over BOTH the long window (sustained budget burn, not a blip) and
    the short window (still happening NOW, not a resolved incident
    paging an hour late). Returns {"firing": bool, "long": r|None,
    "short": r|None}; a window whose total delta is zero (or with no
    history) is no-data ⇒ not firing."""
    from jama16_retina_tpu.obs import alerts as alerts_lib

    now = time.time() if now is None else now
    ratios = {}
    for key, window in (("long", rule.long_s), ("short", rule.short_s)):
        bad = _counter_delta(timeline, rule.bad, window, now)
        total = _counter_delta(timeline, rule.total, window, now)
        if bad is None or total is None or total[0] <= 0:
            ratios[key] = None
            continue
        ratios[key] = max(0.0, bad[0]) / total[0]
    op = alerts_lib._OPS[rule.op]
    firing = all(
        ratios[k] is not None and op(ratios[k], rule.threshold)
        for k in ("long", "short")
    )
    return {"firing": firing, "long": ratios["long"],
            "short": ratios["short"]}


def _append_jsonl(path: str, rec: dict) -> None:
    """One alert record into the fleet's own JSONL (RunLog shape,
    without RunLog — whose lazy open imports jax for the process
    index; the aggregator is an operator CLI that must stay light)."""
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(rec) + "\n")


class _MergedRegistry(registry_lib.Registry):
    """A Registry whose snapshot() IS the merged fleet snapshot — what
    lets the PR-4 FlightRecorder dump fleet state through its normal
    registry seam (its prune counter still lands on a live registry)."""

    def __init__(self, merged: dict):
        super().__init__()
        self._merged = merged

    def snapshot(self) -> dict:  # noqa: D102 - see class docstring
        return self._merged


def evaluate_fleet(fleet_dir: str, rules, now: "float | None" = None,
                   write: bool = True,
                   fleet: "dict | None" = None) -> "tuple[list, dict]":
    """Evaluate fleet-scope rules over the merged timeline; returns
    (firing list, merged latest snapshot).

    Plain-grammar rules replay through an ordinary AlertManager over
    the merged snapshot sequence (``for S``/rate() semantics ride the
    segment history); ``burn()`` rules evaluate via the multi-window
    deltas. Transitions against the persisted sealed state artifact
    (``fleet-alerts.json``) write the standard ``alert`` record into
    ``<fleet_dir>/fleet.jsonl`` and — for NEW firings — one blackbox
    dump of the merged fleet state through the PR-4 FlightRecorder; a
    rule that keeps firing across cron invocations writes/dumps
    nothing new (the state artifact is the cross-invocation dedupe the
    per-run dump cap cannot provide)."""
    from jama16_retina_tpu.obs import alerts as alerts_lib

    now = time.time() if now is None else now
    if fleet is None:
        fleet = read_fleet(fleet_dir)
    timeline = merged_timeline(fleet)
    merged = timeline[-1][1] if timeline else merge_snapshots([])
    plain = [r for r in rules
             if not isinstance(r, alerts_lib.BurnRule)]
    burn = [r for r in rules if isinstance(r, alerts_lib.BurnRule)]
    firing: list = []
    if plain and timeline:
        mgr = alerts_lib.AlertManager(
            plain, registry=registry_lib.Registry()
        )
        fired: list = []
        for t, snap in timeline:
            fired = mgr.evaluate(snapshot=snap, now=t)
        firing.extend(fired)
    for rule in burn:
        verdict = evaluate_burn(timeline, rule, now=now)
        if verdict["firing"]:
            firing.append({
                "rule": rule.name, "metric": rule.name,
                "value": verdict["short"], "threshold": rule.threshold,
                "for_s": rule.long_s, "reason": rule.reason,
                "long": verdict["long"], "short": verdict["short"],
            })
    if write:
        _record_transitions(fleet_dir, firing, merged, now)
    return firing, merged


def _record_transitions(fleet_dir: str, firing: list, merged: dict,
                        now: float) -> None:
    """Diff the current firing set against the sealed state artifact;
    write alert records (+ one dump per NEW firing) only for actual
    transitions, then republish the state."""
    state_path = os.path.join(fleet_dir, "fleet-alerts.json")
    prev_firing: dict = {}
    if os.path.exists(state_path):
        try:
            doc, _seal = artifact_lib.read_sealed_json(
                state_path, artifact="fleet_alerts"
            )
            prev_firing = dict(doc.get("firing", {}))
        except Exception:  # noqa: BLE001 - a torn state file must not
            prev_firing = {}  # block alerting; transitions re-fire once
    cur = {f["rule"]: f for f in firing}
    jsonl = os.path.join(fleet_dir, "fleet.jsonl")
    new_rules = [name for name in cur if name not in prev_firing]
    resolved = [name for name in prev_firing if name not in cur]
    for name in new_rules:
        f = cur[name]
        _append_jsonl(jsonl, {
            "kind": "alert", "t": round(now, 3), "rule": name,
            "state": "firing", "metric": f.get("metric"),
            "value": (round(f["value"], 6)
                      if isinstance(f.get("value"), float) else
                      f.get("value")),
            "threshold": f.get("threshold"), "reason": f.get("reason"),
            "scope": "fleet",
        })
    for name in resolved:
        _append_jsonl(jsonl, {
            "kind": "alert", "t": round(now, 3), "rule": name,
            "state": "resolved", "reason": prev_firing[name],
            "scope": "fleet",
        })
    if new_rules:
        from jama16_retina_tpu.obs import flightrec

        flight = flightrec.FlightRecorder(
            fleet_dir,
            config={"scope": "fleet", "rules": sorted(cur)},
            registry=_MergedRegistry(merged),
            # Fleet-scope dumps diagnose over the STITCHED trace — the
            # cross-lane waterfalls (server lane -> consumer lane) are
            # exactly what a burn-rate firing needs explained
            # (ISSUE 18).
            events_fn=lambda: stitch_trace(fleet_dir),
        )
        # One dump per NEW firing RULE: FlightRecorder dedupes by
        # reason string, so two rules sharing the default reason must
        # get distinct keys or the second rule's firing-time state
        # would be silently skipped.
        seen_reasons: set = set()
        for i, name in enumerate(sorted(new_rules)):
            reason = cur[name].get("reason") or "slo_burn"
            if reason in seen_reasons:
                reason = f"{reason}_{i}"
            seen_reasons.add(reason)
            flight.dump(reason, rule=name, scope="fleet")
    if new_rules or resolved or not os.path.exists(state_path):
        artifact_lib.write_sealed_json(state_path, {
            "kind": "fleet_alerts",
            "t": round(now, 3),
            "firing": {name: f.get("reason") for name, f in cur.items()},
        }, schema=STATE_SCHEMA, version=STATE_VERSION)
