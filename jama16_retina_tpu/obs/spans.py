"""Timing spans + per-window stall attribution for the train loops.

``span(name)`` is the one-liner every layer uses to time a block into a
histogram. The design constraint carried over from the registry: a
DISABLED registry must cost one branch — ``span`` returns a shared
no-op context manager without allocating, so sprinkling spans through
hot paths is free when telemetry is off.

Event-tracing upgrade (ISSUE 4): when the process tracer
(obs/trace.py) is enabled, the SAME ``span()`` call sites additionally
emit a Chrome 'X' (complete) trace event — no call-site changes, and
the both-disabled path is still the shared no-op. ``StallClock``
segments get the same treatment: each measured ``trainer.<kind>``
segment lands in the event timeline, so a step's input-wait/dispatch
decomposition is visible per step in Perfetto, not just as cross-window
quantiles.

``StallClock`` is the trainer's per-log-window stall attribution
(ISSUE 3 tentpole): the wall time of a logging window decomposes into

    input_wait  — blocked in ``next(batches)``: the pipeline-fed gap
                  (BENCH_r05's 10x) measured where it actually bites,
    dispatch    — issuing the jit train step (async dispatch, so this
                  is queue pressure, not device compute),
    pause       — eval/persist blocks between steps,
    save        — checkpoint fetch + write blocking the loop (ISSUE 11:
                  split from ``pause`` so the async-save reclaim is a
                  first-class number),
    other       — everything else (host-side Python, logging).

The five fields land in the existing ``train`` JSONL records next to
``images_per_sec_window`` and MUST sum to ``window_sec`` (the segments
are disjoint sub-intervals of one monotonic window, so ``other`` is the
exact remainder — pinned by tests/test_obs.py). A window dominated by
``input_wait`` says "feed the chip" (tiered/hbm loader, more decode
workers); one dominated by ``pause`` says "space out evals/saves"
(train.save_every_evals); see docs/OBSERVABILITY.md.
"""

from __future__ import annotations

import time

from jama16_retina_tpu.obs import registry as registry_lib
from jama16_retina_tpu.obs import trace as trace_lib


class _Span:
    __slots__ = ("_hist", "_tracer", "_name", "_t0")

    def __init__(self, hist, tracer, name):
        self._hist = hist
        self._tracer = tracer
        self._name = name

    def __enter__(self) -> "_Span":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        t1 = time.perf_counter()
        if self._hist is not None:
            self._hist.observe(t1 - self._t0)
        if self._tracer is not None:
            self._tracer.complete(self._name, self._t0, t1)


class _NoopSpan:
    __slots__ = ()

    def __enter__(self) -> "_NoopSpan":
        return self

    def __exit__(self, *exc) -> None:
        pass


_NOOP = _NoopSpan()


def span(name: str, registry: "registry_lib.Registry | None" = None,
         buckets=registry_lib.DEFAULT_BUCKETS,
         tracer: "trace_lib.Tracer | None" = None):
    """Context manager timing its block into histogram ``name``
    (seconds) AND — when the tracer is enabled — into the event
    timeline as a complete event of the same name. Both disabled ->
    the shared no-op (one branch each, no allocation)."""
    reg = registry if registry is not None else registry_lib.default_registry()
    tr = tracer if tracer is not None else trace_lib.default_tracer()
    reg_on = reg.enabled
    tr_on = tr.enabled
    if not reg_on and not tr_on:
        return _NOOP
    return _Span(
        reg.histogram(name, buckets=buckets) if reg_on else None,
        tr if tr_on else None,
        name,
    )


class StallClock:
    """Per-log-window stall attribution shared by the train loops.

    ``add(kind, dt)`` accumulates one measured segment; ``fields()``
    returns the window's attribution dict and resets. When a registry
    is attached, each segment also feeds a ``trainer.<kind>_s``
    histogram so the periodic telemetry snapshot carries cross-window
    quantiles (a single slow ``next(batches)`` shows up in p99 even
    when the window average looks healthy). When the tracer is enabled,
    every segment — ``measure()`` context or direct ``add()`` —
    additionally lands in the event timeline as ``trainer.<kind>``
    (per-step causality, ISSUE 4).
    """

    KINDS = ("input", "dispatch", "pause", "save")

    def __init__(self, registry: "registry_lib.Registry | None" = None,
                 tracer: "trace_lib.Tracer | None" = None):
        self._reg = registry
        self._hists = {}
        if registry is not None:
            self._hists = {
                k: registry.histogram(
                    f"trainer.{k}_s",
                    help="per-segment stall attribution of the train "
                         "loop (input/dispatch/pause/save), cross-"
                         "window quantiles",
                ) for k in self.KINDS
            }
        self._tracer = (
            tracer if tracer is not None else trace_lib.default_tracer()
        )
        self._trace_names = {k: f"trainer.{k}" for k in self.KINDS}
        now = time.perf_counter()
        self._window_start = now
        self._acc = dict.fromkeys(self.KINDS, 0.0)

    def add(self, kind: str, dt: float, t0: "float | None" = None) -> None:
        """Accumulate one measured segment. When the tracer is enabled
        the segment also lands in the event timeline — ``t0`` (the
        segment's perf_counter start) makes the event exact; without it
        the segment is anchored as ending now, which is what every
        direct ``add('pause', dt)`` call site does anyway (they add at
        pause end)."""
        self._acc[kind] += dt
        h = self._hists.get(kind)
        if h is not None:
            h.observe(dt)
        tr = self._tracer
        if tr.enabled:
            t1 = (t0 + dt) if t0 is not None else time.perf_counter()
            tr.complete(self._trace_names[kind], t1 - dt, t1)

    def measure(self, kind: str):
        """``with stalls.measure('input'): batch = next(batches)``"""
        return _StallSegment(self, kind)

    def fields(self) -> dict:
        """The window's attribution, summing to window_sec by
        construction; resets the window. Rounded AFTER computing the
        remainder so the published fields stay self-consistent to the
        rounding precision."""
        now = time.perf_counter()
        wall = now - self._window_start
        # Segments are disjoint sub-intervals of [window_start, now),
        # so their sum cannot exceed wall; clamp anyway against float
        # accumulation error at very short windows.
        other = max(0.0, wall - sum(self._acc.values()))
        out = {
            "window_sec": round(wall, 4),
            "input_wait_sec": round(self._acc["input"], 4),
            "dispatch_sec": round(self._acc["dispatch"], 4),
            "pause_sec": round(self._acc["pause"], 4),
            # Checkpoint-save stall (ISSUE 11): the slice of 'pause' that
            # is checkpoint I/O, split out so the async-save win — and
            # any regression — is attributable. train.async_save drives
            # this toward 0 (the fetch+write runs off-loop).
            "save_sec": round(self._acc["save"], 4),
            "other_sec": round(other, 4),
        }
        self._window_start = now
        self._acc = dict.fromkeys(self.KINDS, 0.0)
        return out


class _StallSegment:
    __slots__ = ("_clock", "_kind", "_t0")

    def __init__(self, clock: StallClock, kind: str):
        self._clock = clock
        self._kind = kind

    def __enter__(self) -> "_StallSegment":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self._clock.add(
            self._kind, time.perf_counter() - self._t0, self._t0
        )
