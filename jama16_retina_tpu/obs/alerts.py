"""Declarative SLO / quality alert rules over registry snapshots
(ISSUE 5).

The drift monitor (obs/quality.py) publishes judgment as gauges; this
module turns gauges into ACTIONS. A rule is

    metric OP threshold [for SECONDS] [-> reason]

e.g. ``quality.score_psi > 0.2 for 120 -> quality_drift`` or
``serve.request_latency_s.p99 > 0.5 for 60``. Rules are evaluated
against successive ``Registry.snapshot()`` dicts — normally at the
Snapshotter's flush cadence, so alerting rides the existing telemetry
heartbeat with no extra thread. Metric references resolve against
gauges, then counters, then ``<histogram>.{p50,p95,p99,mean,count}``;
``rate(counter)`` is the burn-rate form — the counter's per-second
delta between consecutive snapshots (undefined on the first snapshot,
so rate rules never fire cold).

``for SECONDS`` is the Prometheus semantics: the condition must hold
CONTINUOUSLY for that long before the rule transitions to FIRING. On
the transition the manager

  * writes one ``alert`` JSONL record (state=firing) through the run's
    RunLog — and one more (state=resolved) when the condition clears;
  * trips the flight recorder with the rule's ``reason``
    (``quality_drift`` for the built-in drift/canary rules,
    ``slo_breach`` for user rules by default) — PR 4's machinery caps
    that at ONE dump per reason per run, so a persistently-firing rule
    cannot fill the disk with black boxes;
  * increments ``obs.alerts_fired``.

A metric that does not exist in the snapshot makes the rule INACTIVE
(condition false): quality rules are safe to install unconditionally —
they only arm once the monitor starts publishing.
"""

from __future__ import annotations

import dataclasses
import operator
import re
import time

from absl import logging as absl_logging

from jama16_retina_tpu.obs import registry as registry_lib

_OPS = {
    ">": operator.gt, ">=": operator.ge,
    "<": operator.lt, "<=": operator.le,
    "==": operator.eq, "!=": operator.ne,
}

_HIST_FIELDS = ("p50", "p95", "p99", "mean", "count", "sum")

_RULE_RE = re.compile(
    r"^\s*(?P<metric>rate\([A-Za-z0-9_.]+\)|[A-Za-z0-9_.]+)\s*"
    r"(?P<op>>=|<=|==|!=|>|<)\s*"
    r"(?P<threshold>[-+]?[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?)\s*"
    r"(?:for\s+(?P<for>[0-9]*\.?[0-9]+)\s*s?)?\s*"
    r"(?:->\s*(?P<reason>[A-Za-z0-9_]+))?\s*$"
)


@dataclasses.dataclass(frozen=True)
class AlertRule:
    metric: str
    op: str
    threshold: float
    for_seconds: float = 0.0
    reason: str = "slo_breach"

    @property
    def name(self) -> str:
        txt = f"{self.metric}{self.op}{self.threshold:g}"
        if self.for_seconds:
            txt += f" for {self.for_seconds:g}s"
        return txt


_BURN_RE = re.compile(
    r"^\s*burn\(\s*(?P<bad>[A-Za-z0-9_.]+)\s*/\s*(?P<total>[A-Za-z0-9_.]+)"
    r"\s*,\s*(?P<long>[0-9]*\.?[0-9]+)\s*s?\s*,"
    r"\s*(?P<short>[0-9]*\.?[0-9]+)\s*s?\s*\)\s*"
    r"(?P<op>>=|<=|==|!=|>|<)\s*"
    r"(?P<threshold>[-+]?[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?)\s*"
    r"(?:->\s*(?P<reason>[A-Za-z0-9_]+))?\s*$"
)


@dataclasses.dataclass(frozen=True)
class BurnRule:
    """Multi-window SLO burn-rate rule (ISSUE 15):

        burn(bad_counter/total_counter, LONG, SHORT) OP threshold
            [-> reason]

    e.g. ``burn(serve.shed.deadline/serve.router.rows, 300, 60) > 0.02
    -> slo_burn``. The bad/total counter-delta RATIO must satisfy the
    condition over BOTH trailing windows — the long one proves the
    error budget is burning sustainedly (not a blip), the short one
    proves it is still burning NOW (not a resolved incident paging an
    hour late): the SRE multi-window multi-burn-rate discipline.
    Evaluated by the FLEET aggregator only (obs/fleet.evaluate_burn)
    over merged counter deltas — no single process holds the fleet
    totals, which is the point."""

    bad: str
    total: str
    long_s: float
    short_s: float
    op: str
    threshold: float
    reason: str = "slo_burn"

    @property
    def name(self) -> str:
        return (f"burn({self.bad}/{self.total},{self.long_s:g},"
                f"{self.short_s:g}){self.op}{self.threshold:g}")


def parse_fleet_rule(text: str) -> "AlertRule | BurnRule":
    """One fleet-scope rule: the ``burn()`` multi-window form, or any
    rule of the plain grammar (evaluated over MERGED snapshots, where
    a summed gauge/counter can cross thresholds no single process
    reaches). Raises on anything it cannot parse completely."""
    m = _BURN_RE.match(text)
    if m:
        long_s = float(m.group("long"))
        short_s = float(m.group("short"))
        if short_s >= long_s:
            raise ValueError(
                f"burn rule {text!r}: the short window ({short_s:g}s) "
                f"must be shorter than the long window ({long_s:g}s) — "
                "equal windows degenerate to a single-window rule"
            )
        return BurnRule(
            bad=m.group("bad"), total=m.group("total"),
            long_s=long_s, short_s=short_s,
            op=m.group("op"), threshold=float(m.group("threshold")),
            reason=m.group("reason") or "slo_burn",
        )
    return parse_rule(text)


def fleet_rules(cfg) -> list:
    """The fleet-scope rule set one ExperimentConfig implies: every
    ``obs.fleet_rules`` string through :func:`parse_fleet_rule`.
    Separate from quality_rules/reliability_rules because these are
    evaluated by the AGGREGATOR over merged fleet snapshots, never by
    a process-local AlertManager."""
    return [parse_fleet_rule(text)
            for text in getattr(cfg.obs, "fleet_rules", ()) or ()]


def parse_rule(text: str) -> AlertRule:
    """One rule from the declarative grammar above; raises on anything
    it cannot parse COMPLETELY (a half-understood alert rule is worse
    than none)."""
    m = _RULE_RE.match(text)
    if not m:
        raise ValueError(
            f"cannot parse alert rule {text!r}; expected "
            "'metric OP threshold [for SECONDS] [-> reason]', e.g. "
            "'quality.score_psi > 0.2 for 120 -> quality_drift'"
        )
    if m.group("op") not in _OPS:  # pragma: no cover - regex pins these
        raise ValueError(f"unknown operator in alert rule {text!r}")
    return AlertRule(
        metric=m.group("metric"),
        op=m.group("op"),
        threshold=float(m.group("threshold")),
        for_seconds=float(m.group("for") or 0.0),
        reason=m.group("reason") or "slo_breach",
    )


def quality_rules(qcfg) -> list:
    """The rule set one QualityConfig implies: the built-in drift/canary
    triad when the monitor is enabled (all reason=quality_drift — the
    flight-recorder trigger the acceptance pins), plus every user rule
    string. Empty when quality is off and no user rules exist."""
    rules: list = []
    if getattr(qcfg, "enabled", False):
        f = float(getattr(qcfg, "alert_for_s", 0.0))
        rules += [
            AlertRule("quality.score_psi", ">", float(qcfg.psi_alert),
                      for_seconds=f, reason="quality_drift"),
            AlertRule("quality.input_psi_max", ">",
                      float(qcfg.input_psi_alert),
                      for_seconds=f, reason="quality_drift"),
            AlertRule("quality.canary_ok", "<", 1.0,
                      for_seconds=f, reason="quality_drift"),
        ]
    for text in getattr(qcfg, "alert_rules", ()) or ():
        rules.append(parse_rule(text))
    return rules


def reliability_rules(cfg) -> list:
    """The reliability rule set one ExperimentConfig implies (ISSUE 6).

    Shedding thresholds are EXPRESSED as alert rules over the exact
    gauges the MicroBatcher's shed decision reads
    (``serve.batcher.{queue_depth,in_flight}``), so "we are shedding"
    and "we are alerting" can never disagree; the quarantine rule
    reads the data plane's ``data.quarantined`` burn rate (one poison
    record is routine, a sustained stream is systemic rot); the reload
    rule fires on any rejected rollout. Rules over metrics that never
    get published are inactive — installing these unconditionally
    costs nothing on runs that never shed/quarantine/reload."""
    rules: list = []
    sc = getattr(cfg, "serve", None)
    oc = getattr(cfg, "obs", None)
    if sc is not None:
        if sc.shed_queue_depth > 0:
            rules.append(AlertRule(
                "serve.batcher.queue_depth", ">=",
                float(sc.shed_queue_depth), reason="overload_shed",
            ))
        if sc.shed_in_flight > 0:
            rules.append(AlertRule(
                "serve.batcher.in_flight", ">=",
                float(sc.shed_in_flight), reason="overload_shed",
            ))
    per_s = float(getattr(oc, "quarantine_alert_per_s", 0.0) or 0.0)
    if per_s > 0:
        rules.append(AlertRule(
            "rate(data.quarantined)", ">", per_s, reason="data_quarantine",
        ))
    rules.append(AlertRule(
        "rate(serve.reload_rejected)", ">", 0.0, reason="reload_rejected",
    ))
    # Front-door router (ISSUE 12): sustained dispatch imbalance means
    # the policy (or a sick replica) is concentrating load; a latched
    # scaler-saturation gauge means demand wants more replicas than
    # serve.scaler_max_replicas allows. Both are inactive until the
    # router publishes its gauges.
    rules.append(AlertRule(
        "serve.router.imbalance", ">", 3.0, for_seconds=60.0,
        reason="router_imbalance",
    ))
    rules.append(AlertRule(
        "serve.scaler.saturated", ">=", 1.0, for_seconds=120.0,
        reason="scaler_saturated",
    ))
    # Durable-state integrity (ISSUE 13): ANY detected artifact
    # corruption (a sealed checksum or seal sidecar failing on load)
    # pages — silent on-disk rot is the failure mode the stack cannot
    # otherwise see. Inactive until integrity.corrupt first counts.
    rules.append(AlertRule(
        "rate(integrity.corrupt)", ">", 0.0, reason="artifact_corrupt",
    ))
    # Device-utilization plane (ISSUE 19): sustained low HBM headroom
    # on the tightest local device pages BEFORE the allocator OOMs —
    # the gauge is the DeviceMonitor's worst-device view. Inactive on
    # backends without memory_stats (the gauge never publishes).
    headroom = float(getattr(oc, "device_hbm_headroom_alert", 0.0) or 0.0)
    if headroom > 0:
        rules.append(AlertRule(
            "device.hbm.headroom_frac", "<", headroom,
            for_seconds=60.0, reason="hbm_pressure",
        ))
    return rules


def manager_for(cfg, workdir: str, registry=None,
                on_fire=None) -> "AlertManager | None":
    """The AlertManager a TRAINERLESS process (serving session, batch
    predict) hangs on its Snapshotter: the rules ``cfg.obs.quality``
    implies, wired to a fresh FlightRecorder over ``workdir`` so a
    firing rule writes `alert` records AND trips its
    ``quality_drift``/``slo_breach`` blackbox dump (one per reason per
    run) exactly like a train run. None when obs is off or the config
    implies no rules. One copy of this wiring — the trainer keeps its
    own because its FlightRecorder carries profiler capture hooks and
    step/loss sentinels no serving process has."""
    from jama16_retina_tpu.obs import flightrec

    if not cfg.obs.enabled:
        return None
    rules = quality_rules(cfg.obs.quality) + reliability_rules(cfg)
    if not rules:
        return None
    flight = flightrec.FlightRecorder(
        workdir,
        config=dataclasses.asdict(cfg),
        registry=registry,
        blackbox_events=cfg.obs.blackbox_events,
        # No step loop to watch in a serving/predict process.
        slow_step_factor=float("inf"),
        blackbox_keep=cfg.obs.blackbox_keep,
    )
    return AlertManager(rules, registry=registry, flight=flight,
                        on_fire=on_fire)


def resolve_metric(snapshot: dict, metric: str,
                   prev: "dict | None" = None,
                   dt: "float | None" = None) -> "float | None":
    """A rule's metric reference against one snapshot; None = no data.
    ``prev``/``dt`` feed the rate() form (previous snapshot and the
    seconds between them)."""
    if metric.startswith("rate(") and metric.endswith(")"):
        inner = metric[len("rate("):-1]
        if prev is None or not dt or dt <= 0:
            return None
        cur_v = snapshot.get("counters", {}).get(inner)
        prev_v = prev.get("counters", {}).get(inner)
        if cur_v is None or prev_v is None:
            return None
        return (cur_v - prev_v) / dt
    gauges = snapshot.get("gauges", {})
    if metric in gauges:
        return float(gauges[metric])
    counters = snapshot.get("counters", {})
    if metric in counters:
        return float(counters[metric])
    base, _, field = metric.rpartition(".")
    if field in _HIST_FIELDS:
        h = snapshot.get("histograms", {}).get(base)
        if h is not None and h.get(field) is not None:
            return float(h[field])
    return None


def rule_holds(rule: AlertRule, snapshot: dict) -> bool:
    """One stateless evaluation of a rule's CONDITION against one
    snapshot — no `for` latching, no rate() history. The lifecycle
    WATCH phase uses this to probe its regression rules at its own
    cadence; a missing metric is False (no evidence, no regression)."""
    value = resolve_metric(snapshot, rule.metric)
    return value is not None and _OPS[rule.op](value, rule.threshold)


class _RuleState:
    __slots__ = ("since", "firing")

    def __init__(self):
        self.since: "float | None" = None
        self.firing = False


class AlertManager:
    """Evaluate a rule set against successive registry snapshots.

    One per process (trainer run or serving session); normally driven
    by the Snapshotter's flush (``export.Snapshotter(alerts=...)``), so
    alert latency == telemetry cadence. ``flight`` is the run's
    FlightRecorder (or None): a rule's firing transition trips
    ``flight.dump(rule.reason)``, deduped per reason per run by PR 4's
    rate limit. Not thread-safe by design — exactly one flush loop
    drives it (the Snapshotter contract).
    """

    def __init__(self, rules, registry: "registry_lib.Registry | None" = None,
                 flight=None, on_fire=None):
        self.rules = [
            r if isinstance(r, AlertRule) else parse_rule(r) for r in rules
        ]
        self._registry = (
            registry if registry is not None
            else registry_lib.default_registry()
        )
        self._flight = flight
        # The action seam (ISSUE 8): ``on_fire(info_dict)`` runs ONCE
        # per rule transition to firing — never re-invoked while the
        # rule stays latched — so alerts become actions (the lifecycle
        # controller's trigger rides here). Callback exceptions are
        # COUNTED (obs.alert_callback_errors) and logged, never raised
        # into the Snapshotter's flush thread: a broken action handler
        # must not kill telemetry export.
        self.on_fire = on_fire
        self._state = {r.name: _RuleState() for r in self.rules}
        self._prev_snapshot: "dict | None" = None
        self._prev_t: "float | None" = None
        self._c_fired = self._registry.counter(
            "obs.alerts_fired",
            help="alert rules that transitioned to firing this run",
        )
        self._c_cb_errors = self._registry.counter(
            "obs.alert_callback_errors",
            help="exceptions raised by the on_fire callback (swallowed; "
                 "the flush thread survives)",
        )

    def evaluate(self, snapshot: "dict | None" = None,
                 now: "float | None" = None, runlog=None) -> list:
        """One evaluation pass; returns the currently-FIRING rules as
        dicts (rule/metric/value/threshold/for_s/reason). ``runlog``
        receives the firing/resolved transition records."""
        now = time.time() if now is None else now
        if snapshot is None:
            snapshot = self._registry.snapshot()
        dt = (now - self._prev_t) if self._prev_t is not None else None
        firing = []
        for rule in self.rules:
            st = self._state[rule.name]
            value = resolve_metric(
                snapshot, rule.metric, prev=self._prev_snapshot, dt=dt
            )
            cond = value is not None and _OPS[rule.op](value, rule.threshold)
            if cond:
                if st.since is None:
                    st.since = now
                held = now - st.since
                if not st.firing and held >= rule.for_seconds:
                    st.firing = True
                    self._c_fired.inc()
                    if runlog is not None:
                        runlog.write(
                            "alert", rule=rule.name, state="firing",
                            metric=rule.metric, value=round(value, 6),
                            threshold=rule.threshold,
                            for_s=round(held, 3), reason=rule.reason,
                        )
                    if self._flight is not None:
                        self._flight.dump(
                            rule.reason, rule=rule.name,
                            metric=rule.metric, value=round(value, 6),
                            threshold=rule.threshold,
                        )
                    if self.on_fire is not None:
                        try:
                            self.on_fire({
                                "rule": rule.name, "metric": rule.metric,
                                "value": value,
                                "threshold": rule.threshold,
                                "for_s": held, "reason": rule.reason,
                            })
                        except Exception as e:  # noqa: BLE001
                            self._c_cb_errors.inc()
                            absl_logging.error(
                                "alert on_fire callback failed for %s: "
                                "%s: %s", rule.name, type(e).__name__, e,
                            )
                if st.firing:
                    firing.append({
                        "rule": rule.name, "metric": rule.metric,
                        "value": value, "threshold": rule.threshold,
                        "for_s": held, "reason": rule.reason,
                    })
            else:
                if st.firing and runlog is not None:
                    runlog.write(
                        "alert", rule=rule.name, state="resolved",
                        metric=rule.metric,
                        value=(round(value, 6) if value is not None
                               else None),
                        reason=rule.reason,
                    )
                st.since = None
                st.firing = False
        self._prev_snapshot = snapshot
        self._prev_t = now
        return firing

    def firing(self) -> list:
        """Rule names currently in the firing state (between evaluates)."""
        return [name for name, st in self._state.items() if st.firing]
