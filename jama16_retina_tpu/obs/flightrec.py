"""Black-box flight recorder: anomaly-triggered state dumps.

Aggregate telemetry (obs/registry.py) and the event trace (obs/trace.py)
are only useful if someone is LOOKING when the bad thing happens —
EfficientNets-in-one-hour-scale training (PAPERS.md) relies on
automatic straggler/anomaly capture precisely because nobody is. The
recorder watches for four trigger shapes and, on any of them, dumps the
process's last moments to ``<workdir>/blackbox/``:

  * unhandled exception escaping the train loop (``record_exception``),
  * SIGTERM / SIGINT (``install_signal_handlers`` converts the signal
    to an in-band exception so the dump runs in NORMAL context — a
    handler that snapshots locked registries directly could deadlock
    against the interrupted frame's own metric lock),
  * non-finite loss (``note_loss``: a cheap ``isfinite`` on the loss
    the log path already fetched to host — no extra device sync),
  * a slow step — wall time above ``slow_step_factor`` × the rolling
    median of recent steps (``note_step_time``: one deque append and
    one comparison per step; the median itself is recomputed only at
    trigger-check cadence over a 64-step window).

Each dump directory holds the last-N trace events (``trace.jsonl``, one
event per line — readable even if the process dies mid-write), the full
registry snapshot (``registry.json``), the run config (``config.json``),
a ``meta.json`` (reason/step/time/dropped-events) and — with
``obs.diagnosis_enabled`` (default) — a ``diagnosis.json``: the
critical-path analyzer's typed verdict + evidence fractions + exemplar
waterfalls over the dumped events (obs/criticalpath.py; ISSUE 18), with
the matching ``obs.diagnosis.{verdict,confidence}`` gauges published so
alert rules can read what the dump concluded. Dumps never
touch the run's JSONL (RunLog stays owned by the trainer), are
rate-limited to one per reason per run, and anomaly triggers can
additionally request ONE short ``jax.profiler`` capture per run through
``profile_hook`` (the trainer wires ``_ProfilerWindow.arm``).
"""

from __future__ import annotations

import json
import math
import os
import signal
import statistics
import threading
import time
from collections import deque

import numpy as np

from jama16_retina_tpu.integrity import artifact as artifact_lib
from jama16_retina_tpu.obs import registry as registry_lib
from jama16_retina_tpu.obs import trace as trace_lib


class FlightRecorder:
    """One per run. ``enabled=False`` turns every hook into one branch.

    ``config`` is any JSON-serializable mapping (the trainer passes
    ``dataclasses.asdict(cfg)``); ``profile_hook`` is a zero-arg
    callable invoked at most ONCE per run on NaN/slow-step anomalies.
    """

    STEP_WINDOW = 64          # rolling-median sample size
    MIN_STEP_SAMPLES = 16     # no slow-step verdicts before this many

    def __init__(
        self,
        workdir: str,
        config: "dict | None" = None,
        registry: "registry_lib.Registry | None" = None,
        tracer: "trace_lib.Tracer | None" = None,
        blackbox_events: int = 1024,
        slow_step_factor: float = 4.0,
        profile_hook=None,
        enabled: bool = True,
        blackbox_keep: int = 20,
        diagnosis: bool = True,
        diagnosis_top_k: int = 3,
        events_fn=None,
    ):
        self.enabled = bool(enabled)
        self.workdir = workdir
        self.blackbox_dir = os.path.join(workdir, "blackbox")
        self._config = config or {}
        self._registry = (
            registry if registry is not None
            else registry_lib.default_registry()
        )
        self._tracer = (
            tracer if tracer is not None else trace_lib.default_tracer()
        )
        self.blackbox_events = int(blackbox_events)
        self.slow_step_factor = float(slow_step_factor)
        # Cross-run dump cap (ISSUE 13 satellite): one-per-reason-per-
        # run still grows without bound on a long-lived supervisor
        # restarting runs; after every dump the OLDEST dump dirs beyond
        # ``blackbox_keep`` are pruned (<= 0 disables the cap).
        self.blackbox_keep = int(blackbox_keep)
        # Dump-time diagnosis (ISSUE 18): run the pure critical-path
        # analyzer over the dumped events, write diagnosis.json beside
        # them and publish obs.diagnosis.{verdict,confidence} gauges.
        # Analysis happens ONLY inside dump() — the hot-path hooks
        # never pay for it.
        self.diagnosis = bool(diagnosis)
        self.diagnosis_top_k = int(diagnosis_top_k)
        # Optional event source override: the fleet aggregator passes a
        # stitched-trace thunk so its dumps diagnose across every lane,
        # not just this process's rings.
        self._events_fn = events_fn
        self._profile_hook = profile_hook
        self._profile_fired = False
        self._step_times: deque = deque(maxlen=self.STEP_WINDOW)
        self._step_median: "float | None" = None
        self._steps_since_median = 0
        self._last_step: "int | None" = None
        self._dumped_reasons: set = set()
        self._dump_seq = 0
        self._dump_lock = threading.Lock()
        self._prev_handlers: dict = {}
        self._pending_signal: "int | None" = None
        self.dumps: list[str] = []

    # -- progress context --------------------------------------------------

    def progress(self, step: int) -> None:
        """Latest completed step — dump metadata, one attribute write."""
        self._last_step = int(step)

    # -- anomaly triggers --------------------------------------------------

    def note_loss(self, loss, step: "int | None" = None) -> bool:
        """Cheap non-finite sentinel on an ALREADY-FETCHED loss (scalar
        or per-member array). Returns True when it triggered a dump."""
        if not self.enabled:
            return False
        arr = np.asarray(loss, dtype=np.float64)
        if arr.ndim == 0:
            bad = not math.isfinite(float(arr))
        else:
            bad = not np.isfinite(arr).all()
        if not bad:
            return False
        if step is not None:
            self._last_step = int(step)
        dumped = self.dump(
            "nonfinite_loss",
            loss=(repr(float(arr)) if arr.ndim == 0
                  else [repr(float(x)) for x in arr.ravel()[:16]]),
        ) is not None
        self._request_profile()
        return dumped

    def note_step_time(self, dt: float, step: "int | None" = None) -> bool:
        """Straggler detection: ``dt`` (seconds of one loop iteration,
        eval/checkpoint pauses excluded by the caller) against
        ``slow_step_factor`` × the rolling median of the last
        ``STEP_WINDOW`` steps. Steady-state cost: one deque append, one
        compare against a CACHED median (recomputed every 16 appends —
        a 64-sample median shifts slowly), so the trainer can call this
        every step under the 2% tracing-overhead budget."""
        if not self.enabled:
            return False
        st = self._step_times
        triggered = False
        med = self._step_median
        if (med is not None and med > 0
                and dt > self.slow_step_factor * med):
            if step is not None:
                self._last_step = int(step)
            triggered = self.dump(
                "slow_step",
                step_sec=round(dt, 6),
                rolling_median_sec=round(med, 6),
                factor=self.slow_step_factor,
            ) is not None
            self._request_profile()
        st.append(dt)
        self._steps_since_median += 1
        if (self._steps_since_median >= 16
                and len(st) >= self.MIN_STEP_SAMPLES):
            # An anomalously slow step is IN the window it just joined;
            # the median absorbs it (it would take window/2 slow steps
            # to drag the threshold up), so back-to-back stragglers
            # still compare against a healthy baseline.
            self._step_median = statistics.median(st)
            self._steps_since_median = 0
        return triggered

    def record_exception(self, exc: BaseException) -> "str | None":
        """The unhandled-exception / signal trigger: call from the train
        loop's ``except BaseException`` before re-raising."""
        if not self.enabled:
            return None
        sig = self._pending_signal
        if sig is not None:
            self._pending_signal = None
            reason = {
                signal.SIGTERM: "sigterm", signal.SIGINT: "sigint",
            }.get(sig, f"signal_{sig}")
            return self.dump(reason, signal=int(sig))
        if isinstance(exc, KeyboardInterrupt):
            return self.dump("sigint", error=type(exc).__name__)
        return self.dump(
            "exception", error=f"{type(exc).__name__}: {exc}"
        )

    # -- signals -----------------------------------------------------------

    def install_signal_handlers(self) -> None:
        """SIGTERM/SIGINT -> in-band exception in the main thread, so
        the dump happens in the trainer's normal except/finally path
        (never inside async-signal context where a registry or RunLog
        lock may already be held by the interrupted frame). No-op off
        the main thread — signal.signal would raise there."""
        if not self.enabled:
            return
        if threading.current_thread() is not threading.main_thread():
            return

        def _handler(signum, frame):
            self._pending_signal = signum
            # SystemExit unwinds through the loop's except BaseException
            # (which dumps) and its finally (which cleans up), exactly
            # like any other fatal error.
            raise SystemExit(128 + signum)

        for sig in (signal.SIGTERM, signal.SIGINT):
            self._prev_handlers[sig] = signal.signal(sig, _handler)

    def uninstall_signal_handlers(self) -> None:
        if threading.current_thread() is not threading.main_thread():
            return
        for sig, prev in self._prev_handlers.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):  # pragma: no cover
                pass
        self._prev_handlers = {}

    # -- the dump ----------------------------------------------------------

    def _request_profile(self) -> None:
        """At most ONE trigger-driven profiler capture per run: a
        pathological run (every step slow) must not turn the profiler
        into the workload."""
        if self._profile_fired or self._profile_hook is None:
            return
        self._profile_fired = True
        try:
            self._profile_hook()
        except Exception:  # pragma: no cover - capture is best-effort
            pass

    def dump(self, reason: str, **meta) -> "str | None":
        """Write one blackbox dump dir; returns its path, or None when
        disabled / this reason already dumped this run (rate limit: the
        FIRST occurrence carries the interesting state)."""
        if not self.enabled:
            return None
        with self._dump_lock:
            if reason in self._dumped_reasons:
                return None
            self._dumped_reasons.add(reason)
            self._dump_seq += 1
            seq = self._dump_seq
        d = os.path.join(self.blackbox_dir, f"{seq:02d}-{reason}")
        os.makedirs(d, exist_ok=True)
        if self._events_fn is not None:
            try:
                events = list(self._events_fn())
            except Exception:  # pragma: no cover - stitched source gone
                events = self._tracer.events(last_n=self.blackbox_events)
        else:
            events = self._tracer.events(last_n=self.blackbox_events)
        with open(os.path.join(d, "trace.jsonl"), "w") as f:
            for ev in events:
                f.write(json.dumps(ev) + "\n")
        if self.diagnosis:
            self._diagnose_into(d, events)
        artifact_lib.write_json(
            os.path.join(d, "registry.json"), self._registry.snapshot()
        )
        artifact_lib.write_json(
            os.path.join(d, "config.json"), self._config, default=str
        )
        artifact_lib.write_json(os.path.join(d, "meta.json"), {
            "reason": reason,
            "t": round(time.time(), 3),
            "step": self._last_step,
            "n_trace_events": len(events),
            "trace_events_dropped": self._tracer.dropped(),
            **meta,
        })
        self.dumps.append(d)
        self._prune_blackbox()
        return d

    def _diagnose_into(self, d: str, events: list) -> None:
        """Best-effort dump-time diagnosis (ISSUE 18): the dump must
        land even when the analyzer chokes on exotic events, so this
        never raises. The verdict gauges publish BEFORE the registry
        snapshot is written, so the dump's own registry.json already
        carries them."""
        try:
            from jama16_retina_tpu.obs import criticalpath
            from jama16_retina_tpu.obs import device as device_lib

            # Device-plane refinement (ISSUE 19): when the monitor has
            # published MFU/roofline gauges, a device_bound verdict
            # splits into its typed sub-cause. Reading the registry's
            # latest gauges is exactly the summary obs_report builds
            # from the telemetry record of the same window.
            device = None
            try:
                device = device_lib.summary_from_gauges(
                    self._registry.snapshot()["gauges"]
                )
            except Exception:  # noqa: BLE001 - refinement is optional
                pass
            verdict = criticalpath.diagnose(
                events, top_k=self.diagnosis_top_k, device=device
            )
            self._registry.gauge(
                "obs.diagnosis.verdict",
                help="latest dump-time critical-path verdict as its "
                     "stable numeric code (criticalpath.VERDICT_CODES: "
                     "0 balanced, 1 device, 2 decode, 3 credit, 4 h2d, "
                     "5 queue, 6 device-compute, 7 device-membw, "
                     "8 device-underutilized)",
            ).set(verdict.code)
            self._registry.gauge(
                "obs.diagnosis.confidence",
                help="evidence fraction of the dominant category behind "
                     "the latest obs.diagnosis.verdict (0..1)",
            ).set(verdict.confidence)
            artifact_lib.write_json(
                os.path.join(d, "diagnosis.json"), verdict.as_dict()
            )
        except Exception:  # pragma: no cover - diagnosis is freight
            pass

    def _prune_blackbox(self) -> None:
        """Enforce the cross-run dump cap: keep the ``blackbox_keep``
        NEWEST dump dirs under ``<workdir>/blackbox`` (by mtime —
        per-run seq numbers restart, mtime orders across runs), delete
        the rest oldest-first. Never touches dumps this run just wrote
        unless the cap itself demands it (this run's are the newest).
        Prunes are counted (``obs.blackbox_pruned``) so the GC is
        ledgered like every other deletion (ISSUE 13)."""
        if self.blackbox_keep <= 0:
            return
        try:
            dirs = [
                os.path.join(self.blackbox_dir, n)
                for n in os.listdir(self.blackbox_dir)
            ]
            dirs = sorted(
                (p for p in dirs if os.path.isdir(p)),
                key=os.path.getmtime,
            )
        except OSError:  # pragma: no cover - racing cleanup
            return
        excess = dirs[: max(0, len(dirs) - self.blackbox_keep)]
        if not excess:
            return
        import shutil

        c = self._registry.counter(
            "obs.blackbox_pruned",
            help="blackbox dump directories deleted oldest-first to "
                 "enforce the cross-run obs.blackbox_keep cap",
        )
        for p in excess:
            try:
                shutil.rmtree(p)
                c.inc()
            except OSError:  # pragma: no cover - racing cleanup
                pass
