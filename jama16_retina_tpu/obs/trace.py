"""Event tracing: bounded per-thread ring buffers, Chrome-trace export.

The registry (obs/registry.py) answers "how much / how often"; this
module answers "what was the process doing, in order" — the event-level
timeline the tf.data paper (PAPERS.md) shows bottleneck diagnosis needs,
and the raw material the flight recorder (obs/flightrec.py) dumps when
something goes wrong. Design constraints, in the registry's order:

  * HOT-PATH CHEAP. Recording an event is one enabled-check, one
    ``time.perf_counter()``, and one ring-slot assignment in a buffer
    OWNED by the recording thread — no lock, no allocation beyond the
    event tuple, no I/O. The cost is pinned by bench.py's
    ``tracing_overhead_pct`` guard (same ≤2% budget as the telemetry
    pin) and the per-op bound in tests/test_bench_guard.py.
  * DISABLED == ONE BRANCH. Every record op checks ``enabled`` first;
    ``span()``/``StallClock`` call sites (obs/spans.py) upgrade to
    trace events with NO call-site changes and keep their shared-no-op
    disabled path.
  * BOUNDED BY CONSTRUCTION. Each thread's ring holds at most
    ``buffer_events`` events; old events are overwritten, never
    accumulated — a black-box recorder must be safe to leave on for a
    30k-step run. Readers (``events()``) tolerate concurrent writers:
    a torn slot at the wrap frontier is dropped, not crashed on.

Timestamps are ``time.perf_counter()`` seconds (CLOCK_MONOTONIC on
Linux — the same epoch ``time.monotonic()`` reads, which is what the
serve batcher's request segments are stamped with). Export converts to
the Chrome trace-event JSON the Perfetto UI / chrome://tracing load:
``{"traceEvents": [{"name", "ph", "ts"(us), "pid", "tid", ...}]}``.
"""

from __future__ import annotations

import itertools
import json
import os
import threading
import time

# Process-wide request/trace-id source: unique across engines/batchers
# so one merged timeline never aliases two requests.
_ids = itertools.count(1)


class TraceContext:
    """Serializable distributed-trace identity (ISSUE 15).

    A bare process-local counter would be good enough for one
    process's timeline, aliasing the moment two processes' traces are
    stitched into one fleet view. A TraceContext's id is minted
    ``"<origin_pid>-<n>"`` so it is unique ACROSS the fleet, and the
    context serializes to a plain dict (``wire()``/``from_wire``) small
    enough to ride any existing seam: a router request, the lifecycle
    journal's DRIFT_DETECTED entry, a future RPC header. Events in any
    process that carry the same ``trace_id`` arg belong to the same
    logical request/cycle, which is exactly what the stitched Chrome
    trace groups on."""

    __slots__ = ("trace_id", "parent", "origin_pid")

    def __init__(self, trace_id: "str | None" = None,
                 parent: "str | None" = None,
                 origin_pid: "int | None" = None):
        self.origin_pid = (int(origin_pid) if origin_pid is not None
                           else os.getpid())
        self.trace_id = (str(trace_id) if trace_id is not None
                         else f"{self.origin_pid}-{next(_ids)}")
        self.parent = parent

    def child(self, parent: str) -> "TraceContext":
        """Same trace, one nesting level deeper (``parent`` names the
        span the callee's events hang under)."""
        return TraceContext(self.trace_id, parent=parent,
                            origin_pid=self.origin_pid)

    def wire(self) -> dict:
        """The serializable form every propagation seam carries."""
        out = {"trace_id": self.trace_id, "origin_pid": self.origin_pid}
        if self.parent:
            out["parent"] = self.parent
        return out

    @classmethod
    def from_wire(cls, d: "dict | None") -> "TraceContext | None":
        """None-tolerant inverse of ``wire()`` (a seam without a
        context — a legacy journal entry, a bare submit — propagates
        nothing rather than crashing)."""
        if not isinstance(d, dict) or "trace_id" not in d:
            return None
        return cls(trace_id=d["trace_id"], parent=d.get("parent"),
                   origin_pid=d.get("origin_pid"))


def new_context() -> TraceContext:
    return TraceContext()


# Thread-local ambient context: lets a deep callee (the EscalationPool
# behind a CascadeEngine behind a router replica) stamp the request's
# trace_id without threading a parameter through three layers that
# predate distributed tracing.
_ctx_local = threading.local()


def current_context() -> "TraceContext | None":
    return getattr(_ctx_local, "ctx", None)


def set_context(ctx: "TraceContext | None") -> "TraceContext | None":
    """Install ``ctx`` as this thread's ambient context; returns the
    previous one so callers can restore it."""
    prev = getattr(_ctx_local, "ctx", None)
    _ctx_local.ctx = ctx
    return prev


class use_context:
    """``with use_context(ctx): ...`` — scoped ambient-context install
    (None installs nothing and restores nothing: a bin carrying rows
    of several requests has no single context to claim)."""

    __slots__ = ("_ctx", "_prev", "_installed")

    def __init__(self, ctx: "TraceContext | None"):
        self._ctx = ctx
        self._installed = False

    def __enter__(self) -> "use_context":
        if self._ctx is not None:
            self._prev = set_context(self._ctx)
            self._installed = True
        return self

    def __exit__(self, *exc) -> None:
        if self._installed:
            set_context(self._prev)


class _Ring:
    """Fixed-capacity overwrite-oldest event buffer, single-writer.

    Only the owning thread appends; any thread may snapshot. Slot
    assignment is atomic under the GIL, so a reader sees either the old
    or the new event in a slot — never a torn tuple."""

    __slots__ = ("cap", "buf", "n", "tid", "gen")

    def __init__(self, cap: int, tid: int, gen: int):
        self.cap = cap
        self.buf = [None] * cap
        self.n = 0  # events ever appended; n - cap of them overwritten
        self.tid = tid
        self.gen = gen

    def append(self, ev) -> None:
        self.buf[self.n % self.cap] = ev
        self.n += 1

    def snapshot(self) -> "tuple[list, int]":
        """(events oldest-first, dropped_count) — tolerant of a
        concurrent append racing the copy."""
        n = self.n
        buf = list(self.buf)
        if n <= self.cap:
            events = [e for e in buf[:n] if e is not None]
        else:
            i = n % self.cap
            events = [e for e in buf[i:] + buf[:i] if e is not None]
        return events, max(0, n - self.cap)


class _NoopTrace:
    __slots__ = ()

    def __enter__(self) -> "_NoopTrace":
        return self

    def __exit__(self, *exc) -> None:
        pass


_NOOP = _NoopTrace()


class _TraceSpan:
    __slots__ = ("_tracer", "_name", "_args", "_t0")

    def __init__(self, tracer: "Tracer", name: str, args):
        self._tracer = tracer
        self._name = name
        self._args = args

    def __enter__(self) -> "_TraceSpan":
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc) -> None:
        self._tracer.complete(
            self._name, self._t0, time.perf_counter(), self._args
        )


class Tracer:
    """Per-thread ring buffers of (ph, name, t0, dur, args) events.

    ``enabled=False`` reduces every record op to one branch (handles
    and rings stay valid). One process-wide default instance exists
    (``default_tracer``); tests and embedded uses inject their own.
    ``configure()`` re-arms it per run (the trainer's
    ``_obs_begin_run`` twin of ``Registry.reset``).
    """

    # Retained-ring cap: rings are keyed by a unique ring id, NOT by
    # thread ident (idents are REUSED once a thread exits — keying by
    # them would let a new thread clobber a finished thread's ring,
    # losing exactly the history a flight recorder must keep). The cap
    # bounds memory under thread churn by evicting the oldest-
    # registered ring; this codebase's recording threads are long-lived
    # pools, so eviction is a pathological-case guard, not a hot path.
    MAX_RINGS = 256

    def __init__(self, enabled: bool = False, buffer_events: int = 4096):
        self.enabled = enabled
        self.buffer_events = max(1, int(buffer_events))
        self._lock = threading.Lock()  # protects _rings registration only
        self._rings: dict[int, _Ring] = {}
        self._ring_ids = itertools.count()
        self._local = threading.local()
        # Export epoch: ts are published relative to tracer creation so
        # Chrome timelines start near 0 instead of at host uptime.
        # ``epoch_unix`` is the WALL-CLOCK moment of that same epoch —
        # what lets the fleet stitcher (obs/fleet.py) align timelines
        # from different processes (each perf_counter has a private
        # zero) onto one axis.
        self.epoch = time.perf_counter()
        self.epoch_unix = time.time()
        self._gen = 0

    def _ring(self) -> _Ring:
        r = getattr(self._local, "ring", None)
        if r is None or r.gen != self._gen:
            tid = threading.get_ident()
            r = _Ring(self.buffer_events, tid, self._gen)
            with self._lock:
                self._rings[next(self._ring_ids)] = r
                while len(self._rings) > self.MAX_RINGS:
                    # dicts iterate in insertion order: drop the oldest.
                    self._rings.pop(next(iter(self._rings)))
            self._local.ring = r
        return r

    # -- recording (hot path) ---------------------------------------------

    def instant(self, name: str, args: "dict | None" = None) -> None:
        if not self.enabled:
            return
        self._ring().append(("i", name, time.perf_counter(), None, args))

    def complete(self, name: str, t0: float, t1: float,
                 args: "dict | None" = None) -> None:
        """An explicit begin/end pair as one Chrome 'X' (complete)
        event. ``t0``/``t1`` are perf_counter/monotonic seconds the
        CALLER stamped — what lets the serve batcher publish segments
        that sum exactly to its latency histogram's observation."""
        if not self.enabled:
            return
        self._ring().append(("X", name, t0, max(0.0, t1 - t0), args))

    def begin(self, name: str, args: "dict | None" = None) -> None:
        if not self.enabled:
            return
        self._ring().append(("B", name, time.perf_counter(), None, args))

    def end(self, name: str) -> None:
        if not self.enabled:
            return
        self._ring().append(("E", name, time.perf_counter(), None, None))

    def trace(self, name: str, args: "dict | None" = None):
        """Context manager emitting one complete event (the trace twin
        of ``span()``; disabled -> shared no-op, no allocation)."""
        if not self.enabled:
            return _NOOP
        return _TraceSpan(self, name, args)

    # -- control / export --------------------------------------------------

    def configure(self, enabled: "bool | None" = None,
                  buffer_events: "int | None" = None) -> None:
        """Re-arm for a new run: apply knobs and CLEAR every ring (the
        events belong to the previous run). Existing threads lazily
        pick up fresh rings via the generation counter."""
        if enabled is not None:
            self.enabled = bool(enabled)
        if buffer_events is not None:
            self.buffer_events = max(1, int(buffer_events))
        with self._lock:
            self._gen += 1
            self._rings = {}
        self.epoch = time.perf_counter()
        self.epoch_unix = time.time()

    def clear(self) -> None:
        self.configure()

    def events(self, last_n: "int | None" = None) -> list[dict]:
        """Snapshot every thread's ring as Chrome-shaped event dicts,
        oldest first (merged by timestamp). ``last_n`` keeps only the
        newest N — the flight recorder's ``blackbox_events`` window."""
        with self._lock:
            rings = list(self._rings.values())
        pid = os.getpid()
        out = []
        for r in rings:
            events, _ = r.snapshot()
            for ph, name, t0, dur, args in events:
                ev = {
                    "name": name,
                    "ph": ph,
                    "ts": round((t0 - self.epoch) * 1e6, 3),
                    "pid": pid,
                    "tid": r.tid,
                }
                if ph == "X":
                    ev["dur"] = round(dur * 1e6, 3)
                if args:
                    ev["args"] = dict(args)
                out.append(ev)
        out.sort(key=lambda e: e["ts"])
        if last_n is not None and len(out) > last_n:
            out = out[-last_n:]
        return out

    def dropped(self) -> int:
        """Events overwritten since configure() — summed across rings
        (flight-recorder dump metadata: how much history the window
        could not hold)."""
        with self._lock:
            rings = list(self._rings.values())
        return sum(r.snapshot()[1] for r in rings)


def chrome_trace(events: list) -> dict:
    """Wrap event dicts in the Chrome trace-event JSON object format
    (Perfetto / chrome://tracing loadable)."""
    return {"traceEvents": list(events), "displayTimeUnit": "ms"}


def write_chrome_json(path: str, events: list) -> None:
    from jama16_retina_tpu.integrity import artifact as artifact_lib

    artifact_lib.write_json(path, chrome_trace(events), indent=None)


_default = Tracer()


def default_tracer() -> Tracer:
    """The process-wide tracer every layer records into by default."""
    return _default


def set_default_tracer(tr: Tracer) -> Tracer:
    """Swap the process-wide tracer (tests); returns the previous one."""
    global _default
    prev, _default = _default, tr
    return prev
