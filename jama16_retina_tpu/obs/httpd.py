"""Opt-in stdlib HTTP telemetry endpoint (ISSUE 15 satellite).

The ``.prom`` file serves file-based scrapers (node_exporter textfile
collector); standard PULL scrapers want an HTTP target. ``ObsHttp`` is
that target, stdlib-only (``http.server`` in one daemon thread, no new
dependencies — the container constraint):

  * ``GET /metrics``  — the LIVE ``prometheus_text`` rendering of the
    process registry (not the last flush: a scrape is a snapshot);
  * ``GET /healthz``  — heartbeat freshness as JSON with the SAME
    0/1/2 semantics as ``obs_report --check-heartbeats`` (0 fresh,
    1 stale or wedged — progress stamped but old, 2 no progress ever
    recorded). HTTP 200 for 0, 503 otherwise, so a dumb prober (k8s
    livenessProbe, a load balancer) needs no JSON parsing.
    ``?max_age_s=`` overrides the staleness threshold per probe.

Off by default (``obs.http_port=0``); wired by the Snapshotter's
``serve_http`` at the trainer/server/predict telemetry sites. Binds
0.0.0.0 (a scraper is by definition another host); port 0 picks an
ephemeral port (tests read ``.port``).
"""

from __future__ import annotations

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from jama16_retina_tpu.obs import export as export_lib
from jama16_retina_tpu.obs import registry as registry_lib


class ObsHttp:
    """One daemon-threaded HTTP server over a registry + snapshotter.

    The snapshotter (optional) supplies the heartbeat state /healthz
    reads; without one, /healthz is always status 2 (no heartbeat
    source — the endpoint says so rather than lying "fresh").
    """

    def __init__(self, registry: "registry_lib.Registry | None",
                 port: int, snapshotter=None, max_age_s: float = 300.0,
                 host: str = "0.0.0.0"):
        self._registry = (registry if registry is not None
                          else registry_lib.default_registry())
        self._snapshotter = snapshotter
        self.max_age_s = float(max_age_s)
        obs_http = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # noqa: D102 - silence
                pass

            def do_GET(self):  # noqa: N802 - stdlib casing
                parsed = urlparse(self.path)
                if parsed.path == "/metrics":
                    body = export_lib.prometheus_text(
                        obs_http._registry.snapshot()
                    ).encode("utf-8")
                    self.send_response(200)
                    self.send_header(
                        "Content-Type",
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                if parsed.path == "/healthz":
                    q = parse_qs(parsed.query)
                    try:
                        max_age = float(q["max_age_s"][0])
                    except (KeyError, ValueError, IndexError):
                        max_age = obs_http.max_age_s
                    status, detail = obs_http.health(max_age_s=max_age)
                    body = json.dumps(
                        {"status": status, **detail}
                    ).encode("utf-8")
                    self.send_response(200 if status == 0 else 503)
                    self.send_header("Content-Type", "application/json")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                self.send_response(404)
                self.send_header("Content-Length", "0")
                self.end_headers()

        self._server = ThreadingHTTPServer((host, int(port)), _Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="jama16-obs-http",
            daemon=True,
        )
        self._thread.start()

    def health(self, max_age_s: "float | None" = None,
               now: "float | None" = None) -> "tuple[int, dict]":
        """(status, detail) with --check-heartbeats' 0/1/2 semantics:
        0 fresh, 1 progress stamped but older than the threshold
        (wedged), 2 no snapshotter / no progress ever recorded."""
        max_age = self.max_age_s if max_age_s is None else float(max_age_s)
        now = time.time() if now is None else now
        snap = self._snapshotter
        if snap is None or snap._last_progress_t is None:
            return 2, {"detail": "no heartbeat recorded",
                       **self._device_fields(),
                       **self._audit_fields(now)}
        age = now - snap._last_progress_t
        detail = {
            "step": snap._step,
            "progress_age_s": round(age, 1),
            "max_age_s": max_age,
            **self._device_fields(),
            **self._audit_fields(now),
        }
        if age > max_age:
            detail["detail"] = (
                f"no step progress for {age:.0f}s (> {max_age:.0f}s) "
                "— wedged?"
            )
            return 1, detail
        return 0, detail

    def _device_fields(self) -> dict:
        """Device-plane probe fields (ISSUE 19): the last-sampled HBM
        headroom gauge plus the process compile ledger's last-compile
        age, so a fleet prober can blame a memory-pressured (or
        recompile-storming) process without parsing /metrics. Both are
        None when the device plane never published."""
        from jama16_retina_tpu.obs import device as device_lib

        headroom = None
        try:
            headroom = self._registry.snapshot()["gauges"].get(
                "device.hbm.headroom_frac"
            )
        except Exception:  # noqa: BLE001 - a probe must not raise
            pass
        age = device_lib.compile_ledger().last_compile_age_s()
        return {
            "hbm_headroom_frac": headroom,
            "last_compile_age_s": (
                round(age, 1) if age is not None else None
            ),
        }

    def _audit_fields(self, now: "float | None" = None) -> dict:
        """Audit-plane probe fields (ISSUE 20): spool depth and the age
        of the last durable segment seal, so a prober can spot a
        wedged audit writer (depth climbing, seal age unbounded)
        without parsing /metrics. Both None when no ledger ever
        published — the gauges are peeked, never created."""
        now = time.time() if now is None else now
        depth = seal_age = None
        try:
            gauges = self._registry.snapshot()["gauges"]
            depth = gauges.get("audit.spool_depth")
            last_seal = gauges.get("audit.last_seal_t")
            if last_seal:
                seal_age = round(max(0.0, now - last_seal), 1)
        except Exception:  # noqa: BLE001 - a probe must not raise
            pass
        return {
            "audit_spool_depth": depth,
            "audit_last_seal_age_s": seal_age,
        }

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5.0)
