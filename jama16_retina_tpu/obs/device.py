"""Device-utilization plane (ISSUE 19): HBM by owner, MFU/roofline, compiles.

Three coupled ledgers turn "the hardware" from a black box into typed,
fleet-mergeable telemetry, all riding the existing registry/Snapshotter
stack:

  * **HBM accounting by owner** — :class:`DeviceMonitor` samples
    ``device.memory_stats()`` per local device on the Snapshotter
    cadence into ``device.hbm.*`` gauges, and the module-level *owner
    ledger* lets the known residents (live serving generation, retained
    rollback generation, tiered resident cache, staged run-ahead,
    ingest rings) register their measured footprint so obs_report can
    render HBM-by-owner with the gap shown as *untracked*. The
    ``hbm_pressure`` reliability rule reads
    ``device.hbm.headroom_frac``; ``data/hbm_pipeline.py`` notes its
    derived budget here so budget-vs-occupancy cross-checks as a gauge.
  * **MFU / roofline attribution** — the *program ledger* is the ONE
    place a compiled program's cost_analysis is parsed
    (``physics.program_costs``): the trainer's AOT step and every serve
    bucket register (flops_per_call, bytes_per_call, signature) and
    count dispatches with a plain integer increment (``note_call`` —
    no registry object on the hot path; registries are run-scoped).
    The monitor turns call deltas x window wall into ``device.mfu``
    and achieved-bandwidth gauges per program, plus a static roofline
    classification (compute- vs memory-bound against the chip's ridge
    point) that refines the PR-18 ``device_bound`` verdict
    (obs/criticalpath.py) into typed sub-causes.
  * **Compile ledger** — :func:`compile_timed` wraps every
    lower/compile site (trainer AOT, engine bucket warm, compile-cache
    miss, reload/candidate warm, dtype transform) into
    ``device.compile.{count,sec}`` counters, a per-signature entry
    table, and a slowest-compile exemplar (the ``sec_hist`` histogram's
    exemplar window), so a warm restart's "N compiles, S seconds paid,
    M seconds saved by cache" is auditable in obs_report.

Everything here is host-side and off the request path: the monitor
runs on the Snapshotter flush cadence, disabled costs exactly one
branch, and a CPU backend (no ``memory_stats``) silently publishes no
HBM gauges rather than lying.
"""

from __future__ import annotations

import contextlib
import re
import threading
import time

from jama16_retina_tpu.obs import registry as registry_lib

# Headroom fraction below which a process is considered memory-
# pressured: the reliability rule's default threshold
# (obs/alerts.reliability_rules, knob obs.device_hbm_headroom_alert)
# and the fleet heartbeat blame annotation both read this.
HBM_PRESSURE_HEADROOM = 0.1

# Window MFU at or above which a device_bound verdict refines to
# compute-saturated; below it (with a compute-bound program mix) the
# device is underutilized — the small-batch MFU cliff.
SATURATED_MFU = 0.4

_OWNER_SAFE = re.compile(r"[^A-Za-z0-9_]+")


def _safe(name: str) -> str:
    """Metric-name-safe owner/program token (bounded vocabulary: owners
    and programs are code-chosen literals, never user input)."""
    return _OWNER_SAFE.sub("_", str(name)).strip("_") or "unknown"


# -- HBM owner ledger ------------------------------------------------------
#
# Module-level (like the tracer and fault plan): residents register from
# wherever they live — the serving engine, the data pipeline, an ingest
# ring — without threading a monitor handle through every constructor.
# The monitor publishes whatever is registered at sample time.

_lock = threading.Lock()
_HBM_OWNERS: "dict[str, float]" = {}
_HBM_BUDGET: "float | None" = None


def set_hbm_owner(owner: str, nbytes: float) -> None:
    """Register (or update) a resident's per-device HBM footprint."""
    with _lock:
        _HBM_OWNERS[_safe(owner)] = float(max(0.0, nbytes))


def add_hbm_owner(owner: str, delta: float) -> None:
    """Adjust an owner's footprint by a delta (multi-instance residents
    like ingest rings add on create and subtract on close)."""
    with _lock:
        key = _safe(owner)
        _HBM_OWNERS[key] = max(0.0, _HBM_OWNERS.get(key, 0.0) + float(delta))


def clear_hbm_owner(owner: str) -> None:
    with _lock:
        _HBM_OWNERS.pop(_safe(owner), None)


def hbm_owners() -> "dict[str, float]":
    with _lock:
        return dict(_HBM_OWNERS)


def note_hbm_budget(nbytes: float) -> None:
    """Record the data plane's DERIVED per-chip HBM budget
    (data/hbm_pipeline.hbm_budget_bytes) so the monitor can publish the
    derived-vs-measured cross-check gauges."""
    global _HBM_BUDGET
    _HBM_BUDGET = float(nbytes) if nbytes and nbytes > 0 else None


def reset_hbm_owners() -> None:
    """Test isolation: drop every registered owner and the noted budget."""
    global _HBM_BUDGET
    with _lock:
        _HBM_OWNERS.clear()
    _HBM_BUDGET = None


def tree_device_bytes(tree) -> int:
    """Max per-local-device resident bytes of a pytree of arrays.

    Sharded leaves are charged shard-by-shard to the device actually
    holding them (``addressable_shards``); replicated leaves charge a
    full copy to each device; host arrays (or committed single-device
    trees) fall into one bucket. The max over devices matches the
    worst-device view the ``device.hbm.*`` gauges report."""
    import jax

    per_dev: "dict[object, int]" = {}
    for leaf in jax.tree_util.tree_leaves(tree):
        shards = getattr(leaf, "addressable_shards", None)
        if shards:
            for s in shards:
                d = getattr(s, "device", None)
                data = getattr(s, "data", None)
                n = int(getattr(data, "nbytes", 0) or 0)
                per_dev[d] = per_dev.get(d, 0) + n
        elif hasattr(leaf, "nbytes"):
            per_dev[None] = per_dev.get(None, 0) + int(leaf.nbytes)
    return max(per_dev.values(), default=0)


# -- program ledger (the ONE FLOPs source) ---------------------------------


class ProgramEntry:
    """One compiled program's static costs + a plain-int dispatch count.

    ``note_call`` is the hot-path op: one integer increment, no lock,
    no registry object — the monitor reads deltas at flush cadence and
    publishes the registry counters itself (registries are run-scoped;
    this ledger outlives them)."""

    __slots__ = ("name", "flops", "bytes", "signature", "calls")

    def __init__(self, name: str, flops=None, nbytes=None, signature=""):
        self.name = name
        self.flops = flops
        self.bytes = nbytes
        self.signature = signature or name
        self.calls = 0

    def note_call(self, n: int = 1) -> None:
        self.calls += n

    def intensity(self) -> "float | None":
        """Arithmetic intensity (flops / byte accessed), or None when
        cost_analysis gave no usable numbers."""
        if not self.flops or not self.bytes:
            return None
        return float(self.flops) / float(self.bytes)


class ProgramLedger:
    """Registry of every AOT/compiled program's per-call costs."""

    def __init__(self):
        self._entries: "dict[str, ProgramEntry]" = {}
        self._lock = threading.Lock()

    def register(self, name: str, *, compiled=None, flops_per_call=None,
                 bytes_per_call=None, signature="") -> ProgramEntry:
        """Get-or-create the entry, refreshing static costs. Pass the
        compiled executable to have its cost_analysis parsed HERE — the
        single parse site trainer ceilings and MFU gauges both read."""
        flops, nbytes = flops_per_call, bytes_per_call
        if compiled is not None and (flops is None or nbytes is None):
            from jama16_retina_tpu.utils import physics

            f, b = physics.program_costs(compiled)
            flops = f if flops is None else flops
            nbytes = b if nbytes is None else nbytes
        key = _safe(name)
        with self._lock:
            entry = self._entries.get(key)
            if entry is None:
                entry = self._entries[key] = ProgramEntry(key)
            if flops is not None:
                entry.flops = float(flops)
            if nbytes is not None:
                entry.bytes = float(nbytes)
            if signature:
                entry.signature = signature
            return entry

    def get(self, name: str) -> "ProgramEntry | None":
        with self._lock:
            return self._entries.get(_safe(name))

    def entries(self) -> "list[ProgramEntry]":
        with self._lock:
            return list(self._entries.values())

    def reset(self) -> None:
        with self._lock:
            self._entries.clear()


_PROGRAMS = ProgramLedger()


def program_ledger() -> ProgramLedger:
    """The process program ledger (module-level, like the tracer)."""
    return _PROGRAMS


# -- compile ledger --------------------------------------------------------


class CompileLedger:
    """Per-signature compile counts/seconds + the last-compile clock.

    The registry counters (``device.compile.{count,sec}``) are
    incremented at record time against the CURRENT default registry (or
    an explicitly passed one) so run-scoped registries see their own
    run's compiles; this ledger is the cross-run process view /healthz
    and obs_report's entry table read."""

    def __init__(self):
        self._lock = threading.Lock()
        self.entries: "dict[str, dict]" = {}
        self.count = 0
        self.sec = 0.0
        self.last_t: "float | None" = None

    def record(self, signature: str, sec: float) -> None:
        with self._lock:
            e = self.entries.setdefault(
                signature, {"count": 0, "sec": 0.0, "max_sec": 0.0}
            )
            e["count"] += 1
            e["sec"] += sec
            e["max_sec"] = max(e["max_sec"], sec)
            self.count += 1
            self.sec += sec
            self.last_t = time.time()

    def last_compile_age_s(self, now: "float | None" = None):
        with self._lock:
            if self.last_t is None:
                return None
            return (time.time() if now is None else now) - self.last_t

    def snapshot(self) -> dict:
        """{'count','sec','slowest','entries'} — entries sorted by total
        seconds descending, slowest = the single worst signature."""
        with self._lock:
            rows = [
                {"signature": sig, **dict(e)}
                for sig, e in self.entries.items()
            ]
        rows.sort(key=lambda r: -r["sec"])
        slowest = None
        if rows:
            worst = max(rows, key=lambda r: r["max_sec"])
            slowest = {"signature": worst["signature"],
                       "sec": worst["max_sec"]}
        return {"count": self.count, "sec": self.sec,
                "slowest": slowest, "entries": rows}

    def reset(self) -> None:
        with self._lock:
            self.entries.clear()
            self.count = 0
            self.sec = 0.0
            self.last_t = None


_COMPILES = CompileLedger()


def compile_ledger() -> CompileLedger:
    return _COMPILES


def record_compile(signature: str, sec: float, registry=None) -> None:
    """One compile happened: ledger entry + registry counters + the
    slowest-compile exemplar (the sec_hist histogram keeps the slowest
    exemplar-tagged observation per telemetry window)."""
    _COMPILES.record(signature, sec)
    reg = registry if registry is not None else registry_lib.default_registry()
    reg.counter(
        "device.compile.count",
        help="XLA lower+compile invocations this process paid "
             "(trainer AOT, engine bucket warm, cache miss, "
             "reload/candidate warm, dtype transform)",
    ).inc()
    reg.counter(
        "device.compile.sec",
        help="total wall seconds spent inside lower+compile sites",
    ).inc(sec)
    reg.histogram(
        "device.compile.sec_hist",
        help="per-compile wall seconds; the exemplar names the slowest "
             "compile signature of the telemetry window",
    ).observe(sec, exemplar=signature)


@contextlib.contextmanager
def compile_timed(signature: str, registry=None):
    """Wrap ONE lower/compile site. Times the body and records it into
    the compile ledger + counters even when the compile raises (the
    seconds were still paid)."""
    t0 = time.monotonic()
    try:
        yield
    finally:
        record_compile(signature, time.monotonic() - t0, registry=registry)


def note_compile_saved(sec: float, registry=None) -> None:
    """A compile-cache hit spared this many seconds (the stored
    compile_sec of the entry that deserialized instead of recompiling)."""
    if not sec or sec <= 0:
        return
    reg = registry if registry is not None else registry_lib.default_registry()
    reg.counter(
        "device.compile.saved_sec",
        help="compile seconds spared by compile-cache hits (the stored "
             "cost of each entry that deserialized instead of "
             "recompiling)",
    ).inc(float(sec))


def reset_for_tests() -> None:
    """Test isolation: clear every module-level ledger."""
    reset_hbm_owners()
    _PROGRAMS.reset()
    _COMPILES.reset()


# -- the monitor -----------------------------------------------------------


class DeviceMonitor:
    """Samples HBM stats + program-ledger deltas into gauges on the
    Snapshotter cadence (obs/export.py calls ``sample`` first in every
    flush, so the gauges land in that flush's snapshot).

    ``devices``/``ledger``/``peak_flops_per_s``/``peak_bw_bytes_per_s``/
    ``clock`` are injectable for tests and bench drills; production
    wiring (``monitor_for``) uses real local devices, the process
    ledgers, and the physics tables. Disabled (or constructed with
    ``enabled=False``) costs exactly one branch per flush."""

    def __init__(self, registry=None, *, enabled: bool = True,
                 devices=None, ledger: "ProgramLedger | None" = None,
                 peak_flops_per_s: "float | None" = None,
                 peak_bw_bytes_per_s: "float | None" = None,
                 clock=time.monotonic):
        self.enabled = bool(enabled)
        self._registry = registry
        self._devices = devices
        self._ledger = ledger
        self._peak_flops = peak_flops_per_s
        self._peak_bw = peak_bw_bytes_per_s
        self._clock = clock
        self._prev_calls: "dict[str, int]" = {}
        self._prev_t: "float | None" = None
        self._roofline_published: "set[str]" = set()
        self._compile_count_written = 0

    # -- lazy production defaults (no jax import at construction) ------

    def _reg(self):
        if self._registry is None:
            self._registry = registry_lib.default_registry()
        return self._registry

    def _local_devices(self):
        if self._devices is None:
            import jax

            self._devices = list(jax.local_devices())
        return self._devices

    def _peaks(self):
        if self._peak_flops is None or self._peak_bw is None:
            from jama16_retina_tpu.utils import physics

            if self._peak_flops is None:
                self._peak_flops = physics.peak_flops()
            if self._peak_bw is None:
                self._peak_bw = physics.peak_hbm_bytes_per_sec()
        return self._peak_flops, self._peak_bw

    # -- sampling ------------------------------------------------------

    def sample(self, runlog=None) -> "dict | None":
        """One monitor tick: HBM gauges, owner gauges, MFU/bandwidth/
        roofline gauges from program-ledger deltas, and a
        ``compile_ledger`` runlog record when new compiles landed since
        the last tick. Returns the published values (tests read it) or
        None when disabled."""
        if not self.enabled:
            return None
        out: dict = {}
        try:
            self._sample_hbm(out)
        except Exception:  # noqa: BLE001 - telemetry must not kill a flush
            pass
        try:
            self._sample_programs(out)
        except Exception:  # noqa: BLE001
            pass
        try:
            self._write_compile_record(runlog)
        except Exception:  # noqa: BLE001
            pass
        return out

    def _sample_hbm(self, out: dict) -> None:
        reg = self._reg()
        in_use = peak = limit = None
        headroom = None
        for dev in self._local_devices():
            ms = getattr(dev, "memory_stats", None)
            if not callable(ms):
                continue
            try:
                stats = ms() or {}
            except Exception:  # noqa: BLE001 - backend without stats
                continue
            b_use = stats.get("bytes_in_use")
            b_lim = stats.get("bytes_limit")
            b_peak = stats.get("peak_bytes_in_use", b_use)
            if b_use is None:
                continue
            in_use = max(in_use or 0, int(b_use))
            if b_peak is not None:
                peak = max(peak or 0, int(b_peak))
            if b_lim:
                limit = int(b_lim) if limit is None else min(limit, int(b_lim))
                h = (int(b_lim) - int(b_use)) / float(b_lim)
                headroom = h if headroom is None else min(headroom, h)
        if in_use is not None:
            reg.gauge(
                "device.hbm.bytes_in_use",
                help="HBM bytes in use on the most-loaded local device "
                     "[fleet:max]",
            ).set(float(in_use))
            out["bytes_in_use"] = in_use
        if peak is not None:
            reg.gauge(
                "device.hbm.peak_bytes",
                help="peak HBM bytes in use on the worst local device "
                     "since process start [fleet:max]",
            ).set(float(peak))
            out["peak_bytes"] = peak
        if limit is not None:
            reg.gauge(
                "device.hbm.bytes_limit",
                help="per-device HBM capacity the runtime reports "
                     "(smallest local device) [fleet:min]",
            ).set(float(limit))
            out["bytes_limit"] = limit
        if headroom is not None:
            reg.gauge(
                "device.hbm.headroom_frac",
                help="free HBM fraction on the tightest local device; "
                     "the hbm_pressure reliability rule reads this "
                     "[fleet:min]",
            ).set(round(headroom, 6))
            out["headroom_frac"] = headroom
        owners = hbm_owners()
        for name, nbytes in owners.items():
            reg.gauge(
                f"device.hbm.owner.{name}",
                help="per-device HBM bytes this resident registered "
                     "(owner ledger; the obs_report HBM-by-owner table) "
                     "[fleet:max]",
            ).set(float(nbytes))
        if owners:
            out["owners"] = owners
        if in_use is not None:
            untracked = max(0.0, float(in_use) - sum(owners.values()))
            reg.gauge(
                "device.hbm.untracked_bytes",
                help="bytes_in_use minus every registered owner "
                     "footprint — residency nothing claimed (clamped "
                     "at 0) [fleet:max]",
            ).set(untracked)
            out["untracked_bytes"] = untracked
        if _HBM_BUDGET is not None:
            reg.gauge(
                "device.hbm.derived_budget_bytes",
                help="the data plane's DERIVED per-chip HBM budget "
                     "(data/hbm_pipeline) — cross-check against "
                     "measured occupancy [fleet:min]",
            ).set(float(_HBM_BUDGET))
            out["derived_budget_bytes"] = _HBM_BUDGET
            if in_use is not None:
                occ = float(in_use) / float(_HBM_BUDGET)
                reg.gauge(
                    "device.hbm.budget_occupancy_frac",
                    help="measured bytes_in_use over the derived data-"
                         "plane budget; >1 means the budget math "
                         "underestimates real residency [fleet:max]",
                ).set(round(occ, 6))
                out["budget_occupancy_frac"] = occ

    def _sample_programs(self, out: dict) -> None:
        ledger = self._ledger if self._ledger is not None else _PROGRAMS
        entries = ledger.entries()
        if not entries:
            return
        reg = self._reg()
        peak_flops, peak_bw = self._peaks()
        ridge = (peak_flops / peak_bw) if peak_bw else None
        now = self._clock()
        prev_t, self._prev_t = self._prev_t, now
        calls_now = {e.name: e.calls for e in entries}
        prev_calls, self._prev_calls = self._prev_calls, calls_now
        # Static roofline class: publish once per program, on first
        # sight (the classification depends only on the program and the
        # chip, not the window).
        for e in entries:
            if e.name in self._roofline_published:
                continue
            inten = e.intensity()
            if inten is None or ridge is None:
                continue
            cls = 1.0 if inten >= ridge else 2.0
            reg.gauge(
                f"device.roofline.{e.name}",
                help="roofline class of this program on this chip: "
                     "1 compute-bound (intensity >= ridge point), "
                     "2 memory-bandwidth-bound",
            ).set(cls)
            self._roofline_published.add(e.name)
            out.setdefault("roofline", {})[e.name] = cls
        if prev_t is None:
            return  # first tick: baseline only, no window yet
        dt = now - prev_t
        if dt <= 0:
            return
        import jax

        try:
            n_dev = max(1, jax.local_device_count())
        except Exception:  # noqa: BLE001 - jax not initialized
            n_dev = 1
        total_flops = 0.0
        total_bytes = 0.0
        window_flops: "dict[str, float]" = {}
        for e in entries:
            delta = e.calls - prev_calls.get(e.name, 0)
            if delta <= 0:
                continue
            reg.counter(
                f"device.program.calls.{e.name}",
                help="dispatches of this compiled program (program "
                     "ledger; counted at flush from hot-path integer "
                     "deltas)",
            ).inc(delta)
            if e.flops:
                pf = delta * float(e.flops)
                total_flops += pf
                window_flops[e.name] = pf
                # cost_analysis FLOPs may be whole-program across
                # devices; dividing by local chips keeps MFU
                # conservative (never flattering) — same ambiguity
                # note as physics.rate_ceiling, opposite direction.
                mfu = pf / (dt * peak_flops * n_dev)
                reg.gauge(
                    f"device.mfu.{e.name}",
                    help="window model-FLOPs utilization of this "
                         "program: dispatches x flops_per_call over "
                         "wall x peak x local chips [fleet:mean]",
                ).set(round(mfu, 6))
                out.setdefault("mfu_by_program", {})[e.name] = mfu
            if e.bytes:
                bw = delta * float(e.bytes) / dt
                total_bytes += delta * float(e.bytes)
                reg.gauge(
                    f"device.bw_gbps.{e.name}",
                    help="achieved HBM bandwidth of this program over "
                         "the window (GB/s, cost_analysis bytes "
                         "accessed x dispatches / wall) [fleet:mean]",
                ).set(round(bw / 1e9, 3))
        if total_flops > 0:
            mfu = total_flops / (dt * peak_flops * n_dev)
            reg.gauge(
                "device.mfu",
                help="window model-FLOPs utilization across every "
                     "ledgered program [fleet:mean]",
            ).set(round(mfu, 6))
            out["mfu"] = mfu
        if total_bytes > 0 and peak_bw:
            bw_frac = total_bytes / (dt * peak_bw * n_dev)
            reg.gauge(
                "device.bw_frac",
                help="achieved fraction of peak HBM bandwidth across "
                     "every ledgered program over the window "
                     "[fleet:mean]",
            ).set(round(bw_frac, 6))
            out["bw_frac"] = bw_frac
        if window_flops and ridge is not None:
            dominant = max(window_flops, key=window_flops.get)
            e = ledger.get(dominant)
            inten = e.intensity() if e is not None else None
            if inten is not None:
                cls = 1.0 if inten >= ridge else 2.0
                reg.gauge(
                    "device.roofline.dominant_class",
                    help="roofline class of the program carrying the "
                         "most window FLOPs: 0 none, 1 compute-bound, "
                         "2 memory-bandwidth-bound",
                ).set(cls)
                out["dominant_class"] = cls

    def _write_compile_record(self, runlog) -> None:
        if runlog is None:
            return
        snap = _COMPILES.snapshot()
        if snap["count"] == self._compile_count_written:
            return
        self._compile_count_written = snap["count"]
        runlog.write(
            "compile_ledger",
            count=snap["count"],
            sec=round(snap["sec"], 3),
            slowest=snap["slowest"],
            entries=[
                {"signature": r["signature"], "count": r["count"],
                 "sec": round(r["sec"], 3),
                 "max_sec": round(r["max_sec"], 3)}
                for r in snap["entries"][:12]
            ],
        )


def monitor_for(cfg, registry=None) -> "DeviceMonitor | None":
    """The monitor a telemetry wiring site attaches to its Snapshotter,
    or None when obs (or the device plane) is off — the Snapshotter
    then pays one ``is None`` branch per flush."""
    oc = getattr(cfg, "obs", None)
    if oc is None or not oc.enabled:
        return None
    if not getattr(oc, "device_enabled", True):
        return None
    return DeviceMonitor(registry=registry)


# -- verdict-refinement summary -------------------------------------------


def summary_from_gauges(gauges: "dict | None") -> "dict | None":
    """Distill a registry/telemetry gauge map into the device summary
    ``criticalpath.diagnose(device=...)`` refines device_bound with.
    Returns None when the device plane published nothing (diagnosis
    then keeps the unrefined verdict)."""
    if not gauges:
        return None
    mfu = gauges.get("device.mfu")
    dom = gauges.get("device.roofline.dominant_class")
    if mfu is None and dom is None:
        return None
    cls = {1.0: "compute", 2.0: "memory"}.get(
        float(dom) if dom is not None else None
    )
    programs = {
        k[len("device.mfu."):]: v
        for k, v in gauges.items()
        if k.startswith("device.mfu.")
    }
    return {
        "mfu": float(mfu) if mfu is not None else None,
        "dominant_class": cls,
        "bw_frac": gauges.get("device.bw_frac"),
        "hbm_headroom_frac": gauges.get("device.hbm.headroom_frac"),
        "programs": programs,
    }
