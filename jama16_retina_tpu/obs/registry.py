"""Metric registry: named Counters, Gauges, and fixed-bucket Histograms.

The runtime-telemetry core (ISSUE 3): every layer of the system —
trainer step loop, data tiers, serving engine/batcher — records into
one of three metric kinds through a process-wide default registry (or
an injected instance in tests). Design constraints, in order:

  * HOT-PATH CHEAP. Every op (``inc``/``set``/``observe``) is O(1)
    under a per-metric ``threading.Lock`` whose critical section is a
    couple of float adds — microseconds, measured against the 2%
    overhead pin in bench.py (``telemetry_overhead_pct``) and the
    per-op bound in tests/test_bench_guard.py. No allocation, no
    string formatting, no I/O on the hot path; rendering cost is paid
    only at snapshot time (obs/export.py).
  * DISABLED == ONE BRANCH. ``Registry.enabled`` is checked first in
    every op; a disabled registry's metrics cost one attribute read and
    one branch, nothing else (the contract obs/spans.py extends to
    timing contexts).
  * THREAD-SAFE BY CONSTRUCTION. The serve path records from the
    MicroBatcher worker thread and N submitter threads concurrently
    with the main thread's snapshot; per-metric locks make every op
    and every snapshot linearizable without a global lock that hot
    paths would contend on.

Histograms are fixed-bucket (Prometheus-style cumulative ``le`` bounds
at export): quantiles are estimated at SNAPSHOT time by linear
interpolation inside the bucket containing the target rank — the
standard histogram_quantile estimate, exact at bucket boundaries and
clamped to the largest finite bound for overflow observations. That
trades quantile resolution for an O(buckets) memory footprint and an
O(log buckets) observe, which is what lets request latencies be
recorded per request on the serve path.
"""

from __future__ import annotations

import bisect
import threading
from typing import Iterable

# Default histogram buckets, in SECONDS: spans and latency histograms
# record seconds (the JSONL convention of the train records), covering
# 100us..60s — sub-ms device dispatches up to eval/checkpoint pauses.
DEFAULT_BUCKETS: tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


class Counter:
    """Monotonically increasing count (rows decoded, requests rejected)."""

    __slots__ = ("name", "help", "_registry", "_lock", "_value")

    def __init__(self, name: str, registry: "Registry", help: str = ""):
        self.name = name
        self.help = help
        self._registry = registry
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if not self._registry.enabled:
            return
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Point-in-time level (queue depth, resident rows, in-flight)."""

    __slots__ = ("name", "help", "_registry", "_lock", "_value")

    def __init__(self, name: str, registry: "Registry", help: str = ""):
        self.name = name
        self.help = help
        self._registry = registry
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        if not self._registry.enabled:
            return
        with self._lock:
            self._value = float(v)

    def add(self, delta: float) -> None:
        if not self._registry.enabled:
            return
        with self._lock:
            self._value += delta

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Histogram:
    """Fixed-bucket distribution with snapshot-time quantile estimates.

    ``bounds`` are the finite bucket upper bounds (ascending); an
    implicit +Inf overflow bucket catches everything above the last
    bound. ``observe`` is a bisect + two adds under the metric lock.
    """

    __slots__ = (
        "name", "help", "_registry", "_lock", "bounds", "_counts",
        "_sum", "_count", "_ex_value", "_ex_id",
    )

    def __init__(self, name: str, registry: "Registry",
                 buckets: Iterable[float] = DEFAULT_BUCKETS,
                 help: str = ""):
        self.name = name
        self.help = help
        self._registry = registry
        self._lock = threading.Lock()
        self.bounds = tuple(sorted(float(b) for b in buckets))
        if not self.bounds:
            raise ValueError(f"histogram {name!r} needs >= 1 bucket bound")
        self._counts = [0] * (len(self.bounds) + 1)  # +1 = overflow
        self._sum = 0.0
        self._count = 0
        # Exemplar (ISSUE 15): the SLOWEST observation since the last
        # snapshot, tagged with the caller-supplied id (a request's
        # trace_id). Tumbling at the snapshot cadence, so each
        # telemetry window names the one request to go look at when
        # its p99 breaches an SLO.
        self._ex_value: "float | None" = None
        self._ex_id = None

    def observe(self, v: float, exemplar=None) -> None:
        if not self._registry.enabled:
            return
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1
            if exemplar is not None and (
                    self._ex_value is None or v > self._ex_value):
                self._ex_value = v
                self._ex_id = exemplar

    def _quantile_locked(self, q: float) -> "float | None":
        """Rank-interpolated quantile from the bucket counts (callers
        hold the lock). Overflow observations clamp to the largest
        finite bound — the Prometheus histogram_quantile convention."""
        if self._count == 0:
            return None
        target = q * self._count
        cum = 0.0
        lo = 0.0
        for bound, c in zip(self.bounds, self._counts):
            if c and cum + c >= target:
                frac = (target - cum) / c
                return lo + (bound - lo) * frac
            cum += c
            lo = bound
        return self.bounds[-1]

    def snapshot(self, reset_exemplar: bool = False) -> dict:
        """{'count', 'sum', 'mean', 'p50', 'p95', 'p99', 'buckets',
        'exemplar'} — buckets as (upper_bound, cumulative_count) pairs
        plus the +Inf total, the shape prometheus_text renders
        directly. ``exemplar`` is {'value', 'trace_id'} for the slowest
        exemplar-tagged observation since the last RESETTING snapshot,
        or None. Only the telemetry flush passes ``reset_exemplar=True``
        (its cadence defines the tumbling window); every other consumer
        — an HTTP scrape, a blackbox dump, a test — reads without
        consuming, so a 15 s scraper cannot steal the exemplar the
        60 s flush was about to export."""
        with self._lock:
            counts = list(self._counts)
            total = self._count
            s = self._sum
            quantiles = {
                f"p{int(q * 100)}": self._quantile_locked(q)
                for q in (0.5, 0.95, 0.99)
            }
            exemplar = (
                {"value": self._ex_value, "trace_id": self._ex_id}
                if self._ex_value is not None else None
            )
            if reset_exemplar:
                self._ex_value = None
                self._ex_id = None
        cum, cum_counts = 0, []
        for c in counts[:-1]:
            cum += c
            cum_counts.append(cum)
        return {
            "count": total,
            "sum": s,
            "mean": (s / total) if total else None,
            **quantiles,
            "buckets": list(zip(self.bounds, cum_counts)),
            "exemplar": exemplar,
        }

    @property
    def count(self) -> int:
        with self._lock:
            return self._count


class Registry:
    """Named get-or-create metric store.

    ``enabled=False`` turns every metric op into one branch (the
    explicit no-op mode): handles stay valid, values freeze. One
    process-wide default instance exists (``default_registry``);
    tests and embedded uses inject their own.
    """

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._metrics: dict[str, object] = {}

    def _get_or_create(self, name: str, kind: type, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = kind(name, self, **kwargs)
            elif not isinstance(m, kind):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, not {kind.__name__}"
                )
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get_or_create(name, Counter, help=help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get_or_create(name, Gauge, help=help)

    def histogram(self, name: str,
                  buckets: Iterable[float] = DEFAULT_BUCKETS,
                  help: str = "") -> Histogram:
        return self._get_or_create(name, Histogram, buckets=buckets,
                                   help=help)

    def peek(self, name: str):
        """The registered metric, or None — a read that never CREATES.
        Cross-subsystem observers (the audit ledger stamping canary
        status, health detail) use this so that merely looking at
        another plane's gauge can't register a zero-valued impostor
        when that plane isn't wired."""
        with self._lock:
            return self._metrics.get(name)

    def remove(self, name: str) -> None:
        """Retire a metric from snapshots. Existing handles stay valid
        (their ops just stop being exported) — the bounded-vocabulary
        escape hatch for legitimately generation-scoped metrics like
        ``serve.gen{N}.rows``, whose population would otherwise grow
        one counter per hot-swap for the life of a serving process."""
        with self._lock:
            self._metrics.pop(name, None)

    def reset(self) -> None:
        """Zero every registered metric IN PLACE — handles stay valid.

        Run-scoping for the process-wide registry: each train loop
        resets at run start, so sequential ensemble members (one fit()
        per member in one process) don't leak members 0..m-1's counts
        into member m's telemetry snapshots, while long-lived handles
        created at pipeline/batcher construction keep recording."""
        with self._lock:
            metrics = list(self._metrics.values())
        for m in metrics:
            with m._lock:
                if isinstance(m, Histogram):
                    m._counts = [0] * (len(m.bounds) + 1)
                    m._sum = 0.0
                    m._count = 0
                    m._ex_value = None
                    m._ex_id = None
                else:
                    m._value = 0.0

    def snapshot(self, reset_exemplars: bool = False) -> dict:
        """{'counters': {name: v}, 'gauges': {name: v},
        'histograms': {name: Histogram.snapshot()}, 'help': {name:
        text}} — the one shape every exporter (JSONL record, .prom
        file, obs_report) reads. ``help`` carries only non-empty
        strings (export.prometheus_text renders them as # HELP lines;
        the JSONL exporter drops the map to keep records one line).
        ``reset_exemplars=True`` is reserved for the telemetry flush —
        it closes each histogram's exemplar window (see
        Histogram.snapshot)."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: dict = {"counters": {}, "gauges": {}, "histograms": {},
                     "help": {}}
        for m in metrics:
            if isinstance(m, Counter):
                out["counters"][m.name] = m.value
            elif isinstance(m, Gauge):
                out["gauges"][m.name] = m.value
            elif isinstance(m, Histogram):
                out["histograms"][m.name] = m.snapshot(
                    reset_exemplar=reset_exemplars
                )
            if getattr(m, "help", ""):
                out["help"][m.name] = m.help
        return out


_default = Registry()


def default_registry() -> Registry:
    """The process-wide registry every layer records into by default."""
    return _default


def set_default_registry(reg: Registry) -> Registry:
    """Swap the process-wide registry (tests); returns the previous one
    so callers can restore it."""
    global _default
    prev, _default = _default, reg
    return prev
