"""Model & data quality observability: reference profiles, online drift
detection, and the golden-set canary (ISSUE 5 tentpole).

PR 3/4 made the runtime's INFRA health visible (stall attribution,
latency quantiles, flight-recorder dumps). What they cannot see is the
quantity the paper actually ships: AUC and sensitivity at operating
points chosen on a validation distribution — numbers that silently rot
when the live input or score distribution drifts away from the one the
thresholds were picked on. This module moves `evaluate.py`'s offline
judgment online:

  * REFERENCE PROFILE — a small versioned JSON artifact
    (``build_profile``/``save_profile``/``load_profile``) holding the
    validation split's score histogram (fixed bins over [0, 1]),
    per-channel input-statistic histograms over the post-normalization
    uint8 images (channel means, global std, gray brightness — the
    statistics ``serve/host.py``'s fundus normalization determines),
    the positive base rate, and the chosen operating thresholds.
    Written by ``evaluate.py --profile_out`` (the canonical path for a
    served checkpoint) or the trainer's ``obs.quality.profile_out``.

  * ONLINE DRIFT MONITOR — ``QualityMonitor`` accumulates the same
    histograms from live requests at O(1) bin increments per row
    (vectorized per batch) and, every ``window_scores`` scores
    (tumbling windows), computes PSI against the profile and publishes
    ``quality.score_psi`` / ``quality.input_psi.{stat}`` /
    ``quality.positive_rate`` gauges through the PR-3 registry — so
    drift lands in `telemetry` JSONL records and ``telemetry.prom``
    with no new export path, and obs/alerts.py rules can fire on it.

  * GOLDEN-SET CANARY — ``GoldenCanary``: a pinned image set scored
    through the live engine on a cadence, asserting byte-stable scores
    per (checkpoint, bucket). Distribution tests can't catch a silent
    numerical or preprocessing regression that shifts every score by
    the same small amount; an exact-compare canary can.

Disabled contract (the registry's, inherited): ``enabled=False`` makes
``observe()`` one attribute read and one branch — pinned by bench.py's
``quality_overhead_pct`` guard (monitor ENABLED must stay within 2% of
device_only; disabled is strictly cheaper) and tests/test_quality.py.
"""

from __future__ import annotations

import json
import os
import threading
import time

import numpy as np
from absl import logging as absl_logging

from jama16_retina_tpu.integrity import artifact as artifact_lib
from jama16_retina_tpu.obs import registry as registry_lib

PROFILE_VERSION = 1

# The per-image input statistics the monitor and the profile share.
# All are dimensionless in [0, 1] over the POST-normalization uint8
# image (scaled by /255): per-channel means catch color-balance /
# illumination drift (a new camera, a changed Ben-Graham flag), the
# global std catches contrast collapse, gray brightness is the
# headline exposure statistic.
INPUT_STATS = ("mean_r", "mean_g", "mean_b", "std", "brightness")

# Smoothing floor for PSI/KL proportions: a bin empty on one side must
# not produce an infinite term (the standard epsilon convention).
_EPS = 1e-4


# ---------------------------------------------------------------------------
# Histograms + divergences
# ---------------------------------------------------------------------------


def bin_counts(values: np.ndarray, bins: int) -> np.ndarray:
    """Counts of ``values`` over ``bins`` uniform buckets spanning
    [0, 1], out-of-range values clamped into the edge bins (scores are
    probabilities by construction; input stats are bounded by their
    definitions, so clamping only ever absorbs float dust)."""
    v = np.asarray(values, np.float64).ravel()
    idx = np.clip((v * bins).astype(np.int64), 0, bins - 1)
    return np.bincount(idx, minlength=bins).astype(np.int64)


def _proportions(counts: np.ndarray) -> np.ndarray:
    c = np.asarray(counts, np.float64)
    total = c.sum()
    if total <= 0:
        return np.full(c.shape, 1.0 / c.size)
    return np.maximum(c / total, _EPS)


def psi(ref_counts: np.ndarray, cur_counts: np.ndarray) -> float:
    """Population Stability Index between two same-binning histograms:
    sum((cur - ref) * ln(cur / ref)) over bin proportions. Symmetric in
    sign of the shift; the industry reading is < 0.1 stable, 0.1-0.25
    drifting, > 0.25 shifted (docs/OBSERVABILITY.md §Quality)."""
    p = _proportions(ref_counts)
    q = _proportions(cur_counts)
    return float(np.sum((q - p) * np.log(q / p)))


def psi_debiased(ref_counts: np.ndarray, cur_counts: np.ndarray) -> float:
    """PSI minus its first-order small-sample expectation, clamped at 0.

    A finite window drawn FROM the reference distribution still shows
    positive PSI — asymptotically chi-square-like with expectation
    ``(bins - 1) * (1/n_cur + 1/n_ref)`` (measured: 0.074 for a
    256-score window over 20 bins, exactly the prediction). Publishing
    the raw value would make the alert threshold mean "0.2 including
    noise that scales with 1/window"; subtracting the expectation makes
    ``quality.score_psi > 0.2`` mean "0.2 ABOVE sampling noise"
    regardless of the configured window/bins. This is what the monitor
    publishes; ``psi`` stays the textbook quantity."""
    ref = np.asarray(ref_counts, np.float64)
    cur = np.asarray(cur_counts, np.float64)
    bias = (ref.size - 1) * (
        1.0 / max(1.0, cur.sum()) + 1.0 / max(1.0, ref.sum())
    )
    return max(0.0, psi(ref, cur) - bias)


def kl_divergence(ref_counts: np.ndarray, cur_counts: np.ndarray) -> float:
    """KL(cur || ref) over bin proportions — the asymmetric companion
    obs_report shows next to PSI for debugging which tail moved."""
    p = _proportions(ref_counts)
    q = _proportions(cur_counts)
    return float(np.sum(q * np.log(q / p)))


def input_stat_values(images: np.ndarray) -> dict:
    """Per-image scalar statistics (INPUT_STATS) over uint8 images
    [n, S, S, 3], vectorized in one pass: {stat: float64 [n]}."""
    imgs = np.asarray(images)
    if imgs.ndim != 4 or imgs.shape[-1] != 3:
        raise ValueError(f"expected images [n, S, S, 3], got {imgs.shape}")
    x = imgs.astype(np.float32) / 255.0
    chan = x.mean(axis=(1, 2))  # [n, 3]
    gray = chan @ np.array([0.299, 0.587, 0.114], np.float32)
    return {
        "mean_r": chan[:, 0].astype(np.float64),
        "mean_g": chan[:, 1].astype(np.float64),
        "mean_b": chan[:, 2].astype(np.float64),
        "std": x.reshape(x.shape[0], -1).std(axis=1).astype(np.float64),
        "brightness": gray.astype(np.float64),
    }


# ---------------------------------------------------------------------------
# Reference profile artifact
# ---------------------------------------------------------------------------


def build_profile(
    scores: np.ndarray,
    labels: "np.ndarray | None" = None,
    stat_values: "dict | None" = None,
    thresholds: "list | tuple" = (),
    bins: int = 20,
    meta: "dict | None" = None,
) -> dict:
    """The versioned reference artifact the online monitor compares
    against. ``scores``: referable probabilities in [0, 1] (the binary
    score every head reduces to); ``labels``: binary labels for the
    base rate; ``stat_values``: ``input_stat_values``-shaped dict;
    ``thresholds``: operating-point rows (each carrying at least
    ``threshold``, normally also ``target_specificity``)."""
    scores = np.asarray(scores, np.float64).ravel()
    profile = {
        "version": PROFILE_VERSION,
        "kind": "quality_profile",
        "bins": int(bins),
        "n_examples": int(scores.size),
        "score_hist": bin_counts(scores, bins).tolist(),
        "base_rate": (
            float(np.asarray(labels, np.float64).mean())
            if labels is not None and np.asarray(labels).size else None
        ),
        "thresholds": [
            {k: (float(v) if isinstance(v, (int, float, np.floating))
                 else v)
             for k, v in dict(t).items()}
            for t in thresholds
        ],
        "input_stats": {
            k: bin_counts(v, bins).tolist()
            for k, v in (stat_values or {}).items()
        },
    }
    if meta:
        profile["meta"] = dict(meta)
    return profile


def save_profile(path: str, profile: dict) -> str:
    """Sealed atomic write (integrity/artifact.py, ISSUE 13): a monitor
    loading mid-write must never see a torn artifact, and a bit-flipped
    one must fail its content checksum instead of silently re-shaping
    every PSI the monitor publishes."""
    return artifact_lib.write_sealed_json(
        path, profile, schema="quality.profile", version=PROFILE_VERSION
    )


def load_profile(path: str) -> dict:
    with open(path) as f:
        profile = json.load(f)
    v = profile.get("version")
    if v != PROFILE_VERSION:
        raise ValueError(
            f"quality profile {path!r} has version {v!r}; this runtime "
            f"reads version {PROFILE_VERSION} — re-emit it with "
            "evaluate.py --profile_out"
        )
    if profile.get("kind") != "quality_profile":
        raise ValueError(f"{path!r} is not a quality profile artifact")
    # Checksum after the version/kind refusals keep their own errors:
    # bit rot raises typed ArtifactCorrupt, counted (ISSUE 13).
    artifact_lib.verify_payload(profile, path, artifact="profile")
    return profile


def split_input_stats(
    data_dir: str, split: str, batch_size: int, image_size: int
) -> dict:
    """``input_stat_values`` over one epoch of an eval split — the
    profile's input-histogram source. Imported lazily: profile emission
    is an offline path and must not drag tf.data into the monitor."""
    from jama16_retina_tpu.data import pipeline

    acc: dict = {k: [] for k in INPUT_STATS}
    # Force the single-process view (same rule as predict_split's
    # offline path): eval_batches' default hands each host a LOCAL row
    # block with a GLOBAL mask, and a shard-sliced mask would let a
    # final batch's zero-padding rows into the histograms.
    for batch in pipeline.eval_batches(
        data_dir, split, batch_size, image_size,
        process_index=0, process_count=1,
    ):
        keep = batch["mask"] > 0
        img = batch["image"][keep]
        if img.shape[0] == 0:
            continue
        stats = input_stat_values(img)
        for k in INPUT_STATS:
            acc[k].append(stats[k])
    return {k: np.concatenate(v) if v else np.zeros((0,), np.float64)
            for k, v in acc.items()}


# ---------------------------------------------------------------------------
# Golden-set canary
# ---------------------------------------------------------------------------


def save_canary(path: str, images: np.ndarray,
                scores: "np.ndarray | None" = None) -> str:
    """The canary artifact: pinned images plus (optionally) the pinned
    scores for the (checkpoint, bucket) being served. Without scores
    the first live run pins them (and a restart re-pins — persist the
    scored form for cross-run byte-stability)."""
    import io

    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    payload = {"images": np.asarray(images, np.uint8)}
    if scores is not None:
        payload["scores"] = np.asarray(scores, np.float64)
    # np.savez appends .npz itself when missing; return the name it
    # actually wrote so the value feeds obs.quality.canary_path as-is.
    out = path if path.endswith(".npz") else path + ".npz"
    # Sealed atomic publish (ISSUE 13): serialize in memory, write
    # through the one integrity.write seam, and pin size+sha256 in a
    # seal sidecar — a half-written or bit-flipped canary must raise
    # typed ArtifactCorrupt at load, never silently re-pin scores.
    buf = io.BytesIO()
    np.savez(buf, **payload)
    blob = buf.getvalue()
    artifact_lib.atomic_write_bytes(out, blob)
    artifact_lib.write_seal_sidecar(out, schema="quality.canary",
                                    version=PROFILE_VERSION, blob=blob)
    return out


def load_canary_file(path: str) -> tuple:
    """(images, scores|None) from a save_canary .npz; the seal sidecar
    (when present — pre-seal artifacts load unsealed) is verified
    first, raising counted ArtifactCorrupt on damage."""
    artifact_lib.verify_sidecar(path, artifact="canary")
    with np.load(path) as z:
        images = np.asarray(z["images"], np.uint8)
        scores = (np.asarray(z["scores"], np.float64)
                  if "scores" in z.files else None)
    return images, scores


class GoldenCanary:
    """Byte-stability sentinel over a pinned image set.

    ``check(score_fn)`` scores the pinned images through the LIVE
    scoring path and compares against the reference scores: the first
    check pins them when none were provided. ``atol=0.0`` (default) is
    exact comparison — the scores of a fixed (checkpoint, bucket) pair
    are deterministic, so ANY deviation is a silent numerical or
    preprocessing regression, exactly the class distribution tests
    cannot see. Telemetry: ``quality.canary_ok`` (1/0 gauge, starts
    optimistic at 1 so alert rules don't fire before the first run),
    ``quality.canary_max_dev``, ``quality.canary_runs`` /
    ``quality.canary_failures`` counters.
    """

    def __init__(
        self,
        images: np.ndarray,
        reference_scores: "np.ndarray | None" = None,
        atol: float = 0.0,
        every_s: float = 300.0,
        registry: "registry_lib.Registry | None" = None,
    ):
        self.images = np.asarray(images, np.uint8)
        if self.images.ndim != 4 or self.images.shape[0] == 0:
            raise ValueError(
                f"canary needs images [n>=1, S, S, 3], got "
                f"{self.images.shape}"
            )
        self.reference = (
            np.asarray(reference_scores, np.float64)
            if reference_scores is not None else None
        )
        self.atol = float(atol)
        self.every_s = float(every_s)
        reg = registry if registry is not None else registry_lib.default_registry()
        self._g_ok = reg.gauge(
            "quality.canary_ok",
            help="1 while the last golden-set canary run matched its "
                 "pinned scores; 0 after a deviation [fleet:min]",
        )
        self._g_dev = reg.gauge(
            "quality.canary_max_dev",
            help="max |score - pinned| of the last canary run "
                 "(-1 = score shape mismatched the pinned set) "
                 "[fleet:max]",
        )
        self._c_runs = reg.counter(
            "quality.canary_runs",
            help="golden-set canary scoring passes attempted (cadence "
                 "ticks + explicit runs)",
        )
        self._c_failures = reg.counter(
            "quality.canary_failures",
            help="canary runs whose scores deviated from the pinned set",
        )
        self._g_ok.set(1.0)
        self._last_run: "float | None" = None
        self._claim_lock = threading.Lock()

    def due(self, now: "float | None" = None) -> bool:
        if self.every_s <= 0:
            return False
        if self._last_run is None:
            return True
        now = time.monotonic() if now is None else now
        return (now - self._last_run) >= self.every_s

    def claim_due(self, now: "float | None" = None) -> bool:
        """Atomic due()+stamp: of several concurrent callers landing on
        a cadence boundary (engine.probs is public and thread-safe),
        exactly ONE wins the run slot — the others must not each pay a
        full canary scoring pass on their live request."""
        with self._claim_lock:
            if not self.due(now):
                return False
            self._last_run = time.monotonic() if now is None else now
            return True

    def check(self, score_fn, now: "float | None" = None) -> dict:
        """Score the pinned set through ``score_fn(images) -> [n]`` and
        compare. Returns {'ok', 'pinned', 'max_abs_dev'}; publishes the
        gauges/counters either way. A score_fn that RAISES (mis-sized
        canary set, serving-path regression) is recorded as a canary
        failure — dev sentinel -1, 'error' key in the result — instead
        of propagating: the canary rides live probs() calls, and a
        broken canary must page, not fail real requests every
        ``every_s``."""
        with self._claim_lock:
            # The cadence stamp is claim_due()'s test-and-set state; an
            # explicit check() (controller gates, tests) must not tear
            # it under a concurrent claim (graftlint: locks rule).
            self._last_run = time.monotonic() if now is None else now
        self._c_runs.inc()
        try:
            scores = np.asarray(score_fn(self.images), np.float64).ravel()
        except Exception as e:  # noqa: BLE001 - any scoring failure
            absl_logging.error(
                "golden canary scoring failed: %s: %s", type(e).__name__, e
            )
            self._g_ok.set(0.0)
            self._g_dev.set(-1.0)
            self._c_failures.inc()
            return {"ok": False, "pinned": False,
                    "max_abs_dev": float("inf"),
                    "error": f"{type(e).__name__}: {e}"}
        if self.reference is None:
            self.reference = scores
            self._g_ok.set(1.0)
            self._g_dev.set(0.0)
            return {"ok": True, "pinned": True, "max_abs_dev": 0.0}
        dev = float(np.max(np.abs(scores - self.reference))) \
            if scores.shape == self.reference.shape else float("inf")
        ok = (
            scores.shape == self.reference.shape
            and (np.array_equal(scores, self.reference) if self.atol == 0.0
                 else bool(np.all(np.abs(scores - self.reference)
                                  <= self.atol)))
        )
        self._g_ok.set(1.0 if ok else 0.0)
        # A shape mismatch (checkpoint-head or canary-set swap) has no
        # finite deviation; -1 keeps the failure distinguishable from
        # "matched exactly" in telemetry instead of reporting 0.0.
        self._g_dev.set(-1.0 if dev == float("inf") else dev)
        if not ok:
            self._c_failures.inc()
        return {"ok": ok, "pinned": False, "max_abs_dev": dev}


# ---------------------------------------------------------------------------
# Online drift monitor
# ---------------------------------------------------------------------------


class QualityMonitor:
    """Sliding-window drift detection against a reference profile.

    ``observe(images, scores)`` is the one hot-path hook (the engine
    calls it once per coalesced batch): O(1) bin increments per row,
    vectorized; when ``window_scores`` scores have accumulated the
    window closes — PSIs are computed against the profile and the
    ``quality.*`` gauges republished — and a fresh window starts
    (tumbling windows: every live score lands in exactly one window).

    Publishes through the PR-3 registry (no new export path):

      * ``quality.score_psi``        — live-vs-profile score-histogram PSI
      * ``quality.score_kl``         — KL(live || profile), same window
      * ``quality.input_psi.{stat}`` — one per INPUT_STATS entry
      * ``quality.input_psi_max``    — max over stats (the alert handle)
      * ``quality.positive_rate``    — fraction >= the profile's primary
        operating threshold (compare against the profile's base rate)
      * ``quality.windows`` / ``quality.scores`` counters, and
        ``quality.profile_loaded`` = profile version (the obs_report
        marker distinguishing "no profile configured" from "configured
        but no data" — the exit-2 case of ``--check-alerts``).

    ``enabled=False`` (or a disabled registry) costs one branch per
    ``observe``. Thread-safe: the accumulate+maybe-publish section runs
    under one lock (serve records from the batcher worker while tests/
    bench drive their own threads).
    """

    def __init__(
        self,
        qcfg,
        registry: "registry_lib.Registry | None" = None,
        profile: "dict | None" = None,
        canary: "GoldenCanary | None" = None,
    ):
        self.enabled = bool(getattr(qcfg, "enabled", True))
        self._registry = (
            registry if registry is not None
            else registry_lib.default_registry()
        )
        self.canary = canary
        # Replaceable input-statistics pass: predict.py swaps in the
        # fused serve-preprocess stats (serve/host.stats_only) when
        # serve.fused_preprocess is on, so observe() stops paying a
        # separate host-numpy per-pixel pass per batch.
        self.stats_fn = input_stat_values
        if not self.enabled:
            self.profile = None
            return
        self.bins = int(getattr(qcfg, "score_bins", 20))
        self.window_scores = max(1, int(getattr(qcfg, "window_scores", 256)))
        self.profile = profile
        self._ref_scores = None
        self._ref_stats: dict = {}
        self.threshold = 0.5
        if profile is not None:
            if int(profile.get("bins", -1)) != self.bins:
                raise ValueError(
                    f"profile has {profile.get('bins')} bins but "
                    f"obs.quality.score_bins={self.bins}; histograms must "
                    "share binning to be comparable"
                )
            self._ref_scores = np.asarray(profile["score_hist"], np.float64)
            self._ref_stats = {
                k: np.asarray(v, np.float64)
                for k, v in profile.get("input_stats", {}).items()
                if k in INPUT_STATS
            }
            thr = profile.get("thresholds") or []
            if thr and "threshold" in thr[0]:
                self.threshold = float(thr[0]["threshold"])
        reg = self._registry
        self._lock = threading.Lock()
        self._g_profile = reg.gauge(
            "quality.profile_loaded",
            help="version of the loaded reference profile (0 = none) "
                 "[fleet:min]",
        )
        self._g_profile.set(
            float(profile["version"]) if profile is not None else 0.0
        )
        self._g_score_psi = reg.gauge(
            "quality.score_psi",
            help="debiased PSI of the live score histogram vs the "
                 "reference profile, per tumbling window (0 = at "
                 "sampling noise; >0.25 shifted) [fleet:max]",
        )
        self._g_score_kl = reg.gauge(
            "quality.score_kl",
            help="KL(live score histogram || reference profile) over "
                 "the same tumbling window as quality.score_psi "
                 "[fleet:max]",
        )
        self._g_pos_rate = reg.gauge(
            "quality.positive_rate",
            help="fraction of window scores above the profile's primary "
                 "operating threshold (compare to its base_rate) "
                 "[fleet:mean]",
        )
        self._g_input_max = reg.gauge(
            "quality.input_psi_max",
            help="max input-statistic PSI over "
                 + "/".join(INPUT_STATS) + " [fleet:max]",
        )
        self._g_input = {
            k: reg.gauge(
                f"quality.input_psi.{k}",
                help="debiased PSI of one post-normalization input "
                     "statistic vs the reference profile "
                     f"({'/'.join(INPUT_STATS)}) [fleet:max]",
            ) for k in INPUT_STATS
        }
        self._c_windows = reg.counter(
            "quality.windows",
            help="closed drift windows (each republishes the quality "
                 "gauges); 0 with a profile loaded means no quality data "
                 "yet — obs_report --check-alerts exit 2",
        )
        self._c_scores = reg.counter(
            "quality.scores",
            help="live scores observed by the drift monitor (canary "
                 "traffic excluded)",
        )
        self._reset_window_locked()

    # -- internals ---------------------------------------------------------

    def _reset_window_locked(self) -> None:
        self._score_counts = np.zeros(self.bins, np.int64)
        self._stat_counts = {
            k: np.zeros(self.bins, np.int64) for k in INPUT_STATS
        }
        self._stat_n = 0
        self._pos = 0
        self._n = 0

    def _publish_locked(self) -> None:
        if self._ref_scores is not None:
            self._g_score_psi.set(
                psi_debiased(self._ref_scores, self._score_counts)
            )
            self._g_score_kl.set(
                kl_divergence(self._ref_scores, self._score_counts)
            )
            worst = 0.0
            if self._stat_n:
                for k, ref in self._ref_stats.items():
                    v = psi_debiased(ref, self._stat_counts[k])
                    self._g_input[k].set(v)
                    worst = max(worst, v)
                self._g_input_max.set(worst)
            else:
                # Tumbling-window semantics: a window with no image
                # statistics (score-only call sites, non-image batcher
                # rows) carries no input-drift evidence — republish 0
                # so a past drifted window's gauges can't stay latched
                # and keep the input-PSI alert firing forever.
                for g in self._g_input.values():
                    g.set(0.0)
                self._g_input_max.set(0.0)
        self._g_pos_rate.set(self._pos / max(1, self._n))
        self._c_windows.inc()
        self._reset_window_locked()

    # -- the hot-path hook -------------------------------------------------

    def observe(
        self,
        images: "np.ndarray | None",
        scores: np.ndarray,
        stats: "dict | None" = None,
    ) -> None:
        """One coalesced batch of live traffic: ``scores`` are the
        ensemble-averaged probabilities the engine returned ([n] binary
        or [n, C] multi — reduced to referable), ``images`` the
        post-normalization uint8 rows they came from (None skips input
        statistics, e.g. score-only call sites). ``stats`` lets a
        caller that already computed the INPUT_STATS dict (the fused
        serve preprocess kernel emits it as a byproduct of
        normalization) hand it in and skip this method's own
        per-pixel pass entirely."""
        if not self.enabled or not self._registry.enabled:
            return
        s = np.asarray(scores, np.float64)
        if s.ndim == 2:
            from jama16_retina_tpu.eval import metrics

            s = np.asarray(
                metrics.referable_probs_from_multiclass(s), np.float64
            )
        s = s.ravel()
        if s.size == 0:
            return
        score_add = bin_counts(s, self.bins)
        pos_add = int((s >= self.threshold).sum())
        # Input statistics are the dominant per-batch cost (a full
        # per-pixel pass); only pay it when the profile carries
        # reference histograms to compare against — the no-profile
        # "positive-rate/canary only" mode must cost what it claims.
        if stats is None:
            stats = (
                self.stats_fn(images)
                if images is not None and self._ref_stats else None
            )
        elif not self._ref_stats:
            stats = None
        with self._lock:
            self._score_counts += score_add
            self._pos += pos_add
            self._n += s.size
            self._c_scores.inc(s.size)
            if stats is not None:
                for k in INPUT_STATS:
                    self._stat_counts[k] += bin_counts(stats[k], self.bins)
                self._stat_n += s.size
            if self._n >= self.window_scores:
                self._publish_locked()

    # -- canary ------------------------------------------------------------

    def canary_due(self, now: "float | None" = None) -> bool:
        return (
            self.enabled and self.canary is not None
            and self.canary.due(now)
        )

    def canary_claim(self, now: "float | None" = None) -> bool:
        """canary_due with the run slot atomically claimed — the form
        concurrent serving callers must use (GoldenCanary.claim_due)."""
        return (
            self.enabled and self.canary is not None
            and self.canary.claim_due(now)
        )

    def run_canary(self, score_fn, now: "float | None" = None) -> "dict | None":
        """Score the pinned set now (cadence bypassed); the engine's
        score_fn must BYPASS observe() so canary traffic never pollutes
        the drift windows (ServingEngine wires member_probs-based
        scoring, not probs)."""
        if not self.enabled or self.canary is None:
            return None
        return self.canary.check(score_fn, now=now)


def monitor_from_config(qcfg, registry=None) -> "QualityMonitor | None":
    """The one construction rule every entry point (engine, predict,
    tests) shares: None when disabled; profile/canary artifacts loaded
    from their configured paths — loudly, a typo'd path must not
    silently disable drift detection."""
    if not getattr(qcfg, "enabled", False):
        return None
    profile = load_profile(qcfg.profile_path) if qcfg.profile_path else None
    canary = None
    if qcfg.canary_path:
        images, pinned = load_canary_file(qcfg.canary_path)
        canary = GoldenCanary(
            images, reference_scores=pinned, atol=qcfg.canary_atol,
            every_s=qcfg.canary_every_s, registry=registry,
        )
    return QualityMonitor(
        qcfg, registry=registry, profile=profile, canary=canary
    )
