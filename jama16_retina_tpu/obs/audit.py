"""Prediction provenance & audit plane (ISSUE 20).

A served score in a diabetic-retinopathy screen is a clinical decision;
this module makes every one attributable and reproducible after the
fact. The :class:`AuditLedger` records, per served request, the PR-15
trace id, a sha256 digest of every post-preprocess input row, the
scores, the per-threshold decisions, and the full model lineage (engine
generation, member checkpoint dirs + content digests, cascade path
taken, serve dtype, bucket shapes, policy artifact provenance, canary
status at serve time) — and ``scripts/audit_query.py`` answers
``trace <id>`` (the complete lineage chain through the lifecycle
journal) and ``replay <id>`` (reassemble the recorded generation and
re-score the audited request, bit-identical on fp32).

Design constraints, in the serve path's order:

  * SERVING NEVER BLOCKS. ``record()`` is a sampling decision + one
    bounded-queue ``put_nowait``; a full spool DROPS the record
    (counted ``audit.dropped``), and every exception inside the audit
    plane is counted and swallowed. The hot-path cost is pinned by
    bench.py's ``audit_overhead_pct`` guard (same ≤2% budget as the
    telemetry pin).
  * DURABILITY IS SEGMENTED. A daemon writer thread drains the spool
    and seals ``seg-NNNNNN.json`` segments (``obs.audit.seal_every``
    records each, plus the tail at ``close()``) through the PR-13
    sealed-artifact seam — atomic publish, content digest, the
    ``audit.seal`` fault site for chaos drills. kill -9 loses at most
    the unsealed tail; restart resumes a FRESH segment number after the
    existing maximum, never overwriting a sealed one.
  * CAPTURE IS OPT-IN. ``obs.audit.capture`` additionally spools the
    consented input tensors through the rawshard writer discipline
    (sealed ``.npy`` + sha256) — what ``replay`` re-scores, and the
    capture substrate ROADMAP item 4's continual learning needs.

Digests and lineage hashing run on the WRITER thread, never the
request path; member-checkpoint content digests are cached per
directory for the life of the process.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import queue
import re
import threading
import time

import numpy as np
from absl import logging as absl_logging

from jama16_retina_tpu.integrity import artifact as artifact_lib
from jama16_retina_tpu.obs import faultinject
from jama16_retina_tpu.obs import registry as obs_registry
from jama16_retina_tpu.obs import trace as obs_trace

SEGMENT_SCHEMA = "audit.segment"
SEGMENT_VERSION = 1
RECORD_VERSION = 1

# Sealed segment files: seg-000000.json, seg-000001.json, ... — the
# FleetBus naming discipline, so fsck/retention walk them the same way.
SEGMENT_RE = re.compile(r"^seg-(\d{6})\.json$")

# Replay tolerance band per serving dtype: fp32 replays BIT-identical
# (the acceptance pin); reduced-precision serving legitimately moves
# scores within the same bounds the engine's own load-time parity check
# accepts (serve/quantize.py), so replay bands rather than pins there.
REPLAY_TOLERANCE = {"fp32": 0.0, "bf16": 1e-2, "int8": 5e-2}

_STOP = object()


def segment_paths(audit_dir: str) -> "list[str]":
    """Sealed segment files of one audit dir, oldest first."""
    try:
        names = sorted(
            n for n in os.listdir(audit_dir) if SEGMENT_RE.match(n)
        )
    except OSError:
        return []
    return [os.path.join(audit_dir, n) for n in names]


def row_digests(images) -> "list[str]":
    """sha256 hex digest per post-preprocess input row — the identity
    replay verifies before trusting a captured tensor."""
    arr = np.ascontiguousarray(np.asarray(images))
    return [hashlib.sha256(arr[i].tobytes()).hexdigest()
            for i in range(arr.shape[0])]


# Checkpoint-directory content digests are immutable once written (a
# retrain writes a NEW candidate dir), so one walk per directory per
# process is enough — and it runs on the audit writer thread, never the
# request path.
_dir_digest_cache: "dict[str, str]" = {}
_dir_digest_lock = threading.Lock()


def checkpoint_digest(member_dir: str) -> str:
    """Content digest of one member checkpoint dir: sha256 over the
    sorted (relative path, size, file sha256) listing. What the audit
    record pins as lineage and what replay re-verifies — a swapped or
    edited checkpoint flips this even when the path is unchanged."""
    key = os.path.abspath(member_dir)
    with _dir_digest_lock:
        got = _dir_digest_cache.get(key)
    if got is not None:
        return got
    h = hashlib.sha256()
    if os.path.isdir(key):
        for root, dirs, files in sorted(os.walk(key)):
            dirs.sort()
            for name in sorted(files):
                p = os.path.join(root, name)
                try:
                    h.update(os.path.relpath(p, key).encode())
                    h.update(str(os.path.getsize(p)).encode())
                    h.update(artifact_lib.sha256_file(p).encode())
                except OSError:
                    h.update(b"<unreadable>")
    else:
        h.update(b"<missing>")
    digest = h.hexdigest()
    with _dir_digest_lock:
        _dir_digest_cache[key] = digest
    return digest


def _referable(scores) -> np.ndarray:
    """Scores -> referable probability [n] for either head (the scalar
    per-threshold decisions are made on)."""
    s = np.asarray(scores, np.float64)
    if s.ndim == 2:
        from jama16_retina_tpu.eval import metrics

        s = np.asarray(
            metrics.referable_probs_from_multiclass(s), np.float64
        )
    return s.ravel()


class AuditLedger:
    """Off-request-path sealed audit ledger (see module docstring).

    ``thresholds``: the operating thresholds per-row decisions are
    recorded at (the evaluate.py operating points; empty records
    probabilities only). ``config_name``/``config_overrides`` pin how
    the serving config was built, so ``replay`` can rebuild the exact
    engine; ``policy_provenance`` is the resolved serve-policy artifact
    identity (serve/policy.py) stamped into every record.
    """

    def __init__(self, audit_dir: str, *,
                 registry: "obs_registry.Registry | None" = None,
                 sample: float = 1.0, seal_every: int = 64,
                 capture: bool = False, queue_max: int = 1024,
                 thresholds=(), config_name: str = "",
                 config_overrides=(),
                 policy_provenance: "dict | None" = None):
        self.dir = audit_dir
        os.makedirs(audit_dir, exist_ok=True)
        self.sample = float(sample)
        # Deterministic every-Nth sampling (the shadow sampler's
        # discipline): sample=1.0 audits everything, 0.1 every 10th
        # request; <= 0 records nothing.
        self._every = (0 if self.sample <= 0
                       else max(1, int(round(1.0 / min(1.0, self.sample)))))
        self.seal_every = max(1, int(seal_every))
        self.capture = bool(capture)
        self.thresholds = tuple(float(t) for t in thresholds)
        self.config_name = str(config_name)
        self.config_overrides = tuple(str(o) for o in config_overrides)
        self.policy_provenance = (
            dict(policy_provenance) if policy_provenance else None
        )
        reg = (registry if registry is not None
               else obs_registry.default_registry())
        self._registry = reg
        self._c_records = reg.counter(
            "audit.records",
            help="served-request audit records accepted into the spool "
                 "(post-sampling; audit plane, ISSUE 20)",
        )
        self._c_rows = reg.counter(
            "audit.rows",
            help="served rows covered by accepted audit records",
        )
        self._c_dropped = reg.counter(
            "audit.dropped",
            help="audit records LOST: spool full, writer stopped, or a "
                 "failed segment seal — serving is never blocked for "
                 "audit durability, losses are counted instead",
        )
        self._c_sealed = reg.counter(
            "audit.sealed_segments",
            help="audit segments sealed durably (atomic sealed-JSON "
                 "publish via the integrity/artifact seam)",
        )
        self._c_seal_errors = reg.counter(
            "audit.seal_errors",
            help="audit segment seal attempts that failed (disk fault, "
                 "injected audit.seal chaos) — the segment's records "
                 "are dropped and counted, the writer keeps going",
        )
        self._c_captured = reg.counter(
            "audit.captured",
            help="input tensors spooled by obs.audit.capture via the "
                 "rawshard writer discipline (sealed .npy + sha256)",
        )
        self._g_depth = reg.gauge(
            "audit.spool_depth",
            help="audit records queued to the writer thread (bounded "
                 "at obs.audit.queue_max; a persistently full spool "
                 "drops records)",
        )
        self._g_last_seal = reg.gauge(
            "audit.last_seal_t",
            help="unix time of the last durable audit segment seal "
                 "(0 = none yet); /healthz derives "
                 "audit_last_seal_age_s from it",
        )
        self._q: "queue.Queue" = queue.Queue(maxsize=max(1, int(queue_max)))
        self._count = 0
        self._count_lock = threading.Lock()
        self._closed = False
        # Resume numbering after the existing maximum: a restarted
        # process begins a FRESH segment, never overwriting sealed
        # history (the kill -9 crash-semantics contract).
        seq = -1
        for p in segment_paths(audit_dir):
            m = SEGMENT_RE.match(os.path.basename(p))
            if m:
                seq = max(seq, int(m.group(1)))
        self._seg_seq = seq + 1
        self._buf: list = []
        self._writer = threading.Thread(
            target=self._writer_loop, name="jama16-audit-writer",
            daemon=True,
        )
        self._writer.start()

    # -- the serving-side surface (never blocks, never raises) -----------

    @property
    def spool_depth(self) -> int:
        return self._q.qsize()

    def record(self, images, scores, *, trace_id: "str | None" = None,
               model: str = "default", replica: "int | None" = None,
               generation: "int | None" = None, member_dirs=None,
               engine=None, escalated=None, speculative: bool = False,
               cascade: "dict | None" = None) -> bool:
        """Enqueue one served request for audit. Returns True when the
        record was accepted (sampled in AND the spool had room); every
        failure path is counted, none raises into serving."""
        try:
            if self._closed or self._every == 0:
                return False
            with self._count_lock:
                self._count += 1
                if self._count % self._every:
                    return False
            if trace_id is None:
                ctx = obs_trace.current_context()
                trace_id = ctx.trace_id if ctx is not None else None
            item = {
                "images": np.asarray(images),
                "scores": np.asarray(scores),
                "trace_id": trace_id,
                "model": str(model),
                "replica": replica,
                "generation": generation,
                "member_dirs": (list(member_dirs)
                                if member_dirs is not None else None),
                "engine": engine,
                "escalated": (np.asarray(escalated, bool).tolist()
                              if escalated is not None else None),
                "speculative": bool(speculative),
                "cascade": dict(cascade) if cascade else None,
                "t": time.time(),
            }
            try:
                self._q.put_nowait(item)
            except queue.Full:
                self._c_dropped.inc()
                return False
            self._c_records.inc()
            self._c_rows.inc(int(item["images"].shape[0]))
            self._g_depth.set(self._q.qsize())
            return True
        except Exception as e:  # noqa: BLE001 - audit must never fail serving
            self._c_dropped.inc()
            absl_logging.error(
                "audit record failed (request unaffected): %s: %s",
                type(e).__name__, e,
            )
            return False

    # -- the writer thread ------------------------------------------------

    def _writer_loop(self) -> None:
        while True:
            item = self._q.get()
            if item is _STOP:
                break
            if "__seal__" in item:  # a flush() checkpoint request
                self._seal()
                item["__seal__"].set()
                continue
            self._g_depth.set(self._q.qsize())
            try:
                self._buf.append(self._build_record(item))
            except Exception as e:  # noqa: BLE001 - counted, not fatal
                self._c_dropped.inc()
                absl_logging.error(
                    "audit record build failed: %s: %s",
                    type(e).__name__, e,
                )
            if len(self._buf) >= self.seal_every:
                self._seal()
        self._seal()  # the tail, on close()

    def _lineage(self, item: dict) -> dict:
        """The model-lineage half of a record, resolved on the writer
        thread: generation id, member dirs + cached content digests,
        serve dtype and bucket shapes, plus the cascade path taken."""
        engine = item["engine"]
        member_dirs = item["member_dirs"]
        generation = item["generation"]
        # A routed replica may be a composed CascadeEngine: its
        # ensemble half carries the generation lineage (and, when the
        # record didn't already, the cascade identity).
        ens = getattr(engine, "ensemble", None)
        if ens is not None and not hasattr(engine, "_gen"):
            if item["cascade"] is None:
                sgen = getattr(
                    getattr(engine, "student", None), "_gen", None
                )
                if sgen is not None:
                    # escalated stays None: the per-row mask is
                    # internal to the cascade at this seam — the
                    # record is honest about what it pinned, and
                    # replay reports such records unreplayable
                    # rather than guessing the path.
                    item["cascade"] = {
                        "student_dirs": list(sgen.member_dirs)
                    }
            engine = ens
        if member_dirs is None and engine is not None:
            gen = getattr(engine, "_gen", None)
            if gen is not None and (generation is None
                                    or int(gen.gen_id) == int(generation)):
                member_dirs = gen.member_dirs
                if generation is None:
                    generation = int(gen.gen_id)
        out = {
            "generation": (int(generation)
                           if generation is not None else None),
            "member_dirs": (list(member_dirs)
                            if member_dirs else None),
            "member_digests": (
                {d: checkpoint_digest(d) for d in member_dirs}
                if member_dirs else None
            ),
            "serve_dtype": str(getattr(engine, "dtype", "fp32")),
            "buckets": [int(b) for b in getattr(engine, "buckets", ())],
            "max_batch": None,
        }
        cfg = getattr(engine, "cfg", None)
        if cfg is not None:
            out["max_batch"] = int(cfg.serve.max_batch)
        if item["escalated"] is not None or item["cascade"] is not None:
            out["cascade"] = {
                "escalated": item["escalated"],
                "speculative": item["speculative"],
                **(item["cascade"] or {}),
            }
        return out

    def _build_record(self, item: dict) -> dict:
        images, scores = item["images"], item["scores"]
        ref = _referable(scores)
        rec = {
            "record_version": RECORD_VERSION,
            "t": round(item["t"], 3),
            "trace_id": item["trace_id"],
            "model": item["model"],
            "replica": item["replica"],
            "n": int(images.shape[0]),
            "input_sha256": row_digests(images),
            "scores": np.asarray(scores, np.float64).tolist(),
            "referable": ref.tolist(),
            "decisions": {
                f"{t:g}": (ref >= t).tolist() for t in self.thresholds
            },
            **self._lineage(item),
            "policy": self.policy_provenance,
            "canary_ok": self._canary_status(),
            "config": {
                "name": self.config_name,
                "overrides": list(self.config_overrides),
            },
        }
        if self.capture:
            rec["capture"] = self._capture(item, images)
        return rec

    def _canary_status(self) -> "float | None":
        """The golden-canary gauge AT SERVE TIME (None when no canary
        is wired) — read, never created: registering the gauge here
        would make an un-monitored deployment look like a failing one."""
        g = self._registry.peek("quality.canary_ok")
        return float(g.value) if g is not None else None

    def _capture(self, item: dict, images) -> "dict | None":
        """Spool the consented input tensor through the rawshard
        writer discipline (sealed atomic .npy; the sha256 of the
        written bytes rides the record, so replay verifies the file
        before trusting it)."""
        try:
            from jama16_retina_tpu.data import rawshard

            cap_dir = os.path.join(self.dir, "capture")
            os.makedirs(cap_dir, exist_ok=True)
            name = f"cap-{self._seg_seq:06d}-{len(self._buf):04d}.npy"
            digest = rawshard._atomic_save(
                os.path.join(cap_dir, name), np.asarray(images)
            )
            self._c_captured.inc()
            return {"file": os.path.join("capture", name),
                    "sha256": digest}
        except Exception as e:  # noqa: BLE001 - counted, not fatal
            self._c_dropped.inc()
            absl_logging.error(
                "audit capture failed (record kept, digests only): "
                "%s: %s", type(e).__name__, e,
            )
            return None

    def _seal(self) -> None:
        """Durably publish the buffered records as one sealed segment.
        A failure (real disk fault or the ``audit.seal`` chaos site)
        loses exactly this segment's records — counted twice over
        (``audit.seal_errors`` + per-record ``audit.dropped``), logged,
        and the writer keeps draining; serving never notices."""
        if not self._buf:
            return
        buf, self._buf = self._buf, []
        path = os.path.join(self.dir, f"seg-{self._seg_seq:06d}.json")
        try:
            faultinject.check("audit.seal")
            artifact_lib.write_sealed_json(path, {
                "kind": "audit_segment",
                "seq": self._seg_seq,
                "records": buf,
            }, schema=SEGMENT_SCHEMA, version=SEGMENT_VERSION)
        except Exception as e:  # noqa: BLE001 - counted, not fatal
            self._c_seal_errors.inc()
            self._c_dropped.inc(len(buf))
            absl_logging.error(
                "audit segment seal failed (%d records lost): %s: %s",
                len(buf), type(e).__name__, e,
            )
            return
        self._seg_seq += 1
        self._c_sealed.inc()
        self._g_last_seal.set(time.time())

    # -- control ----------------------------------------------------------

    def flush(self, timeout_s: float = 10.0) -> None:
        """Drain the spool and seal everything buffered so far (tests,
        smoke, cadence callers). Serving-side ``record`` keeps working
        afterwards — this is a checkpoint, not a close."""
        deadline = time.monotonic() + timeout_s
        while (not self._q.empty()) and time.monotonic() < deadline:
            time.sleep(0.005)
        # One sentinel round-trip makes the writer seal its buffer:
        # re-arm the loop by sending a no-op seal request.
        evt = threading.Event()
        self._q.put({"__seal__": evt})
        evt.wait(timeout=max(0.0, deadline - time.monotonic()))

    def close(self, timeout_s: float = 10.0) -> None:
        """Stop the writer and seal the tail. Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._q.put(_STOP)
        self._writer.join(timeout=timeout_s)
        self._g_depth.set(0)


def resolve_audit_dir(cfg, workdir: "str | None") -> "str | None":
    """Where the ledger spools: ``obs.audit.dir`` wins; empty falls
    back to ``<workdir>/audit``; neither = None (skip, loudly)."""
    ac = cfg.obs.audit
    if ac.dir:
        return ac.dir
    if workdir:
        return os.path.join(workdir, "audit")
    return None


def ledger_for(cfg, workdir: "str | None" = None, *,
               registry: "obs_registry.Registry | None" = None,
               thresholds=None, config_overrides=(),
               policy_provenance: "dict | None" = None
               ) -> "AuditLedger | None":
    """The wiring-site constructor: None when ``obs.audit.enabled`` is
    off (one branch at the call site) or no directory is resolvable.
    ``thresholds`` defaults to ``serve.cascade_thresholds`` — the
    operating points the deployment decides on."""
    ac = cfg.obs.audit
    if not ac.enabled:
        return None
    audit_dir = resolve_audit_dir(cfg, workdir)
    if audit_dir is None:
        absl_logging.error(
            "obs.audit.enabled is set but neither obs.audit.dir nor a "
            "workdir is available — audit ledger NOT started"
        )
        return None
    if thresholds is None:
        thresholds = cfg.serve.cascade_thresholds or ()
    return AuditLedger(
        audit_dir,
        registry=registry,
        sample=ac.sample,
        seal_every=ac.seal_every,
        capture=ac.capture,
        queue_max=ac.queue_max,
        thresholds=thresholds,
        config_name=cfg.name,
        config_overrides=config_overrides,
        policy_provenance=policy_provenance,
    )


# ---------------------------------------------------------------------------
# Readers: lineage queries + deterministic replay (scripts/audit_query.py)
# ---------------------------------------------------------------------------


def iter_records(audit_dir: str, strict: bool = False):
    """Yield ``(record, segment_path)`` across every sealed segment,
    oldest first. A corrupt/torn segment raises in ``strict`` mode;
    otherwise it is skipped with a loud log line (graftfsck is the
    classifier, the query tool the survivor)."""
    for path in segment_paths(audit_dir):
        try:
            payload, _seal = artifact_lib.read_sealed_json(
                path, artifact="audit"
            )
        except Exception as e:  # noqa: BLE001 - skip damaged segments
            if strict:
                raise
            absl_logging.warning(
                "audit segment %s unreadable (%s: %s) — skipped; run "
                "scripts/graftfsck.py to classify",
                path, type(e).__name__, e,
            )
            continue
        for rec in payload.get("records", ()):
            yield rec, path


def find_records(audit_dir: str, trace_id: str) -> "list[dict]":
    """Every sealed record carrying ``trace_id`` (a multi-bin routed
    request, or a fused bin's per-request slices, may have several)."""
    return [rec for rec, _p in iter_records(audit_dir)
            if rec.get("trace_id") == trace_id]


def _load_journal_entries(journal_dir: str) -> "list[dict]":
    path = os.path.join(journal_dir, "journal.json")
    if not os.path.exists(path):
        return []
    doc, _seal = artifact_lib.read_sealed_json(path, artifact="journal")
    return list(doc.get("entries", ()))


def lineage_chain(record: dict,
                  journal_dir: "str | None" = None) -> dict:
    """The complete provenance chain behind one audit record: score ->
    generation -> promoting lifecycle cycle -> gate verdicts ->
    training data manifest -> warm-start donors. Journal-less
    deployments (a bare predict batch) get the record's own lineage
    with ``cycle: None`` — every link that exists is rendered, none is
    invented."""
    chain = {
        "trace_id": record.get("trace_id"),
        "model": record.get("model"),
        "generation": record.get("generation"),
        "member_dirs": record.get("member_dirs"),
        "member_digests": record.get("member_digests"),
        "serve_dtype": record.get("serve_dtype"),
        "policy": record.get("policy"),
        "canary_ok": record.get("canary_ok"),
        "cascade": record.get("cascade"),
        "cycle": None,
    }
    if not journal_dir:
        return chain
    entries = _load_journal_entries(journal_dir)
    gen = record.get("generation")
    cycle = None
    for e in entries:
        if (e.get("state") in ("STAGED_ROLLOUT", "COMMIT")
                and e.get("generation") == gen):
            cycle = e["cycle"]
    if cycle is None:
        return chain
    ce = [e for e in entries if e.get("cycle") == cycle]

    def _find(state):
        for e in reversed(ce):
            if e.get("state") == state:
                return e
        return None

    drift = _find("DRIFT_DETECTED")
    retrain = _find("RETRAIN")
    gate = _find("GATE")
    chain["cycle"] = cycle
    chain["drift"] = drift
    chain["retrain"] = retrain
    chain["gate_verdicts"] = gate.get("verdicts") if gate else None
    chain["rollout"] = _find("STAGED_ROLLOUT")
    chain["commit"] = _find("COMMIT")
    # Warm-start donors: the live set the cycle's trigger snapshotted
    # (what RETRAIN fine-tuned from), refined per member by the durable
    # RETRAIN_DONE markers when the candidate dirs still exist.
    donors = list((drift or {}).get("live_member_dirs") or ())
    markers = []
    for d in (retrain or {}).get("member_dirs") or ():
        marker = os.path.join(d, "RETRAIN_DONE.json")
        if os.path.exists(marker):
            try:
                doc, _seal = artifact_lib.read_sealed_json(marker)
                markers.append({"member_dir": d,
                                "init_from": doc.get("init_from"),
                                "steps": doc.get("steps"),
                                "best_auc": doc.get("best_auc")})
            except Exception:  # noqa: BLE001 - marker is advisory
                pass
    chain["warm_start_donors"] = donors or None
    chain["retrain_markers"] = markers or None
    chain["data_manifest"] = (retrain or {}).get("data_manifest")
    return chain


@dataclasses.dataclass(frozen=True)
class ReplayVerdict:
    """Typed outcome of one deterministic replay."""

    trace_id: "str | None"
    ok: bool
    kind: str  # bit_equal | within_band | score_mismatch |
    #            lineage_changed | no_capture | unreplayable
    dtype: str = "fp32"
    max_abs_dev: "float | None" = None
    tolerance: float = 0.0
    detail: str = ""

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def _replay_config(record: dict, extra_overrides=()):
    """Rebuild the serving config the record was scored under: the
    recorded preset + recorded overrides (+ caller extras), then the
    recorded serve dtype / bucket shapes pinned on top — the shapes,
    and therefore the fp32 bits, match the served dispatch exactly."""
    from jama16_retina_tpu import configs

    cfg = configs.get_config(record["config"]["name"])
    ov = list(record["config"].get("overrides") or ())
    ov += list(extra_overrides)
    if ov:
        cfg = configs.override(cfg, ov)
    serve = dataclasses.replace(
        cfg.serve,
        dtype=record.get("serve_dtype", "fp32"),
        bucket_sizes=tuple(record.get("buckets") or ()),
        **({"max_batch": int(record["max_batch"])}
           if record.get("max_batch") else {}),
    )
    return cfg.replace(serve=serve)


def load_captured(audit_dir: str, record: dict) -> np.ndarray:
    """The captured input tensor, verified against the record twice:
    file bytes vs the capture sha256, then per-row digests vs
    ``input_sha256`` — replay must score the exact served bytes or
    refuse."""
    cap = record.get("capture")
    if not cap:
        raise FileNotFoundError(
            "record carries no captured input (obs.audit.capture was "
            "off at serve time) — replay needs the original tensors"
        )
    path = os.path.join(audit_dir, cap["file"])
    actual = artifact_lib.sha256_file(path)
    if actual != cap["sha256"]:
        artifact_lib.count_corrupt("audit")
        raise artifact_lib.ArtifactCorrupt(
            path, cap["sha256"], actual, artifact="audit",
            detail="captured audit tensor",
        )
    images = np.load(path)
    if row_digests(images) != record["input_sha256"]:
        raise ValueError(
            f"captured tensor {path} does not match the record's "
            "per-row input digests — refusing to replay"
        )
    return images


def replay_record(record: dict, audit_dir: str, *,
                  extra_overrides=(), workdir: "str | None" = None,
                  registry: "obs_registry.Registry | None" = None
                  ) -> ReplayVerdict:
    """Reassemble the recorded generation through the EngineSpec/
    compile-cache path, re-score the audited request, and pin the
    outcome: fp32 BIT-identical, reduced precision tolerance-banded.
    A mismatch (or changed lineage) returns a typed verdict and dumps
    an ``audit_replay_mismatch`` blackbox into ``workdir``."""
    dtype = str(record.get("serve_dtype", "fp32"))
    trace_id = record.get("trace_id")
    member_dirs = record.get("member_dirs")
    if not member_dirs:
        return _mismatch(ReplayVerdict(
            trace_id=trace_id, ok=False, kind="lineage_changed",
            dtype=dtype, detail="record carries no member dirs",
        ), record, workdir, registry)
    # Lineage first: replay through a swapped checkpoint would compare
    # scores of a DIFFERENT model and call the ledger a liar.
    want = record.get("member_digests") or {}
    for d in member_dirs:
        have = checkpoint_digest(d)
        if want.get(d) and have != want[d]:
            return _mismatch(ReplayVerdict(
                trace_id=trace_id, ok=False, kind="lineage_changed",
                dtype=dtype,
                detail=f"checkpoint {d} digest {have[:12]} != sealed "
                       f"{want[d][:12]}",
            ), record, workdir, registry)
    casc = record.get("cascade")
    if (casc and casc.get("student_dirs")
            and casc.get("escalated") is None):
        return ReplayVerdict(
            trace_id=trace_id, ok=False, kind="unreplayable",
            dtype=dtype,
            detail="cascade record without a sealed escalation "
                   "mask (routed-replica seam) — the served path "
                   "cannot be re-walked deterministically",
        )
    try:
        images = load_captured(audit_dir, record)
    except FileNotFoundError as e:
        return ReplayVerdict(trace_id=trace_id, ok=False,
                             kind="no_capture", dtype=dtype,
                             detail=str(e))
    from jama16_retina_tpu import models
    from jama16_retina_tpu.serve.assemble import EngineSpec, assemble

    cfg = _replay_config(record, extra_overrides)
    model = models.build(cfg.model)
    if casc and casc.get("student_dirs"):
        replayed = _replay_cascade(cfg, model, record, images)
    else:
        engine = assemble(EngineSpec(
            cfg=cfg, member_dirs=tuple(member_dirs), model=model,
            cascade=False,
        ))
        replayed = np.asarray(engine.probs(images), np.float64)
    served = np.asarray(record["scores"], np.float64)
    if replayed.shape != served.shape:
        return _mismatch(ReplayVerdict(
            trace_id=trace_id, ok=False, kind="score_mismatch",
            dtype=dtype,
            detail=f"shape {replayed.shape} vs sealed {served.shape}",
        ), record, workdir, registry)
    dev = float(np.max(np.abs(replayed - served))) if served.size else 0.0
    tol = REPLAY_TOLERANCE.get(dtype, 0.0)
    if dtype == "fp32":
        if np.array_equal(replayed, served):
            return ReplayVerdict(trace_id=trace_id, ok=True,
                                 kind="bit_equal", dtype=dtype,
                                 max_abs_dev=dev, tolerance=0.0)
    elif dev <= tol:
        return ReplayVerdict(trace_id=trace_id, ok=True,
                             kind="within_band", dtype=dtype,
                             max_abs_dev=dev, tolerance=tol)
    return _mismatch(ReplayVerdict(
        trace_id=trace_id, ok=False, kind="score_mismatch", dtype=dtype,
        max_abs_dev=dev, tolerance=tol,
        detail=f"max |replayed - served| = {dev:g} (tolerance {tol:g})",
    ), record, workdir, registry)


def _replay_cascade(cfg, model, record: dict, images) -> np.ndarray:
    """Re-walk the recorded cascade path: the student scores every
    row, the recorded escalation mask (the path TAKEN, not recomputed)
    selects which rows the full ensemble re-scores — the same bucket
    shapes as the served dispatch, so fp32 stays bit-identical."""
    from jama16_retina_tpu.serve.assemble import EngineSpec, assemble

    casc = record["cascade"]
    student = assemble(EngineSpec(
        cfg=cfg, member_dirs=tuple(casc["student_dirs"]), model=model,
        cascade=False,
    ))
    out = np.asarray(student.probs(images), np.float64)
    mask = np.asarray(casc.get("escalated") or (), bool)
    if mask.any():
        ensemble = assemble(EngineSpec(
            cfg=cfg, member_dirs=tuple(record["member_dirs"]),
            model=model, cascade=False,
        ))
        out = np.array(out)
        if casc.get("speculative"):
            esc = np.asarray(ensemble.probs(images), np.float64)
            out[mask] = esc[mask]
        else:
            out[mask] = np.asarray(
                ensemble.probs(images[mask]), np.float64
            )
    return out


def _mismatch(verdict: ReplayVerdict, record: dict,
              workdir: "str | None",
              registry: "obs_registry.Registry | None") -> ReplayVerdict:
    """Every failed replay is a blackbox moment: dump the verdict +
    record identity through the flight recorder (one per reason per
    run), so the mismatch survives for forensics even when the CLI's
    exit code is all the operator noticed."""
    if workdir:
        try:
            from jama16_retina_tpu.obs import flightrec

            flightrec.FlightRecorder(
                workdir, registry=registry
            ).dump("audit_replay_mismatch",
                   verdict=verdict.as_dict(),
                   trace_id=record.get("trace_id"),
                   generation=record.get("generation"),
                   model=record.get("model"))
        except Exception as e:  # noqa: BLE001 - forensics best-effort
            absl_logging.error(
                "audit_replay_mismatch blackbox dump failed: %s: %s",
                type(e).__name__, e,
            )
    return verdict
