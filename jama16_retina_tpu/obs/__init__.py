"""Unified runtime telemetry (ISSUE 3): counters/gauges/histograms with
stall attribution across the trainer, the data tiers, and serving.

Before this subsystem the only runtime signals were the train loop's
JSONL records and the offline bench — the 10x pipeline-fed gap
(BENCH_r05) had to be diagnosed with hand-written one-off benchmarks,
and the serving engine exposed zero runtime telemetry. tf.data's lesson
(arXiv:2101.12127) is that FIRST-CLASS input-pipeline instrumentation
is what makes such bottlenecks routinely visible; this package applies
it system-wide:

  * ``registry`` — named Counters/Gauges/fixed-bucket Histograms with
    snapshot quantiles; O(1) lock-guarded hot-path ops; a process-wide
    default registry plus injectable instances for tests.
  * ``spans``    — ``span(name)`` timing contexts feeding histograms
    (one branch when disabled), and ``StallClock``: the trainer's
    per-window stall attribution (input-wait / dispatch / pause /
    other, summing to window wall time).
  * ``export``   — the periodic Snapshotter: ``telemetry`` records
    through the run's RunLog JSONL, an atomically-rewritten
    ``<workdir>/telemetry.prom`` (Prometheus text format), and an
    explicit per-process ``heartbeat`` record (step +
    last_progress_t) replacing the implicit metrics.p{N}.jsonl-mtime
    probe of SURVEY.md §5.3.

Event tracing + the black-box flight recorder (ISSUE 4) ride on top:

  * ``trace``    — bounded per-thread ring buffers of timestamped
    events with Chrome trace-event JSON export (Perfetto-loadable);
    ``span()``/``StallClock`` call sites upgrade to trace events with
    no call-site changes, and the serve path stamps request-scoped
    segment events (queue-wait / window-fill / device / resolve) that
    sum to ``serve.request_latency_s``.
  * ``flightrec`` — anomaly-triggered dumps of last-N trace events +
    registry snapshot + config to ``<workdir>/blackbox/`` on unhandled
    exception, SIGTERM/SIGINT, non-finite loss, or a step above
    ``obs.slow_step_factor`` × the rolling median — plus one
    trigger-driven ``jax.profiler`` capture per run through the
    trainer's ``_ProfilerWindow.arm``.

Model & data quality observability (ISSUE 5) closes the loop from
infra health to MODEL health:

  * ``quality``  — versioned reference profiles (val-split score +
    input-statistic histograms, base rate, operating thresholds;
    ``evaluate.py --profile_out``), the online ``QualityMonitor``
    (windowed PSI/KL drift gauges ``quality.score_psi`` /
    ``quality.input_psi.{stat}`` / ``quality.positive_rate`` through
    this registry), and the byte-stable ``GoldenCanary``.
  * ``alerts``   — declarative SLO rules (``metric OP threshold [for
    SECONDS] [-> reason]``, incl. ``rate()`` burn-rate form) evaluated
    at Snapshotter flush cadence; firing writes ``alert`` JSONL
    records, trips the flight recorder's ``quality_drift`` /
    ``slo_breach`` triggers (one dump per reason per run), and flips
    ``scripts/obs_report.py --check-alerts`` exit status.

Render either output with ``scripts/obs_report.py``; the metric-name
glossary lives in docs/OBSERVABILITY.md. The hot-path cost is pinned by
bench.py's telemetry- and tracing-overhead guards (device_only with
either enabled must stay within 2% of off) and
tests/test_bench_guard.py's per-op bound.
"""

from jama16_retina_tpu.obs.alerts import AlertManager, AlertRule, parse_rule
from jama16_retina_tpu.obs.flightrec import FlightRecorder
from jama16_retina_tpu.obs.quality import (
    GoldenCanary,
    QualityMonitor,
    build_profile,
    load_profile,
    monitor_from_config,
    psi,
    save_profile,
)
from jama16_retina_tpu.obs.registry import (
    DEFAULT_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
    default_registry,
    set_default_registry,
)
from jama16_retina_tpu.obs.spans import StallClock, span
from jama16_retina_tpu.obs.trace import (
    Tracer,
    chrome_trace,
    default_tracer,
    set_default_tracer,
)

__all__ = [
    "DEFAULT_BUCKETS",
    "AlertManager",
    "AlertRule",
    "Counter",
    "FlightRecorder",
    "Gauge",
    "GoldenCanary",
    "Histogram",
    "QualityMonitor",
    "Registry",
    "StallClock",
    "Tracer",
    "build_profile",
    "chrome_trace",
    "default_registry",
    "default_tracer",
    "load_profile",
    "monitor_from_config",
    "parse_rule",
    "psi",
    "save_profile",
    "set_default_registry",
    "set_default_tracer",
    "span",
]
