"""Offline preprocessing layer (SURVEY.md N3; reference R3/R4/R6/R10)."""

from jama16_retina_tpu.preprocess.fundus import (  # noqa: F401
    FundusNotFound,
    find_fundus_circle,
    resize_and_center_fundus,
)
