"""Dataset preprocessing runners (reference R3/R4/R10, SURVEY.md §3.3).

Shared machinery for ``preprocess_eyepacs.py`` / ``preprocess_messidor.py``:
flexible label-CSV parsing, stratified train/val/test partitioning,
image -> fundus-normalize -> JPEG -> sharded TFRecords. Pure CPU.

Label CSVs in the wild differ (EyePACS ``image,level``; Messidor-2
``Image name;Retinopathy grade;...``), so the parser sniffs the delimiter
and picks the name/grade columns by header keywords, falling back to
(first, second) column for headerless files.
"""

from __future__ import annotations

import csv
import dataclasses
import os
from typing import Iterator, Sequence

import numpy as np

from jama16_retina_tpu.data import tfrecord
from jama16_retina_tpu.preprocess import fundus

IMAGE_EXTENSIONS = (".jpeg", ".jpg", ".png", ".tif", ".tiff", ".JPG")


def parse_labels_csv(path: str) -> dict[str, int]:
    """-> {image_name_without_extension: grade}."""
    with open(path, newline="") as fh:
        sample = fh.read(4096)
        fh.seek(0)
        delim = ";" if sample.count(";") > sample.count(",") else ","
        rows = list(csv.reader(fh, delimiter=delim))
    if not rows:
        raise ValueError(f"empty labels file {path!r}")

    header = [c.strip().lower() for c in rows[0]]
    name_col, grade_col = 0, 1
    has_header = any(not _is_int(c) for c in rows[0][1:2]) and any(
        k in " ".join(header) for k in ("image", "name", "level", "grade")
    )
    if has_header:
        for i, col in enumerate(header):
            if "image" in col or "name" in col:
                name_col = i
                break
        for i, col in enumerate(header):
            if "level" in col or "grade" in col or "retinopathy" in col:
                grade_col = i
                break
        rows = rows[1:]

    labels: dict[str, int] = {}
    for row in rows:
        if len(row) <= max(name_col, grade_col) or not row[name_col].strip():
            continue
        name = os.path.splitext(row[name_col].strip())[0]
        labels[name] = int(float(row[grade_col].strip()))
    if not labels:
        raise ValueError(f"no (name, grade) rows parsed from {path!r}")
    return labels


def _is_int(s: str) -> bool:
    try:
        int(float(s.strip()))
        return True
    except (ValueError, AttributeError):
        return False


def find_image(data_dir: str, name: str) -> str | None:
    for ext in IMAGE_EXTENSIONS:
        p = os.path.join(data_dir, name + ext)
        if os.path.exists(p):
            return p
    return None


def stratified_split(
    labels: dict[str, int], val_frac: float, test_frac: float, seed: int = 0
) -> dict[str, list[tuple[str, int]]]:
    """Per-grade shuffle then slice — keeps grade marginals equal across
    splits (the reference partitioned per-class; SURVEY.md R3)."""
    rng = np.random.default_rng(seed)
    splits: dict[str, list[tuple[str, int]]] = {"train": [], "val": [], "test": []}
    by_grade: dict[int, list[str]] = {}
    for name, g in sorted(labels.items()):
        by_grade.setdefault(g, []).append(name)
    for g, names in sorted(by_grade.items()):
        names = list(names)
        rng.shuffle(names)
        n = len(names)
        n_test = int(round(n * test_frac))
        n_val = int(round(n * val_frac))
        for name in names[:n_test]:
            splits["test"].append((name, g))
        for name in names[n_test:n_test + n_val]:
            splits["val"].append((name, g))
        for name in names[n_test + n_val:]:
            splits["train"].append((name, g))
    return splits


@dataclasses.dataclass
class PreprocessStats:
    written: int = 0
    skipped_missing: int = 0
    skipped_unreadable: int = 0
    skipped_no_fundus: int = 0
    skipped_low_quality: int = 0
    # Summary of the gradability scores of WRITTEN records (the filter
    # threshold should be chosen from the report's distribution).
    quality_mean: float = 0.0
    quality_min: float = 0.0

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


def _process_one(task: tuple) -> tuple:
    """The per-image stage — one (name, grade) -> (status, quality-dict,
    serialized example bytes). Module-level and arg-packed so the
    ``--workers`` process pool can pickle it; the serial path runs the
    SAME function, which is what makes the pooled output byte-identical
    by construction (every stage here — cv2 decode, fundus normalize,
    JPEG encode, proto serialize — is deterministic per image)."""
    (name, grade, data_dir, image_size, ben_graham, jpeg_quality,
     encoding, min_quality) = task
    import cv2

    path = find_image(data_dir, name)
    if path is None:
        return "missing", None, None
    bgr = cv2.imread(path, cv2.IMREAD_COLOR)
    if bgr is None:
        return "unreadable", None, None
    rgb = bgr[..., ::-1]
    try:
        norm, q = fundus.resize_and_center_fundus(
            rgb, diameter=image_size, ben_graham=ben_graham,
            with_quality=True,
        )
    except fundus.FundusNotFound:
        return "no_fundus", None, None
    if q["quality"] < min_quality:
        return "low_quality", q, None
    if encoding == "raw":
        ex = tfrecord.make_raw_example(norm, grade, name, quality=q["quality"])
    else:
        ex = tfrecord.make_example(
            tfrecord.encode_jpeg(norm, quality=jpeg_quality),
            grade, name, quality=q["quality"],
        )
    # deterministic=True: proto MAP fields (the Features dict) otherwise
    # serialize in per-process hash order, and the pooled run's spawned
    # children each have their own hash seed — the records would parse
    # identically but differ byte-for-byte from the serial run's.
    return "written", q, ex.SerializeToString(deterministic=True)


def process_split(
    items: Sequence[tuple[str, int]],
    data_dir: str,
    out_dir: str,
    split: str,
    image_size: int = 299,
    num_shards: int = 16,
    ben_graham: bool = False,
    jpeg_quality: int = 92,
    encoding: str = "jpeg",
    min_quality: float = 0.0,
    workers: int = 0,
) -> PreprocessStats:
    """Normalize every (name, grade) image and write TFRecord shards.

    ``encoding='raw'`` stores pre-decoded uint8 pixels (~9x disk at
    299px) so the training host never pays a per-epoch JPEG decode —
    the feed-rate mitigation measured in bench.py / docs/PERF.md.

    Every image gets a gradability score (fundus.gradability_stats),
    stored in its record (image/quality) and in the per-image report CSV
    ``<out_dir>/quality_<split>.csv``; ``min_quality`` > 0 additionally
    DROPS images scoring below it — the executable form of the original
    JAMA study's image-quality grading step (docs/QUALITY.md).

    ``workers`` > 0 fans the per-image stage (_process_one) over that
    many processes (SURVEY.md §3.3: "parallelized over CPU workers" —
    ~0.1-0.3 s/image serial means hours over EyePACS' ~88k images on a
    one-core loop, and preprocessing sits on the critical path of the
    end-to-end wall-clock story). ``imap`` keeps results in item order,
    and the single consumer below does ALL writing, so shards and the
    quality CSV are byte-identical to the serial run's (pinned by
    tests/test_preprocess.py). Spawned (not forked) children: the
    parent may already hold an initialized TF runtime, which does not
    survive fork.
    """
    if encoding not in ("jpeg", "raw"):
        raise ValueError(f"encoding must be jpeg|raw, got {encoding!r}")
    stats = PreprocessStats()
    qualities: list[float] = []
    os.makedirs(out_dir, exist_ok=True)
    report_path = os.path.join(out_dir, f"quality_{split}.csv")
    report = open(report_path, "w", newline="")
    report_csv = csv.writer(report)
    report_csv.writerow(["name", "grade", "quality", "lap_var", "mean",
                        "std", "written"])

    tasks = [
        (name, grade, data_dir, image_size, ben_graham, jpeg_quality,
         encoding, min_quality)
        for name, grade in items
    ]
    _BUMP = {
        "missing": "skipped_missing",
        "unreadable": "skipped_unreadable",
        "no_fundus": "skipped_no_fundus",
        "low_quality": "skipped_low_quality",
    }

    def consume(results) -> Iterator[bytes]:
        for (name, grade, *_), (status, q, data) in zip(tasks, results):
            if q is not None:
                keep = status == "written"
                report_csv.writerow([
                    name, grade, q["quality"], q["lap_var"], q["mean"],
                    q["std"], int(keep),
                ])
            if status != "written":
                setattr(stats, _BUMP[status],
                        getattr(stats, _BUMP[status]) + 1)
                continue
            stats.written += 1
            qualities.append(q["quality"])
            yield data

    pool = None
    if workers > 0:
        import multiprocessing as mp

        pool = mp.get_context("spawn").Pool(workers)
        results = pool.imap(_process_one, tasks, chunksize=8)
    else:
        results = map(_process_one, tasks)
    ok = False
    try:
        tfrecord.write_example_shards(
            consume(results), out_dir, split, num_shards
        )
        ok = True
    finally:
        report.close()
        if pool is not None:
            if ok:
                pool.close()
            else:
                # imap's feeder has already queued the FULL task list;
                # close()+join() here would decode every remaining image
                # (hours at EyePACS scale) before the writer's error
                # (disk full, Ctrl-C) ever surfaced.
                pool.terminate()
            pool.join()
    if qualities:
        stats.quality_mean = round(float(np.mean(qualities)), 4)
        stats.quality_min = round(float(np.min(qualities)), 4)
    return stats
