"""Fundus normalization (reference R6: ``lib/preprocess``, SURVEY.md §3.3).

Raw EyePACS/Messidor photographs are rectangular frames with the roughly
circular retina somewhere inside, at wildly varying scales and exposure.
The reference normalizes each image so the fundus disc has a fixed
radius, centered, on black background, cropped to 299x299 — that is what
this module reproduces, CPU-side with OpenCV/numpy (it never touches the
TPU; SURVEY.md §1 preprocessing layer).

Pipeline per image:
  1. threshold a downsampled grayscale copy to find lit (non-background)
     pixels;
  2. fit the fundus circle from the lit region's bounding extent;
  3. uniformly rescale so the circle's diameter equals
     ``diameter * fill`` pixels;
  4. paste centered on a black ``diameter x diameter`` canvas;
  5. optionally apply a circular mask to zero residual border glare.

An optional contrast enhancement (``ben_graham=True``: subtract a local
Gaussian average — the classic Kaggle-DR trick) is provided for the
quality push toward the 0.97 AUC target (SURVEY.md §6 note); it is OFF
by default to match the reference's plain normalization.
"""

from __future__ import annotations

import dataclasses

import numpy as np


class FundusNotFound(ValueError):
    """No circular lit region detected (blank/corrupt photograph)."""


@dataclasses.dataclass(frozen=True)
class Circle:
    cx: float
    cy: float
    radius: float


def find_fundus_circle(
    image_rgb: np.ndarray, threshold: int = 12, min_radius_frac: float = 0.05
) -> Circle:
    """Locate the fundus disc: bounding extent of above-threshold pixels.

    Row/column projections of the lit mask are robust to the dark corners
    and small specular highlights typical of fundus frames, and cost one
    pass over a grayscale copy — no Hough transform needed.
    """
    if image_rgb.ndim != 3 or image_rgb.shape[-1] != 3:
        raise ValueError(f"expected HWC RGB, got shape {image_rgb.shape}")
    gray = image_rgb.astype(np.float32).mean(axis=-1)
    mask = gray > threshold
    rows = np.flatnonzero(mask.any(axis=1))
    cols = np.flatnonzero(mask.any(axis=0))
    if rows.size == 0 or cols.size == 0:
        raise FundusNotFound("no pixels above background threshold")
    y0, y1 = rows[0], rows[-1]
    x0, x1 = cols[0], cols[-1]
    # The disc is the inscribed circle of the lit extent; when the frame
    # crops top/bottom (common in EyePACS), width is the trustworthy axis.
    radius = max(x1 - x0 + 1, y1 - y0 + 1) / 2.0
    cx = (x0 + x1 + 1) / 2.0
    cy = (y0 + y1 + 1) / 2.0
    if radius < min_radius_frac * max(image_rgb.shape[:2]):
        raise FundusNotFound(f"detected radius {radius:.1f}px too small")
    return Circle(cx=cx, cy=cy, radius=radius)


def _gaussian_blur(image: np.ndarray, sigma: float) -> np.ndarray:
    import cv2

    return cv2.GaussianBlur(image, (0, 0), sigmaX=sigma, sigmaY=sigma)


def ben_graham_enhance(image: np.ndarray, alpha: float = 4.0) -> np.ndarray:
    """Subtract the local average color (Gaussian ~radius/30) — evens out
    illumination differences between cameras; from the winning Kaggle
    EyePACS recipe. Input/output uint8 RGB."""
    f = image.astype(np.float32)
    blur = _gaussian_blur(f, sigma=max(image.shape[0] / 30.0, 1.0))
    out = alpha * (f - blur) + 128.0
    return np.clip(out, 0, 255).astype(np.uint8)


def _circle_mask(diameter: int, fill: float) -> np.ndarray:
    yy, xx = np.mgrid[0:diameter, 0:diameter]
    r = diameter * fill / 2.0
    return ((xx - diameter / 2 + 0.5) ** 2
            + (yy - diameter / 2 + 0.5) ** 2) <= r * r


def gradability_stats(
    norm_rgb: np.ndarray, fill: float = 0.98
) -> dict[str, float]:
    """Cheap image-quality / gradability heuristics for one NORMALIZED
    fundus canvas (pre-enhancement), restricted to the fundus circle.

    The replication's hypothesized AUC gap vs the original JAMA study is
    the original's non-public image-quality grading (docs/QUALITY.md,
    SURVEY.md §6 note) — this is the executable stand-in: a [0, 1]
    ``quality`` score combining

      * sharpness  — Laplacian variance inside the circle (the classic
        focus measure; blur collapses it),
      * illumination — penalize under/over-exposed means (a window, not
        a target: fundus cameras differ in brightness),
      * contrast   — grayscale std inside the circle (washed-out frames
        carry no gradeable vasculature).

    Each term saturates smoothly; the score is their product. It is a
    HEURISTIC proxy for gradability, meant for ranking/filtering
    (``--min_quality``), not a calibrated probability — thresholds
    should be chosen by inspecting the preprocessing report's
    distribution.
    """
    import cv2

    if norm_rgb.ndim != 3 or norm_rgb.shape[0] != norm_rgb.shape[1]:
        raise ValueError(f"expected square HWC canvas, got {norm_rgb.shape}")
    d = norm_rgb.shape[0]
    gray = cv2.cvtColor(norm_rgb, cv2.COLOR_RGB2GRAY)
    mask = _circle_mask(d, fill)
    vals = gray[mask].astype(np.float32)
    lap = cv2.Laplacian(gray, cv2.CV_32F)
    lap_var = float(lap[mask].var())
    mean = float(vals.mean())
    std = float(vals.std())
    # Saturation constants chosen on synthetic + public fundus ranges:
    # sharp fundus photographs at 299px sit at lap_var ~100-1000, heavy
    # blur < 10; usable illumination means ~40-220 of 255; gradeable
    # contrast std ≳ 25.
    sharpness = 1.0 - float(np.exp(-lap_var / 50.0))
    if mean < 40.0:
        illum = mean / 40.0
    elif mean > 220.0:
        illum = max(0.0, (255.0 - mean) / 35.0)
    else:
        illum = 1.0
    contrast = 1.0 - float(np.exp(-std / 25.0))
    return {
        "quality": round(sharpness * illum * contrast, 4),
        "lap_var": round(lap_var, 2),
        "mean": round(mean, 2),
        "std": round(std, 2),
    }


def resize_and_center_fundus(
    image_rgb: np.ndarray,
    diameter: int = 299,
    fill: float = 0.98,
    circular_mask: bool = True,
    ben_graham: bool = False,
    threshold: int = 12,
    with_quality: bool = False,
):
    """Normalize one photograph to a centered fixed-radius fundus
    (the reference's ``resize_and_center_fundus``, SURVEY.md R6).

    Returns uint8 RGB ``[diameter, diameter, 3]`` — or, with
    ``with_quality``, a ``(canvas, gradability_stats)`` pair where the
    stats are computed on the PRE-enhancement canvas (ben-graham
    deliberately flattens illumination and boosts edges, which would
    blind the very heuristics meant to catch bad captures). Raises
    FundusNotFound for blank frames (callers count and skip these, as
    the reference's preprocessing scripts did).
    """
    import cv2

    circle = find_fundus_circle(image_rgb, threshold=threshold)
    scale = (diameter * fill) / (2.0 * circle.radius)
    resized = cv2.resize(
        image_rgb, None, fx=scale, fy=scale,
        interpolation=cv2.INTER_AREA if scale < 1 else cv2.INTER_CUBIC,
    )
    cx, cy = circle.cx * scale, circle.cy * scale

    canvas = np.zeros((diameter, diameter, 3), dtype=np.uint8)
    # Source window centered on the fundus, clipped to the resized frame.
    half = diameter / 2.0
    sx0 = int(round(cx - half)); sy0 = int(round(cy - half))
    sx1, sy1 = sx0 + diameter, sy0 + diameter
    dx0 = max(0, -sx0); dy0 = max(0, -sy0)
    sx0 = max(0, sx0); sy0 = max(0, sy0)
    sx1 = min(resized.shape[1], sx1); sy1 = min(resized.shape[0], sy1)
    w = sx1 - sx0; h = sy1 - sy0
    if w <= 0 or h <= 0:
        raise FundusNotFound("fundus window fell outside the frame")
    canvas[dy0:dy0 + h, dx0:dx0 + w] = resized[sy0:sy1, sx0:sx1]

    quality = gradability_stats(canvas, fill) if with_quality else None
    if ben_graham:
        canvas = ben_graham_enhance(canvas)
    if circular_mask:
        canvas[~_circle_mask(diameter, fill)] = 0
    return (canvas, quality) if with_quality else canvas
