"""Flax Inception-v3, weight-matched to the TF-Slim layout.

The reference builds its model with the TF-Slim ``inception_v3`` graph
builder (BASELINE.json:5; SURVEY.md R7). This is a from-scratch Flax
re-implementation of that architecture — stem, Mixed_5b..Mixed_7c blocks,
optional auxiliary head off Mixed_6e, global average pool, dropout,
logits — with module names mirroring the slim variable scopes so a weight
transplant is a mechanical tree rename (tested against
``tf.keras.applications.InceptionV3``, the locally available twin of the
slim builder; SURVEY.md §4.2).

Input: NHWC float images, nominally 299x299x3 in [-1, 1].
Output: ``(logits[N, num_classes], aux_logits or None)``.

TPU notes: all convs run in bfloat16 on the MXU with float32 BN (see
``common.ConvBN``); the whole forward is trace-once/static-shape, so XLA
fuses the elementwise tails into the conv kernels.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax
import jax.numpy as jnp

from jama16_retina_tpu.models.common import BN_EPS, BN_MOMENTUM, ConvBN


def _avg_pool_same(x):
    # count_include_pad=False: TF/slim AvgPool averages over valid (non-
    # padded) cells only; flax's include-pad default drifts every branch_pool
    # output at the spatial boundary (caught by the keras transplant parity
    # test — logit corr 0.9987 instead of exact).
    return nn.avg_pool(
        x, (3, 3), strides=(1, 1), padding="SAME", count_include_pad=False
    )


class InceptionA(nn.Module):
    """35x35 block (slim Mixed_5b/5c/5d): 1x1 / 5x5 / double-3x3 / pool."""

    pool_features: int
    dtype: Any = jnp.bfloat16
    axis_name: str | None = None

    @nn.compact
    def __call__(self, x, train: bool):
        cbn = lambda f, k, name: ConvBN(  # noqa: E731
            f, k, dtype=self.dtype, axis_name=self.axis_name, name=name
        )
        b1 = cbn(64, (1, 1), "Branch_0_Conv2d_0a_1x1")(x, train)
        b5 = cbn(48, (1, 1), "Branch_1_Conv2d_0a_1x1")(x, train)
        b5 = cbn(64, (5, 5), "Branch_1_Conv2d_0b_5x5")(b5, train)
        b3 = cbn(64, (1, 1), "Branch_2_Conv2d_0a_1x1")(x, train)
        b3 = cbn(96, (3, 3), "Branch_2_Conv2d_0b_3x3")(b3, train)
        b3 = cbn(96, (3, 3), "Branch_2_Conv2d_0c_3x3")(b3, train)
        bp = _avg_pool_same(x)
        bp = cbn(self.pool_features, (1, 1), "Branch_3_Conv2d_0b_1x1")(bp, train)
        return jnp.concatenate([b1, b5, b3, bp], axis=-1)


class InceptionB(nn.Module):
    """35->17 grid reduction (slim Mixed_6a)."""

    dtype: Any = jnp.bfloat16
    axis_name: str | None = None

    @nn.compact
    def __call__(self, x, train: bool):
        cbn = lambda f, k, s, p, name: ConvBN(  # noqa: E731
            f, k, strides=s, padding=p, dtype=self.dtype,
            axis_name=self.axis_name, name=name,
        )
        b3 = cbn(384, (3, 3), (2, 2), "VALID", "Branch_0_Conv2d_1a_3x3")(x, train)
        bd = cbn(64, (1, 1), (1, 1), "SAME", "Branch_1_Conv2d_0a_1x1")(x, train)
        bd = cbn(96, (3, 3), (1, 1), "SAME", "Branch_1_Conv2d_0b_3x3")(bd, train)
        bd = cbn(96, (3, 3), (2, 2), "VALID", "Branch_1_Conv2d_1a_3x3")(bd, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b3, bd, bp], axis=-1)


class InceptionC(nn.Module):
    """17x17 block with factorized 7x7 (slim Mixed_6b..6e)."""

    channels_7x7: int
    dtype: Any = jnp.bfloat16
    axis_name: str | None = None

    @nn.compact
    def __call__(self, x, train: bool):
        c7 = self.channels_7x7
        cbn = lambda f, k, name: ConvBN(  # noqa: E731
            f, k, dtype=self.dtype, axis_name=self.axis_name, name=name
        )
        b1 = cbn(192, (1, 1), "Branch_0_Conv2d_0a_1x1")(x, train)
        b7 = cbn(c7, (1, 1), "Branch_1_Conv2d_0a_1x1")(x, train)
        b7 = cbn(c7, (1, 7), "Branch_1_Conv2d_0b_1x7")(b7, train)
        b7 = cbn(192, (7, 1), "Branch_1_Conv2d_0c_7x1")(b7, train)
        bd = cbn(c7, (1, 1), "Branch_2_Conv2d_0a_1x1")(x, train)
        bd = cbn(c7, (7, 1), "Branch_2_Conv2d_0b_7x1")(bd, train)
        bd = cbn(c7, (1, 7), "Branch_2_Conv2d_0c_1x7")(bd, train)
        bd = cbn(c7, (7, 1), "Branch_2_Conv2d_0d_7x1")(bd, train)
        bd = cbn(192, (1, 7), "Branch_2_Conv2d_0e_1x7")(bd, train)
        bp = _avg_pool_same(x)
        bp = cbn(192, (1, 1), "Branch_3_Conv2d_0b_1x1")(bp, train)
        return jnp.concatenate([b1, b7, bd, bp], axis=-1)


class InceptionD(nn.Module):
    """17->8 grid reduction (slim Mixed_7a)."""

    dtype: Any = jnp.bfloat16
    axis_name: str | None = None

    @nn.compact
    def __call__(self, x, train: bool):
        cbn = lambda f, k, s, p, name: ConvBN(  # noqa: E731
            f, k, strides=s, padding=p, dtype=self.dtype,
            axis_name=self.axis_name, name=name,
        )
        b3 = cbn(192, (1, 1), (1, 1), "SAME", "Branch_0_Conv2d_0a_1x1")(x, train)
        b3 = cbn(320, (3, 3), (2, 2), "VALID", "Branch_0_Conv2d_1a_3x3")(b3, train)
        b7 = cbn(192, (1, 1), (1, 1), "SAME", "Branch_1_Conv2d_0a_1x1")(x, train)
        b7 = cbn(192, (1, 7), (1, 1), "SAME", "Branch_1_Conv2d_0b_1x7")(b7, train)
        b7 = cbn(192, (7, 1), (1, 1), "SAME", "Branch_1_Conv2d_0c_7x1")(b7, train)
        b7 = cbn(192, (3, 3), (2, 2), "VALID", "Branch_1_Conv2d_1a_3x3")(b7, train)
        bp = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        return jnp.concatenate([b3, b7, bp], axis=-1)


class InceptionE(nn.Module):
    """8x8 block with expanded filter-bank splits (slim Mixed_7b/7c)."""

    dtype: Any = jnp.bfloat16
    axis_name: str | None = None

    @nn.compact
    def __call__(self, x, train: bool):
        cbn = lambda f, k, name: ConvBN(  # noqa: E731
            f, k, dtype=self.dtype, axis_name=self.axis_name, name=name
        )
        b1 = cbn(320, (1, 1), "Branch_0_Conv2d_0a_1x1")(x, train)

        b3 = cbn(384, (1, 1), "Branch_1_Conv2d_0a_1x1")(x, train)
        b3 = jnp.concatenate(
            [
                cbn(384, (1, 3), "Branch_1_Conv2d_0b_1x3")(b3, train),
                cbn(384, (3, 1), "Branch_1_Conv2d_0c_3x1")(b3, train),
            ],
            axis=-1,
        )
        bd = cbn(448, (1, 1), "Branch_2_Conv2d_0a_1x1")(x, train)
        bd = cbn(384, (3, 3), "Branch_2_Conv2d_0b_3x3")(bd, train)
        bd = jnp.concatenate(
            [
                cbn(384, (1, 3), "Branch_2_Conv2d_0c_1x3")(bd, train),
                cbn(384, (3, 1), "Branch_2_Conv2d_0d_3x1")(bd, train),
            ],
            axis=-1,
        )
        bp = _avg_pool_same(x)
        bp = cbn(192, (1, 1), "Branch_3_Conv2d_0b_1x1")(bp, train)
        return jnp.concatenate([b1, b3, bd, bp], axis=-1)


class _Kernel(nn.Module):
    """Bare conv-kernel holder whose scope name mirrors nn.Conv's, so
    S2DStemConv's parameter tree is IDENTICAL to ConvBN's
    (<name>/conv/kernel, float32 (3,3,3,32)) — checkpoints, the keras
    transplant map, and the baseline stem all interchange freely."""

    shape: tuple

    @nn.compact
    def __call__(self):
        return self.param(
            "kernel", nn.initializers.lecun_normal(), self.shape, jnp.float32
        )


class S2DStemConv(nn.Module):
    """Space-to-depth form of the stride-2 3x3 VALID stem conv
    (ModelConfig.stem_s2d; the MLPerf-ResNet input trick, re-derived for
    this stem): pad 299->300, fold 2x2 spatial blocks into channels
    (B,150,150,12), and convolve with a 2x2/stride-1 kernel built
    IN-GRAPH from the same logical (3,3,3,32) parameter —

        W'[Di,Dj,(di,dj,c),o] = W[2Di+di, 2Dj+dj, c, o]   (0 past 3x3)

    which computes exactly the original conv's sums: output pixel i
    covers original rows 2i..2i+3, of which the 3x3 taps are the
    non-zero ones, and the 300th padded row/col only ever meets the
    zeroed tap offset 3. The point is MXU shape, not math: a 3-channel
    input conv wastes 125/128 of the MXU's contracting lanes, the
    12-channel form 4x less, and the largest low-channel activation
    (299^2x3) never exists on device. BN/ReLU identical to ConvBN."""

    features: int = 32
    dtype: Any = jnp.bfloat16
    axis_name: str | None = None

    @nn.compact
    def __call__(self, x, train: bool):
        c_in = x.shape[-1]
        w = _Kernel((3, 3, c_in, self.features), name="conv")()
        w4 = jnp.pad(w, ((0, 1), (0, 1), (0, 0), (0, 0)))
        # (4,4,c,o) -> (Di,di,Dj,dj,c,o) -> (Di,Dj,di,dj,c,o) -> 2x2 HWIO
        w_s2d = (
            w4.reshape(2, 2, 2, 2, c_in, self.features)
            .transpose(0, 2, 1, 3, 4, 5)
            .reshape(2, 2, 4 * c_in, self.features)
        )
        n, h, w_sz, _ = x.shape
        assert h == w_sz, "stem_s2d assumes square inputs"
        # Blocks must cover every row the 2x2 block-conv reads for the
        # original output size (h-3)//2 + 1; the trailing zero-pad rows
        # only ever meet the zeroed tap offset 3 (exactness note above).
        blocks = (h - 3) // 2 + 2
        pad = 2 * blocks - h
        x = jnp.pad(x, ((0, 0), (0, pad), (0, pad), (0, 0)))
        # (di, dj, c) fold order matches w_s2d's (di slowest).
        x = (
            x.reshape(n, blocks, 2, blocks, 2, c_in)
            .transpose(0, 1, 3, 2, 4, 5)
            .reshape(n, blocks, blocks, 4 * c_in)
        )
        y = jax.lax.conv_general_dilated(
            x.astype(self.dtype), w_s2d.astype(self.dtype),
            window_strides=(1, 1), padding="VALID",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        y = nn.BatchNorm(
            use_running_average=not train,
            momentum=BN_MOMENTUM, epsilon=BN_EPS, use_scale=False,
            dtype=self.dtype,
            axis_name=self.axis_name if train else None,
            name="bn",
        )(y)
        return nn.relu(y).astype(self.dtype)


class AuxHead(nn.Module):
    """Slim auxiliary classifier off Mixed_6e (17x17x768 input)."""

    num_classes: int
    dtype: Any = jnp.bfloat16
    axis_name: str | None = None

    @nn.compact
    def __call__(self, x, train: bool):
        x = nn.avg_pool(x, (5, 5), strides=(3, 3), padding="VALID")
        x = ConvBN(
            128, (1, 1), dtype=self.dtype, axis_name=self.axis_name,
            name="Conv2d_1b_1x1",
        )(x, train)
        x = ConvBN(
            768, x.shape[1:3], padding="VALID", dtype=self.dtype,
            axis_name=self.axis_name, name="Conv2d_2a_5x5",
        )(x, train)
        x = x.mean(axis=(1, 2))
        x = nn.Dense(
            self.num_classes, dtype=jnp.float32, param_dtype=jnp.float32,
            name="Logits",
        )(x.astype(jnp.float32))
        return x


class InceptionV3(nn.Module):
    """The flagship backbone (reference R7, BASELINE.json:7)."""

    num_classes: int = 1
    aux_head: bool = True
    dropout_rate: float = 0.2
    dtype: Any = jnp.bfloat16
    axis_name: str | None = None
    stem_s2d: bool = False
    remat_stem: bool = False

    def _stem(self, x, train: bool):
        """Stem: 299x299x3 -> 35x35x192 (the HBM-heavy low-channel part;
        both VERDICT r3 #2 levers act here and only here)."""
        kw = dict(dtype=self.dtype, axis_name=self.axis_name)
        if self.stem_s2d:
            x = S2DStemConv(name="Conv2d_1a_3x3", **kw)(x, train)
        else:
            x = ConvBN(32, (3, 3), strides=(2, 2), padding="VALID",
                       name="Conv2d_1a_3x3", **kw)(x, train)
        x = ConvBN(32, (3, 3), padding="VALID", name="Conv2d_2a_3x3", **kw)(x, train)
        x = ConvBN(64, (3, 3), name="Conv2d_2b_3x3", **kw)(x, train)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")
        x = ConvBN(80, (1, 1), padding="VALID", name="Conv2d_3b_1x1", **kw)(x, train)
        x = ConvBN(192, (3, 3), padding="VALID", name="Conv2d_4a_3x3", **kw)(x, train)
        return nn.max_pool(x, (3, 3), strides=(2, 2), padding="VALID")

    @nn.compact
    def __call__(self, x, train: bool = False):
        kw = dict(dtype=self.dtype, axis_name=self.axis_name)
        x = x.astype(self.dtype)
        if self.remat_stem:
            # Method-level nn.remat keeps every stem parameter at its
            # original path (self's scope is shared); train is static.
            x = nn.remat(type(self)._stem, static_argnums=(2,))(
                self, x, train
            )
        else:
            x = self._stem(x, train)

        # 35x35 blocks.
        x = InceptionA(pool_features=32, name="Mixed_5b", **kw)(x, train)
        x = InceptionA(pool_features=64, name="Mixed_5c", **kw)(x, train)
        x = InceptionA(pool_features=64, name="Mixed_5d", **kw)(x, train)
        # 17x17 blocks.
        x = InceptionB(name="Mixed_6a", **kw)(x, train)
        x = InceptionC(channels_7x7=128, name="Mixed_6b", **kw)(x, train)
        x = InceptionC(channels_7x7=160, name="Mixed_6c", **kw)(x, train)
        x = InceptionC(channels_7x7=160, name="Mixed_6d", **kw)(x, train)
        x = InceptionC(channels_7x7=192, name="Mixed_6e", **kw)(x, train)

        aux = None
        if self.aux_head:
            aux = AuxHead(
                num_classes=self.num_classes, name="AuxLogits", **kw
            )(x, train)

        # 8x8 blocks.
        x = InceptionD(name="Mixed_7a", **kw)(x, train)
        x = InceptionE(name="Mixed_7b", **kw)(x, train)
        x = InceptionE(name="Mixed_7c", **kw)(x, train)

        # Head: global average pool -> dropout -> logits (float32).
        x = x.mean(axis=(1, 2)).astype(jnp.float32)
        x = nn.Dropout(rate=self.dropout_rate, deterministic=not train)(x)
        logits = nn.Dense(
            self.num_classes, dtype=jnp.float32, param_dtype=jnp.float32,
            name="Logits",
        )(x)
        return logits, aux
