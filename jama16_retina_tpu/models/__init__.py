"""Model zoo behind the ``build()`` plugin boundary (SURVEY.md N5).

The reference exposes a TF-Slim ``inception_v3`` graph builder; the north
star (BASELINE.json:5) makes the model builder the plugin boundary so the
surrounding train/eval code never sees architecture details. Here
``build(model_cfg)`` returns a Flax module with one uniform call contract:

    variables = model.init(rngs, images, train=False)
    (logits, aux_logits), mutated = model.apply(
        variables, images, train=True,
        mutable=["batch_stats"], rngs={"dropout": key})

``aux_logits`` is ``None`` for architectures without an auxiliary head.
``axis_name`` threads the data-parallel mesh axis into BatchNorm for the
explicit pmap/shard_map path; under jit-over-global-arrays it stays None
because XLA GSPMD already computes global-batch statistics (SURVEY.md N8).
"""

from __future__ import annotations

import jax.numpy as jnp

from jama16_retina_tpu.configs import ModelConfig
from jama16_retina_tpu.models.efficientnet import EfficientNet
from jama16_retina_tpu.models.inception_v3 import InceptionV3
from jama16_retina_tpu.models.resnet import ResNet50
from jama16_retina_tpu.models.tiny_cnn import TinyCNN

_DTYPES = {
    "bfloat16": jnp.bfloat16,
    "float32": jnp.float32,
}


def build(cfg: ModelConfig, axis_name: str | None = None,
          backend: str = "flax"):
    """Construct the model named by ``cfg.arch`` (reference R7).

    ``backend`` is the plugin boundary from the north star
    (BASELINE.json:5 ``model.build(backend=...)``): ``"flax"`` (default)
    returns the TPU-native Flax module; ``"tf"`` returns the legacy-graph
    stand-in — a tf.keras InceptionV3 whose weights are loaded from the
    same orbax checkpoints (models/tf_backend.py) so the evaluation code
    downstream is untouched.
    """
    if backend == "tf":
        from jama16_retina_tpu.models import tf_backend

        return tf_backend.build_tf(cfg)
    if backend != "flax":
        raise ValueError(f"unknown backend {backend!r} (want 'flax' or 'tf')")
    dtype = _DTYPES[cfg.compute_dtype]
    common = dict(
        num_classes=cfg.num_classes,
        dtype=dtype,
        axis_name=axis_name,
        dropout_rate=cfg.dropout_rate,
    )
    if cfg.arch == "inception_v3":
        return InceptionV3(
            aux_head=cfg.aux_head,
            stem_s2d=cfg.stem_s2d,
            remat_stem=cfg.remat_stem,
            **common,
        )
    if cfg.arch == "resnet50":
        return ResNet50(**common)
    if cfg.arch == "efficientnet_b4":
        return EfficientNet.b4(**common)
    if cfg.arch == "tiny_cnn":
        return TinyCNN(**common)
    raise ValueError(f"unknown arch {cfg.arch!r}")
