"""Weight transplant: tf.keras InceptionV3 -> the Flax tree (SURVEY.md §4.2).

Operationalizes "weight-matched Flax Inception-v3" (BASELINE.json:5)
against the locally available twin of the reference's TF-Slim builder,
``tf.keras.applications.InceptionV3``. Both builders create the same 94
conv+BN pairs in the same program order; keras encodes that creation
order in its layer-name suffixes (``conv2d_17`` / its paired
``batch_normalization_17``), while this package encodes it in slim-style
scope names (``Mixed_6b/Branch_1_Conv2d_0b_1x7``). ``FLAX_CONV_ORDER``
below is the explicit bridge: the flax module paths listed in keras
creation order. Every transplanted kernel is shape-checked, so an
ordering mistake fails loudly rather than producing silently-wrong
weights.

Layout facts this relies on (asserted where cheap):
  * keras conv kernels are HWIO — identical to flax; no transpose.
  * both builders use bias-free convs and scale-free BatchNorm
    (beta/moving_mean/moving_variance only), eps 1e-3.
  * the classifier head is a Dense on the 2048-d pooled features
    (keras ``predictions`` -> flax ``Logits``).
  * keras InceptionV3 has no auxiliary head; the flax aux head (a slim
    feature) is untouched by the transplant.
"""

from __future__ import annotations

import re
from typing import Any

import numpy as np

# Flax module paths of the 94 ConvBN cells, in the order the keras/slim
# builders create them: stem, then each mixed block branch-by-branch in
# source order (branch outputs are concatenated in this same order).
_STEM = [
    ("Conv2d_1a_3x3",), ("Conv2d_2a_3x3",), ("Conv2d_2b_3x3",),
    ("Conv2d_3b_1x1",), ("Conv2d_4a_3x3",),
]
_BLOCK_A = [  # Mixed_5b/5c/5d
    "Branch_0_Conv2d_0a_1x1",
    "Branch_1_Conv2d_0a_1x1", "Branch_1_Conv2d_0b_5x5",
    "Branch_2_Conv2d_0a_1x1", "Branch_2_Conv2d_0b_3x3", "Branch_2_Conv2d_0c_3x3",
    "Branch_3_Conv2d_0b_1x1",
]
_BLOCK_B = [  # Mixed_6a
    "Branch_0_Conv2d_1a_3x3",
    "Branch_1_Conv2d_0a_1x1", "Branch_1_Conv2d_0b_3x3", "Branch_1_Conv2d_1a_3x3",
]
_BLOCK_C = [  # Mixed_6b..6e
    "Branch_0_Conv2d_0a_1x1",
    "Branch_1_Conv2d_0a_1x1", "Branch_1_Conv2d_0b_1x7", "Branch_1_Conv2d_0c_7x1",
    "Branch_2_Conv2d_0a_1x1", "Branch_2_Conv2d_0b_7x1", "Branch_2_Conv2d_0c_1x7",
    "Branch_2_Conv2d_0d_7x1", "Branch_2_Conv2d_0e_1x7",
    "Branch_3_Conv2d_0b_1x1",
]
_BLOCK_D = [  # Mixed_7a
    "Branch_0_Conv2d_0a_1x1", "Branch_0_Conv2d_1a_3x3",
    "Branch_1_Conv2d_0a_1x1", "Branch_1_Conv2d_0b_1x7", "Branch_1_Conv2d_0c_7x1",
    "Branch_1_Conv2d_1a_3x3",
]
_BLOCK_E = [  # Mixed_7b/7c
    "Branch_0_Conv2d_0a_1x1",
    "Branch_1_Conv2d_0a_1x1", "Branch_1_Conv2d_0b_1x3", "Branch_1_Conv2d_0c_3x1",
    "Branch_2_Conv2d_0a_1x1", "Branch_2_Conv2d_0b_3x3",
    "Branch_2_Conv2d_0c_1x3", "Branch_2_Conv2d_0d_3x1",
    "Branch_3_Conv2d_0b_1x1",
]

FLAX_CONV_ORDER: list[tuple[str, ...]] = (
    _STEM
    + [("Mixed_5b", n) for n in _BLOCK_A]
    + [("Mixed_5c", n) for n in _BLOCK_A]
    + [("Mixed_5d", n) for n in _BLOCK_A]
    + [("Mixed_6a", n) for n in _BLOCK_B]
    + [("Mixed_6b", n) for n in _BLOCK_C]
    + [("Mixed_6c", n) for n in _BLOCK_C]
    + [("Mixed_6d", n) for n in _BLOCK_C]
    + [("Mixed_6e", n) for n in _BLOCK_C]
    + [("Mixed_7a", n) for n in _BLOCK_D]
    + [("Mixed_7b", n) for n in _BLOCK_E]
    + [("Mixed_7c", n) for n in _BLOCK_E]
)


def _creation_index(name: str, prefix: str) -> int | None:
    """'conv2d' -> 0, 'conv2d_17' -> 17; None for unrelated layers."""
    m = re.fullmatch(rf"{prefix}(?:_(\d+))?", name)
    if not m:
        return None
    return int(m.group(1) or 0)


def keras_conv_bn_pairs(keras_model) -> list[tuple[Any, Any]]:
    """The 94 (Conv2D, BatchNormalization) pairs in CREATION order.

    ``model.layers`` is topological order, but each ``conv2d_N`` was
    created together with ``batch_normalization_N`` (keras
    ``conv2d_bn``), so the name index is the reliable pairing/order key.
    """
    import tensorflow as tf

    convs: dict[int, Any] = {}
    bns: dict[int, Any] = {}
    for layer in keras_model.layers:
        if isinstance(layer, tf.keras.layers.Conv2D):
            idx = _creation_index(layer.name, "conv2d")
            if idx is not None:
                convs[idx] = layer
        elif isinstance(layer, tf.keras.layers.BatchNormalization):
            idx = _creation_index(layer.name, "batch_normalization")
            if idx is not None:
                bns[idx] = layer
    # keras name counters are process-global, so the first index is an
    # arbitrary offset (94 if another InceptionV3 was built earlier in the
    # process) — and conv2d/batch_normalization counters advance
    # independently. Creation order is the rank within each contiguous
    # index range, so pair by rank, not by absolute index.
    conv_idx, bn_idx = sorted(convs), sorted(bns)
    contiguous = lambda xs: xs == list(range(xs[0], xs[0] + len(xs)))
    if (len(convs) != len(bns) or not convs
            or not contiguous(conv_idx) or not contiguous(bn_idx)):
        raise ValueError(
            "unexpected keras layer naming: conv indices "
            f"{conv_idx[:5]}.. vs bn indices {bn_idx[:5]}.. — "
            "non-contiguous creation indices break order-based pairing"
        )
    return [(convs[i], bns[j]) for i, j in zip(conv_idx, bn_idx)]


def _set_in(tree: dict, path: tuple[str, ...], leaf: str, value, expect_shape):
    node = tree
    for p in path:
        node = node[p]
    old = node[leaf]
    if tuple(np.shape(old)) != tuple(expect_shape):
        raise ValueError(
            f"shape mismatch at {'/'.join(path)}/{leaf}: flax "
            f"{tuple(np.shape(old))} vs keras {tuple(expect_shape)}"
        )
    node[leaf] = np.asarray(value, dtype=np.asarray(old).dtype)


def transplant_from_keras(
    keras_model, params, batch_stats
) -> tuple[Any, Any]:
    """Return (params, batch_stats) with the keras weights copied in.

    Covers the full backbone (94 ConvBN cells) and the classifier Dense
    when the class counts match; leaves the flax aux head (absent from
    keras) untouched. Raises on any shape mismatch.
    """
    import jax

    params = jax.tree.map(np.asarray, jax.device_get(params))
    batch_stats = jax.tree.map(np.asarray, jax.device_get(batch_stats))

    pairs = keras_conv_bn_pairs(keras_model)
    if len(pairs) != len(FLAX_CONV_ORDER):
        raise ValueError(
            f"expected {len(FLAX_CONV_ORDER)} conv/bn pairs, keras model "
            f"has {len(pairs)}"
        )
    for (conv, bn), path in zip(pairs, FLAX_CONV_ORDER):
        kernel = conv.kernel.numpy()  # HWIO in both frameworks
        _set_in(params, (*path, "conv"), "kernel", kernel, kernel.shape)
        beta = bn.beta.numpy()
        _set_in(params, (*path, "bn"), "bias", beta, beta.shape)
        _set_in(batch_stats, (*path, "bn"), "mean",
                bn.moving_mean.numpy(), beta.shape)
        _set_in(batch_stats, (*path, "bn"), "var",
                bn.moving_variance.numpy(), beta.shape)

    # Classifier head ('predictions' -> 'Logits') when the widths agree.
    dense = next(
        (l for l in keras_model.layers if l.name == "predictions"), None
    )
    if dense is not None and "Logits" in params:
        k = dense.kernel.numpy()
        if tuple(np.shape(params["Logits"]["kernel"])) == tuple(k.shape):
            _set_in(params, ("Logits",), "kernel", k, k.shape)
            _set_in(params, ("Logits",), "bias",
                    dense.bias.numpy(), dense.bias.shape)
    return params, batch_stats
