"""Flax ResNet-50 — backbone swap option (BASELINE.json:11, SURVEY.md N5).

Standard bottleneck-v1 ResNet-50 (He et al. 2016): 7x7/2 stem, 3-4-6-3
bottleneck stages with expansion 4. Unlike the Inception cell, ResNet BN
keeps its learned scale (no ReLU directly after the residual-add path's
last BN). Same ``(logits, aux=None)`` contract and numerics policy as the
rest of the zoo (bf16 convs, f32 BN, f32 head).
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from jama16_retina_tpu.models.common import BN_EPS, BN_MOMENTUM


class Bottleneck(nn.Module):
    features: int  # inner width; output is 4x
    strides: tuple = (1, 1)
    dtype: Any = jnp.bfloat16
    axis_name: str | None = None

    @nn.compact
    def __call__(self, x, train: bool):
        def conv(f, k, s, name):
            return nn.Conv(
                f, k, strides=s, padding="SAME", use_bias=False,
                dtype=self.dtype, param_dtype=jnp.float32, name=name,
            )

        def bn(name):
            return nn.BatchNorm(
                use_running_average=not train, momentum=BN_MOMENTUM,
                epsilon=BN_EPS, use_scale=True, dtype=jnp.float32,
                axis_name=self.axis_name if train else None, name=name,
            )

        residual = x
        y = conv(self.features, (1, 1), (1, 1), "conv1")(x)
        y = nn.relu(bn("bn1")(y)).astype(self.dtype)
        y = conv(self.features, (3, 3), self.strides, "conv2")(y)
        y = nn.relu(bn("bn2")(y)).astype(self.dtype)
        y = conv(self.features * 4, (1, 1), (1, 1), "conv3")(y)
        y = bn("bn3")(y)
        if residual.shape[-1] != y.shape[-1] or self.strides != (1, 1):
            residual = conv(
                self.features * 4, (1, 1), self.strides, "conv_proj"
            )(residual)
            residual = bn("bn_proj")(residual)
        return nn.relu(y + residual).astype(self.dtype)


class ResNet50(nn.Module):
    num_classes: int = 1
    dropout_rate: float = 0.2
    dtype: Any = jnp.bfloat16
    axis_name: str | None = None
    stage_sizes: tuple = (3, 4, 6, 3)

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(
            64, (7, 7), strides=(2, 2), padding=[(3, 3), (3, 3)],
            use_bias=False, dtype=self.dtype, param_dtype=jnp.float32,
            name="conv_init",
        )(x)
        x = nn.BatchNorm(
            use_running_average=not train, momentum=BN_MOMENTUM,
            epsilon=BN_EPS, use_scale=True, dtype=jnp.float32,
            axis_name=self.axis_name if train else None, name="bn_init",
        )(x)
        x = nn.relu(x).astype(self.dtype)
        x = nn.max_pool(x, (3, 3), strides=(2, 2), padding="SAME")
        for i, n_blocks in enumerate(self.stage_sizes):
            for j in range(n_blocks):
                strides = (2, 2) if i > 0 and j == 0 else (1, 1)
                x = Bottleneck(
                    features=64 * 2**i, strides=strides, dtype=self.dtype,
                    axis_name=self.axis_name, name=f"stage{i + 1}_block{j + 1}",
                )(x, train)
        x = x.mean(axis=(1, 2)).astype(jnp.float32)
        x = nn.Dropout(rate=self.dropout_rate, deterministic=not train)(x)
        logits = nn.Dense(self.num_classes, dtype=jnp.float32, name="Logits")(x)
        return logits, None
