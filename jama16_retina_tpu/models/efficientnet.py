"""Flax EfficientNet — backbone swap option (BASELINE.json:11, SURVEY.md N5).

From-scratch implementation of EfficientNet (Tan & Le 2019): MBConv
inverted-bottleneck blocks with depthwise convs, squeeze-and-excitation,
swish activation, and compound width/depth scaling. ``EfficientNet.b4``
builds the B4 scaling (width 1.4, depth 1.8) the BASELINE config names.

TPU notes: depthwise convs lower to XLA ``feature_group_count`` convs; SE
is two tiny matmuls on the pooled vector (negligible); stochastic depth
uses a per-block Bernoulli on the residual branch, traced once (no Python
branching on data).
"""

from __future__ import annotations

import math
from typing import Any, Sequence

import flax.linen as nn
import jax
import jax.numpy as jnp

# (expand_ratio, kernel, stride, out_filters_b0, repeats_b0)
_B0_BLOCKS = (
    (1, 3, 1, 16, 1),
    (6, 3, 2, 24, 2),
    (6, 5, 2, 40, 2),
    (6, 3, 2, 80, 3),
    (6, 5, 1, 112, 3),
    (6, 5, 2, 192, 4),
    (6, 3, 1, 320, 1),
)
_SE_RATIO = 0.25
_BN_MOMENTUM = 0.99  # EfficientNet's own BN momentum (not the Inception one)
_BN_EPS = 1e-3


def round_filters(filters: int, width_mult: float) -> int:
    """EfficientNet channel rounding: nearest multiple of 8, never < 90%."""
    filters *= width_mult
    new = max(8, int(filters + 4) // 8 * 8)
    if new < 0.9 * filters:
        new += 8
    return int(new)


def round_repeats(repeats: int, depth_mult: float) -> int:
    return int(math.ceil(depth_mult * repeats))


class MBConv(nn.Module):
    in_filters: int
    out_filters: int
    expand_ratio: int
    kernel: int
    strides: int
    drop_rate: float
    dtype: Any = jnp.bfloat16
    axis_name: str | None = None

    @nn.compact
    def __call__(self, x, train: bool):
        def bn(name):
            return nn.BatchNorm(
                use_running_average=not train, momentum=_BN_MOMENTUM,
                epsilon=_BN_EPS, use_scale=True, dtype=jnp.float32,
                axis_name=self.axis_name if train else None, name=name,
            )

        inputs = x
        expanded = self.in_filters * self.expand_ratio
        if self.expand_ratio != 1:
            x = nn.Conv(
                expanded, (1, 1), use_bias=False, dtype=self.dtype,
                param_dtype=jnp.float32, name="expand_conv",
            )(x)
            x = nn.swish(bn("expand_bn")(x)).astype(self.dtype)
        # Depthwise conv.
        x = nn.Conv(
            expanded, (self.kernel, self.kernel),
            strides=(self.strides, self.strides), padding="SAME",
            feature_group_count=expanded, use_bias=False, dtype=self.dtype,
            param_dtype=jnp.float32, name="depthwise_conv",
        )(x)
        x = nn.swish(bn("depthwise_bn")(x)).astype(self.dtype)
        # Squeeze-and-excitation on the *unexpanded* input width.
        se_filters = max(1, int(self.in_filters * _SE_RATIO))
        se = x.mean(axis=(1, 2), keepdims=True)
        se = nn.Conv(
            se_filters, (1, 1), dtype=self.dtype, param_dtype=jnp.float32,
            name="se_reduce",
        )(se)
        se = nn.swish(se)
        se = nn.Conv(
            expanded, (1, 1), dtype=self.dtype, param_dtype=jnp.float32,
            name="se_expand",
        )(se)
        x = x * nn.sigmoid(se)
        # Project.
        x = nn.Conv(
            self.out_filters, (1, 1), use_bias=False, dtype=self.dtype,
            param_dtype=jnp.float32, name="project_conv",
        )(x)
        x = bn("project_bn")(x).astype(self.dtype)
        if self.strides == 1 and self.in_filters == self.out_filters:
            if train and self.drop_rate > 0.0:
                # Stochastic depth: drop the whole residual branch per-example.
                keep = 1.0 - self.drop_rate
                mask = jax.random.bernoulli(
                    self.make_rng("dropout"), keep, (x.shape[0], 1, 1, 1)
                ).astype(x.dtype)
                x = x * mask / keep
            x = x + inputs
        return x


class EfficientNet(nn.Module):
    num_classes: int = 1
    width_mult: float = 1.0
    depth_mult: float = 1.0
    dropout_rate: float = 0.2
    drop_connect_rate: float = 0.2
    dtype: Any = jnp.bfloat16
    axis_name: str | None = None
    blocks: Sequence = _B0_BLOCKS

    @classmethod
    def b4(cls, **kw):
        # dropout_rate arrives from ModelConfig (the b4 preset sets 0.4).
        return cls(width_mult=1.4, depth_mult=1.8, **kw)

    @nn.compact
    def __call__(self, x, train: bool = False):
        def bn(name):
            return nn.BatchNorm(
                use_running_average=not train, momentum=_BN_MOMENTUM,
                epsilon=_BN_EPS, use_scale=True, dtype=jnp.float32,
                axis_name=self.axis_name if train else None, name=name,
            )

        x = x.astype(self.dtype)
        stem = round_filters(32, self.width_mult)
        x = nn.Conv(
            stem, (3, 3), strides=(2, 2), padding="SAME", use_bias=False,
            dtype=self.dtype, param_dtype=jnp.float32, name="stem_conv",
        )(x)
        x = nn.swish(bn("stem_bn")(x)).astype(self.dtype)

        total_blocks = sum(
            round_repeats(r, self.depth_mult) for (_, _, _, _, r) in self.blocks
        )
        block_idx = 0
        in_filters = stem
        for stage, (expand, kernel, stride, out_b0, repeats_b0) in enumerate(
            self.blocks
        ):
            out_filters = round_filters(out_b0, self.width_mult)
            for rep in range(round_repeats(repeats_b0, self.depth_mult)):
                x = MBConv(
                    in_filters=in_filters,
                    out_filters=out_filters,
                    expand_ratio=expand,
                    kernel=kernel,
                    strides=stride if rep == 0 else 1,
                    drop_rate=self.drop_connect_rate * block_idx / total_blocks,
                    dtype=self.dtype,
                    axis_name=self.axis_name,
                    name=f"stage{stage + 1}_block{rep + 1}",
                )(x, train)
                in_filters = out_filters
                block_idx += 1

        head = round_filters(1280, self.width_mult)
        x = nn.Conv(
            head, (1, 1), use_bias=False, dtype=self.dtype,
            param_dtype=jnp.float32, name="head_conv",
        )(x)
        x = nn.swish(bn("head_bn")(x))
        x = x.mean(axis=(1, 2)).astype(jnp.float32)
        x = nn.Dropout(rate=self.dropout_rate, deterministic=not train)(x)
        logits = nn.Dense(self.num_classes, dtype=jnp.float32, name="Logits")(x)
        return logits, None
