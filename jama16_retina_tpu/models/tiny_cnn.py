"""Tiny CNN backbone for tests and CI smoke runs (SURVEY.md §4 fixtures).

Small enough to train in seconds on the CPU backend, but structurally
honest: same ConvBN cell (so cross-replica BN paths are exercised), same
``(logits, aux)`` contract as the real backbones.
"""

from __future__ import annotations

from typing import Any

import flax.linen as nn
import jax.numpy as jnp

from jama16_retina_tpu.models.common import ConvBN


class TinyCNN(nn.Module):
    num_classes: int = 1
    dropout_rate: float = 0.1
    features: tuple = (16, 32, 64)
    dtype: Any = jnp.float32
    axis_name: str | None = None

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        for i, f in enumerate(self.features):
            x = ConvBN(
                f, (3, 3), strides=(2, 2), dtype=self.dtype,
                axis_name=self.axis_name, name=f"conv{i}",
            )(x, train)
        x = x.mean(axis=(1, 2)).astype(jnp.float32)
        x = nn.Dropout(rate=self.dropout_rate, deterministic=not train)(x)
        logits = nn.Dense(self.num_classes, dtype=jnp.float32, name="Logits")(x)
        return logits, None
