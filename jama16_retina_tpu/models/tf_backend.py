"""The ``backend="tf"`` side of the ``build()`` plugin boundary.

The north star (BASELINE.json:5) makes the model builder the plugin
boundary: ``model.build(backend=...)`` returns "either the legacy TF
graph or a weight-matched Flax Inception-v3 — so the AUC and
sensitivity-at-fixed-specificity evaluation code is untouched". The
legacy TF-Slim graph itself cannot be ported (the reference tree is
empty, SURVEY.md §0), so the TF side is the locally available twin:
``tf.keras.applications.InceptionV3``, loaded with weights restored from
a *Flax* orbax checkpoint via the inverse of
:mod:`jama16_retina_tpu.models.transplant`'s keras→flax name map.

That makes ``evaluate.py --device=tf`` a genuine second backend: the
same TFRecords, the same orbax checkpoints, the same
``eval/metrics.py`` — only the forward pass runs in TF on host CPU.
Byte-compatible report schema across backends is pinned by
tests/test_tf_backend.py.
"""

from __future__ import annotations

import numpy as np

from jama16_retina_tpu.configs import ModelConfig
from jama16_retina_tpu.models import transplant


def build_tf(cfg: ModelConfig):
    """Keras InceptionV3 with the config's head — the "legacy TF graph"
    half of the plugin boundary. Raw logits (no classifier activation);
    the head nonlinearity lives in :func:`predict_probs`, mirroring
    train_lib._probs."""
    import tensorflow as tf

    if cfg.arch != "inception_v3":
        raise ValueError(
            "the TF backend covers the reference's model, Inception-v3 "
            f"(BASELINE.json:5); got arch={cfg.arch!r}"
        )
    size = cfg.image_size
    return tf.keras.applications.InceptionV3(
        weights=None,
        include_top=True,
        classes=cfg.num_classes,
        classifier_activation=None,
        input_shape=(size, size, 3),
    )


def load_flax_state(keras_model, params, batch_stats) -> None:
    """Inverse transplant: copy a Flax checkpoint into the keras graph.

    Uses the same creation-order pairing as transplant.py (94 ConvBN
    cells + the Logits/predictions Dense); the flax aux head has no keras
    counterpart and is skipped — eval never runs the aux head. Every copy
    is shape-checked by keras' ``assign``.
    """
    import jax

    params = jax.tree.map(np.asarray, jax.device_get(params))
    batch_stats = jax.tree.map(np.asarray, jax.device_get(batch_stats))

    pairs = transplant.keras_conv_bn_pairs(keras_model)
    if len(pairs) != len(transplant.FLAX_CONV_ORDER):
        raise ValueError(
            f"expected {len(transplant.FLAX_CONV_ORDER)} conv/bn pairs, "
            f"keras model has {len(pairs)}"
        )

    def _get(tree, path):
        node = tree
        for p in path:
            node = node[p]
        return node

    for (conv, bn), path in zip(pairs, transplant.FLAX_CONV_ORDER):
        conv.kernel.assign(_get(params, (*path, "conv"))["kernel"])
        bn.beta.assign(_get(params, (*path, "bn"))["bias"])
        bn.moving_mean.assign(_get(batch_stats, (*path, "bn"))["mean"])
        bn.moving_variance.assign(_get(batch_stats, (*path, "bn"))["var"])

    dense = next(
        (l for l in keras_model.layers if l.name == "predictions"), None
    )
    if dense is None:
        raise ValueError("keras model has no 'predictions' head layer")
    dense.kernel.assign(params["Logits"]["kernel"])
    dense.bias.assign(params["Logits"]["bias"])


def predict_probs(
    keras_model, images_u8: np.ndarray, head: str, tta: bool = False
) -> np.ndarray:
    """uint8 batch -> probabilities, numerically parallel to the jit
    eval step: the same /127.5-1 normalization (augment.normalize), the
    same head nonlinearity (train_lib._probs), and the same 4-flip-view
    averaging when ``tta`` (train_lib.make_eval_step)."""
    import tensorflow as tf

    x = images_u8.astype(np.float32) / 127.5 - 1.0
    views = (
        [x, x[:, :, ::-1], x[:, ::-1, :], x[:, ::-1, ::-1]] if tta else [x]
    )

    def probs_of(view):
        logits = keras_model(
            tf.convert_to_tensor(np.ascontiguousarray(view)), training=False
        ).numpy()
        if head == "binary":
            return 1.0 / (1.0 + np.exp(-logits[:, 0]))
        e = np.exp(logits - logits.max(axis=-1, keepdims=True))
        return e / e.sum(axis=-1, keepdims=True)

    return np.mean([probs_of(v) for v in views], axis=0)
