"""Shared model building blocks with the TPU numerics policy.

Policy (SURVEY.md §7.7): convolutions/matmuls run in ``dtype`` (bfloat16
by default — MXU-native), while BatchNorm statistics and normalization
run in float32. Parameters are always float32 (``param_dtype``).

``axis_name`` mirrors the reference's cross-replica BatchNorm requirement
(BASELINE.json:5 "cross-replica BatchNorm psum over ICI"): when the model
runs under ``pmap``/``shard_map`` with a named data axis, BatchNorm batch
moments are averaged over that axis so the 32-image *global* batch defines
the statistics, not the per-replica slice (SURVEY.md §7 hard part b).
Under ``jit`` over global arrays, the batch axis is one logical array and
XLA GSPMD inserts the same all-reduce automatically, so ``axis_name`` must
stay ``None`` there.
"""

from __future__ import annotations

from typing import Any, Sequence

import flax.linen as nn
import jax.numpy as jnp

# TF-Slim/keras InceptionV3 batch-norm hyperparameters: eps 1e-3 and no
# learned scale (gamma) — relu follows immediately, making gamma redundant.
BN_EPS = 1e-3
BN_MOMENTUM = 0.9


class ConvBN(nn.Module):
    """Conv -> BatchNorm -> ReLU, the unit cell of every backbone here.

    Matches the TF-Slim ``conv2d + batch_norm`` arg-scope cell the
    reference's Inception-v3 is built from (SURVEY.md R7): no conv bias
    (BN absorbs it), BN without scale, ReLU activation.
    """

    features: int
    kernel: Sequence[int] = (3, 3)
    strides: Sequence[int] = (1, 1)
    padding: str = "SAME"
    use_scale: bool = False
    activation: Any = nn.relu
    dtype: Any = jnp.bfloat16
    axis_name: str | None = None

    @nn.compact
    def __call__(self, x, train: bool):
        x = nn.Conv(
            self.features,
            tuple(self.kernel),
            strides=tuple(self.strides),
            padding=self.padding,
            use_bias=False,
            dtype=self.dtype,
            param_dtype=jnp.float32,
            name="conv",
        )(x)
        # dtype=self.dtype keeps the activation stream bf16 end to end —
        # the train step is HBM-bandwidth-bound (profiled: ~23 GB/step
        # with f32 BN activations), and flax promotes the mean/var
        # reductions to float32 internally regardless
        # (normalization._compute_stats force_float32_reductions), so
        # bf16 here halves BN-boundary traffic without degrading the
        # statistics. Running stats stay float32 (flax default).
        x = nn.BatchNorm(
            use_running_average=not train,
            momentum=BN_MOMENTUM,
            epsilon=BN_EPS,
            use_scale=self.use_scale,
            dtype=self.dtype,
            axis_name=self.axis_name if train else None,
            name="bn",
        )(x)
        x = self.activation(x) if self.activation is not None else x
        return x.astype(self.dtype)
