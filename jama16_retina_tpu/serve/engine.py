"""Persistent serving engine: device-resident stacked ensembles.

The pre-existing inference surface (predict.py before this subsystem)
paid O(k·images) per screening request: every ensemble member restored
from orbax per process, k sequential jit forwards per batch, a fresh
compile per invocation. This engine is the resident form of the same
math:

  * all k members restore ONCE and stack into one [k] parameter tree on
    device (train_lib.stack_states — opt_state dropped, so the
    residency is params+batch_stats only);
  * each batch is served by ONE dispatch of the stacked forward
    (train_lib.make_serving_step). The default lax.map member form is
    bit-identical per member to the sequential restore+forward path at
    the same batch shape — the parity contract that let predict.py be
    rewired on top of this engine with byte-identical JSONL output
    (pinned by tests/test_serve.py);
  * inputs pad into a small set of bucketed batch shapes
    (serve.bucket_sizes), so jit compiles once per bucket and never per
    request size. Zero-fill padding rows are provably inert: eval-mode
    forwards are row-independent (BN uses stored moments), so a kept
    row's probabilities do not depend on its neighbors — the property
    the bucket/coalescing machinery rests on, pinned by test;
  * H2D overlaps device compute: per-bucket chunks are placed with
    pipeline.staged_put (per-shard async puts) and all chunk dispatches
    are queued before the first device_get, so the runtime uploads
    chunk i+1 while chunk i computes.
"""

from __future__ import annotations

import threading
import time

import jax
import numpy as np
from absl import logging as absl_logging

from jama16_retina_tpu import models, train_lib
from jama16_retina_tpu.configs import ExperimentConfig, ServeConfig
from jama16_retina_tpu.data import pipeline
from jama16_retina_tpu.eval import metrics
from jama16_retina_tpu.obs import device as device_lib
from jama16_retina_tpu.obs import faultinject
from jama16_retina_tpu.obs import quality as quality_lib
from jama16_retina_tpu.obs import registry as obs_registry
from jama16_retina_tpu.obs import trace as obs_trace
from jama16_retina_tpu.obs.spans import span
from jama16_retina_tpu.parallel import mesh as mesh_lib
from jama16_retina_tpu.serve import compilecache, quantize
from jama16_retina_tpu.serve.quantize import DtypeRejected


class ReloadRejected(RuntimeError):
    """A candidate checkpoint set failed its pre-swap gate (golden
    canary deviation, or a warm-up forward error): the live generation
    keeps serving, the candidate never took a request. Counted under
    ``serve.reload_rejected`` and surfaced by the reliability alert
    rule — a rejected rollout must page, not silently retry."""


class RollbackUnavailable(RuntimeError):
    """``engine.rollback()`` was asked for an instant re-swap but no
    previous generation is retained (never swapped, already rolled
    back, or the ``serve.rollback_keep_s`` window expired and the tree
    was released). The caller must fall back to ``reload()`` from the
    previous checkpoint set on disk."""


class _ShadowSession:
    """One candidate generation shadow-scoring a deterministic fraction
    of live traffic (ISSUE 8 STAGED_ROLLOUT).

    Sampling is every-Nth *request* (N = round(1/fraction)), counted
    under a lock — deterministic under a fixed request sequence, no
    RNG. A sampled request pays the candidate forward on its own
    thread (the standard shadow price: that request's latency roughly
    doubles); a shadow-scoring failure is COUNTED
    (``serve.shadow.errors``), never raised into the live request.
    Comparison evidence (rows, max/mean |candidate - live|) is what
    the lifecycle journal records before a promote — advisory, not a
    gate: a retrained candidate legitimately moves scores.
    """

    __slots__ = ("gen", "member_dirs", "every", "count", "requests",
                 "rows", "max_abs_dev", "sum_abs_dev", "errors", "lock")

    def __init__(self, gen: "_Generation", member_dirs, fraction: float):
        if not (0.0 < fraction <= 1.0):
            raise ValueError(
                f"shadow fraction must be in (0, 1], got {fraction}"
            )
        self.gen = gen
        self.member_dirs = list(member_dirs) if member_dirs else None
        self.every = max(1, int(round(1.0 / fraction)))
        self.count = 0
        self.requests = 0
        self.rows = 0
        self.max_abs_dev = 0.0
        self.sum_abs_dev = 0.0
        self.errors = 0
        self.lock = threading.Lock()

    def claim(self) -> bool:
        """Deterministic sampling decision for one live request."""
        with self.lock:
            self.count += 1
            return self.count % self.every == 0

    def record(self, live: np.ndarray, shadow: np.ndarray) -> None:
        dev = np.abs(
            np.asarray(shadow, np.float64) - np.asarray(live, np.float64)
        )
        with self.lock:
            self.requests += 1
            self.rows += int(dev.shape[0]) if dev.ndim else 1
            self.max_abs_dev = max(self.max_abs_dev, float(dev.max()))
            self.sum_abs_dev += float(dev.sum())

    def report(self) -> dict:
        with self.lock:
            return {
                "requests": self.requests,
                "rows": self.rows,
                "errors": self.errors,
                "max_abs_dev": round(self.max_abs_dev, 9),
                "mean_abs_dev": round(
                    self.sum_abs_dev / self.rows, 9
                ) if self.rows else None,
            }


class _Generation:
    """One immutable serving generation (ISSUE 6 hot swap).

    Everything a request needs to complete is snapshotted here — the
    device-resident stacked state, its member count, provenance, and a
    per-generation row counter — so ``engine.reload()`` can build
    generation N+1 entirely off the request path and swap the engine's
    handle atomically (one Python reference assignment). In-flight
    requests that already grabbed generation N finish on N's state;
    new requests see N+1; no request ever observes a half-swapped
    engine."""

    __slots__ = ("gen_id", "state", "n_members", "member_dirs", "c_rows")

    def __init__(self, gen_id: int, state, n_members: int,
                 member_dirs, c_rows):
        self.gen_id = gen_id
        self.state = state
        self.n_members = n_members
        self.member_dirs = list(member_dirs) if member_dirs else None
        self.c_rows = c_rows


def resolve_buckets(sc: ServeConfig, divisor: int = 1) -> tuple[int, ...]:
    """The padded batch shapes the engine compiles for.

    Explicit ``serve.bucket_sizes`` are taken verbatim (sorted,
    deduplicated); the largest must cover ``serve.max_batch`` or chunks
    at the coalescing cap would have no bucket to land in. Empty = auto:
    powers of two from 8 up to max_batch — at most ~log2(max_batch)
    compiles, and a partial chunk wastes at most half its bucket.

    ``divisor``: the serving mesh's data-axis size. Batch rows shard
    across that axis, so every bucket must divide by it — auto buckets
    are rounded UP to the next multiple; explicit buckets that don't
    divide are rejected HERE, at engine construction, instead of
    surfacing as an opaque XLA uneven-sharding error on the first
    request that hits the bad shape.
    """
    if sc.max_batch < 1:
        raise ValueError(f"serve.max_batch must be >= 1, got {sc.max_batch}")
    divisor = max(1, int(divisor))
    if sc.bucket_sizes:
        buckets = tuple(sorted({int(b) for b in sc.bucket_sizes}))
        if buckets[0] < 1:
            raise ValueError(f"bucket sizes must be >= 1: {sc.bucket_sizes}")
        bad = [b for b in buckets if b % divisor]
        if bad:
            raise ValueError(
                f"bucket sizes {bad} do not divide across the serving "
                f"mesh's data axis ({divisor} devices); every bucket "
                f"must be a multiple of {divisor}"
            )
        if buckets[-1] < sc.max_batch:
            raise ValueError(
                f"largest bucket {buckets[-1]} < serve.max_batch "
                f"{sc.max_batch}: chunks at the coalescing cap would have "
                "no compiled shape"
            )
        return buckets
    out, b = [], 8
    while b < sc.max_batch:
        out.append(b)
        b *= 2
    out.append(sc.max_batch)
    return tuple(sorted({-(-b // divisor) * divisor for b in out}))


class ServingEngine:
    """Restore-once, stacked, bucket-batched ensemble inference.

    Construct from checkpoint dirs (the production path) or hand a
    pre-stacked state directly (``state=``; bench/tests skip the orbax
    round-trip that way). ``mesh``: a DATA mesh — state replicated,
    batch rows sharded across the data axis, exactly make_eval_step's
    serving-side layout.
    """

    def __init__(
        self,
        cfg: ExperimentConfig,
        member_dirs: "list[str] | None" = None,
        *,
        model=None,
        mesh=None,
        state: "train_lib.TrainState | None" = None,
        registry: "obs_registry.Registry | None" = None,
    ):
        self.cfg = cfg
        self.model = model if model is not None else models.build(cfg.model)
        self.mesh = mesh
        # Telemetry (obs/): per-bucket pad-waste + compile counters, the
        # in-flight chunk gauge, and a per-engine-call batch counter —
        # the knobs-to-metrics map is in docs/OBSERVABILITY.md. Tests
        # inject a Registry; production uses the process default.
        self.registry = (
            registry if registry is not None
            else obs_registry.default_registry()
        )
        if registry is None:
            # Same wiring rule as the trainer's run entry: the engine's
            # own config decides whether the process-default registry
            # records (a prior obs.enabled=false fit in this process
            # must not silently mute serving telemetry). The process
            # tracer gets the same treatment — a serving session never
            # runs trainer._obs_begin_run, so obs.trace_enabled must be
            # applied here for the batcher's request segments to record.
            self.registry.enabled = cfg.obs.enabled
            obs_trace.default_tracer().configure(
                enabled=cfg.obs.enabled and cfg.obs.trace_enabled,
                buffer_events=cfg.obs.trace_buffer_events,
            )
        self._c_rows = self.registry.counter(
            "serve.engine.rows",
            help="real (pre-padding) rows the engine forwarded",
        )
        self._c_batches = self.registry.counter(
            "serve.engine.batches",
            help="bucketed chunks dispatched through the stacked "
                 "forward",
        )
        self._g_in_flight = self.registry.gauge(
            "serve.engine.in_flight",
            help="engine chunks dispatched but not yet fetched (the "
                 "bounded dispatch window)",
        )
        # Model-quality observability (obs/quality.py; ISSUE 5): the
        # drift monitor + golden canary, or None when obs.quality is
        # off — the disabled serve path pays exactly one branch per
        # probs() call. Artifacts (profile/canary) load HERE, at engine
        # construction, so a typo'd path fails the session loudly
        # instead of silently serving unmonitored.
        self.quality = quality_lib.monitor_from_config(
            cfg.obs.quality, registry=self.registry
        ) if cfg.obs.enabled else None
        if self.quality is not None and cfg.serve.fused_preprocess:
            # Fused serve preprocess (ISSUE 16): the monitor's input
            # stats come from the one fused pass (serve/host.stats_only)
            # instead of a second host-numpy per-pixel sweep.
            from jama16_retina_tpu.serve import host as serve_host

            _reg = self.registry
            self.quality.stats_fn = lambda rows: serve_host.stats_only(
                rows, fused=True, registry=_reg
            )
        if self.quality is not None and self.quality.canary is not None:
            want = (cfg.model.image_size, cfg.model.image_size, 3)
            got = tuple(self.quality.canary.images.shape[1:])
            if got != want:
                # Catch the mis-sized artifact at session start: the
                # canary rides live probs() calls, and a shape error
                # there would fail one real request per cadence tick.
                raise ValueError(
                    f"canary images are {got} but this engine serves "
                    f"{want} (model.image_size={cfg.model.image_size}) — "
                    "re-pin obs.quality.canary_path for this checkpoint"
                )
        # Per-bucket counter handles, created on a bucket's first use:
        # the steady-state path is a plain dict hit — no f-string, no
        # registry lock (the hot-path contract in obs/registry.py).
        self._bucket_counters: dict = {}
        # Reliability wiring (ISSUE 6): the deterministic fault plan
        # (obs/faultinject.py) arms at session start — env var wins,
        # then obs.fault_plan; both empty leaves whatever a test armed.
        faultinject.arm_from_env_or_config(cfg.obs.fault_plan)
        self._c_reloads = self.registry.counter(
            "serve.reloads",
            help="hot-swap generation reloads that went live",
        )
        self._c_reload_rejected = self.registry.counter(
            "serve.reload_rejected",
            help="candidate generations rejected before the swap "
                 "(canary deviation / restore or warm-up failure); the "
                 "old generation kept serving",
        )
        self._g_generation = self.registry.gauge(
            "serve.generation",
            help="currently-serving model generation (0 = the "
                 "construction-time checkpoint set) [fleet:max]",
        )
        # Lifecycle seams (ISSUE 8): instant rollback off the retained
        # previous generation, and the shadow-scoring session a staged
        # rollout samples live traffic through.
        self._c_rollbacks = self.registry.counter(
            "serve.rollbacks",
            help="instant re-swaps to the retained previous generation "
                 "(lifecycle ROLLBACK; no restore from disk)",
        )
        self._c_shadow_requests = self.registry.counter(
            "serve.shadow.requests",
            help="live requests shadow-scored through a staged-rollout "
                 "candidate generation",
        )
        self._c_shadow_rows = self.registry.counter(
            "serve.shadow.rows",
            help="rows shadow-scored through a staged-rollout candidate",
        )
        self._c_shadow_errors = self.registry.counter(
            "serve.shadow.errors",
            help="shadow-scoring failures (counted, never raised into "
                 "the live request they rode)",
        )
        self._g_shadow_dev = self.registry.gauge(
            "serve.shadow.max_abs_dev",
            help="running max |candidate - live| score deviation over "
                 "the current shadow session [fleet:max]",
        )
        self._prev_gen: "_Generation | None" = None
        self._prev_gen_t: float = 0.0
        self._shadow: "_ShadowSession | None" = None
        self._batch_sharding = (
            mesh_lib.batch_sharding(mesh) if mesh is not None else None
        )
        # Cheap-path serving (ISSUE 10): the engine's precision axis.
        # fp32 keeps every program/path byte-identical to before the
        # axis existed; bf16/int8 transform the stacked state at build
        # time (serve/quantize.py) and are canary-gated below.
        self.dtype = quantize.check_dtype(cfg.serve.dtype)
        # Prediction provenance (ISSUE 20): an AuditLedger attached by
        # the wiring site (predict.py, start_telemetry) records every
        # served request's lineage off the request path. None = one
        # attribute read per probs call.
        self.audit = None
        self._c_dtype_rows = self.registry.counter(
            f"serve.dtype_rows.{self.dtype}",
            help="real rows forwarded by an engine of this serving "
                 "dtype (per-dtype traffic share; fp32/bf16/int8)",
        )
        self._step = train_lib.make_serving_step(
            cfg, self.model, mesh=mesh,
            member_parallel=cfg.serve.member_parallel,
            param_transform=quantize.dequant_transform(self.dtype),
        )
        self.max_batch = int(cfg.serve.max_batch)
        divisor = (
            int(mesh.shape[mesh_lib._batch_axis(mesh)])
            if mesh is not None else 1
        )
        self.buckets = resolve_buckets(cfg.serve, divisor=divisor)
        # One rollout at a time: two racing reload() calls would both
        # derive gen_id N+1 from the same live handle and silently
        # discard one swap (with its row attribution).
        self._reload_lock = threading.Lock()
        # Persistent AOT compile cache (ISSUE 10 zero cold-start):
        # per-(bucket, mesh, dtype, k) serialized executables under a
        # model-fingerprinted directory. Opened BEFORE generation 0 so
        # a stale-fingerprint directory refuses the session up front
        # (CompileCacheStale names the rebuild command) instead of
        # after a full restore.
        self._compiled: dict = {}
        self._compiled_k: "int | None" = None
        # Program-ledger entries per bucket (obs/device.py; ISSUE 19):
        # dispatch counting is one dict lookup + integer increment.
        self._prog_entries: dict = {}
        self._cache = (
            compilecache.CompileCache(
                cfg.serve.compile_cache_dir,
                compilecache.model_fingerprint(cfg, mesh=mesh),
                registry=self.registry,
            )
            if cfg.serve.compile_cache_dir else None
        )
        self._g_warmup_sec = self.registry.gauge(
            "serve.engine.warmup_sec",
            help="seconds from engine construction to every bucket "
                 "executable ready (cache-warmed restarts are the "
                 "serve_warm_start_sec story; 0 = no compile cache "
                 "configured, first request pays the compile) "
                 "[fleet:max]",
        )
        # Generation 0: the construction-time checkpoint set. Without a
        # compile cache it is built unwarmed — the first request
        # compiles, exactly the historical behavior bench's warmup
        # accounting measures; with one, every bucket is AOT-compiled
        # or deserialized here, so the first request is already warm.
        self._gen = self._build_generation(
            0, member_dirs=member_dirs, state=state
        )
        self._gen.c_rows = self._register_gen_rows(0)
        self._g_generation.set(0)
        if self._cache is not None:
            self._warm_from_cache(self._gen)
        self._dtype_construction_gate()
        self._note_residency()

    # -- generations (ISSUE 6 hot swap) -----------------------------------

    @property
    def state(self):
        """The live generation's device-resident stacked state."""
        return self._gen.state

    @property
    def n_members(self) -> int:
        return self._gen.n_members

    @property
    def generation(self) -> int:
        """Id of the generation new requests dispatch on."""
        return self._gen.gen_id

    # How many generations' row counters stay exported after a swap:
    # the live one, the one draining its last in-flight requests, and a
    # little history for the report — NOT one forever per reload (a
    # server hot-swapping hourly for a month would otherwise grow ~720
    # counters into every telemetry record and .prom snapshot).
    GEN_ROWS_KEEP = 4

    def _register_gen_rows(self, gen_id: int) -> "obs_registry.Counter":
        """The exported per-generation row ledger, attached at go-live;
        generations older than GEN_ROWS_KEEP are retired from snapshots
        (their drained handles keep working, just unexported)."""
        retire = gen_id - self.GEN_ROWS_KEEP
        if retire >= 0:
            self.registry.remove(f"serve.gen{retire}.rows")
        return self.registry.counter(
            f"serve.gen{gen_id}.rows",
            help="rows served by this model generation (response "
                 "attribution: the per-generation ledger)",
        )

    def _note_residency(self) -> None:
        """Refresh the HBM owner ledger (obs/device.py; ISSUE 19) after
        any generation mutation: the live stacked state under
        ``serve_live``, the retained rollback generation under
        ``serve_retained`` (cleared when nothing is retained). Off the
        request path — callers are construction/reload/rollback/release
        sites — and best-effort: residency accounting must never fail a
        swap."""
        try:
            device_lib.set_hbm_owner(
                "serve_live", device_lib.tree_device_bytes(self._gen.state)
            )
            prev = self._prev_gen
            if prev is not None:
                device_lib.set_hbm_owner(
                    "serve_retained",
                    device_lib.tree_device_bytes(prev.state),
                )
            else:
                device_lib.clear_hbm_owner("serve_retained")
        except Exception:  # noqa: BLE001 - accounting only
            pass

    def _build_generation(self, gen_id: int, member_dirs=None,
                          state: "train_lib.TrainState | None" = None,
                          warm: bool = False) -> _Generation:
        """Restore -> stack -> place -> (optionally) warm every bucket,
        entirely off the request path: nothing here touches the live
        ``self._gen``."""
        if state is None:
            if not member_dirs:
                raise ValueError(
                    "ServingEngine needs member checkpoint dirs (or a "
                    "pre-stacked state=)"
                )
            from jama16_retina_tpu import trainer

            state = train_lib.stack_states([
                trainer.restore_for_eval(self.cfg, self.model, d)
                for d in member_dirs
            ])
        else:
            # Serving never steps the optimizer; drop its moments from
            # the device residency whatever the caller handed over.
            state = state.replace(opt_state=None)
        # Serving dtype transform (ISSUE 10; serve/quantize.py):
        # fp32 = identity, bf16 = cast, int8 = Q8Leaf quantization.
        # Idempotent, so a candidate state that already went through a
        # generation build (begin_shadow -> promote) is untouched.
        # Non-fp32 transforms jit-compile cast/quantize programs —
        # a compile-ledger site (ISSUE 19); fp32 pays nothing.
        if self.dtype != "fp32":
            with device_lib.compile_timed(f"serve_dtype_{self.dtype}",
                                          registry=self.registry):
                state = quantize.state_for_dtype(state, self.dtype)
        else:
            state = quantize.state_for_dtype(state, self.dtype)
        n_members = int(state.step.shape[0])
        if mesh_lib.has_member_axis(self.mesh):
            # Member-sharded serving (ISSUE 14): the stacked tree
            # shards across the mesh's member axis — each device group
            # resides (and forwards) only k/m members. Divisibility is
            # checked HERE, at generation build, with the knob named,
            # instead of surfacing as an XLA uneven-sharding error on
            # the first dispatch.
            m = int(self.mesh.shape["member"])
            if n_members % m:
                raise ValueError(
                    f"{n_members} stacked member(s) do not shard "
                    f"across the serving mesh's {m}-way member axis — "
                    "parallel.member_axis_size must divide the "
                    "ensemble member count"
                )
            place = mesh_lib.member_sharding(self.mesh)
        else:
            place = (
                mesh_lib.replicated(self.mesh) if self.mesh is not None
                else jax.local_devices()[0]
            )
        gen = _Generation(
            gen_id, jax.device_put(state, place), n_members, member_dirs,
            # DETACHED counter (not registered): a candidate's gate
            # scoring (canary through member_probs) must not pollute the
            # exported per-generation row ledger — the registered
            # counter is attached only when the generation goes LIVE
            # (_register_gen_rows at construction / swap time).
            obs_registry.Counter(f"serve.gen{gen_id}.rows", self.registry),
        )
        if warm:
            # Every bucket forwarded once on the CANDIDATE state before
            # it can take a request: the swap never hands a live caller
            # a cold compile or a shape error the gate could have
            # caught (the shared self._step jit cache — or the
            # compile-cache executables, when member counts match —
            # makes repeat warms cheap: same shapes, same program).
            size = self.cfg.model.image_size
            for b in self.buckets:
                zeros = np.zeros((b, size, size, 3), np.uint8)
                # Compile-ledger site (ISSUE 19): a candidate warm that
                # actually compiles (no shared jit cache entry, no AOT
                # executable) shows up as real seconds under this
                # signature; a cache-shared warm records ~0 s — the
                # honest "this warm was free" entry.
                with device_lib.compile_timed(f"serve_warm_b{b}",
                                              registry=self.registry):
                    jax.device_get(self._dispatch_fn(b, gen)(
                        gen.state, {"image": self._place(zeros)}
                    ))
        return gen

    def _dispatch_fn(self, bucket: int, gen: "_Generation"):
        """The executable one chunk dispatches through: the persistent-
        cache AOT executable when one exists for this bucket AND the
        generation's member count matches what it was compiled for
        (a reload to a different k changes the stacked shapes), else
        the shared jit fast path."""
        if gen.n_members == self._compiled_k:
            fn = self._compiled.get(bucket)
            if fn is not None:
                return fn
        return self._step

    def _warm_from_cache(self, gen: "_Generation") -> None:
        """Populate the per-bucket executable table from the persistent
        compile cache (hit: deserialize, ms) or by AOT-compiling and
        saving (miss: one real compile, exactly what the first request
        would have paid — now paid here, once, durable). Sets
        ``serve.engine.warmup_sec``; after this every bucket serves its
        first request warm."""
        t0 = time.monotonic()
        size = self.cfg.model.image_size
        mesh_shape = (
            tuple(self.mesh.devices.shape) if self.mesh is not None
            else (1,)
        )
        load_sec = 0.0
        for b in self.buckets:
            zeros = np.zeros((b, size, size, 3), np.uint8)
            placed = self._place(zeros)
            key = self._cache.entry_key(
                b, mesh_shape, self.dtype, gen.n_members
            )
            t_load = time.monotonic()
            fn = self._cache.load(key)  # counts its own hit/miss
            load_sec += time.monotonic() - t_load
            if fn is not None:
                # Proof-run the DESERIALIZED executable before a live
                # request rides it. A loaded entry that cannot actually
                # run here (an entry-key collision across an engine
                # change the fingerprint missed, a runtime-version
                # surprise) is one more degrade-to-recompile case —
                # the cache contract, not a failed session.
                try:
                    jax.device_get(fn(gen.state, {"image": placed}))
                except Exception as e:  # noqa: BLE001 - degrade
                    absl_logging.warning(
                        "cached executable %s deserialized but failed "
                        "its proof-run (%s: %s); recompiling",
                        key, type(e).__name__, e,
                    )
                    self._cache.c_misses.inc()
                    fn = None
            if fn is None:
                t_c = time.monotonic()
                fn = self._step.lower(
                    gen.state, {"image": placed}
                ).compile()
                compile_sec = time.monotonic() - t_c
                # Compile-ledger site (ISSUE 19): the cache-miss
                # compile, with its measured seconds stored INTO the
                # cache entry so a later hit can count what it saved.
                device_lib.record_compile(
                    f"serve_b{b}", compile_sec, registry=self.registry
                )
                self._cache.save(key, fn, compile_sec=compile_sec)
                # Fresh-compile proof-run: a failure HERE is a real
                # engine/model error and must propagate.
                jax.device_get(fn(gen.state, {"image": placed}))
            self._compiled[b] = fn
            # Program ledger (ISSUE 19): per-bucket MFU/roofline
            # attribution — cost_analysis may be unavailable on a
            # deserialized executable (entry costs stay None; the
            # gauges just skip it).
            self._prog_entries[b] = device_lib.program_ledger().register(
                f"serve_b{b}", compiled=fn
            )
        self._compiled_k = gen.n_members
        self._cache.g_load_sec.set(load_sec)
        self._g_warmup_sec.set(time.monotonic() - t0)

    def _dtype_construction_gate(self) -> None:
        """The quantized-engine quality gate (ISSUE 10): a non-fp32
        engine with a PINNED golden canary must reproduce the pinned
        scores within ``serve.dtype_canary_max_dev`` or it is refused
        HERE — before any request — with typed :class:`DtypeRejected`.
        fp32 engines skip (their contract is the canary's own
        byte-stability check); engines without a pinned canary serve
        ungated, loudly."""
        if self.dtype == "fp32":
            return
        q = self.quality
        canary = q.canary if q is not None else None
        if canary is None or canary.reference is None:
            absl_logging.warning(
                "serve.dtype=%s engine has no pinned golden canary; "
                "the quantized numerics are UNGATED — pin one via "
                "obs.quality.canary_path for the construction-time "
                "parity check", self.dtype,
            )
            return
        scores = np.asarray(
            metrics.ensemble_average(list(
                self.member_probs(canary.images, _gen=self._gen)
            )), np.float64,
        ).ravel()
        ref = np.asarray(canary.reference, np.float64).ravel()
        dev = (
            float(np.max(np.abs(scores - ref)))
            if scores.shape == ref.shape else float("inf")
        )
        bound = float(self.cfg.serve.dtype_canary_max_dev)
        if dev > bound:
            raise DtypeRejected(
                f"serve.dtype={self.dtype} deviates from the pinned "
                f"golden canary by {dev:.6g} (> serve."
                f"dtype_canary_max_dev={bound:g}); the quantized engine "
                "never took a request — serve fp32, or loosen the bound "
                "deliberately with this deviation in hand"
            )
        absl_logging.info(
            "serve.dtype=%s passed the golden-canary gate "
            "(max dev %.6g <= %g)", self.dtype, dev, bound,
        )

    def reload(self, member_dirs=None, *,
               state: "train_lib.TrainState | None" = None) -> dict:
        """Hot-swap to a new checkpoint set with ZERO dropped requests.

        Generation N+1 is built completely off the request path
        (restore -> stack -> device placement -> warm every bucket ->
        golden-canary gate), then the engine's generation handle is
        swapped in one atomic reference assignment: requests already
        dispatched keep finishing on generation N, new requests land on
        N+1. A candidate that fails its gate NEVER takes a request —
        the old generation keeps serving, ``serve.reload_rejected``
        increments (the reliability alert rule reads its rate), and
        ``ReloadRejected`` (canary) or the restore's own error
        propagates to the rollout driver.

        Returns {'generation', 'n_members', 'canary_checked'[,
        'canary_max_dev']} for the rollout driver's ledger. Reloads are
        serialized (one rollout at a time); requests never block on the
        lock — they read the handle, not the lock."""
        with self._reload_lock:
            return self._reload_locked(member_dirs, state)

    def _release_retained_locked(self, why: str) -> None:
        """Drop the retained generation BEFORE building a candidate:
        a new rollout supersedes the old rollback target (rolling back
        across two swaps is not a thing — the pre-pre-swap model is a
        reload-from-disk decision, not an instant re-swap), and
        holding it through the build would put live + retained +
        candidate (3x) on the device at once. Peak residency during
        any reload therefore stays at the ~2x PR 6 documented."""
        if self._prev_gen is None:
            return
        absl_logging.info(
            "releasing retained generation %d (%s)",
            self._prev_gen.gen_id, why,
        )
        self._prev_gen = None

    def release_retained(self) -> None:
        """Explicitly drop the retained previous generation (frees its
        device residency). The lifecycle controller calls this at
        COMMIT — once the post-swap watch judged the rollout healthy,
        paying 2x HBM until the window expires buys nothing."""
        with self._reload_lock:
            self._prev_gen = None
            self._note_residency()

    def _reload_locked(self, member_dirs, state) -> dict:
        cur = self._gen
        new_id = cur.gen_id + 1
        self._release_retained_locked("superseded by a new rollout")
        try:
            gen = self._build_generation(
                new_id, member_dirs=member_dirs, state=state, warm=True
            )
        except Exception:
            # Restore/stack/warm failure: the candidate is unusable —
            # same rejected-rollout ledger as a canary miss, original
            # error kept (the corrupt-checkpoint message names the
            # member and path; utils/checkpoint.py).
            self._c_reload_rejected.inc()
            raise
        info: dict = {
            "generation": new_id, "n_members": gen.n_members,
            "canary_checked": False,
        }
        q = self.quality
        canary = q.canary if q is not None else None
        if canary is not None and canary.reference is not None:
            # Score the pinned golden set THROUGH the candidate — a
            # non-destructive twin of GoldenCanary.check: the live
            # canary's gauges/cadence stay untouched (they describe the
            # serving generation, which this candidate is not yet).
            scores = np.asarray(
                metrics.ensemble_average(list(
                    self.member_probs(canary.images, _gen=gen)
                )), np.float64,
            ).ravel()
            ref = canary.reference
            dev = (
                float(np.max(np.abs(scores - ref)))
                if scores.shape == ref.shape else float("inf")
            )
            ok = scores.shape == ref.shape and (
                np.array_equal(scores, ref) if canary.atol == 0.0
                else bool(dev <= canary.atol)
            )
            info["canary_checked"] = True
            info["canary_max_dev"] = (
                None if dev == float("inf") else dev
            )
            if not ok:
                self._c_reload_rejected.inc()
                absl_logging.error(
                    "reload rejected: candidate generation %d deviates "
                    "from the golden canary (max dev %s, atol %g) — "
                    "generation %d keeps serving",
                    new_id, dev, canary.atol, cur.gen_id,
                )
                raise ReloadRejected(
                    f"candidate generation {new_id} failed the golden "
                    f"canary (max deviation {dev} vs atol "
                    f"{canary.atol}); generation {cur.gen_id} keeps "
                    "serving"
                )
        # Going live: attach the EXPORTED row ledger (gate-scoring rows
        # stayed on the detached counter) and retire ledgers of long-
        # drained generations, THEN swap — one reference assignment
        # (atomic under the GIL). In-flight requests hold their own
        # generation reference and complete on it; generation N's
        # device buffers free once the last such request drains.
        gen.c_rows = self._register_gen_rows(new_id)
        # Retain the outgoing generation for serve.rollback_keep_s
        # (ISSUE 8): within that window rollback() is one handle
        # re-swap — the old stacked tree is still device-resident and
        # warm, no restore from disk. Costs one extra model residency,
        # the same transient ~2x a reload already needs.
        if self.cfg.serve.rollback_keep_s > 0:
            self._prev_gen = cur
            self._prev_gen_t = time.monotonic()
        # Any shadow session described the OLD live generation; a swap
        # invalidates its comparison baseline.
        self._shadow = None
        self._gen = gen
        self._c_reloads.inc()
        self._g_generation.set(new_id)
        self._note_residency()
        absl_logging.info(
            "serving generation %d live (%d members)", new_id,
            gen.n_members,
        )
        return info

    def rollback(self) -> dict:
        """Instant re-swap to the retained previous generation
        (ISSUE 8 lifecycle ROLLBACK): one atomic handle assignment —
        the previous stacked tree is still device-resident from the
        retention window, so no restore, no warm-up, no canary pass
        stands between "regression detected" and "old model serving".

        The restored state is minted as a NEW generation (ids stay
        monotonic, the per-generation row ledger stays unambiguous).
        Raises :class:`RollbackUnavailable` when nothing is retained
        (never swapped / already rolled back) or the
        ``serve.rollback_keep_s`` window expired — callers fall back
        to ``reload()`` from the previous checkpoint set on disk.
        Returns {'generation', 'restored_from', 'n_members'}."""
        with self._reload_lock:
            prev = self._prev_gen
            keep_s = self.cfg.serve.rollback_keep_s
            if prev is None:
                raise RollbackUnavailable(
                    "no previous generation retained (never swapped, or "
                    "already rolled back); reload() the previous "
                    "checkpoint set instead"
                )
            age = time.monotonic() - self._prev_gen_t
            if keep_s <= 0 or age > keep_s:
                self._prev_gen = None
                raise RollbackUnavailable(
                    f"retained generation {prev.gen_id} expired "
                    f"({age:.0f}s old vs serve.rollback_keep_s="
                    f"{keep_s:g}); reload() the previous checkpoint set "
                    "instead"
                )
            cur = self._gen
            new_id = cur.gen_id + 1
            gen = _Generation(
                new_id, prev.state, prev.n_members, prev.member_dirs,
                self._register_gen_rows(new_id),
            )
            self._prev_gen = None  # one rollback per swap, by design
            self._shadow = None
            self._gen = gen
            self._c_rollbacks.inc()
            self._g_generation.set(new_id)
            self._note_residency()
            absl_logging.warning(
                "ROLLBACK: generation %d live again as generation %d "
                "(was serving %d)", prev.gen_id, new_id, cur.gen_id,
            )
            return {
                "generation": new_id,
                "restored_from": prev.gen_id,
                "n_members": gen.n_members,
            }

    # -- staged-rollout shadow seam (ISSUE 8) ------------------------------

    def prepare_candidate(self, member_dirs=None, *,
                          state: "train_lib.TrainState | None" = None,
                          warm: bool = False):
        """Build a candidate generation handle entirely off the
        request path — restore, stack, device-place, optionally warm —
        WITHOUT installing it anywhere. The lifecycle GATE phase scores
        through the handle via ``member_probs(images, _gen=handle)``;
        its rows land on a detached counter, never the live ledger."""
        return self._build_generation(
            self._gen.gen_id + 1, member_dirs=member_dirs, state=state,
            warm=warm,
        )

    def begin_shadow(self, member_dirs=None, *,
                     state: "train_lib.TrainState | None" = None,
                     candidate=None, fraction: float = 0.25) -> dict:
        """Start shadow-scoring a deterministic fraction of live
        requests through a candidate generation. Pass ``candidate``
        (a ``prepare_candidate`` handle, reused so the gate and the
        shadow score the same residency) or checkpoint ``member_dirs``/
        ``state`` to build one here (warmed: a sampled live request
        must never eat a candidate compile). One session at a time;
        a reload/rollback clears the session (its baseline died)."""
        with self._reload_lock:
            if self._shadow is not None:
                raise RuntimeError(
                    "a shadow session is already active; end_shadow() "
                    "it first"
                )
            if candidate is None:
                candidate = self._build_generation(
                    self._gen.gen_id + 1, member_dirs=member_dirs,
                    state=state, warm=True,
                )
            self._shadow = _ShadowSession(
                candidate, candidate.member_dirs, fraction
            )
            return {"fraction": fraction, "every": self._shadow.every}

    def shadow_report(self) -> "dict | None":
        """Comparison evidence of the active session (None = none)."""
        sh = self._shadow
        return sh.report() if sh is not None else None

    def end_shadow(self, promote: bool = False) -> "dict | None":
        """Stop sampling; with ``promote=True`` swap the candidate live
        through the full ``reload()`` path (warm + canary gate + atomic
        swap + retention of the outgoing generation). Returns the final
        shadow report (plus reload info under 'reload' on promote).
        The session is CLAIMED under the reload lock — of two racing
        enders exactly one gets the report (and the promote); the
        reload itself runs after release (it re-takes the lock)."""
        with self._reload_lock:
            sh = self._shadow
            self._shadow = None
        if sh is None:
            return None
        report = sh.report()
        if promote:
            report = dict(report)
            report["reload"] = self.reload(
                member_dirs=sh.member_dirs, state=sh.gen.state
            )
        return report

    def _shadow_sample(self, sh: "_ShadowSession", images: np.ndarray,
                      live_out: np.ndarray) -> None:
        """Score one sampled live request through the candidate; any
        failure is counted, logged, and swallowed — shadow evidence
        must never fail the live request it rode."""
        try:
            shadow_out = metrics.ensemble_average(
                list(self.member_probs(images, _gen=sh.gen))
            )
            sh.record(live_out, shadow_out)
            self._c_shadow_requests.inc()
            self._c_shadow_rows.inc(images.shape[0])
            self._g_shadow_dev.set(sh.max_abs_dev)
        except Exception as e:  # noqa: BLE001 - advisory path
            with sh.lock:
                sh.errors += 1
            self._c_shadow_errors.inc()
            absl_logging.error(
                "shadow scoring failed (live request unaffected): "
                "%s: %s", type(e).__name__, e,
            )

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        # Unreachable: chunks are capped at max_batch <= buckets[-1].
        raise ValueError(f"no bucket covers chunk of {n} rows")

    def _place(self, padded: np.ndarray):
        if self._batch_sharding is not None:
            return pipeline.staged_put(padded, self._batch_sharding)
        return jax.device_put(padded, jax.local_devices()[0])

    def member_probs(self, images: np.ndarray, *,
                     _gen: "_Generation | None" = None) -> np.ndarray:
        """uint8 images [n, S, S, 3] -> per-member probabilities
        [k, n] (binary) or [k, n, C] (multi head).

        Chunks at max_batch, pads each chunk to its bucket shape with
        zero rows, and keeps a BOUNDED window of dispatched chunks in
        flight (fetching chunk i-2 only after dispatching chunk i): the
        H2D/compute overlap of queue-ahead without letting device
        residency grow with request size — a 50k-image screening batch
        holds at most 3 chunks of buffers on device, not the whole
        input. Padding rows are trimmed off on host.

        ``_gen`` (internal): pin the generation this call dispatches on
        — the handle is read ONCE here, so a concurrent ``reload()``
        never splits one request across two generations.
        """
        gen = _gen if _gen is not None else self._gen
        images = np.asarray(images)
        if images.ndim != 4:
            raise ValueError(
                f"expected images [n, S, S, 3], got shape {images.shape}"
            )
        if images.shape[0] == 0:
            raise ValueError("empty request: no rows to score")
        import collections

        max_in_flight = 2
        pending: collections.deque = collections.deque()
        outs = []

        def drain_one():
            p, n = pending.popleft()
            self._g_in_flight.set(len(pending))
            # span() (obs/spans.py) doubles as a trace event when the
            # process tracer is on — the engine-internal sub-segments
            # (pad / H2D+forward dispatch / device_get) nest inside the
            # batcher's per-request `device` segment in the timeline.
            with span("serve.engine.device_get_s", self.registry):
                outs.append(np.asarray(jax.device_get(p))[:, :n])

        for lo in range(0, images.shape[0], self.max_batch):
            chunk = images[lo:lo + self.max_batch]
            bucket = self._bucket_for(chunk.shape[0])
            # Per-bucket telemetry: pad waste is the rows the bucket
            # shape burns beyond the real chunk (the bucket-granularity
            # cost the auto power-of-two ladder bounds at <=50%), and
            # the compile counter ticks on a bucket's FIRST use — a
            # production engine whose compile counters keep growing has
            # a bucket set that defeats compile-once-per-bucket.
            pad_rows = bucket - chunk.shape[0]
            self._c_rows.inc(chunk.shape[0])
            self._c_dtype_rows.inc(chunk.shape[0])
            gen.c_rows.inc(chunk.shape[0])
            self._c_batches.inc()
            c_pad = self._bucket_counters.get(bucket)
            if c_pad is None:
                c_pad = self._bucket_counters[bucket] = self.registry.counter(
                    f"serve.pad_rows_b{bucket}",
                    help="pad waste: rows this bucket shape burned "
                         "beyond real chunk rows",
                )
                self.registry.counter(
                    f"serve.bucket_compiles_b{bucket}",
                    help="ticks on this bucket's FIRST use; growth after "
                         "warmup defeats compile-once-per-bucket",
                ).inc()
            c_pad.inc(pad_rows)
            with span("serve.engine.pad_s", self.registry):
                if pad_rows:
                    pad = np.zeros((pad_rows, *chunk.shape[1:]), chunk.dtype)
                    padded = np.concatenate([chunk, pad])
                else:
                    padded = chunk
            # Fault seam (obs/faultinject.py site "engine.dispatch"):
            # one global read + branch unarmed; armed chaos plans
            # inject a dispatch failure here to drive the batcher's
            # window-error recovery deterministically.
            faultinject.check("engine.dispatch")
            # One span over placement + dispatch: the forward is async
            # (this times H2D staging and queue pressure, not device
            # compute — device time is visible as the device_get drain).
            # Dispatch rides the persistent-cache AOT executable when
            # one matches this (bucket, member count), else the jit.
            with span("serve.engine.dispatch_s", self.registry):
                dev = self._dispatch_fn(bucket, gen)(
                    gen.state, {"image": self._place(padded)}
                )
            prog = self._prog_entries.get(bucket)
            if prog is not None:
                prog.note_call()
            pending.append((dev, chunk.shape[0]))
            self._g_in_flight.set(len(pending))
            if len(pending) > max_in_flight:
                drain_one()
        while pending:
            drain_one()
        return outs[0] if len(outs) == 1 else np.concatenate(outs, axis=1)

    def probs(self, images: np.ndarray) -> np.ndarray:
        """Ensemble-averaged probabilities per row — the same
        metrics.ensemble_average (float64 mean over members) every other
        entry point applies, so a k=1 engine returns the member's probs
        exactly and a k>1 engine matches evaluate.py/predict.py
        averaging bit for bit.

        This is the quality-monitored serving surface (ISSUE 5): every
        live batch feeds the drift monitor's windows, and the golden-set
        canary runs here when its cadence is due — scored through
        ``member_probs`` directly so canary traffic never pollutes the
        drift histograms it guards."""
        return self.probs_with_generation(images)[0]

    def probs_with_generation(
        self, images: np.ndarray
    ) -> "tuple[np.ndarray, int]":
        """``probs`` plus the id of the generation that served the rows
        (ISSUE 6 attribution: during a ``reload`` every response is
        attributable to exactly ONE generation — the handle is read
        once, before any dispatch, and pinned for the whole request
        including its canary ride-along)."""
        gen = self._gen
        out = metrics.ensemble_average(
            list(self.member_probs(images, _gen=gen))
        )
        # Staged-rollout shadow (ISSUE 8): a deterministic fraction of
        # live requests also scores through the candidate generation;
        # inactive = one attribute read + branch.
        sh = self._shadow
        if sh is not None and sh.claim():
            self._shadow_sample(sh, images, out)
        q = self.quality
        if q is not None:
            q.observe(images, out)
            if q.canary_claim():
                q.run_canary(
                    lambda imgs: metrics.ensemble_average(
                        list(self.member_probs(imgs, _gen=gen))
                    )
                )
        # Audit ledger (ISSUE 20): one non-blocking enqueue stamped
        # with the SAME pinned generation the rows were served by.
        al = self.audit
        if al is not None:
            al.record(images, out, generation=gen.gen_id,
                      member_dirs=gen.member_dirs, engine=self)
        return out, gen.gen_id

    def make_batcher(self):
        """A MicroBatcher wired to this engine under cfg.serve's
        coalescing knobs; results are ensemble-averaged rows. The
        model's row shape/dtype are pinned so a malformed request is
        rejected at submit() instead of failing its coalesced window's
        co-riders."""
        from jama16_retina_tpu.serve.batcher import MicroBatcher

        size = self.cfg.model.image_size
        return MicroBatcher(
            self.probs,
            max_batch=self.cfg.serve.max_batch,
            max_wait_ms=self.cfg.serve.max_wait_ms,
            row_shape=(size, size, 3),
            row_dtype=np.uint8,
            registry=self.registry,
            shed_queue_depth=self.cfg.serve.shed_queue_depth,
            shed_in_flight=self.cfg.serve.shed_in_flight,
            default_deadline_ms=self.cfg.serve.default_deadline_ms,
        )

    def start_telemetry(self, workdir: str,
                        every_s: "float | None" = None,
                        alerts=None):
        """A Snapshotter over this engine's registry: `telemetry` +
        `heartbeat` JSONL records in ``workdir`` and an atomically
        rewritten ``<workdir>/telemetry.prom`` per flush — the serving
        twin of the trainer's periodic export (ISSUE 3 acceptance:
        a ServingEngine session produces both artifacts). The caller
        drives the cadence (``maybe_flush()`` between requests, or a
        wrapper thread) and must ``close()`` it; the snapshotter owns
        the RunLog it opens here. ``every_s`` defaults to the config's
        ``obs.flush_every_s`` — the same knob the trainer honors.

        SLO/quality alerting (ISSUE 5) rides the same flush: when the
        config implies rules (obs.quality enabled and/or
        obs.quality.alert_rules) and no ``alerts`` manager is injected,
        one is built here with its own FlightRecorder over ``workdir``
        — so a drifting serving session writes `alert` records AND
        trips a ``quality_drift``/``slo_breach`` blackbox dump (one per
        reason per run), exactly like a train run."""
        from jama16_retina_tpu.obs import alerts as obs_alerts
        from jama16_retina_tpu.obs import export as obs_export

        if alerts is None:
            alerts = obs_alerts.manager_for(
                self.cfg, workdir, registry=self.registry
            )
        # Fleet segment bus (ISSUE 15): serving sessions publish under
        # the "server" role when obs.fleet_dir is set (None = one
        # branch per flush); obs.http_port opts into the live
        # /metrics + /healthz endpoint.
        from jama16_retina_tpu.obs import fleet as obs_fleet

        # Audit ledger (ISSUE 20): a serving session that starts
        # telemetry with obs.audit.enabled gets its provenance ledger
        # here unless the wiring site already attached one. The ledger
        # outlives the snapshotter (daemon writer; unsealed tail at
        # exit is the documented crash semantics) — close() it
        # explicitly to seal the tail.
        if self.audit is None and self.cfg.obs.audit.enabled:
            from jama16_retina_tpu.obs import audit as obs_audit

            self.audit = obs_audit.ledger_for(
                self.cfg, workdir, registry=self.registry
            )
        snap = obs_export.Snapshotter(
            self.registry, workdir,
            every_s=(every_s if every_s is not None
                     else self.cfg.obs.flush_every_s),
            alerts=alerts,
            fleet=obs_fleet.bus_for(self.cfg, "server",
                                    registry=self.registry),
            # Device-utilization plane (ISSUE 19): same flush cadence
            # as the trainer's wiring site; None = one branch.
            device=device_lib.monitor_for(self.cfg,
                                          registry=self.registry),
        )
        if self.cfg.obs.http_port > 0:
            snap.serve_http(self.cfg.obs.http_port)
        return snap
