"""Persistent serving engine: device-resident stacked ensembles.

The pre-existing inference surface (predict.py before this subsystem)
paid O(k·images) per screening request: every ensemble member restored
from orbax per process, k sequential jit forwards per batch, a fresh
compile per invocation. This engine is the resident form of the same
math:

  * all k members restore ONCE and stack into one [k] parameter tree on
    device (train_lib.stack_states — opt_state dropped, so the
    residency is params+batch_stats only);
  * each batch is served by ONE dispatch of the stacked forward
    (train_lib.make_serving_step). The default lax.map member form is
    bit-identical per member to the sequential restore+forward path at
    the same batch shape — the parity contract that let predict.py be
    rewired on top of this engine with byte-identical JSONL output
    (pinned by tests/test_serve.py);
  * inputs pad into a small set of bucketed batch shapes
    (serve.bucket_sizes), so jit compiles once per bucket and never per
    request size. Zero-fill padding rows are provably inert: eval-mode
    forwards are row-independent (BN uses stored moments), so a kept
    row's probabilities do not depend on its neighbors — the property
    the bucket/coalescing machinery rests on, pinned by test;
  * H2D overlaps device compute: per-bucket chunks are placed with
    pipeline.staged_put (per-shard async puts) and all chunk dispatches
    are queued before the first device_get, so the runtime uploads
    chunk i+1 while chunk i computes.
"""

from __future__ import annotations

import jax
import numpy as np

from jama16_retina_tpu import models, train_lib
from jama16_retina_tpu.configs import ExperimentConfig, ServeConfig
from jama16_retina_tpu.data import pipeline
from jama16_retina_tpu.eval import metrics
from jama16_retina_tpu.obs import quality as quality_lib
from jama16_retina_tpu.obs import registry as obs_registry
from jama16_retina_tpu.obs import trace as obs_trace
from jama16_retina_tpu.obs.spans import span
from jama16_retina_tpu.parallel import mesh as mesh_lib


def resolve_buckets(sc: ServeConfig, divisor: int = 1) -> tuple[int, ...]:
    """The padded batch shapes the engine compiles for.

    Explicit ``serve.bucket_sizes`` are taken verbatim (sorted,
    deduplicated); the largest must cover ``serve.max_batch`` or chunks
    at the coalescing cap would have no bucket to land in. Empty = auto:
    powers of two from 8 up to max_batch — at most ~log2(max_batch)
    compiles, and a partial chunk wastes at most half its bucket.

    ``divisor``: the serving mesh's data-axis size. Batch rows shard
    across that axis, so every bucket must divide by it — auto buckets
    are rounded UP to the next multiple; explicit buckets that don't
    divide are rejected HERE, at engine construction, instead of
    surfacing as an opaque XLA uneven-sharding error on the first
    request that hits the bad shape.
    """
    if sc.max_batch < 1:
        raise ValueError(f"serve.max_batch must be >= 1, got {sc.max_batch}")
    divisor = max(1, int(divisor))
    if sc.bucket_sizes:
        buckets = tuple(sorted({int(b) for b in sc.bucket_sizes}))
        if buckets[0] < 1:
            raise ValueError(f"bucket sizes must be >= 1: {sc.bucket_sizes}")
        bad = [b for b in buckets if b % divisor]
        if bad:
            raise ValueError(
                f"bucket sizes {bad} do not divide across the serving "
                f"mesh's data axis ({divisor} devices); every bucket "
                f"must be a multiple of {divisor}"
            )
        if buckets[-1] < sc.max_batch:
            raise ValueError(
                f"largest bucket {buckets[-1]} < serve.max_batch "
                f"{sc.max_batch}: chunks at the coalescing cap would have "
                "no compiled shape"
            )
        return buckets
    out, b = [], 8
    while b < sc.max_batch:
        out.append(b)
        b *= 2
    out.append(sc.max_batch)
    return tuple(sorted({-(-b // divisor) * divisor for b in out}))


class ServingEngine:
    """Restore-once, stacked, bucket-batched ensemble inference.

    Construct from checkpoint dirs (the production path) or hand a
    pre-stacked state directly (``state=``; bench/tests skip the orbax
    round-trip that way). ``mesh``: a DATA mesh — state replicated,
    batch rows sharded across the data axis, exactly make_eval_step's
    serving-side layout.
    """

    def __init__(
        self,
        cfg: ExperimentConfig,
        member_dirs: "list[str] | None" = None,
        *,
        model=None,
        mesh=None,
        state: "train_lib.TrainState | None" = None,
        registry: "obs_registry.Registry | None" = None,
    ):
        self.cfg = cfg
        self.model = model if model is not None else models.build(cfg.model)
        self.mesh = mesh
        # Telemetry (obs/): per-bucket pad-waste + compile counters, the
        # in-flight chunk gauge, and a per-engine-call batch counter —
        # the knobs-to-metrics map is in docs/OBSERVABILITY.md. Tests
        # inject a Registry; production uses the process default.
        self.registry = (
            registry if registry is not None
            else obs_registry.default_registry()
        )
        if registry is None:
            # Same wiring rule as the trainer's run entry: the engine's
            # own config decides whether the process-default registry
            # records (a prior obs.enabled=false fit in this process
            # must not silently mute serving telemetry). The process
            # tracer gets the same treatment — a serving session never
            # runs trainer._obs_begin_run, so obs.trace_enabled must be
            # applied here for the batcher's request segments to record.
            self.registry.enabled = cfg.obs.enabled
            obs_trace.default_tracer().configure(
                enabled=cfg.obs.enabled and cfg.obs.trace_enabled,
                buffer_events=cfg.obs.trace_buffer_events,
            )
        self._c_rows = self.registry.counter("serve.engine.rows")
        self._c_batches = self.registry.counter("serve.engine.batches")
        self._g_in_flight = self.registry.gauge("serve.engine.in_flight")
        # Model-quality observability (obs/quality.py; ISSUE 5): the
        # drift monitor + golden canary, or None when obs.quality is
        # off — the disabled serve path pays exactly one branch per
        # probs() call. Artifacts (profile/canary) load HERE, at engine
        # construction, so a typo'd path fails the session loudly
        # instead of silently serving unmonitored.
        self.quality = quality_lib.monitor_from_config(
            cfg.obs.quality, registry=self.registry
        ) if cfg.obs.enabled else None
        if self.quality is not None and self.quality.canary is not None:
            want = (cfg.model.image_size, cfg.model.image_size, 3)
            got = tuple(self.quality.canary.images.shape[1:])
            if got != want:
                # Catch the mis-sized artifact at session start: the
                # canary rides live probs() calls, and a shape error
                # there would fail one real request per cadence tick.
                raise ValueError(
                    f"canary images are {got} but this engine serves "
                    f"{want} (model.image_size={cfg.model.image_size}) — "
                    "re-pin obs.quality.canary_path for this checkpoint"
                )
        # Per-bucket counter handles, created on a bucket's first use:
        # the steady-state path is a plain dict hit — no f-string, no
        # registry lock (the hot-path contract in obs/registry.py).
        self._bucket_counters: dict = {}
        if state is None:
            if not member_dirs:
                raise ValueError(
                    "ServingEngine needs member checkpoint dirs (or a "
                    "pre-stacked state=)"
                )
            from jama16_retina_tpu import trainer

            state = train_lib.stack_states([
                trainer.restore_for_eval(cfg, self.model, d)
                for d in member_dirs
            ])
        else:
            # Serving never steps the optimizer; drop its moments from
            # the device residency whatever the caller handed over.
            state = state.replace(opt_state=None)
        self.n_members = int(state.step.shape[0])
        place = (
            mesh_lib.replicated(mesh) if mesh is not None
            else jax.local_devices()[0]
        )
        self.state = jax.device_put(state, place)
        self._batch_sharding = (
            mesh_lib.batch_sharding(mesh) if mesh is not None else None
        )
        self._step = train_lib.make_serving_step(
            cfg, self.model, mesh=mesh,
            member_parallel=cfg.serve.member_parallel,
        )
        self.max_batch = int(cfg.serve.max_batch)
        divisor = (
            int(mesh.shape[mesh_lib._batch_axis(mesh)])
            if mesh is not None else 1
        )
        self.buckets = resolve_buckets(cfg.serve, divisor=divisor)

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        # Unreachable: chunks are capped at max_batch <= buckets[-1].
        raise ValueError(f"no bucket covers chunk of {n} rows")

    def _place(self, padded: np.ndarray):
        if self._batch_sharding is not None:
            return pipeline.staged_put(padded, self._batch_sharding)
        return jax.device_put(padded, jax.local_devices()[0])

    def member_probs(self, images: np.ndarray) -> np.ndarray:
        """uint8 images [n, S, S, 3] -> per-member probabilities
        [k, n] (binary) or [k, n, C] (multi head).

        Chunks at max_batch, pads each chunk to its bucket shape with
        zero rows, and keeps a BOUNDED window of dispatched chunks in
        flight (fetching chunk i-2 only after dispatching chunk i): the
        H2D/compute overlap of queue-ahead without letting device
        residency grow with request size — a 50k-image screening batch
        holds at most 3 chunks of buffers on device, not the whole
        input. Padding rows are trimmed off on host.
        """
        images = np.asarray(images)
        if images.ndim != 4:
            raise ValueError(
                f"expected images [n, S, S, 3], got shape {images.shape}"
            )
        if images.shape[0] == 0:
            raise ValueError("empty request: no rows to score")
        import collections

        max_in_flight = 2
        pending: collections.deque = collections.deque()
        outs = []

        def drain_one():
            p, n = pending.popleft()
            self._g_in_flight.set(len(pending))
            # span() (obs/spans.py) doubles as a trace event when the
            # process tracer is on — the engine-internal sub-segments
            # (pad / H2D+forward dispatch / device_get) nest inside the
            # batcher's per-request `device` segment in the timeline.
            with span("serve.engine.device_get_s", self.registry):
                outs.append(np.asarray(jax.device_get(p))[:, :n])

        for lo in range(0, images.shape[0], self.max_batch):
            chunk = images[lo:lo + self.max_batch]
            bucket = self._bucket_for(chunk.shape[0])
            # Per-bucket telemetry: pad waste is the rows the bucket
            # shape burns beyond the real chunk (the bucket-granularity
            # cost the auto power-of-two ladder bounds at <=50%), and
            # the compile counter ticks on a bucket's FIRST use — a
            # production engine whose compile counters keep growing has
            # a bucket set that defeats compile-once-per-bucket.
            pad_rows = bucket - chunk.shape[0]
            self._c_rows.inc(chunk.shape[0])
            self._c_batches.inc()
            c_pad = self._bucket_counters.get(bucket)
            if c_pad is None:
                c_pad = self._bucket_counters[bucket] = self.registry.counter(
                    f"serve.pad_rows_b{bucket}"
                )
                self.registry.counter(f"serve.bucket_compiles_b{bucket}").inc()
            c_pad.inc(pad_rows)
            with span("serve.engine.pad_s", self.registry):
                if pad_rows:
                    pad = np.zeros((pad_rows, *chunk.shape[1:]), chunk.dtype)
                    padded = np.concatenate([chunk, pad])
                else:
                    padded = chunk
            # One span over placement + dispatch: the forward is async
            # (this times H2D staging and queue pressure, not device
            # compute — device time is visible as the device_get drain).
            with span("serve.engine.dispatch_s", self.registry):
                dev = self._step(self.state, {"image": self._place(padded)})
            pending.append((dev, chunk.shape[0]))
            self._g_in_flight.set(len(pending))
            if len(pending) > max_in_flight:
                drain_one()
        while pending:
            drain_one()
        return outs[0] if len(outs) == 1 else np.concatenate(outs, axis=1)

    def probs(self, images: np.ndarray) -> np.ndarray:
        """Ensemble-averaged probabilities per row — the same
        metrics.ensemble_average (float64 mean over members) every other
        entry point applies, so a k=1 engine returns the member's probs
        exactly and a k>1 engine matches evaluate.py/predict.py
        averaging bit for bit.

        This is the quality-monitored serving surface (ISSUE 5): every
        live batch feeds the drift monitor's windows, and the golden-set
        canary runs here when its cadence is due — scored through
        ``member_probs`` directly so canary traffic never pollutes the
        drift histograms it guards."""
        out = metrics.ensemble_average(list(self.member_probs(images)))
        q = self.quality
        if q is not None:
            q.observe(images, out)
            if q.canary_claim():
                q.run_canary(
                    lambda imgs: metrics.ensemble_average(
                        list(self.member_probs(imgs))
                    )
                )
        return out

    def make_batcher(self):
        """A MicroBatcher wired to this engine under cfg.serve's
        coalescing knobs; results are ensemble-averaged rows. The
        model's row shape/dtype are pinned so a malformed request is
        rejected at submit() instead of failing its coalesced window's
        co-riders."""
        from jama16_retina_tpu.serve.batcher import MicroBatcher

        size = self.cfg.model.image_size
        return MicroBatcher(
            self.probs,
            max_batch=self.cfg.serve.max_batch,
            max_wait_ms=self.cfg.serve.max_wait_ms,
            row_shape=(size, size, 3),
            row_dtype=np.uint8,
            registry=self.registry,
        )

    def start_telemetry(self, workdir: str,
                        every_s: "float | None" = None,
                        alerts=None):
        """A Snapshotter over this engine's registry: `telemetry` +
        `heartbeat` JSONL records in ``workdir`` and an atomically
        rewritten ``<workdir>/telemetry.prom`` per flush — the serving
        twin of the trainer's periodic export (ISSUE 3 acceptance:
        a ServingEngine session produces both artifacts). The caller
        drives the cadence (``maybe_flush()`` between requests, or a
        wrapper thread) and must ``close()`` it; the snapshotter owns
        the RunLog it opens here. ``every_s`` defaults to the config's
        ``obs.flush_every_s`` — the same knob the trainer honors.

        SLO/quality alerting (ISSUE 5) rides the same flush: when the
        config implies rules (obs.quality enabled and/or
        obs.quality.alert_rules) and no ``alerts`` manager is injected,
        one is built here with its own FlightRecorder over ``workdir``
        — so a drifting serving session writes `alert` records AND
        trips a ``quality_drift``/``slo_breach`` blackbox dump (one per
        reason per run), exactly like a train run."""
        from jama16_retina_tpu.obs import alerts as obs_alerts
        from jama16_retina_tpu.obs import export as obs_export

        if alerts is None:
            alerts = obs_alerts.manager_for(
                self.cfg, workdir, registry=self.registry
            )
        return obs_export.Snapshotter(
            self.registry, workdir,
            every_s=(every_s if every_s is not None
                     else self.cfg.obs.flush_every_s),
            alerts=alerts,
        )
