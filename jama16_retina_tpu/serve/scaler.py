"""Replica autoscaling signals: a pure, hysteresis-guarded policy over
the router's own load gauges (ISSUE 12).

The scaling question — "how many engine replicas should be serving?" —
is answered the way the ingest autotuner (data/autotune.py) answers its
knob questions: a PURE ``decide()`` over tumbling-window statistics,
with hysteresis so a stationary workload converges and stays converged.
Same stats in, same decision out — which is what lets the tests pin
exact decision sequences, and what makes the desired-replica gauge
trustworthy as an external autoscaling signal (a k8s HPA reading
``serve.scaler.desired_replicas`` sees policy, not noise).

The router drives this at its tick cadence and ACTS on the output
in-process (activate / drain replicas) when it owns a replica factory;
without one the signals still publish — the gauge is the product, the
in-process actuation is the proof it closes.

Hysteresis shape (constants module-level so tests pin shipped values):

  * scale UP needs ``HOT_WINDOWS`` consecutive hot windows — a window
    is hot when the queue backlog exceeds ``QUEUE_HIGH`` of one
    dispatch wave's capacity, in-flight utilization exceeds
    ``IN_FLIGHT_HIGH``, or the p99 latency breaches the SLO;
  * scale DOWN needs ``QUIET_WINDOWS`` consecutive quiet windows
    (empty queue, utilization under ``IN_FLIGHT_LOW``, p99 under half
    the SLO) — the same decay discipline the autotuner applies;
  * the band between holds still AND resets both streaks (windows must
    be consecutive);
  * one replica per decision, bounded by [min_replicas, max_replicas];
    a decision pinned at max_replicas while still hot reports
    ``saturated`` — the condition the scaler-saturation alert reads.
"""

from __future__ import annotations

import dataclasses

# --- Policy constants (pinned by tests/test_router.py) ------------------
QUEUE_HIGH = 0.5       # queued rows > this fraction of one dispatch
                       # wave (active * max_batch) = backlog building
IN_FLIGHT_HIGH = 0.75  # in-flight rows / capacity above = replicas busy
IN_FLIGHT_LOW = 0.25   # below (with an empty queue) = over-provisioned
HOT_WINDOWS = 2        # consecutive hot windows before one scale-up
QUIET_WINDOWS = 3      # consecutive quiet windows before one scale-down
MIN_WINDOW_S = 0.05    # shorter windows carry no usable signal


@dataclasses.dataclass(frozen=True)
class ScalerStats:
    """One tumbling window's load signals, normalized by the router:
    mean queued rows, mean in-flight rows, and the window's p99 request
    latency (0 = unknown/no requests)."""

    window_sec: float
    queue_rows: float
    in_flight_rows: float
    p99_latency_s: float = 0.0


@dataclasses.dataclass(frozen=True)
class ScalerState:
    """Controller memory threaded through ``decide`` — explicit state
    keeps the decision function pure (the autotuner's ControlState
    pattern)."""

    hot_windows: int = 0
    quiet_windows: int = 0


@dataclasses.dataclass(frozen=True)
class ScalerLimits:
    min_replicas: int = 1
    max_replicas: int = 8
    # p99 SLO in seconds; 0 disables the latency signal.
    slo_p99_s: float = 0.0


@dataclasses.dataclass(frozen=True)
class ScalerDecision:
    desired: int
    state: ScalerState
    reason: str
    saturated: bool = False


def decide(stats: ScalerStats, active: int, max_batch: int,
           state: ScalerState, limits: ScalerLimits) -> ScalerDecision:  # graftlint: deterministic
    """One pure scaling decision (same stats, same state -> same
    decision; no clocks, no RNG — pinned by tests/test_router.py).

    ``active`` is the replica count the window's stats describe;
    ``max_batch`` sizes one dispatch wave. The returned ``desired`` is
    at most one step from ``active`` and always inside the limits."""
    active = max(1, int(active))
    lo = max(1, int(limits.min_replicas))
    hi = max(lo, int(limits.max_replicas))
    clamped = min(hi, max(lo, active))
    if stats.window_sec < MIN_WINDOW_S:
        return ScalerDecision(clamped, state, "window_too_short")
    capacity = float(active * max(1, int(max_batch)))
    in_flight_frac = stats.in_flight_rows / capacity
    slo = float(limits.slo_p99_s)
    slo_hot = slo > 0 and stats.p99_latency_s > slo
    hot = (
        stats.queue_rows > QUEUE_HIGH * capacity
        or in_flight_frac > IN_FLIGHT_HIGH
        or slo_hot
    )
    quiet = (
        stats.queue_rows == 0
        and in_flight_frac < IN_FLIGHT_LOW
        and (slo <= 0 or stats.p99_latency_s < 0.5 * slo)
    )
    if hot:
        streak = state.hot_windows + 1
        if streak >= HOT_WINDOWS:
            if clamped >= hi:
                # Still hot at the ceiling: hold, report saturation
                # (the alert-rule condition), keep the streak so the
                # signal stays loud every window.
                return ScalerDecision(
                    hi, ScalerState(hot_windows=min(streak, HOT_WINDOWS)),
                    "saturated_at_max", saturated=True,
                )
            return ScalerDecision(
                min(hi, clamped + 1), ScalerState(),
                "scale_up:" + ("slo_p99" if slo_hot else
                               "queue" if stats.queue_rows
                               > QUEUE_HIGH * capacity else "in_flight"),
            )
        return ScalerDecision(
            clamped, ScalerState(hot_windows=streak), "hot_streak"
        )
    if quiet:
        streak = state.quiet_windows + 1
        if streak >= QUIET_WINDOWS and clamped > lo:
            return ScalerDecision(
                clamped - 1, ScalerState(), "scale_down:quiet"
            )
        return ScalerDecision(
            clamped, ScalerState(quiet_windows=min(streak, QUIET_WINDOWS)),
            "quiet_streak",
        )
    # The hysteresis band: hold, and reset both streaks — hot/quiet
    # evidence must be CONSECUTIVE to move the replica count.
    return ScalerDecision(clamped, ScalerState(), "hold")
