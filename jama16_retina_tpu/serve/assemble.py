"""Engine assembly: ONE composable seam from declared intent to a
built serving engine (ISSUE 14 tentpole).

The serve stack grew four cooperating layers that each wrapped the next
ad hoc — ``engine.py`` (restore→stack→device_put), ``cascade.py``
(a CascadeEngine hand-wired around two engines), ``quantize.py``
(via ``make_serving_step(param_transform=)``), ``compilecache.py``
(keyed per-mesh) — and every constructor site (predict.py's three
paths, the router's replica factory, the lifecycle CLI) re-derived the
wiring positionally. :class:`EngineSpec` makes the composition
declarative: mesh shape, serving dtype, cascade, compile cache, and
member count are FIELDS of one frozen spec, and :func:`assemble` is the
one function that turns a spec into a ready engine.

Contracts:

  * **Bit-identity at the default spec.** A 1-device ``EngineSpec``
    (``parallel.serve_devices`` <= 1, no explicit mesh) constructs the
    engine through byte-for-byte the same calls the pre-seam code made
    — mesh=None, same constructor arguments — so every existing parity
    pin (engine vs sequential path, predict.py byte-identical JSONL)
    rides ``assemble()`` unchanged (pinned by tests/test_podscale.py).
  * **The mesh is config.** With no explicit ``mesh``, the serving mesh
    comes from ``parallel.serve_devices`` / ``parallel.member_axis_size``
    (mesh_lib.make_serve_mesh): 0/1 = the mesh-less legacy engine,
    >1 = GSPMD data-sharded serving, member_axis_size > 1 additionally
    shards the stacked tree across the member axis (the pod form).
  * **Cascade composes, not wraps.** ``student_dirs`` (or
    ``serve.cascade_student_dir``) assembles the ISSUE-10 cascade with
    exactly predict.py's historical quality/registry wiring — including
    the detached-registry dtype-gate construction for non-fp32
    ensembles — behind the same spec.

Construction sites (all through here): predict.py's single-engine,
cascade, and router-replica paths; scripts/lifecycle_run.py's
controller engine; the mesh-scaling dryrun and smoke harnesses.
"""

from __future__ import annotations

import dataclasses
from typing import Any

from jama16_retina_tpu.configs import ExperimentConfig
from jama16_retina_tpu.parallel import mesh as mesh_lib


@dataclasses.dataclass(frozen=True)
class EngineSpec:
    """Everything an engine assembly needs, declared up front.

    ``cfg`` carries the knob surface (serve.dtype, cascade band/
    thresholds, compile cache dir, parallel.* mesh axes); the spec adds
    the per-deployment identities: which checkpoints, which (optional)
    explicit mesh, which registry. ``member_dirs`` XOR ``state`` is the
    engine's restore source (exactly ServingEngine's contract).
    """

    cfg: ExperimentConfig
    # Ensemble member checkpoint dirs (the restore-once source); empty
    # needs ``state``.
    member_dirs: tuple = ()
    # Distilled-student checkpoint dirs: non-empty assembles a
    # CascadeEngine (student scores all rows, the ensemble only the
    # escalation band). Empty falls back to
    # ``serve.cascade_student_dir`` (discovered), then to no cascade.
    student_dirs: tuple = ()
    # Pre-stacked TrainState (bench/tests skip the orbax round-trip).
    state: Any = None
    # Pre-built flax model (the checkpoint schema); None builds one.
    model: Any = None
    # Explicit jax Mesh — wins over the config derivation. None derives
    # from cfg.parallel (make_serve_mesh; None at <=1 serve_devices).
    mesh: Any = None
    # Telemetry registry; None = the engine's own default wiring.
    registry: Any = None
    # Cascade-level QualityMonitor; None builds one from cfg.obs.quality
    # when the spec assembles a cascade (predict.py's wiring).
    quality: Any = None
    # Run the cascade's go-live gates (golden canary + operating-point
    # parity) before returning — typed CascadeRejected on failure.
    go_live: bool = False
    # False assembles the PLAIN ensemble engine even when
    # ``serve.cascade_student_dir`` is set — the router's replica
    # factory builds its cascade by composition around a SHARED
    # escalation pool, so its member/student sub-engines must assemble
    # un-cascaded.
    cascade: bool = True

    def n_members(self) -> int:
        if self.member_dirs:
            return len(self.member_dirs)
        if self.state is not None:
            return int(self.state.step.shape[0])
        return 1


def resolve_mesh(spec: EngineSpec):
    """The serving mesh this spec assembles over: the explicit mesh
    when one is injected, else the ``parallel.*``-derived one (None —
    the bit-identity single-device construction — unless
    ``parallel.serve_devices`` > 1)."""
    if spec.mesh is not None:
        return spec.mesh
    return mesh_lib.make_serve_mesh(
        spec.cfg.parallel, n_members=spec.n_members()
    )


def _quality_off(cfg: ExperimentConfig) -> ExperimentConfig:
    """cfg with the engine-level quality monitor disabled — the
    sub-engine config of every cascade/replica assembly (the merged
    view or replica 0 owns quality; sub-engines must not
    double-observe)."""
    return cfg.replace(obs=dataclasses.replace(
        cfg.obs, quality=dataclasses.replace(
            cfg.obs.quality, enabled=False,
        ),
    ))


def _resolve_student_dirs(spec: EngineSpec) -> tuple:
    if not spec.cascade:
        return ()
    if spec.student_dirs:
        return tuple(spec.student_dirs)
    if spec.cfg.serve.cascade_student_dir:
        from jama16_retina_tpu.utils import checkpoint as ckpt_lib

        return tuple(ckpt_lib.discover_member_dirs(
            spec.cfg.serve.cascade_student_dir
        ))
    return ()


def assemble(spec: EngineSpec):
    """Spec -> ready engine (ServingEngine, or CascadeEngine when the
    spec carries a student). The one home of the serve stack's
    composition rules; see the module docstring for the contracts."""
    from jama16_retina_tpu import models
    from jama16_retina_tpu.serve.engine import ServingEngine

    cfg = spec.cfg
    model = spec.model if spec.model is not None else models.build(cfg.model)
    mesh = resolve_mesh(spec)
    member_dirs = list(spec.member_dirs) if spec.member_dirs else None
    student_dirs = _resolve_student_dirs(spec)

    if not student_dirs:
        # The plain ensemble engine — at the default spec this is
        # byte-for-byte the legacy construction (mesh=None, same
        # arguments), which is what keeps every parity pin honest.
        return ServingEngine(
            cfg, member_dirs, model=model, mesh=mesh, state=spec.state,
            registry=spec.registry,
        )

    # Cascade assembly (ISSUE 10 wiring, now declarative): quality
    # observability lives on the CASCADE (the merged scores are what
    # the deployment serves), so both sub-engines build quality-off —
    # EXCEPT the ensemble half under a non-fp32 dtype with a pinned
    # canary, whose DtypeRejected construction gate needs the
    # engine-level canary on a DETACHED registry (its gauges must not
    # collide with the cascade's merged-view monitor).
    from jama16_retina_tpu.obs import quality as quality_lib
    from jama16_retina_tpu.obs import registry as obs_registry
    from jama16_retina_tpu.serve.cascade import CascadeEngine

    sub = _quality_off(cfg)
    if (cfg.serve.dtype != "fp32"
            and cfg.obs.quality.enabled
            and cfg.obs.quality.canary_path):
        ensemble = ServingEngine(
            cfg, member_dirs, model=model, mesh=mesh, state=spec.state,
            registry=obs_registry.Registry(),
        )
        # The monitor existed to arm the one-shot construction gate;
        # steady-state quality lives on the cascade below.
        ensemble.quality = None
    else:
        ensemble = ServingEngine(
            sub, member_dirs, model=model, mesh=mesh, state=spec.state,
            registry=spec.registry,
        )
    quality = spec.quality
    if quality is None and cfg.obs.enabled:
        quality = quality_lib.monitor_from_config(cfg.obs.quality)
    if quality is not None and cfg.serve.fused_preprocess:
        # Fused serve preprocess (ISSUE 16): the cascade's merged-view
        # monitor reads its input stats from the fused pass, same as
        # the engine-level install in serve/engine.py.
        from jama16_retina_tpu.serve import host as serve_host

        _reg = (spec.registry if spec.registry is not None
                else obs_registry.default_registry())
        quality.stats_fn = lambda rows: serve_host.stats_only(
            rows, fused=True, registry=_reg
        )
    engine = CascadeEngine(
        cfg,
        ServingEngine(sub, list(student_dirs), model=model, mesh=mesh),
        ensemble,
        registry=(spec.registry if spec.registry is not None
                  else obs_registry.default_registry()),
        quality=quality,
    )
    if spec.go_live:
        engine.go_live()
    return engine
